"""Public simulation API: settings -> model -> devices -> fields -> step loop.

This is the TPU-native analog of the reference's ``Simulation`` module
(``src/simulation/public.jl`` + ``communication.jl:15-46``):

* ``initialization(args)``  -> parse config, select devices, build the
  domain decomposition, initialize fields (``communication.jl:15-33``).
* ``Simulation.iterate(n)`` -> advance n steps (``public.jl:45-71``); halo
  exchange + stencil update + "swap" all live inside one jitted
  ``lax.fori_loop`` so XLA fuses and overlaps them — there is no per-step
  host round-trip, unlike the reference which re-dispatches from strings
  every step (``public.jl:47``, SURVEY defect #9).
* ``Simulation.get_fields()`` -> host copies of the model's fields
  (``Simulation_CPU.jl:125-133``; ghost stripping is a no-op here because
  fields are stored interior-shaped).

Multi-model: the physics comes from a registered model declaration
(``models/``: named fields, per-field boundary constants, typed params,
pure reaction, init) selected by the ``[model]`` TOML table; Gray-Scott
is the default and flagship. ``self.fields`` is the model's field tuple
in declaration order (``self.u``/``self.v`` alias fields 0/1 for the
two-field models). Everything below the model boundary — halo exchange,
split-phase overlap, temporal blocking, autotune, snapshots, and the
fused Pallas kernel itself — is model-generic: ``ops/kernelgen``
trace-inlines the model's pure reaction into the slab pipeline, and
Pallas eligibility is a feasibility check on the reaction's jaxpr
(``kernelgen.generation_gate_reason``, recorded as the ``kernel_gate``
provenance in ``kernel_selection``), not a model-name gate.

Distribution: with >1 device of the selected platform, fields are sharded
``P('x','y','z')`` over a 3D ``jax.sharding.Mesh`` (the ``MPI.Cart_create``
analog) and the step runs under ``shard_map`` with ``lax.ppermute`` halos
(``parallel/halo.py``). With 1 device the ghost shell is a constant pad.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.6 style
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

# The flag that skips the varying-mesh-axes/replication check was renamed
# check_rep -> check_vma across jax versions; resolve the spelling this
# jax actually takes so the sharded runner constructs on both.
import inspect as _inspect

_SHARD_MAP_CHECK_FLAG = (
    "check_vma"
    if "check_vma" in _inspect.signature(shard_map).parameters
    else "check_rep"
)

from .config import settings as config
from .config.env import env_float, env_raw, env_str
from .config.settings import Settings
from .models import get_model
from .ops import noise as noise_ops
from .ops import stencil, validate_kernel_language
from .parallel import halo, temporal
from .parallel.domain import CartDomain
from .utils.log import _is_primary

AXIS_NAMES = ("x", "y", "z")


def default_fuse() -> int:
    """Temporal-blocking depth for single-block Pallas runs.

    Deeper fusion cuts HBM passes per step ~1/k until stage compute
    fills the DMA envelope; after the round-3 op diet (mul-form
    Laplacian, 2D-amortized noise hash) the measured optimum on the v5e
    moved from k=4 to k=5 (`benchmarks/results/ab_r3_deepfuse_*`).
    ``GS_FUSE`` overrides; off-TPU the interpreter pays per-stage
    simulation cost, so tests keep the historical depth 2.
    """
    v = env_str("GS_FUSE", "")
    if v:
        try:
            return max(1, int(v))
        except ValueError as e:
            raise ValueError(
                f"GS_FUSE must be a positive integer, got {v!r}"
            ) from e
    return 5 if jax.default_backend() == "tpu" else 2


#: Platforms this process has already reached successfully — skips the
#: bounded subprocess probe on subsequent Simulation constructions.
_reached_platforms: set = set()

#: Cache dirs already pointed at jax's persistent compilation cache —
#: makes :func:`_enable_compile_cache` idempotent per path.
_compile_cache_armed: set = set()


def _enable_compile_cache(path: str) -> None:
    """Point JAX's persistent compilation cache at ``path``.

    Armed at Simulation construction (before the first jit) when
    ``config.resolve_compile_cache`` yields a directory — supervisor
    restart attempts and repeated bench invocations then load compiled
    executables from disk instead of re-lowering the same runners. The
    floors are dropped to zero so the small programs of tests and smoke
    runs are cached too (the runner cache key includes the full program,
    so correctness is unaffected). Best-effort: a jax without the config
    knobs degrades to uncached compiles with a warning, not a failure.
    """
    import os

    if path in _compile_cache_armed:
        return
    os.makedirs(path, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        # The cache object initializes lazily at the FIRST compile and
        # then pins its directory; a process that already jitted
        # anything (warmups, earlier Simulations) must reset it or the
        # new directory silently never receives entries.
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception as e:  # pragma: no cover — jax version drift
        import sys

        print(
            f"gray-scott: warning: persistent compilation cache "
            f"unavailable ({e}); compiles will not be reused",
            file=sys.stderr,
        )
        return
    _compile_cache_armed.add(path)


def _bounded_tpu_probe(timeout: float) -> Optional[str]:
    """Probe TPU reachability in a subprocess with a hard wall-clock
    bound; returns an error string, or None when the chip answered.

    Initializing a remote-tunnel PJRT client ("axon"-style platforms)
    blocks *indefinitely* when no chip grant is available; probing
    out-of-process keeps this process un-wedged and able to report a
    clear error. SIGTERM before SIGKILL — a SIGKILLed PJRT client can
    wedge the grant server-side.
    """
    import subprocess
    import sys

    src = (
        "import jax, jax.numpy as jnp;"
        "jax.devices('tpu');"
        "print('GSPROBE-OK', float(jnp.ones((8, 8)).sum()))"
    )
    proc = subprocess.Popen(
        [sys.executable, "-c", src],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        out, err = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            proc.communicate(timeout=10)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
        return f"TPU probe timed out after {timeout:.0f}s (tunnel wedged?)"
    if "GSPROBE-OK" in out:
        return None
    tail = err.strip().splitlines()[-1] if err.strip() else "no output"
    return f"TPU probe failed (rc={proc.returncode}): {tail}"


def select_devices(platform: str):
    """Devices of the requested platform (reference backend dispatch analog).

    For CPU runs the platform list is pinned to "cpu" before the first
    device query: initializing *all* registered backends would create the
    TPU-tunnel client too, which blocks when no chip grant is available —
    a CPU-only run must never depend on the accelerator being reachable.

    For TPU runs an unreachable chip must fail in seconds with a clear
    error, not hang ``Simulation.__init__`` forever: the first TPU
    construction in a process runs a bounded out-of-process probe
    (``GS_TPU_PROBE_TIMEOUT`` seconds, default 60; ``0`` disables, e.g.
    when a parent process already probed).
    """
    import os

    if platform == "cpu" and "cpu" not in _reached_platforms:
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError as e:
            # Backends are already initialized, so the pin is a no-op.
            # Only safe to continue if CPU devices are in fact reachable —
            # the jax.devices() below verifies exactly that; say why.
            import sys

            print(
                f"gray-scott: note: platform pin to cpu was too late ({e}); "
                "continuing with already-initialized backends",
                file=sys.stderr,
            )
    elif platform == "tpu" and platform not in _reached_platforms:
        timeout = env_float("GS_TPU_PROBE_TIMEOUT", 60.0)
        if timeout > 0:
            probe_err = _bounded_tpu_probe(timeout)
            if probe_err is not None:
                raise RuntimeError(
                    f"Backend 'TPU' requested but the chip is not "
                    f"reachable: {probe_err}. Retry later, or set "
                    "GS_TPU_PROBE_TIMEOUT=0 to dial without the guard."
                )
    try:
        devices = jax.devices(platform)
    except RuntimeError as e:
        raise RuntimeError(
            f"Backend {platform!r} requested in config but no such JAX "
            f"devices are available: {e}"
        ) from e
    _reached_platforms.add(platform)
    from .resilience.sdc import device_name, resolve_blocklist

    blocked = resolve_blocklist()
    if blocked:
        kept = [d for d in devices if device_name(d) not in blocked]
        if not kept:
            raise RuntimeError(
                f"all {len(devices)} {platform} devices are "
                "quarantined (GS_DEVICE_BLOCKLIST / fleet quarantine "
                "docs) — no compute inventory left"
            )
        devices = kept
    return devices


def mesh_for_topology(shape, devices, backend: str):
    """Device array for a mesh of ``shape`` over ``devices``.

    On TPU, maps the logical mesh onto the physical ICI topology
    (v4/v5p are 3D tori) so the 6-face ppermute halo exchange rides
    single-hop links — the TPU analog of MPI_Cart_create's
    reorder=true. Virtual/CPU meshes have no topology to exploit and
    use enumeration order. Shared by the 3D spatial mesh and the
    ensemble engine's 4D (member, x, y, z) mesh.
    """
    if backend == "tpu":
        try:
            from jax.experimental import mesh_utils

            return mesh_utils.create_device_mesh(shape, devices=devices)
        except (ValueError, NotImplementedError, AssertionError) as e:
            import sys

            print(
                "gray-scott: warning: topology-aware mesh failed "
                f"({e}); falling back to enumeration order — halo "
                "ppermutes may ride multi-hop ICI links",
                file=sys.stderr,
            )
    return np.array(devices).reshape(shape)


class FieldSnapshot:
    """A device-detached capture of the model's fields draining to the
    host.

    Produced by :meth:`Simulation.snapshot_async`: the fields are copied
    into fresh device buffers and every addressable shard has a
    non-blocking device-to-host transfer in flight by the time the
    constructor returns. :meth:`blocks` resolves (blocking only on the
    remaining transfer time) to the ``local_blocks()`` format —
    ``[(offsets, sizes, *field_blocks), ...]`` with one block per model
    field in declaration order (for Gray-Scott: ``(offsets, sizes,
    u_block, v_block)``) — so a background writer thread can
    serialize/write while the driver thread dispatches the next compute
    chunk (``io/async_writer.py``).

    Lifetime contract: the snapshot owns its device buffers outright —
    it stays valid across later ``iterate`` calls even though those
    donate (and thereby delete) the simulation's own field buffers.
    """

    def __init__(self, parts, step: int, health=None,
                 field_names=("u", "v"), numerics=None,
                 checksums=None, enc_parts=None, enc_meta=None):
        #: Simulation step the snapshot was taken at.
        self.step = step
        self._parts = parts  # [(offsets, true_sizes, *field_devs), ...]
        self._blocks = None
        #: Lossy-codec parts (docs/PRECISION.md): same per-shard shape
        #: as ``_parts`` but coded fields carry their uint payloads —
        #: the bytes in flight are the compressed ones. ``enc_meta``
        #: maps field index -> (bits, lo_dev, hi_dev, dtype_str).
        self._enc_parts = enc_parts
        self._enc_meta = enc_meta or {}
        #: Model field names, for the health report's attribution.
        self.field_names = tuple(field_names)
        #: Device scalars of the fused health probe
        #: (``resilience/health.device_probe``) when the snapshot was
        #: taken with ``health=True``; resolved by :meth:`health_report`.
        self._health = health
        #: Device scalars of the fused numerics probe
        #: (``obs/numerics.device_numerics_probe``) when taken with
        #: ``numerics=True``; resolved by :meth:`numerics_report`.
        self._numerics = numerics
        #: Device scalars of the fused per-field integrity checksum
        #: (``resilience/integrity.device_field_checksum``) when taken
        #: with ``checksum=True``; re-derived host-side from the very
        #: bytes bound for the stores in :meth:`blocks` — a mismatch
        #: raises before anything is written.
        self._checksums = checksums

    def health_report(self):
        """Resolved :class:`~.resilience.health.HealthReport` for this
        snapshot, or None when no probe was requested. Blocks only on
        the probe's few scalars — the block D2H stays in flight."""
        if self._health is None:
            return None
        from .resilience.health import HealthReport

        finite, *minmax = self._health
        return HealthReport(
            bool(finite), *(float(x) for x in minmax),
            names=self.field_names,
        )

    def numerics_report(self):
        """Resolved :class:`~.obs.numerics.NumericsReport` for this
        snapshot, or None when no numerics probe was requested. Blocks
        only on the probe's scalars — the block D2H stays in flight,
        like :meth:`health_report`."""
        if self._numerics is None:
            return None
        from .obs import numerics as obs_numerics

        return obs_numerics.resolve_report(
            self._numerics, self.field_names
        )

    def has_checksums(self) -> bool:
        return self._checksums is not None

    def checksum_report(self):
        """Resolved per-field device checksums ``{field: int}``, or
        None when the probe was not requested — the values the store
        writers record in the integrity sidecar."""
        if self._checksums is None:
            return None
        return {
            n: int(np.asarray(c))
            for n, c in zip(self.field_names, self._checksums)
        }

    def _host_checksums(self, host_parts):
        """Per-field checksums recomputed from the resolved host
        arrays (full shard storage, pads included — the same elements
        the device reduction covered)."""
        from .resilience.integrity import host_field_checksum

        totals = [0] * len(self.field_names)
        for part in host_parts:
            for fi, arr in enumerate(part[2:]):
                totals[fi] = (
                    totals[fi] + host_field_checksum(arr)
                ) % (1 << 32)
        return totals

    def _verify_checksums(self, host_parts) -> None:
        """Compare the in-graph device-side checksums against the
        host-side recomputation over the landed bytes; a mismatch is
        data that changed somewhere on the device-copy → D2H path and
        raises before the poisoned step can reach any store."""
        from .resilience.integrity import CorruptionError

        host = self._host_checksums(host_parts)
        for name, dev, got in zip(
            self.field_names, self._checksums, host
        ):
            want = int(np.asarray(dev))
            if got != want:
                raise CorruptionError(
                    f"device-side field checksum mismatch: device "
                    f"{want:#010x}, host {got:#010x} — snapshot bytes "
                    "were silently corrupted in flight",
                    step=self.step, var=name,
                )

    def blocks(self):
        """Host blocks ``[(offsets, sizes, *field_blocks), ...]``,
        clipped to the true domain; blocks until the in-flight D2H
        transfers land (idempotent — resolved once, then cached).
        Snapshots taken with ``checksum=True`` verify the landed bytes
        against the fused device-side checksum first
        (:class:`~.resilience.integrity.CorruptionError` on mismatch —
        classified ``corruption`` by the supervisor).

        Returns a :class:`~.io.codec.BoundaryBlocks` list: the exact
        blocks in the list body (empty when this boundary skipped the
        exact copy — a lossy-output-only boundary), with the codec
        form, when captured, on its ``encoded`` attribute (coded
        fields as :class:`~.io.codec.EncodedField`, uncoded ones as
        plain arrays). Plain-list consumers are unaffected."""
        if self._blocks is None:
            from .io.codec import BoundaryBlocks, EncodedField

            exact = []
            if self._parts is not None:
                host_parts = [
                    (offsets, true) + tuple(np.asarray(d) for d in devs)
                    for offsets, true, *devs in self._parts
                ]
                if self._checksums is not None:
                    self._verify_checksums(host_parts)
                for offsets, true, *hosts in host_parts:
                    sl = tuple(slice(0, t) for t in true)
                    exact.append(
                        (offsets, true) + tuple(h[sl] for h in hosts)
                    )
            out = BoundaryBlocks(exact)
            if self._enc_parts is not None:
                enc_blocks = []
                for offsets, true, *devs in self._enc_parts:
                    sl = tuple(slice(0, t) for t in true)
                    entries = []
                    for i, d in enumerate(devs):
                        h = np.asarray(d)[sl]
                        meta = self._enc_meta.get(i)
                        if meta is None:
                            entries.append(h)
                        else:
                            bits, lo, hi, dt = meta
                            entries.append(EncodedField(
                                h, float(np.asarray(lo)),
                                float(np.asarray(hi)), bits, dt,
                            ))
                    enc_blocks.append((offsets, true) + tuple(entries))
                out.encoded = enc_blocks
            self._blocks = out
            self._parts = None  # release the device buffers
            self._enc_parts = None
        return self._blocks


class Simulation:
    """A running simulation of one registered model bound to a set of
    devices (Gray-Scott by default; ``[model]`` TOML table selects)."""

    #: Snapshot container class — the ensemble engine substitutes a
    #: member-aware one (``ensemble/engine.EnsembleFieldSnapshot``).
    snapshot_cls = FieldSnapshot
    #: True on :class:`~.ensemble.engine.EnsembleSimulation`: the step
    #: body runs under ``vmap`` over a leading member axis, which
    #: changes a few per-shard decisions (e.g. interpret-mode Pallas is
    #: not vmapped on CPU — the XLA fallback is).
    is_ensemble = False

    def __init__(
        self,
        settings: Settings,
        *,
        n_devices: Optional[int] = None,
        seed: int = 0,
        mesh_dims: Optional[Tuple[int, int, int]] = None,
    ):
        self.settings = settings
        #: Programmatic mesh-dims override (docs/RESHARD.md): the live
        #: in-job reshape path builds the TARGET simulation with an
        #: explicit factorization instead of mutating GS_TPU_MESH_DIMS
        #: (process-global env is thread-unsafe under the serve worker
        #: fleet). Wins over the env override in ``_make_domain``, and
        #: pins the mesh against auto-kernel mesh adoption below.
        self._mesh_dims_override = (
            tuple(int(d) for d in mesh_dims)
            if mesh_dims is not None else None
        )
        #: The registered model declaration this run integrates —
        #: fields, boundaries, params, reaction (``models/``).
        self.model = get_model(
            getattr(settings, "model", "grayscott") or "grayscott"
        )
        backend, self.kernel_language = config.load_backend_and_lang(settings)
        # Validate eagerly so an unavailable kernel language fails at
        # construction, not at first iterate (the reference defers all
        # dispatch errors to runtime fallbacks, public.jl:31-32, 77-78).
        validate_kernel_language(self.kernel_language)
        from .ops import kernelgen

        #: Why the kernel generator cannot lower this model's reaction
        #: into the fused Pallas kernel, or None when it can
        #: (docs/KERNELGEN.md). ONE statement of the model-side gate:
        #: explicit-Pallas validation, the Auto branch, and the
        #: autotuner shortlist below all consult this same reason.
        self._kernel_gate_reason = kernelgen.generation_gate_reason(
            self.model
        )
        if (self.kernel_language == "pallas"
                and self._kernel_gate_reason is not None):
            raise ValueError(
                f"kernel_language = 'Pallas' cannot be generated for "
                f"model {self.model.name!r}: {self._kernel_gate_reason} "
                f"(use 'Plain'/'XLA' or 'Auto')"
            )
        self.dtype = config.resolve_precision(settings)
        self._base_dtype = self.dtype
        #: Mixed-precision compute posture (docs/PRECISION.md,
        #: GS_COMPUTE_PRECISION / compute_precision key): "f32"
        #: (default — today's compute, bitwise), "bf16_f32acc" (fields,
        #: halo slabs, and stores held in bfloat16; Laplacian +
        #: reaction + Euler update accumulated in float32), or
        #: "equality" (pinned f32 AND a loud refusal of any lossy
        #: snapshot codec — the operator escape hatch asserting byte
        #: identity with a pre-posture build). Under an authorizing
        #: posture the measured autotuner may adopt the per-config
        #: winner across the precision axis below.
        self.compute_precision = config.resolve_compute_precision(
            settings
        )
        #: Accumulation dtype for the XLA reaction/Laplacian paths —
        #: equals the storage dtype except under ``bf16_f32acc``, where
        #: storage drops to bf16 and accumulation stays f32 (the Pallas
        #: kernel's own ``_compute_dtype`` applies the same rule
        #: in-kernel for bf16 fields).
        self.compute_dtype = self.dtype
        if self.compute_precision == "bf16_f32acc":
            self.dtype = jnp.bfloat16
            self.compute_dtype = jnp.float32
        #: Lossy snapshot codec posture (docs/PRECISION.md,
        #: GS_SNAPSHOT_BITS / snapshot_bits key): resolved here so a
        #: misconfiguration (unknown field, equality + codec) fails at
        #: construction and the posture joins the tuning-cache key.
        from .io.codec import resolve_snapshot_codec

        self.snapshot_codec = resolve_snapshot_codec(
            settings, self.model.field_names
        )

        # Persistent compilation cache (GS_COMPILE_CACHE / compile_cache
        # key; default on under supervision) — must be armed before the
        # first jit below. CPU is refused: this jax's CPU executable
        # serialization does not round-trip faithfully (measured: a
        # cache-loaded sharded runner corrupted 8 cells by O(1) and
        # tripped the NaN health guard on a supervised restart), and a
        # cache that can change a trajectory is worse than recompiling.
        # GS_COMPILE_CACHE_FORCE=1 overrides for cache-wiring tests.
        import os as _os

        self.compile_cache_dir = config.resolve_compile_cache(settings)
        if self.compile_cache_dir and backend == "cpu" and (
            env_raw("GS_COMPILE_CACHE_FORCE") != "1"
        ):
            if env_raw("GS_COMPILE_CACHE") or settings.compile_cache:
                # Explicitly requested — refuse loudly, not silently.
                import sys as _sys

                print(
                    "gray-scott: warning: persistent compilation cache "
                    "disabled on the CPU backend (executable "
                    "serialization does not round-trip bitwise on this "
                    "jax; set GS_COMPILE_CACHE_FORCE=1 to override)",
                    file=_sys.stderr,
                )
            self.compile_cache_dir = None
        if self.compile_cache_dir:
            _enable_compile_cache(self.compile_cache_dir)

        devices = select_devices(backend)
        if n_devices is not None:
            if n_devices > len(devices):
                raise ValueError(
                    f"requested {n_devices} devices, only {len(devices)} "
                    f"{backend} devices available"
                )
            devices = devices[:n_devices]
        elif len(devices) > 1:
            from .resilience.sdc import resolve_blocklist

            if resolve_blocklist():
                # Quarantine shrank the inventory to a count that may
                # not decompose this L (7 devices cannot split a
                # 32-cube): trim to the largest feasible prefix rather
                # than failing a restart the quarantine itself forced.
                for k in range(len(devices), 0, -1):
                    try:
                        self._make_domain(devices[:k])
                    except ValueError:
                        continue
                    devices = devices[:k]
                    break

        self.domain = self._make_domain(devices)
        self.sharded = self.domain.n_blocks > 1
        #: Split-phase halo exchange (GS_COMM_OVERLAP / comm_overlap
        #: key; docs/OVERLAP.md): "auto" = on for sharded runs. The
        #: trajectory is bitwise identical either way — overlap only
        #: removes the data dependency between the exchange and the
        #: interior compute so XLA can hide the ICI transfer.
        self.comm_overlap = (
            self.sharded
            and config.resolve_comm_overlap(settings) != "off"
        )
        #: True once a runner trace actually built a split-phase round
        #: (degenerate geometries fall back to the fused round even
        #: with overlap armed) — introspection for tests and stats.
        self.overlap_applied = False
        #: Communication-avoiding s-step exchange depth (GS_HALO_DEPTH
        #: / halo_depth key; docs/TEMPORAL.md): each exchange round
        #: transfers a (chain_depth x halo_depth)-deep ghost frame once
        #: and the chain advances that many steps on progressively
        #: shrinking valid regions — the XLA window chain in HBM, the
        #: generated Pallas chains as a deepened VMEM-resident
        #: in-kernel walk. 1 = today's one-exchange-per-round
        #: schedule (byte-identical); resolved "auto" stays 1 unless
        #: the measured autotuner adopts a deeper k below.
        self._halo_depth_pinned, self.halo_depth = (
            config.resolve_halo_depth(settings)
        )
        #: Set when a requested halo_depth > 1 was degraded because
        #: the Pallas chain's deepened working set is geometry- or
        #: VMEM-infeasible for this local block (the slab ledger's
        #: numbers ride along) — provenance for stats/tests.
        self.halo_depth_gate = None
        self._auto_fuse = None
        if self.kernel_language == "auto":
            # Resolve via the ICI cost model for the ACTUAL run config
            # (mesh dims, L, dtype, device generation) — see
            # parallel/icimodel.select_kernel for the policy. When the
            # operator did not force a mesh, the chain is projected at
            # its best swept factorization and the winning mesh/depth
            # are adopted. The decision is logged once on process 0 and
            # recorded in ``self.kernel_selection`` for the stats echo.
            import os as _os

            from .parallel import icimodel

            try:
                kind = devices[0].device_kind
            except Exception:
                kind = ""
            mesh_forced = (
                bool(env_str("GS_TPU_MESH_DIMS", ""))
                or self._mesh_dims_override is not None
            )
            if self._kernel_gate_reason is not None:
                # Generator feasibility gate (docs/KERNELGEN.md): the
                # fused kernel is generated from the model's reaction,
                # so Auto resolves to XLA only when generation is
                # infeasible — an EXPLICIT decision recorded in the
                # provenance with the generator's reason, and the
                # autotuner below searches XLA candidates only.
                self.kernel_language = "xla"
                self.kernel_selection = {
                    "reason": (
                        f"no Pallas kernel can be generated for model "
                        f"'{self.model.name}' "
                        f"({self._kernel_gate_reason}); XLA path"
                    ),
                    "kernel_gate": {
                        "model": self.model.name,
                        "generated": False,
                        "reason": self._kernel_gate_reason,
                    },
                }
            else:
                self.kernel_language, self.kernel_selection = (
                    icimodel.select_kernel(
                        self.domain.dims, settings.L, platform=backend,
                        device_kind=kind,
                        itemsize=np.dtype(self.dtype).itemsize,
                        fuse=default_fuse(),
                        n_fields=self.model.n_fields,
                        sweep_mesh=self.sharded and not mesh_forced,
                        # Auto's pick must reflect the comm this run
                        # will actually expose: the calibrated overlap
                        # when the split-phase exchange is armed,
                        # fully-exposed otherwise.
                        overlap="auto" if self.comm_overlap else 0.0,
                    )
                )
            if self.sharded:
                row = next(
                    (r for r in self.kernel_selection.get("rows", [])
                     if r["kernel"] == self.kernel_language), None,
                )
                if row is not None:
                    if (self.kernel_language == "pallas"
                            and not mesh_forced):
                        picked = tuple(
                            int(x) for x in row["mesh"].split(",")
                        )
                        if picked != self.domain.dims:
                            self.domain = CartDomain(
                                L=settings.L, dims=picked
                            )
                            self.kernel_selection["adopted_mesh"] = (
                                list(picked)
                            )
                    if not env_str("GS_FUSE", ""):
                        # Honor the winning row's swept depth for BOTH
                        # languages — the projection that justified the
                        # pick assumed it (still capped by the runner's
                        # own feasibility checks).
                        self._auto_fuse = int(row["fuse"])
            # Measured autotuner (tune/, docs/TUNING.md), consulted
            # AFTER the analytic decision and mesh adoption settled so
            # the tuning-cache key describes the mesh this run actually
            # uses. Modes: off/cached leave the analytic pick untouched
            # (cached applies a prior measured winner on a cache hit,
            # with zero measurement); quick/full measure the model's
            # shortlist on the real step function here, within
            # GS_AUTOTUNE_BUDGET_S.
            from . import tune

            link_gbps, links = icimodel.fabric_for(kind)
            decision = tune.autotune(
                settings,
                dims=self.domain.dims, L=settings.L, platform=backend,
                device_kind=kind, dtype=str(np.dtype(self.dtype)),
                noise=float(settings.noise),
                itemsize=int(np.dtype(self.dtype).itemsize),
                n_devices=n_devices, seed=seed,
                analytic_kernel=self.kernel_language,
                analytic_fuse=max(1, int(self._fuse_base())),
                comm_overlap=self.comm_overlap,
                overlap_toggle=(
                    self.sharded
                    and config.resolve_comm_overlap(settings) == "auto"
                ),
                link_gbps=link_gbps, links=links,
                # The model joins the tuning-cache key (a Brusselator
                # run must never adopt a Gray-Scott-measured winner)
                # and gates the candidate space to what this model's
                # kernels can actually run.
                model=self.model.name,
                n_fields=self.model.n_fields,
                pallas_allowed=(self._kernel_gate_reason is None),
                # Generator-contract version (schema v7): winners are
                # measured against THIS generator's kernels; 0 when no
                # Pallas kernel can be generated (XLA-only shortlist).
                kernel_generator=(
                    kernelgen.GENERATOR_VERSION
                    if self._kernel_gate_reason is None else 0
                ),
                # A pinned s-step depth joins the tuning-cache key and
                # is respected, not searched; "auto" (0) lets the
                # tuner widen the shortlist across k.
                halo_depth=(self.halo_depth if self._halo_depth_pinned
                            else 0),
                # The ADOPTED placement joins the key (schema v5,
                # docs/RESHARD.md): an elastically resumed run is a
                # different placement, and a winner tuned on mesh A
                # (or another process count) must never be applied on
                # mesh B.
                procs=jax.process_count(),
                # Precision + codec postures join the key (schema v6,
                # docs/PRECISION.md): a bf16-measured winner must never
                # be adopted by an f32 run (different HBM/halo bytes,
                # different schedule), and the bf16_f32acc posture arms
                # the precision CANDIDATE AXIS — the tuner measures
                # both precisions and the winner below may adopt
                # either, per config.
                compute_precision=self.compute_precision,
                snapshot_codec=self.snapshot_codec.posture(),
                **self._tune_extras(),
            )
            self.kernel_selection["autotune"] = decision.provenance
            if decision.provenance.get("source") in ("cache", "measured"):
                self.kernel_language = decision.kernel
                if decision.fuse is not None and not env_str(
                        "GS_FUSE", ""):
                    self._auto_fuse = decision.fuse
                if (decision.comm_overlap is not None and self.sharded
                        and config.resolve_comm_overlap(settings)
                        == "auto"):
                    self.comm_overlap = decision.comm_overlap
                if (decision.halo_depth is not None
                        and not self._halo_depth_pinned):
                    self.halo_depth = max(1, int(decision.halo_depth))
                if (decision.compute_precision is not None
                        and self.compute_precision == "bf16_f32acc"
                        and decision.compute_precision
                        in config.COMPUTE_PRECISIONS):
                    # Per-config precision adoption (docs/PRECISION.md):
                    # only an authorizing bf16_f32acc posture searches
                    # the precision axis, and the measured winner may
                    # keep bf16 or fall back to f32 for THIS config.
                    # Params and fields are built after this block, so
                    # the adopted dtype is what the run materializes.
                    self.compute_precision = decision.compute_precision
                    if self.compute_precision == "bf16_f32acc":
                        self.dtype = jnp.bfloat16
                        self.compute_dtype = jnp.float32
                    else:
                        self.dtype = self._base_dtype
                        self.compute_dtype = self._base_dtype
                if decision.bx is not None and not env_str(
                        "GS_BX", ""):
                    # GS_BX is read at kernel-trace time; an env pin is
                    # the one channel that reaches it. Process-wide by
                    # nature — recorded in the provenance, and an
                    # operator's own GS_BX always wins.
                    _os.environ["GS_BX"] = str(decision.bx)
                    decision.provenance["bx_env_pinned"] = True
                self._apply_tune_extras(decision)
            if _is_primary():
                import sys as _sys

                _prov = decision.provenance
                print(
                    "gray-scott: kernel_language=Auto resolved to "
                    f"{self.kernel_language!r} "
                    f"({self.kernel_selection.get('reason', '')}; "
                    f"autotune {_prov['mode']}, "
                    f"{_prov.get('source', 'analytic')} pick)",
                    file=_sys.stderr,
                )
        else:
            self.kernel_selection = None
        if isinstance(self.kernel_selection, dict):
            # Adopted-precision provenance (docs/PRECISION.md): every
            # stats/bench consumer of kernel_selection sees which
            # posture the run actually materialized next to the
            # kernel/fuse decision it rode in on.
            self.kernel_selection["compute_precision"] = (
                self.compute_precision
            )
            self.kernel_selection["snapshot_codec"] = (
                self.snapshot_codec.posture()
            )
            if self.kernel_language == "pallas":
                # Generated-kernel provenance (docs/KERNELGEN.md): a
                # resolved Pallas pick is a generator product, and
                # artifacts must be able to tell generator eras apart
                # (gs_report --check validates these attrs).
                self.kernel_selection["generated"] = True
                self.kernel_selection["generator_version"] = (
                    kernelgen.GENERATOR_VERSION
                )
        if (self.kernel_language == "pallas" and self.halo_depth > 1
                and self.sharded):
            # The generated Pallas chains run a REAL s-step schedule
            # (docs/TEMPORAL.md): one (fuse x halo_depth)-deep exchange
            # round feeds fuse*halo_depth in-kernel Euler steps over
            # progressively shrinking VMEM-resident valid regions — no
            # HBM round-trip between the inner steps. Feasibility is
            # the chain dispatch geometry composed with the VMEM slab
            # ledger (``pallas_stencil.max_feasible_chain_depth``);
            # infeasible k degrades to the deepest feasible k' LOUDLY,
            # with the ledger numbers in the provenance, so a config
            # written against the old blanket degrade fails
            # loud-and-explained instead of silently changing schedule.
            from .ops import pallas_stencil as _ps

            local = tuple(int(x) for x in self.domain.local_shape)
            dims = self.domain.dims
            itemsize = int(jnp.dtype(self.dtype).itemsize)
            sublane = 16 if self.dtype == jnp.bfloat16 else 8
            mid = _ps.mid_itemsize_for(self.dtype)
            nf = self.model.n_fields
            path = ("x-chain" if dims[1] == 1 and dims[2] == 1
                    else "xy-chain")

            def _cap(depth):
                return _ps.max_feasible_chain_depth(
                    local, dims, itemsize, depth, sublane,
                    mid_itemsize=mid, n_fields=nf,
                )

            d = max(1, _cap(self._fuse_base()))
            applied = next(
                (k for k in range(self.halo_depth, 0, -1)
                 if _cap(d * k) == d * k), 1,
            )
            if applied < self.halo_depth:
                self.halo_depth_gate = {
                    "requested": self.halo_depth,
                    "applied": applied,
                    "kind": "geometry-infeasible",
                    "reason": (
                        f"halo_depth={self.halo_depth} needs a "
                        f"{d * self.halo_depth}-deep in-kernel chain "
                        f"(fuse base {d} x halo_depth) on the Pallas "
                        f"{path}, but local block {local} "
                        f"({itemsize}-byte fields x {nf}) serves at "
                        f"most depth {d * applied} under the chain "
                        "geometry caps and the "
                        f"{_ps._vmem_budget()}-byte VMEM slab budget; "
                        f"running halo_depth={applied}"
                    ),
                    "geometry": {
                        "path": path,
                        "local_shape": list(local),
                        "fuse_base": int(d),
                        "requested_depth": int(d * self.halo_depth),
                        "feasible_depth": int(d * applied),
                        "vmem_budget_bytes": int(_ps._vmem_budget()),
                        "itemsize": itemsize,
                        "n_fields": int(nf),
                    },
                }
                if isinstance(self.kernel_selection, dict):
                    self.kernel_selection["halo_depth_gate"] = (
                        self.halo_depth_gate
                    )
                if _is_primary():
                    import sys as _sys

                    print(
                        "gray-scott: warning: "
                        + self.halo_depth_gate["reason"],
                        file=_sys.stderr,
                    )
                self.halo_depth = applied
        if (self.sharded and self.halo_depth > 1
                and self.kernel_language != "pallas"):
            # The s-step frame is exchanged in ONE single-hop round:
            # every slab must consist of owned cells, so the effective
            # exchange depth (chain depth x k) cannot exceed the local
            # block's smallest extent. Refuse loudly at construction —
            # a silently-capped k would misreport the schedule every
            # artifact records.
            d = max(1, min(self._fuse_base(),
                           min(self.domain.local_shape)))
            deep = d * self.halo_depth
            cap = min(self.domain.local_shape)
            if deep > cap:
                raise config.SettingsError(
                    f"halo_depth={self.halo_depth} needs a {deep}-deep "
                    f"ghost exchange (chain depth {d} x halo_depth), "
                    f"but the local block {self.domain.local_shape} "
                    f"supports at most {cap}; lower halo_depth/GS_FUSE "
                    "or use fewer devices per axis"
                )
        self.params = self._make_params()
        self.use_noise = self._resolve_use_noise()
        self.base_key = self._make_base_key(seed)
        self.step = 0
        #: Elastic-restore provenance (docs/RESHARD.md): set by
        #: ``reshard.restore.restore_run`` to the plan's describe()
        #: when this run resumed a checkpoint written on a DIFFERENT
        #: layout; None for fresh runs and same-shape resumes. Echoed
        #: into the RunStats config by the driver.
        self.reshard = None
        #: Executable analytics (``obs/xstats.py``): armed by GS_XSTATS
        #: / the ``xstats`` key, or implicitly whenever the persistent
        #: compile cache is — its hit/miss story must be observable.
        #: Each instrumented runner compile appends its record here;
        #: the driver merges the list into the RunStats ``executables``
        #: section.
        from .obs import xstats as obs_xstats

        self.xstats_enabled = (
            obs_xstats.resolve_xstats(settings)
            or bool(self.compile_cache_dir)
        )
        self.executables: list = []
        self._runners: Dict[int, object] = {}
        self._snapshot_fns: Dict[Tuple[bool, bool], object] = {}
        #: Non-donating replay twins of the runners, keyed by
        #: (nsteps, device permutation) — the SDC screening seam
        #: (resilience/sdc.py). Separate cache: a donating runner would
        #: consume the retained anchor buffers it must preserve.
        self._replay_fns: Dict[tuple, tuple] = {}

        self._build_mesh(devices, backend)
        #: The model's field arrays, declaration order (a tuple — the
        #: state the runner advances; ``u``/``v`` alias fields 0/1).
        self.fields = self._init_fields()

    # ----------------------------------------------------- field aliases
    # Two-field models (Gray-Scott, Brusselator, FHN) read naturally as
    # (u, v); the canonical state is ``self.fields``.

    @property
    def u(self):
        return self.fields[0]

    @u.setter
    def u(self, value):
        self.fields = (value,) + tuple(self.fields[1:])

    @property
    def v(self):
        return self.fields[1]

    @v.setter
    def v(self, value):
        self.fields = (self.fields[0], value) + tuple(self.fields[2:])

    def _field_index(self, field) -> int:
        """Resolve a field reference — model field name, the legacy
        ``"u"``/``"v"`` aliases, or an integer index."""
        if isinstance(field, int):
            return field
        if field in self.model.field_names:
            return self.model.field_names.index(field)
        alias = {"u": 0, "v": 1}.get(field)
        if alias is not None and alias < self.model.n_fields:
            return alias
        raise ValueError(
            f"unknown field {field!r} for model {self.model.name!r} "
            f"(fields: {', '.join(self.model.field_names)})"
        )

    # ------------------------------------------------- construction hooks
    # Overridden by ensemble/engine.EnsembleSimulation, which threads a
    # leading member axis through every one of these while the step
    # body, halo exchange, autotune and I/O plumbing stay shared.

    def _make_domain(self, devices) -> CartDomain:
        """Spatial decomposition over the selected devices."""
        return CartDomain.create(
            len(devices), self.settings.L,
            dims=self._mesh_dims_override,
        )

    def _make_params(self):
        """Typed params pytree, routed through the model declaration
        (``[model]`` table > legacy flat keys > declared defaults).
        Params live at the COMPUTE dtype: identical to the storage
        dtype except under ``bf16_f32acc``, where the f32 params feed
        the f32 accumulation directly (docs/PRECISION.md)."""
        return self.model.make_params(self.settings, self.compute_dtype)

    def _resolve_use_noise(self) -> bool:
        return self.settings.noise != 0.0

    def _make_base_key(self, seed: int):
        return jax.random.PRNGKey(seed)

    def _tune_extras(self) -> dict:
        """Extra kwargs for ``tune.autotune`` (ensemble size etc.)."""
        return {}

    def _apply_tune_extras(self, decision) -> None:
        """Apply decision fields beyond kernel/fuse/overlap/bx."""

    def _probe_fn(self):
        """The device-side health probe fused into the snapshot copy."""
        from .resilience.health import device_probe

        return device_probe

    def _numerics_probe_fn(self):
        """The device-side numerics probe (per-field min/max/mean/L2/
        finite reductions) fused into the snapshot copy — or run alone
        per round under ``GS_NUMERICS=every_round``."""
        from .obs.numerics import device_numerics_probe

        return device_numerics_probe

    def _build_mesh(self, devices, backend: str) -> None:
        """Construct ``self.mesh`` / ``self.field_sharding`` (or pin
        ``self.device`` for the single-device case)."""
        if self.sharded:
            mesh_devices = mesh_for_topology(
                self.domain.dims, devices, backend
            )
            self.mesh = Mesh(mesh_devices, AXIS_NAMES)
            self.field_sharding = NamedSharding(self.mesh, P(*AXIS_NAMES))
        else:
            self.mesh = None
            self.field_sharding = None
            self.device = devices[0]

    def _fuse_base(self) -> int:
        """Chain/temporal-blocking depth before the runner's own caps:
        the Auto-swept depth when Auto adopted one (GS_FUSE unset),
        else ``default_fuse()`` (GS_FUSE or the platform default)."""
        if self._auto_fuse is not None:
            return self._auto_fuse
        return default_fuse()

    # ------------------------------------------------------------------ init

    def _init_fields(self) -> Tuple[jax.Array, ...]:
        """Sharded field construction: each device shard is built locally
        for its block (multi-host ready), mirroring the reference's
        per-rank ``init_fields`` (``Simulation_CPU.jl:14-72``). The
        initial condition is the model's declared ``init``."""
        L, dtype = self.settings.L, self.dtype
        if not self.sharded:
            return tuple(
                jax.device_put(f, self.device)
                for f in self.model.init(L, dtype)
            )

        dom = self.domain
        # Non-divisible L stores a padded grid (equal blocks, pad cells
        # at global coords >= L held at the boundary value — exactly
        # what the model's init produces for out-of-seed cells).
        gshape = dom.storage_shape

        def make(field_idx: int):
            def cb(index):
                offsets = tuple(s.start or 0 for s in index)
                sizes = tuple(
                    (s.stop or g) - (s.start or 0)
                    for s, g in zip(index, gshape)
                )
                return self.model.init(
                    L, dtype, offsets=offsets, sizes=sizes
                )[field_idx]

            return jax.make_array_from_callback(
                gshape, self.field_sharding, cb
            )

        return tuple(make(i) for i in range(self.model.n_fields))

    # ---------------------------------------------------------------- runner

    def _local_run(self, *args, nsteps: int):
        """``nsteps`` fused steps on one (local) block. Called directly on a
        single device, or per-shard under ``shard_map``.

        ``args`` is the model's field tuple (declaration order) followed
        by ``(base_key, step0, params)`` — the variadic field prefix is
        what makes the runner model-generic (one field for heat, two for
        Gray-Scott/Brusselator/FHN, n for anything registered).

        Noise everywhere comes from the position-keyed stream
        (``ops/noise.py``): one shared key, absolute step index, global
        cell coordinates — so the trajectory is invariant under step
        chunking, shard layout, and temporal fusion.
        """
        *fields, base_key, step0, params = args
        fields = tuple(fields)
        model = self.model
        use_noise = self.use_noise
        sharded = self.sharded
        dims = self.domain.dims
        L = self.settings.L
        boundaries = model.boundaries
        dtype = fields[0].dtype
        # bf16_f32acc accumulation dtype (docs/PRECISION.md): None-like
        # (equal to the storage dtype) on every other posture, so the
        # default paths trace the historical graph bit for bit.
        cdt = self.compute_dtype
        key_i32 = lax.bitcast_convert_type(base_key, jnp.int32)

        if sharded:
            block = self.domain.local_shape
            offs = jnp.stack(
                [
                    lax.axis_index(ax) * jnp.int32(b)
                    for ax, b in zip(AXIS_NAMES, block)
                ]
            )
        else:
            offs = jnp.zeros((3,), jnp.int32)

        padded = sharded and self.domain.padded
        overlap_on = self.comm_overlap

        def pin_block(fields):
            """Re-pin each block's pad cells (global coords >= L) to the
            field's boundary value — required after every chain round
            with non-divisible L: the chain's final stage writes them
            unpinned, and the next round's stencil reads them as the
            frozen ghost shell."""
            fields = tuple(fields)
            if not padded:
                return fields
            return tuple(
                temporal.pin_out_of_domain(f, bv, offs, L)
                for f, bv in zip(fields, boundaries)
            )

        def unit_noise(step_idx, offsets, shape):
            return noise_ops.uniform_pm1_block(
                key_i32, step_idx, offsets, shape, L, dtype
            )

        def run_chain_rounds(chain, fuse, fields):
            """Drive ``nsteps`` as full-depth chain rounds plus a
            shallower remainder chain — the shared loop of all three
            temporal-blocking paths (1D x-chain, 3D Pallas chain,
            sharded XLA chain). ``chain(fields, step, depth)`` maps the
            field tuple through one exchange-plus-depth-steps round."""

            def chain_body(i, carry):
                return chain(carry, step0 + fuse * i, fuse)

            rounds, rem = divmod(nsteps, fuse)
            fields = lax.fori_loop(0, rounds, chain_body, fields)
            if rem:
                fields = chain(fields, step0 + fuse * rounds, rem)
            return fields

        if self.kernel_language == "pallas":
            # The fused kernel is GENERATED from the model declaration
            # (ops/kernelgen): the gate in __init__ guarantees the
            # model's reaction trace-inlines, and the spec is the jit
            # static argument every launch below shares.
            from .ops import kernelgen, pallas_stencil

            spec = kernelgen.get_spec(model)
            n_f = spec.n_fields

            def step_seeds(step_idx):
                return jnp.stack(
                    [key_i32[0], key_i32[1], jnp.asarray(step_idx, jnp.int32)]
                )

            # Concurrent interpret-mode kernels deadlock under shard_map
            # (global interpreter state) — sharded CPU runs take the XLA
            # fallback inside fused_step; real TPU runs the fused kernel.
            # Ensemble bodies run under vmap, where interpret mode is a
            # liability too (per-member re-interpretation): the XLA
            # fallback is the same elementwise program, bitwise.
            allow_interpret = not sharded and not self.is_ensemble

            def kernel_step(fields_k, step_idx, faces):
                return pallas_stencil.fused_step(
                    fields_k, params, step_seeds(step_idx), faces,
                    spec=spec, use_noise=use_noise,
                    allow_interpret=allow_interpret,
                    fuse=1, offsets=offs, row=L,
                )

            if sharded and dims[1] == 1 and dims[2] == 1:
                # 1D x-sharded mesh (GS_TPU_MESH_DIMS=n,1,1): the ONLY
                # shard boundaries are x faces — the kernel's natural
                # element (leading-dim slabs, no lane-alignment issue) —
                # so the in-kernel fused chain runs ACROSS the shard
                # boundary: one 2-ppermute exchange of k-wide x slabs
                # feeds one fuse=k kernel launch per chain. Unlike the
                # general 3D chain below (single-step kernel stages +
                # XLA ghost advance), every sharded step here runs at
                # the fused single-chip schedule — the fastest
                # pod-slice layout for the Pallas language (<=16 chips;
                # at higher counts the 1D surface/volume ratio loses to
                # 3D, see BASELINE.md's ICI projection).
                fuse = min(
                    self._fuse_base(), max(nsteps, 1),
                    self.domain.local_shape[0],
                )
                if self.halo_depth > 1:
                    # Communication-avoiding s-step schedule
                    # (docs/TEMPORAL.md): the exchange round carries a
                    # (fuse x halo_depth)-deep slab pair and the
                    # in-kernel chain walks all of it before the next
                    # exchange — the EXACT program a halo_depth=1
                    # chain at depth fuse*halo_depth lowers to, so
                    # k at depth d is bitwise identical to k=1 at
                    # depth k*d. Feasibility was gated at
                    # construction; nsteps still bounds the final
                    # round, and the VMEM cap below re-checks the
                    # realized depth.
                    fuse = min(
                        fuse * self.halo_depth, max(nsteps, 1),
                        self.domain.local_shape[0],
                    )
                # The exchange width must match a chain depth the
                # Mosaic kernel can actually serve — an infeasible
                # depth would silently run every step on the XLA
                # fallback (e.g. the v5p-16 pod shape 64x512x512 f32
                # fits fuse=3, not 5). Depth 1 falls through to the
                # 12-face single-step exchange below.
                feasible = pallas_stencil.max_feasible_fuse(
                    *self.domain.local_shape,
                    jnp.dtype(self.dtype).itemsize, fuse,
                    mid_itemsize=pallas_stencil.mid_itemsize_for(
                        self.dtype
                    ),
                    n_fields=n_f,
                )
                if feasible < fuse:
                    capped = max(feasible, 1)
                    pallas_stencil._warn_once(
                        f"x-chain depth capped at {capped} "
                        f"(fuse={fuse} does not fit VMEM for local grid "
                        f"{self.domain.local_shape})"
                    )
                    fuse = capped

                def chain(fields_c, step, depth):
                    if depth == 1:
                        faces_full = halo.exchange_faces(
                            fields_c, boundaries, AXIS_NAMES, dims
                        )
                        return pin_block(
                            kernel_step(fields_c, step, faces_full)
                        )
                    pairs = halo.exchange_x_slabs(
                        fields_c, boundaries, AXIS_NAMES[0], dims[0], depth
                    )
                    if overlap_on and fields_c[0].shape[0] >= 2 * depth:
                        # Split-phase round (docs/OVERLAP.md): the same
                        # 2-ppermute slab exchange is issued first, but
                        # the kernel chains on frozen-constant x faces
                        # — no data dependency on the collectives — and
                        # the arrived slabs feed only the two k-thick x
                        # bands stitched afterwards. Each band is the
                        # SAME chain program (the x-chain XLA reference,
                        # ``_xla_xchain_fallback``) on a k-plane body
                        # whose x faces are the arrived slab and the
                        # adjacent owned planes — same structure, same
                        # per-cell op order, so XLA's codegen cannot
                        # drift a ulp between the fused and split
                        # lowerings. Blocks shallower than 2k have no
                        # interior to hide behind and take the fused
                        # round below.
                        self.overlap_applied = True
                        k = depth
                        nx = fields_c[0].shape[0]
                        faces_z = tuple(
                            f for fs in halo.frozen_slabs(
                                fields_c, boundaries, 0, k
                            ) for f in fs
                        )
                        interior = list(pallas_stencil.fused_step(
                            fields_c, params, step_seeds(step), faces_z,
                            spec=spec, use_noise=use_noise,
                            allow_interpret=allow_interpret,
                            fuse=k, offsets=offs, row=L,
                        ))
                        # Band faces stay field-major (lo, hi): the low
                        # band reads the arrived lo slab and the owned
                        # planes above it, the high band mirrors that.
                        jobs = (
                            (tuple(f[:k] for f in fields_c),
                             tuple(x for f, (lo, _hi) in zip(fields_c,
                                                             pairs)
                                   for x in (lo, f[k:2 * k])),
                             0),
                            (tuple(f[nx - k:] for f in fields_c),
                             tuple(x for f, (_lo, hi) in zip(fields_c,
                                                             pairs)
                                   for x in (f[nx - 2 * k:nx - k], hi)),
                             nx - k),
                        )
                        for body_f, faces_b, d_x in jobs:
                            band = pallas_stencil._xla_xchain_fallback(
                                body_f, params, step_seeds(step),
                                faces_b, spec=spec, fuse=k,
                                use_noise=use_noise,
                                offsets=jnp.stack([
                                    offs[0] + d_x, offs[1], offs[2],
                                ]),
                                row=L,
                            )
                            interior = [
                                lax.dynamic_update_slice(
                                    fi, bi, (d_x, 0, 0)
                                )
                                for fi, bi in zip(interior, band)
                            ]
                        return pin_block(tuple(interior))
                    faces_x = tuple(f for pr in pairs for f in pr)
                    return pin_block(pallas_stencil.fused_step(
                        fields_c, params, step_seeds(step), faces_x,
                        spec=spec, use_noise=use_noise,
                        allow_interpret=allow_interpret,
                        fuse=depth, offsets=offs, row=L,
                    ))

                return run_chain_rounds(chain, fuse, fields)

            if sharded:
                # xy-chain (+ z-band correction when z is sharded): the
                # in-kernel k-deep chain crosses x AND y shard
                # boundaries (y is the cheap sublane dim), so every
                # sharded stage runs at the fused single-chip schedule;
                # only sharded-z sides pay a thin XLA band recompute
                # (``parallel/temporal.xy_chain``). One exchange round
                # per k steps, like the XLA language's chain.
                block = self.domain.local_shape
                cap = [block[0], block[1]]
                if dims[2] > 1:
                    # z-band windows need local nz >= 2*depth.
                    cap.append(block[2] // 2)
                # Floor of 1: a cap of 0 (local nz == 1 on a z-sharded
                # mesh) must degrade to the depth-1 12-face path, not
                # divide by zero in run_chain_rounds.
                fuse = max(1, min(self._fuse_base(), max(nsteps, 1), *cap))
                if self.halo_depth > 1:
                    # s-step exchange (docs/TEMPORAL.md): deepen the
                    # in-kernel chain to fuse*halo_depth — one
                    # (fuse x halo_depth)-deep ``halo_pad_wide`` frame
                    # per round, same program as halo_depth=1 at the
                    # product depth, so the round count (and the
                    # collective count with it) drops by halo_depth.
                    fuse = max(1, min(
                        fuse * self.halo_depth, max(nsteps, 1), *cap,
                    ))
                sublane = 16 if self.dtype == jnp.bfloat16 else 8
                feasible = pallas_stencil.max_feasible_fuse_ypad(
                    *block, jnp.dtype(self.dtype).itemsize, fuse, sublane,
                    mid_itemsize=pallas_stencil.mid_itemsize_for(
                        self.dtype
                    ),
                    n_fields=n_f,
                )
                if feasible < fuse:
                    pallas_stencil._warn_once(
                        f"xy-chain depth capped at {max(feasible, 1)} "
                        f"(fuse={fuse} does not fit VMEM for local grid "
                        f"{block} with its y halo)"
                    )
                    fuse = max(feasible, 1)

                def chain(fields_c, step, depth):
                    if depth == 1:
                        faces_full = halo.exchange_faces(
                            fields_c, boundaries, AXIS_NAMES, dims
                        )
                        return pin_block(
                            kernel_step(fields_c, step, faces_full)
                        )

                    def chain_kernel(fields_p, faces_p, stp, offs_p):
                        return pallas_stencil.fused_step(
                            fields_p, params, step_seeds(stp), faces_p,
                            spec=spec, use_noise=use_noise,
                            allow_interpret=allow_interpret,
                            fuse=depth, offsets=offs_p, row=L,
                        )

                    def band_kernel(fields_b, faces_b, stp, offs_b):
                        # The x-chain XLA reference — the SAME program
                        # structure as the fused kernel's own fallback,
                        # which keeps recomputed bands bitwise equal.
                        return pallas_stencil._xla_xchain_fallback(
                            fields_b, params, step_seeds(stp), faces_b,
                            spec=spec, fuse=depth, use_noise=use_noise,
                            offsets=offs_b, row=L,
                        )

                    ov = overlap_on and temporal.xy_overlap_feasible(
                        block, dims, depth
                    )
                    if ov:
                        self.overlap_applied = True
                    return pin_block(temporal.xy_chain(
                        fields_c, params, model, depth=depth, step=step,
                        offs=offs, chain_kernel=chain_kernel,
                        use_noise=use_noise, unit_noise=unit_noise,
                        row=L, axis_names=AXIS_NAMES, axis_sizes=dims,
                        boundaries=boundaries, sublane=sublane,
                        overlap=ov, band_kernel=band_kernel,
                    ))

                return run_chain_rounds(chain, fuse, fields)

            # Single block: in-kernel temporal blocking (``fuse`` steps
            # per HBM pass — the slab pipeline is DMA-envelope-bound on
            # the v5e, so per-step time scales ~1/fuse); the noise stream
            # is keyed on absolute (step, cell), so fusion/chunking does
            # not change the trajectory.
            fuse = min(self._fuse_base(), max(nsteps, 1))

            def body(i, carry):
                return pallas_stencil.fused_step(
                    carry, params, step_seeds(step0 + fuse * i), None,
                    spec=spec, use_noise=use_noise,
                    allow_interpret=allow_interpret,
                    fuse=fuse, offsets=offs, row=L,
                )

            rounds, rem = divmod(nsteps, fuse)
            fields = lax.fori_loop(0, rounds, body, fields)
            if rem:
                fields = pallas_stencil.fused_step(
                    fields, params, step_seeds(step0 + fuse * rounds),
                    None, spec=spec, use_noise=use_noise,
                    allow_interpret=allow_interpret,
                    fuse=rem, offsets=offs, row=L,
                )
            return tuple(fields)

        # ---- XLA kernel path ----

        def single_step(i, carry):
            if sharded:
                fields_pad = halo.halo_pad(
                    carry, boundaries, AXIS_NAMES, dims
                )
            else:
                fields_pad = tuple(
                    stencil.pad_with_boundary(f, bv)
                    for f, bv in zip(carry, boundaries)
                )
            if use_noise:
                nz = params.noise * unit_noise(
                    step0 + i, offs, carry[0].shape
                )
            else:
                nz = jnp.asarray(0.0, dtype)
            return pin_block(
                stencil.reaction_update(fields_pad, nz, params, model,
                                        compute_dtype=cdt)
            )

        # Split-phase gate for the XLA window mode: only band windows
        # thin along the LEADING (x) axis are codegen-stable on XLA:CPU
        # — shrinking a trailing extent is exactly the shape change its
        # FP-contraction decisions key on (measured: x-thin frame
        # windows reproduce the full window chain bitwise through k=4;
        # y- and z-thin windows drift 1 ulp at some shapes). So the
        # window mode overlaps 1D x-sharded meshes; multi-axis meshes
        # take the fused round here and get their overlap through the
        # Pallas chains, whose band recomputes share the kernel
        # fallback's structure (and whose z bands are identical in both
        # modes). docs/OVERLAP.md "Bitwise-identity guarantee".
        overlap_xla = overlap_on and dims[1] == 1 and dims[2] == 1

        if not sharded or (nsteps < 2 and not overlap_xla):
            return lax.fori_loop(0, nsteps, single_step, fields)

        # Sharded temporal blocking: ONE width-k halo exchange feeds k
        # steps — stage s recomputes step n+1+s on a window extending
        # (k-1-s) cells beyond the block (neighbor-owned ring cells
        # reproduce the owner's values bitwise: same inputs via the
        # corner-propagated halo, same position-keyed noise), and the
        # shrinking ring doubles as the next stage's ghost shell. Cuts
        # the exchange count per step by k (the cost
        # ``communication.jl:138-199`` pays every step). The chain body
        # is ``temporal.window_chain`` on the exchanged frame — the same
        # shrinking-window program the band recomputes use, which is
        # what makes the split-phase stitch bitwise.
        fuse = min(self._fuse_base(), nsteps, min(self.domain.local_shape))
        if self.halo_depth > 1:
            # Communication-avoiding s-step schedule (docs/TEMPORAL.md):
            # one exchange round carries a (fuse x halo_depth)-deep
            # frame and the shrinking-window chain advances all of it
            # before the next exchange — the same program shape a
            # (fuse x halo_depth)-deep chain round lowers to, so
            # halo_depth=k at depth d is bitwise identical to
            # halo_depth=1 at depth k*d. Geometry was validated at
            # construction; nsteps still bounds the final round.
            fuse = min(fuse * self.halo_depth, nsteps,
                       min(self.domain.local_shape))

        def chain(fields_c, step, depth):
            """``depth`` steps from one ``depth``-wide exchange."""
            if overlap_xla:
                # Split-phase round (docs/OVERLAP.md): issue the same
                # corner-propagated exchange with no consumer on the
                # interior chain's dataflow path, run the chain on a
                # frozen-constant frame, then stitch the k-thick
                # sharded-face bands recomputed from the arrived frame
                # — bitwise the same values.
                self.overlap_applied = True
                pending = halo.start_exchange(
                    fields_c, boundaries, AXIS_NAMES, dims, depth
                )
                frozen = halo.frozen_frame(fields_c, boundaries, depth)
                fields_i = temporal.window_chain(
                    frozen, params, model, depth=depth, step=step,
                    origin=offs - depth, row=L, use_noise=use_noise,
                    unit_noise=unit_noise, boundaries=boundaries,
                    final_pin=padded, compute_dtype=cdt,
                )
                fields_w = pending.finish()
                return temporal.stitch_bands_from_frame(
                    fields_i, fields_w, params, model, depth=depth,
                    step=step, offs=offs, row=L, axis_sizes=dims,
                    use_noise=use_noise, unit_noise=unit_noise,
                    boundaries=boundaries, compute_dtype=cdt,
                )
            fields_w = halo.halo_pad_wide(
                fields_c, boundaries, AXIS_NAMES, dims, depth
            )
            # Global-coordinate pinning per stage: ring cells outside
            # the domain AND, for non-divisible L, pad cells inside the
            # block — both must read back as the frozen ghost. The
            # final stage (m_out == 0) has no ring, so divisible-L runs
            # skip its provably-all-true mask (final_pin).
            return temporal.window_chain(
                fields_w, params, model, depth=depth, step=step,
                origin=offs - depth, row=L, use_noise=use_noise,
                unit_noise=unit_noise, boundaries=boundaries,
                final_pin=padded, compute_dtype=cdt,
            )

        return run_chain_rounds(chain, fuse, fields)

    def _make_step_fn(self, nsteps: int, mesh=None):
        """The un-jitted ``nsteps``-step advance — one construction
        shared by :meth:`_runner` (jitted WITH field donation, the live
        path) and :meth:`replay_fields` (jitted without donation,
        optionally on a permuted ``mesh`` — the SDC screening path), so
        replay runs the very same traced program as the trajectory it
        checks."""
        local = partial(self._local_run, nsteps=nsteps)
        nf = self.model.n_fields
        if self.sharded:
            spec = P(*AXIS_NAMES)
            rep = P()
            return shard_map(
                local,
                mesh=self.mesh if mesh is None else mesh,
                in_specs=(spec,) * nf + (rep, rep, rep),
                out_specs=(spec,) * nf,
                # pallas_call outputs carry no varying-mesh-axes metadata;
                # skip the vma/replication check (shardings are fully
                # explicit here; flag spelling is version-dependent).
                **{_SHARD_MAP_CHECK_FLAG: False},
            )
        return local

    def _runner(self, nsteps: int):
        """Compiled ``nsteps``-step advance, cached per nsteps."""
        fn = self._runners.get(nsteps)
        if fn is not None:
            return fn

        nf = self.model.n_fields
        fn = jax.jit(
            self._make_step_fn(nsteps), donate_argnums=tuple(range(nf))
        )
        return self._register_runner(nsteps, fn)

    def _register_runner(self, nsteps: int, fn):
        """Cache a freshly-built runner — under executable analytics
        (``obs/xstats.py``) it is AOT-compiled here (the same
        ``lower().compile()`` path :meth:`compile_chunk` uses — the
        identical program, so trajectories are unchanged) with compile
        wall time, cost/memory analysis, collective counts, and the
        persistent-cache outcome captured per executable. Off means
        one boolean check; the jit wrapper is stored untouched."""
        if self.xstats_enabled:
            from .obs import xstats as obs_xstats

            fn = obs_xstats.instrument_compile(self, fn, nsteps)
        self._runners[nsteps] = fn
        return fn

    def compile_chunk(self, nsteps: int) -> None:
        """Ahead-of-time compile the ``nsteps`` runner without executing
        a single step.

        Launch support: pod jobs can pay the (20-60 s) compile before
        opening streams/checkpoints rather than inside the first
        ``iterate`` call, and a driver can compile-check a configuration
        without advancing the simulation. The compiled executable
        replaces the cached runner (same call signature), so ``iterate``
        uses it directly — compiling here and re-tracing on call would
        defeat the point. Note the first *execution* still pays a one-off
        device program-load (~tens of ms).
        """
        runner = self._runner(nsteps)
        if not hasattr(runner, "lower"):
            return  # already AOT-compiled
        compiled = runner.lower(
            *self.fields, self.base_key, jnp.int32(self.step), self.params
        ).compile()
        self._runners[nsteps] = compiled

    # ------------------------------------------------------------- replay
    # The redundant-compute seam behind resilience/sdc.py: re-run rounds
    # from a retained anchor WITHOUT donating or advancing the live
    # state, optionally on a permuted device placement (shadow mode).

    def retain_fields(self) -> tuple:
        """Fresh non-donated device copies of the live fields — the SDC
        screener's boundary anchor. Same +0-copy idiom as
        :meth:`snapshot_async` (no D2H, no aliasing with the donated
        runner buffers), so retaining is bitwise-transparent to the
        trajectory."""
        return self._copy_fields(self.fields)

    def _copy_fields(self, fields) -> tuple:
        """Fresh non-donated device copies of a field tuple (sharding
        preserved)."""
        fn = getattr(self, "_retain_fn", None)
        if fn is None:

            def copy(*fields):
                return tuple(f + jnp.zeros((), f.dtype) for f in fields)

            fn = self._retain_fn = jax.jit(copy)
        return tuple(fn(*fields))

    def _replay_arg_shardings(self, mesh):
        """Shardings for (base_key, params) when the replay runs on an
        alternate mesh — both replicated for the spatial engine (the
        ensemble engine member-shards them)."""
        rep = NamedSharding(mesh, P())
        return rep, rep

    def replay_fields(
        self, fields, step0: int, nsteps: int, devices=None,
    ) -> tuple:
        """Recompute ``nsteps`` steps from ``fields`` (the state at
        absolute step ``step0``) and return the resulting field tuple,
        leaving the live state untouched.

        The replay jits the SAME step construction as :meth:`iterate`
        (:meth:`_make_step_fn`) *with the same donation signature*:
        XLA:CPU's FP-contraction decisions are donation-sensitive (a
        non-donating twin of the donating live runner drifts 1 ulp in
        the Pallas overlap bands), so the replay donates fresh copies
        of the anchor — never the caller's retained buffers — and the
        compiled program is the live executable bit for bit. With that,
        bitwise determinism — noise keyed by (key, absolute step,
        global cell), exchange schedule fixed — makes replay-vs-live an
        exact equality on any placement. ``devices`` optionally
        rebuilds the mesh over a permuted device assignment of the same
        shape (SDC shadow mode: a deterministic per-core fault cannot
        self-confirm); inputs are device_put onto the permuted sharding
        first."""
        if nsteps <= 0:
            return tuple(fields)
        if devices is None:
            # Same-placement (spot) replay IS the live runner: the one
            # compiled executable serves both, so spot screening pays
            # recompute only — no twin compile — and replay-equals-live
            # is the executable's own determinism, not a compiler
            # coincidence.
            fn, sharding, device = self._runner(nsteps), None, None
        else:
            key = (int(nsteps), tuple(d.id for d in devices))
            entry = self._replay_fns.get(key)
            if entry is None:
                mesh = None
                sharding = None
                device = None
                if self.mesh is not None:
                    mesh = Mesh(
                        np.array(devices).reshape(self.mesh.devices.shape),
                        self.mesh.axis_names,
                    )
                    sharding = NamedSharding(mesh, self.field_sharding.spec)
                else:
                    device = devices[0]
                # Donation mirrors the live runner: XLA:CPU codegen is
                # donation-sensitive (a non-donating twin drifts 1 ulp
                # in the Pallas overlap bands).
                fn = jax.jit(
                    self._make_step_fn(nsteps, mesh),
                    donate_argnums=tuple(range(self.model.n_fields)),
                )
                entry = self._replay_fns[key] = (fn, sharding, device)
            fn, sharding, device = entry
        base_key, params = self.base_key, self.params
        # The donated field args must be fresh buffers: a bisection
        # replays from one anchor several times, and device_put onto an
        # unchanged sharding is an alias, not a copy.
        fields = self._copy_fields(fields)
        if sharding is not None:
            fields = tuple(jax.device_put(f, sharding) for f in fields)
            kck, pck = self._replay_arg_shardings(sharding.mesh)
            base_key = jax.device_put(base_key, kck)
            params = jax.device_put(params, pck)
        elif device is not None:
            fields = tuple(jax.device_put(f, device) for f in fields)
            base_key = jax.device_put(base_key, device)
            params = jax.device_put(params, device)
        return tuple(fn(*fields, base_key, jnp.int32(step0), params))

    # ---------------------------------------------------------------- public

    def iterate(self, nsteps: int = 1) -> None:
        """Advance the simulation ``nsteps`` steps (``public.jl:45-71``)."""
        if nsteps <= 0:
            return
        runner = self._runner(nsteps)
        self.fields = tuple(runner(
            *self.fields, self.base_key, jnp.int32(self.step), self.params
        ))
        self.step += nsteps

    def _shard_parts(self, *arrays):
        """Per-addressable-shard ``(offsets, true_sizes, *field_devs)``
        — the device-side half of the output path: each entry carries
        the shard's global (start, count) box clipped to the true
        domain (non-divisible L stores pad cells past L on the high
        edge of the last block per axis; framework internals that never
        leave the process) plus one single-device shard array per model
        field."""
        L = self.settings.L
        first = arrays[0]

        def box(index):
            # Slices are unhashable before py3.12, so shards are matched
            # across fields by their (start, count) box, not the raw
            # index.
            idx = index if isinstance(index, tuple) else (index,)
            offsets = tuple(sl.start or 0 for sl in idx)
            sizes = tuple(
                (sl.stop or g) - (sl.start or 0)
                for sl, g in zip(idx, first.shape)
            )
            return offsets, sizes

        other_shards = [
            {box(s.index): s for s in a.addressable_shards}
            for a in arrays[1:]
        ]
        parts = []
        for sh in first.addressable_shards:
            offsets, sizes = box(sh.index)
            true = tuple(min(L - o, s) for o, s in zip(offsets, sizes))
            parts.append(
                (offsets, true, sh.data)
                + tuple(m[(offsets, sizes)].data for m in other_shards)
            )
        return parts

    def snapshot_async(
        self, *, health: bool = False, numerics: bool = False,
        checksum: bool = False, bitflip=None, encode=None,
        exact: bool = True,
    ) -> FieldSnapshot:
        """Capture the current (u, v) for overlapped output: returns a
        :class:`FieldSnapshot` with non-blocking D2H transfers already
        in flight, so the caller can hand it to a background writer and
        immediately dispatch the next compute chunk.

        The fields are first copied into FRESH device buffers (one
        asynchronously dispatched device-side pass): the next donated
        ``iterate`` call aliases the current field buffers into its
        outputs and marks them deleted, which invalidates every shard
        view of them — holding a reference to the old arrays does NOT
        protect the data. The copy is storage the runner never sees, so
        the snapshot stays valid for as long as the consumer needs it.

        ``health=True`` additionally evaluates the fused
        ``isfinite``/range probe (``resilience/health.device_probe``)
        inside the SAME jitted program — the fields are read from HBM
        once for both copy and probe, and the five scalars ride the
        boundary's existing D2H (``FieldSnapshot.health_report``).
        ``numerics=True`` fuses the per-field min/max/mean/L2/finite
        reductions (``obs/numerics.device_numerics_probe``) into the
        same program the same way (``FieldSnapshot.numerics_report``).
        ``checksum=True`` (``GS_CKPT_VERIFY=full``) fuses the per-field
        integrity checksum
        (``resilience/integrity.device_field_checksum``) in next to
        them; ``FieldSnapshot.blocks`` re-derives it from the landed
        host bytes and refuses a mismatching boundary. ``bitflip``
        (chaos hook, the ``bitflip`` fault kind) flips one bit of the
        device-side COPY after the probes ran — silent write-path
        corruption, field/member-addressable, live trajectory
        untouched.

        ``encode`` (docs/PRECISION.md — the lossy snapshot codec) maps
        field indices to quantization bit widths: coded fields are
        additionally quantized to uint payloads INSIDE the same jitted
        program (``io/codec.device_quantize`` — the exact field is
        read from HBM once for copy, probes, and encode together) and
        only the compressed bytes ride the D2H for them.
        ``exact=False`` skips the exact copies entirely (a lossy-
        output-only boundary — the D2H volume win); at least one of
        ``exact``/``encode`` must be requested.
        """
        from .io import codec as io_codec

        enc_items = (
            tuple(sorted((int(i), int(b)) for i, b in encode.items()))
            if encode else None
        )
        if not exact and enc_items is None:
            raise ValueError(
                "snapshot_async(exact=False) needs an encode spec — "
                "a boundary with neither captures nothing"
            )
        key = (health, numerics, checksum, enc_items, exact)
        fn = self._snapshot_fns.get(key)
        if fn is None:
            # +0 forces a real output buffer (no donation, so XLA never
            # aliases inputs into outputs); sharding follows the inputs.
            device_probe = self._probe_fn() if health else None
            num_probe = self._numerics_probe_fn() if numerics else None
            ck_probe = self._checksum_probe_fn() if checksum else None
            spec = dict(enc_items) if enc_items else None

            def copy(*fields):
                res = {}
                copies = (
                    tuple(f + jnp.zeros((), f.dtype) for f in fields)
                    if exact else None
                )
                if copies is not None:
                    res["copies"] = copies
                if spec is not None:
                    entries, lohi = [], []
                    for i, f in enumerate(fields):
                        bits = spec.get(i)
                        if bits is None:
                            # Uncoded fields ride the codec set as
                            # exact copies (one buffer, shared with
                            # the exact set when both are captured).
                            entries.append(
                                copies[i] if copies is not None
                                else f + jnp.zeros((), f.dtype)
                            )
                        else:
                            q, lo, hi = io_codec.device_quantize(
                                f, bits
                            )
                            entries.append(q)
                            lohi.append((lo, hi))
                    res["enc"] = tuple(entries)
                    res["enc_lohi"] = tuple(lohi)
                if device_probe is not None:
                    res["health"] = device_probe(*fields)
                if num_probe is not None:
                    res["numerics"] = num_probe(*fields)
                if ck_probe is not None:
                    res["checksums"] = ck_probe(*fields)
                return res

            fn = self._snapshot_fns[key] = jax.jit(copy)
        res = fn(*self.fields)
        copies = res.get("copies")
        probe = res.get("health")
        nums = res.get("numerics")
        cksums = res.get("checksums")
        enc = res.get("enc")
        if bitflip is not None:
            if copies is not None:
                copies = self._apply_snapshot_bitflip(copies, bitflip)
            else:
                enc = self._apply_snapshot_bitflip(enc, bitflip)
        parts = self._shard_parts(*copies) if copies is not None else None
        enc_parts, enc_meta = None, None
        if enc is not None:
            enc_parts = self._shard_parts(*enc)
            enc_meta = {}
            for (i, bits), (lo, hi) in zip(enc_items, res["enc_lohi"]):
                enc_meta[i] = (
                    bits, lo, hi, str(np.dtype(self.dtype)),
                )
        for plist in (parts, enc_parts):
            for part in plist or ():
                for dev in part[2:]:
                    dev.copy_to_host_async()
        return self.snapshot_cls(
            parts, self.step, health=probe, numerics=nums,
            checksums=cksums, field_names=self.model.field_names,
            enc_parts=enc_parts, enc_meta=enc_meta,
        )

    def _checksum_probe_fn(self):
        from .resilience.integrity import device_field_checksum

        return device_field_checksum

    def _apply_snapshot_bitflip(self, copies, field="u"):
        """The ``bitflip`` fault body: XOR one bit of one field's
        snapshot COPY (after the checksum probe read the pristine
        fields) — exactly the silent write-path corruption the
        device-side checksum exists to catch. The live field buffers
        are untouched: the trajectory is unchanged, only this
        boundary's bytes are wrong."""
        from .resilience.integrity import apply_bitflip

        i = self._field_index(field if field is not True else "u")
        flipped = apply_bitflip(copies[i], (0,) * copies[i].ndim)
        return copies[:i] + (flipped,) + copies[i + 1:]

    def numerics_stats(self):
        """One probe-only numerics reduction over the live fields,
        resolved to a :class:`~.obs.numerics.NumericsReport` — the
        ``GS_NUMERICS=every_round`` path, for rounds that end at no
        write boundary (boundaries get the probe fused into the
        snapshot copy instead). A pure read of the field buffers: the
        trajectory is untouched."""
        fn = getattr(self, "_numerics_fn", None)
        if fn is None:
            probe = self._numerics_probe_fn()

            def run(*fields):
                return probe(*fields)

            fn = self._numerics_fn = jax.jit(run)
        return self._resolve_numerics_host(fn(*self.fields))

    def _resolve_numerics_host(self, raw):
        from .obs import numerics as obs_numerics

        return obs_numerics.resolve_report(raw, self.model.field_names)

    def poison_nan(self, field="u") -> None:
        """Chaos/testing hook (``resilience/faults.py`` kind ``nan``):
        set one cell of ``field`` (a model field name, the legacy
        ``"u"``/``"v"`` aliases, or an index) to NaN, modelling a
        numerical blow-up the health guard must catch at the next
        boundary. A scatter on the live buffers; sharding is
        preserved."""
        i = self._field_index(field)
        arr = self.fields[i]
        poisoned = arr.at[(0,) * arr.ndim].set(
            jnp.asarray(float("nan"), arr.dtype)
        )
        self.fields = (
            self.fields[:i] + (poisoned,) + self.fields[i + 1:]
        )

    def poison_drift(self, field="u", factor: float = 8.0) -> None:
        """Chaos/testing hook (``resilience/faults.py`` kind
        ``drift``): scale a small corner box of ``field`` by
        ``factor`` — a large but FINITE excursion, the numerical
        signature of a mixed-precision accumulation going wrong
        without blowing up. The corner sits outside the reaction seed
        (the activator field is zero there for every registered
        model's init), so the excursion decays diffusively instead of
        feeding the reaction: the health guard stays green
        (everything finite), while the field's max statistic jumps by
        ~``factor`` and the numerics drift signal
        (``obs/numerics.py``) must trip the
        :class:`~.resilience.health.DriftGate` per
        ``GS_DRIFT_POLICY``. A scatter on the live buffers; sharding
        is preserved."""
        i = self._field_index(field)
        arr = self.fields[i]
        box = tuple(slice(0, 2) for _ in range(arr.ndim))
        scaled = arr.at[box].multiply(
            jnp.asarray(factor, arr.dtype)
        )
        self.fields = (
            self.fields[:i] + (scaled,) + self.fields[i + 1:]
        )

    def _sdc_site(self, arr, device=None):
        """``(device_name, global index)`` for the ``sdc`` poison: the
        center cell of the target device's shard, so the flip lands
        squarely inside one device's block and diffusion keeps the
        divergence centered there over a short screening window —
        the attribution's blast-center rule sees a clean signal.
        Default target: the highest-id device owning a shard."""
        by_name = {}
        for sh in arr.addressable_shards:
            d = sh.device
            by_name[f"{d.platform}:{d.id}"] = sh
        if device is None:
            name = max(
                by_name,
                key=lambda n: (
                    n.rsplit(":", 1)[0], int(n.rsplit(":", 1)[1]),
                ),
            )
        else:
            name = device
            if name not in by_name:
                raise ValueError(
                    f"sdc fault device {name!r} owns no addressable "
                    f"shard (have: {', '.join(sorted(by_name))})"
                )
        sh = by_name[name]
        idx = sh.index if isinstance(sh.index, tuple) else (sh.index,)
        index = tuple(
            (sl.start or 0) + ((sl.stop or g) - (sl.start or 0)) // 2
            for sl, g in zip(idx, arr.shape)
        )
        return name, index

    def poison_sdc(self, device=None, field="u") -> str:
        """Chaos/testing hook (``resilience/faults.py`` kind ``sdc``):
        XOR the lowest bit of ONE LIVE cell in the shard owned by the
        named device, BEFORE the round runs — a compute-path fault
        model. The corrupted value is an *input* to the step program,
        so the trajectory diverges from a clean replay and SDC
        screening must detect it and attribute it back to this device.
        Contrast PR 14's snapshot-copy ``bitflip``, which corrupts
        write-path bytes only and must stay invisible to SDC checks
        (asserted in tier-1). Returns the poisoned device's name for
        the injection record.

        The flip hits the mantissa MSB of the storage word (bit 6 of a
        2-byte word, bit 22 of a 4-byte one): a lowest-bit flip at a
        flat-region cell is diffusively absorbed below one ulp within
        a round, while real SDC flips arbitrary bits — the screening
        contract targets persistent wrong answers. The flipped value
        stays finite (mantissa-only), so the health guard stays green
        and only screening can catch it."""
        from .resilience.integrity import apply_bitflip

        i = self._field_index(field)
        arr = self.fields[i]
        name, index = self._sdc_site(arr, device)
        bit = 6 if jnp.dtype(arr.dtype).itemsize == 2 else 22
        flipped = apply_bitflip(arr, index, bit=bit)
        if getattr(self, "field_sharding", None) is not None:
            # The scatter's jit can hand back a resharded (replicated)
            # output; the live state must keep the mesh sharding.
            flipped = jax.device_put(flipped, self.field_sharding)
        self.fields = (
            self.fields[:i] + (flipped,) + self.fields[i + 1:]
        )
        return name

    def local_blocks(self):
        """Per-addressable-shard ``(offsets, sizes, *field_blocks)``
        (for Gray-Scott: ``(offsets, sizes, u_block, v_block)``).

        The multi-host output path: each process writes only the blocks it
        owns, with their global (start, count) boxes — the ADIOS2
        per-rank-decomposition analog (``IO.jl:60-67``). Single device
        yields one whole-grid block.

        Synchronous form: reads the live field buffers directly (no
        device-side copy) and blocks until the values are on the host —
        callers must consume the result before the next ``iterate``.
        For output overlapped with compute use :meth:`snapshot_async`.
        """
        jax.block_until_ready(self.fields)
        return self.snapshot_cls(
            self._shard_parts(*self.fields), self.step,
            field_names=self.model.field_names,
        ).blocks()

    def restore_from_reader(self, reader, step_index: int, step: int) -> None:
        """Restore state with per-shard selection reads — each process
        pulls only its own blocks from the checkpoint store (scalable
        multi-host restart; no full-array gather). Store variables are
        the model's declared field names."""
        names = self.model.field_names
        if not self.sharded:
            self.restore_fields(
                tuple(
                    reader.get(name, step=step_index) for name in names
                ),
                step,
            )
            return

        storage = self.domain.storage_shape
        L = self.settings.L

        def make(name: str, bv: float):
            def cb(index):
                start = [s.start or 0 for s in index]
                count = [
                    (s.stop or g) - (s.start or 0)
                    for s, g in zip(index, storage)
                ]
                # The store holds the true L^3 domain; pad cells (only
                # present for non-divisible L) are reconstructed at the
                # boundary value, exactly as a fresh init would.
                true = [min(L - st, c) for st, c in zip(start, count)]
                block = reader.get(
                    name, step=step_index, start=start, count=true
                ).astype(self.dtype)
                if tuple(true) != tuple(count):
                    buf = np.full(count, bv, dtype=self.dtype)
                    buf[tuple(slice(0, t) for t in true)] = block
                    return buf
                return block

            return jax.make_array_from_callback(
                storage, self.field_sharding, cb
            )

        self.fields = tuple(
            make(name, bv)
            for name, bv in zip(names, self.model.boundaries)
        )
        self.step = int(step)

    def restore_fields(self, fields, step: int) -> None:
        """Restore state from full host field arrays (fixes the
        reference's hardcoded ``restart_step = 0``,
        ``src/GrayScott.jl:77-78``). ``fields`` follows the model's
        declaration order."""
        fields = tuple(jnp.asarray(f, self.dtype) for f in fields)
        if len(fields) != self.model.n_fields:
            raise ValueError(
                f"Checkpoint has {len(fields)} fields; model "
                f"{self.model.name!r} declares {self.model.n_fields}"
            )
        expected = (self.settings.L,) * 3
        for name, f in zip(self.model.field_names, fields):
            if f.shape != expected:
                raise ValueError(
                    f"Checkpoint shape {name}={f.shape} does not match "
                    f"L={self.settings.L}"
                )
        if self.sharded and self.domain.padded:
            # Rebuild the pad shell at the boundary value (the stored
            # arrays cover only the true domain).
            pads = [
                (0, g - self.settings.L)
                for g in self.domain.storage_shape
            ]
            fields = tuple(
                jnp.pad(f, pads, constant_values=bv)
                for f, bv in zip(fields, self.model.boundaries)
            )
        target = self.field_sharding if self.sharded else self.device
        self.fields = tuple(jax.device_put(f, target) for f in fields)
        self.step = int(step)

    def restore(self, u: np.ndarray, v: np.ndarray, step: int) -> None:
        """Two-field compatibility form of :meth:`restore_fields` (the
        historical Gray-Scott signature)."""
        self.restore_fields((u, v), step)

    def get_fields(self) -> Tuple[np.ndarray, ...]:
        """Host copies of the model's fields (declaration order),
        clipped to the true ``L^3`` domain — the ghost-strip + D->H
        analog (``Simulation_CPU.jl:125-133``, ``CUDAExt.jl:199-209``;
        the strip also removes the storage pad of a non-divisible
        sharded L)."""
        jax.block_until_ready(self.fields)
        L = self.settings.L
        return tuple(
            np.asarray(f)[:L, :L, :L] for f in self.fields
        )

    def block_until_ready(self) -> None:
        jax.block_until_ready(self.fields)

    def layout(self):
        """The :class:`~.reshard.plan.LayoutMeta` describing this run's
        adopted decomposition — what its checkpoints record, and the
        "new" side of an elastic restore plan (docs/RESHARD.md)."""
        from .reshard.restore import layout_of

        return layout_of(self)

    def metrics_labels(self) -> dict:
        """The label set every metric of this run carries
        (``obs/metrics.py``): model / mesh / resolved kernel, so one
        scrape endpoint distinguishes runs sharing a host. The ensemble
        engine extends it with the member count."""
        return {
            "model": self.model.name,
            "mesh": "x".join(str(d) for d in self.domain.dims),
            "kernel": self.kernel_language,
        }

    def device_memory_stats(self) -> list:
        """Per-local-device allocator stats for the metrics registry
        (``obs/metrics.py``): HBM in use / peak per device, the number
        an operator watches for creeping fragmentation on a week-long
        campaign. Backends without ``memory_stats`` (CPU) contribute
        nothing — the list is empty there, and callers treat that as
        "no data", not zero."""
        out = []
        for d in jax.local_devices():
            try:
                ms = d.memory_stats()
            except Exception:  # noqa: BLE001 — optional PJRT surface
                ms = None
            if not ms:
                continue
            out.append({
                "device": f"{d.platform}:{d.id}",
                "bytes_in_use": int(ms.get("bytes_in_use", 0)),
                "peak_bytes_in_use": int(ms.get("peak_bytes_in_use", 0)),
            })
        return out


def initialization(
    args, *, n_devices: Optional[int] = None, seed: int = 0
) -> Tuple[Settings, CartDomain, Simulation]:
    """Parse config and build a ready-to-run simulation
    (reference ``Simulation.initialization``, ``communication.jl:15-33``)."""
    settings = config.get_settings(list(args))
    sim = Simulation(settings, n_devices=n_devices, seed=seed)
    return settings, sim.domain, sim


def finalize() -> None:
    """Reference-parity no-op (``communication.jl:40-46``): JAX needs no
    explicit teardown; kept so driver code mirrors the reference flow."""
