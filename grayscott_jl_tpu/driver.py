"""Simulation driver: the reference's ``GrayScott.main`` step loop.

Flow (``src/GrayScott.jl:68-103``): initialization -> output stream init ->
step loop -> write every ``plotgap`` -> close -> finalize; plus what the
reference only declares (SURVEY defect #4): checkpoint every
``checkpoint_freq`` and restart from ``restart_input``.

Idiomatic-JAX difference: the loop advances in fused chunks — the number of
steps to the next output/checkpoint boundary runs as one jitted
``lax.fori_loop`` on device (halo exchange included), with host contact
only at the boundaries. The reference instead crosses the host boundary
every single step (``public.jl:45-71``).
"""

from __future__ import annotations

import time
from typing import List, Optional

from .config.settings import get_settings
from .simulation import Simulation, finalize
from .utils.log import Logger


def _next_boundary(step: int, period: int, limit: int) -> int:
    """Next multiple of ``period`` after ``step``, capped at ``limit``."""
    if period <= 0:
        return limit
    return min(limit, (step // period + 1) * period)


def main(args: List[str], *, n_devices: Optional[int] = None, seed: int = 0):
    """Run a full simulation from CLI args (reference ``GrayScott.main``)."""
    settings = get_settings(list(args))
    sim = Simulation(settings, n_devices=n_devices, seed=seed)
    log = Logger(verbose=settings.verbose)

    restart_step = 0
    if settings.restart:
        from .io.checkpoint import load_checkpoint

        u, v, restart_step = load_checkpoint(settings.restart_input, settings)
        sim.restore(u, v, restart_step)
        log.info(f"Restarted from {settings.restart_input} at step {restart_step}")

    from .io.checkpoint import CheckpointWriter
    from .io.stream import SimStream

    stream = SimStream(settings, sim.domain, sim.dtype)
    ckpt = CheckpointWriter(settings, sim.dtype) if settings.checkpoint else None

    step = restart_step
    t0 = time.perf_counter()
    while step < settings.steps:
        boundary = min(
            _next_boundary(step, settings.plotgap, settings.steps),
            _next_boundary(
                step,
                settings.checkpoint_freq if ckpt is not None else 0,
                settings.steps,
            ),
        )
        sim.iterate(boundary - step)
        step = boundary

        if settings.plotgap > 0 and step % settings.plotgap == 0:
            log.info(
                f"Simulation at step {step} writing output step "
                f"{step // settings.plotgap}"
            )
            u, v = sim.get_fields()
            stream.write_step(step, u, v)

        if (
            ckpt is not None
            and settings.checkpoint_freq > 0
            and step % settings.checkpoint_freq == 0
        ):
            u, v = sim.get_fields()
            ckpt.save(step, u, v)
            log.info(f"Checkpoint written at step {step}")

    sim.block_until_ready()
    elapsed = time.perf_counter() - t0
    cells = settings.L**3 * (settings.steps - restart_step)
    log.info(
        f"Completed {settings.steps - restart_step} steps in {elapsed:.3f}s "
        f"({cells / max(elapsed, 1e-9):.3e} cell-updates/s)"
    )

    stream.close()
    if ckpt is not None:
        ckpt.close()
    finalize()
    return sim
