"""Simulation driver: the reference's ``GrayScott.main`` step loop.

Flow (``src/GrayScott.jl:68-103``): initialization -> output stream init ->
step loop -> write every ``plotgap`` -> close -> finalize; plus what the
reference only declares (SURVEY defect #4): checkpoint every
``checkpoint_freq`` and restart from ``restart_input``.

Idiomatic-JAX difference: the loop advances in fused chunks — the number of
steps to the next output/checkpoint boundary runs as one jitted
``lax.fori_loop`` on device (halo exchange included), with host contact
only at the boundaries. The reference instead crosses the host boundary
every single step (``public.jl:45-71``).

Output is overlapped with compute: each boundary captures an async
:class:`~.simulation.FieldSnapshot` (non-blocking D2H) and submits it to
the bounded background writer (``io/async_writer.py``), so
serialization/VTK/disk for step N drain while steps N+1.. compute.
``GS_ASYNC_IO_DEPTH`` bounds the in-flight steps (0 = the reference's
synchronous flow); the pipeline preserves step order, applies
backpressure when full, surfaces writer errors on this thread, and is
drained before the run is declared complete.

Kernel scheduling (``tune/``, docs/TUNING.md): with
``kernel_language = "Auto"`` the Simulation constructor consults the
measured autotuner (``GS_AUTOTUNE`` / ``autotune`` TOML key) behind the
analytic ICI-model dispatch; the decision provenance (mode, cache
hit/miss, candidates timed, tuning seconds) lands in the RunStats
``kernel_selection`` section below, next to the supervisor's
degradation provenance.

Observability (``obs/``, docs/OBSERVABILITY.md): the loop below is the
instrumentation spine — every phase boundary is one watchdog heartbeat
which is one trace span edge (``GS_TRACE``), every ``RunStats`` phase
is a nested span, each fused round feeds the step-latency histogram
(``GS_METRICS``), and lifecycle/fault/recovery markers route through
the unified event stream (``GS_EVENTS``). ``GS_PROFILE=start:stop``
brackets a step range with a ``jax.profiler`` device capture. All of
it observes host-side control flow only: trajectories are bitwise
identical with observability on or off.

Resilience (``resilience/``): :func:`main` is split into the supervision
dispatch and :func:`run_once`, the single-attempt loop. ``GS_SUPERVISE``
routes through ``resilience.supervisor.supervise`` — failure
classification, backoff, checkpoint auto-resume, Pallas->XLA
degradation. ``run_once`` itself hosts the boundary-time hooks: the
deterministic fault plan (``GS_FAULTS``), the device-side health guard
on the snapshot path (``GS_HEALTH_POLICY``), and a close-on-any-exit
guarantee for the output/checkpoint stores (an async-writer re-raise
must not leak open stores or a half-written rollback sidecar).
"""

from __future__ import annotations

import copy
import os
import time
from typing import List, Optional

from .config.env import env_int, env_raw, env_str
from .config.settings import Settings, get_settings
from .simulation import Simulation, finalize
from .utils.log import Logger


def _next_boundary(step: int, period: int, limit: int) -> int:
    """Next multiple of ``period`` after ``step``, capped at ``limit``."""
    if period <= 0:
        return limit
    return min(limit, (step // period + 1) * period)


def _resolve_reshape_dims(req, sim):
    """Resolve a live-reshape request to concrete target mesh dims, or
    None for an infeasible / no-op request (docs/RESHARD.md).

    The serve side stays JAX-free, so its elastic policy sends scale
    HINTS (``{"scale": "grow"|"shrink"}``) and the driver — the layer
    that can see the device inventory — resolves them: grow doubles the
    spatial device count toward the idle chips, shrink halves it to
    donate the slice. An explicit ``{"mesh_dims": [x, y, z]}`` pins the
    target outright.
    """
    from .parallel.domain import CartDomain, dims_create

    if not isinstance(req, dict):
        return None
    member_shards = int(getattr(sim, "member_shards", 1))
    cur = sim.domain.n_blocks
    if req.get("mesh_dims"):
        dims = tuple(int(d) for d in req["mesh_dims"])
    else:
        scale = req.get("scale")
        if scale == "grow":
            n = cur * 2
        elif scale == "shrink":
            n = cur // 2
        else:
            return None
        if n < 1:
            return None
        dims = dims_create(n, 3)
    n = dims[0] * dims[1] * dims[2]
    from .resilience.sdc import usable_devices

    if n * member_shards > len(usable_devices()):
        return None  # not enough (non-quarantined) chips to grow into
    try:
        CartDomain.create(n, sim.settings.L, dims=dims)
    except ValueError:
        return None  # infeasible for this L — refuse the hint quietly
    if dims == tuple(sim.domain.dims):
        return None
    return dims


def maybe_initialize_distributed() -> None:
    """Multi-host bring-up (replaces the reference's ``MPI.Init``,
    ``communication.jl:20``).

    Activated by ``GS_TPU_COORDINATOR`` (host:port) +
    ``GS_TPU_NUM_PROCESSES`` + ``GS_TPU_PROCESS_ID`` for explicit launch
    (works on CPU for testing), or ``GS_TPU_DISTRIBUTED=auto`` for
    TPU-pod autodetection via ``jax.distributed.initialize()``.
    """
    import os

    import jax

    coord = env_raw("GS_TPU_COORDINATOR")
    if coord:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=env_int("GS_TPU_NUM_PROCESSES"),
            process_id=env_int("GS_TPU_PROCESS_ID"),
        )
    elif env_raw("GS_TPU_DISTRIBUTED") == "auto":
        jax.distributed.initialize()


def main(args: List[str], *, n_devices: Optional[int] = None, seed: int = 0):
    """Run a full simulation from CLI args (reference ``GrayScott.main``).

    With supervision armed (``GS_SUPERVISE`` / ``supervise`` TOML key)
    the run goes through the restart loop; otherwise a single open-loop
    :func:`run_once` — the reference's behavior, plus guaranteed store
    closure on failure.

    ``GS_SEED`` overrides the base PRNG seed (default 0) without an API
    call — e.g. to launch the solo-run equivalent of ensemble member k
    (seed ``base + k``; docs/ENSEMBLE.md).
    """
    settings = get_settings(list(args))
    env_seed = env_str("GS_SEED", "").strip()
    if env_seed:
        seed = int(env_seed)

    # Split-phase exchange support flags (async collective-permute +
    # latency-hiding scheduler) must reach XLA before the backend
    # initializes; TPU-only flags, and pointless when the operator
    # pinned the fused exchange.
    from .config import settings as config_mod

    backend, _lang = config_mod.load_backend_and_lang(settings)
    if backend == "tpu" and config_mod.resolve_comm_overlap(settings) != "off":
        from .utils.benchmark import inject_overlap_xla_flags

        inject_overlap_xla_flags()

    maybe_initialize_distributed()

    from .resilience import supervisor

    if supervisor.supervision_enabled(settings):
        # Multi-host runs are supervised for real (the old per-process
        # refusal is gone): classified failures rendezvous on a quorum
        # restart step so all ranks restart together
        # (resilience/rendezvous.py, docs/RESILIENCE.md).
        return supervisor.supervise(settings, n_devices=n_devices, seed=seed)
    return run_once(settings, n_devices=n_devices, seed=seed)


def _close_quietly(store) -> None:
    """Best-effort close on the failure path: the store may hold an
    open step from a writer-thread death — a secondary close error must
    never mask the exception already in flight."""
    try:
        store.close()
    except Exception:  # noqa: BLE001 — deliberately swallowed
        pass


def _with_checksums(fn, checksums):
    """Bind the boundary's device-side field checksums onto a write
    target (``write_step``/``save`` grow an optional ``checksums``
    kwarg) — runs on the async writer's worker thread."""

    def wrapped(step, blocks):
        return fn(step, blocks, checksums=checksums)

    return wrapped


def _with_io_fault(plan, journal, fn):
    """Wrap an ``AsyncStepWriter`` target so a due ``io_error`` fault
    raises inside it — surfacing on the driver thread as a transient
    ``AsyncIOError``, exactly the path a real disk hiccup takes.
    Runs on the writer's worker thread; plan/journal are thread-safe.
    """
    from .resilience.faults import InjectedIOError

    def wrapped(step, blocks):
        fault = plan.take("io_error", step)
        if fault is not None:
            journal.record(
                event="injected", kind="io_error", step=step,
                planned_step=fault.step,
            )
            raise InjectedIOError(
                f"injected transient I/O error at step {step} "
                f"(planned step {fault.step})"
            )
        return fn(step, blocks)

    return wrapped


def run_once(
    settings: Settings,
    *,
    n_devices: Optional[int] = None,
    seed: int = 0,
    context=None,
    sim_factory=None,
    reshape_poll=None,
):
    """One supervised-or-not simulation attempt.

    ``context`` is the supervisor's
    :class:`~.resilience.supervisor.SupervisorContext` (shared fault
    plan + journal across attempts, degradation provenance); standalone
    runs build their own from the environment. Raises on failure —
    classification and recovery live in the supervisor, not here.

    ``sim_factory`` (optional) supplies the Simulation instead of the
    constructors below — the warm-ensemble seam the serve worker fleet
    uses (``serve/worker.py``): a factory can hand back an
    already-compiled :class:`~.ensemble.engine.EnsembleSimulation`
    rebound to this launch's members (``repack``), so a packed batch
    pays zero recompilation. Called as
    ``sim_factory(settings, n_devices=..., seed=...)``.

    ``reshape_poll`` (optional) is the between-rounds live-reshape hook
    (docs/RESHARD.md "In-job reshapes"): called at every round
    boundary; a truthy return — ``{"mesh_dims": [x, y, z]}`` or
    ``{"scale": "grow"|"shrink"}`` — moves the LIVE state onto the
    target mesh via ``reshard.restore.reshape_live`` (no checkpoint
    round-trip, continuation bitwise-identical) and the run keeps
    stepping on the new layout. The serve elastic policy
    (``serve/elastic.py``) feeds this hook.
    """
    from .resilience.faults import (
        FaultPlan,
        ShutdownListener,
        resolve_graceful_shutdown,
    )
    from .resilience.health import HealthGuard
    from .resilience.supervisor import FaultJournal
    from .resilience.watchdog import Watchdog, resolve_watchdog

    if context is not None:
        plan, journal = context.plan, context.journal
    else:
        plan = FaultPlan.from_env(settings)
        journal = FaultJournal.from_env(settings)
    guard = HealthGuard.from_env(settings)

    # Hang watchdog + graceful-shutdown listener bracket the whole
    # attempt: the watchdog's "compile" deadline must already be armed
    # while the Simulation constructor jits (and autotunes), and a
    # SIGTERM during compile should still exit through the graceful
    # path at the first boundary.
    from .obs import events as obs_events

    deadlines = resolve_watchdog(settings)
    wd = Watchdog(deadlines, journal=journal).start() if deadlines else None
    shutdown = ShutdownListener(
        enabled=resolve_graceful_shutdown(settings), watchdog=wd,
        # Live preemption notice on the unified event stream the moment
        # the signal lands (the boundary-time graceful_shutdown journal
        # marker follows later, possibly much later on a long round).
        on_request=lambda signum: obs_events.get_events().emit(
            "shutdown_requested", signum=signum
        ),
    ).install()
    try:
        return _run_once_inner(
            settings, n_devices=n_devices, seed=seed, context=context,
            plan=plan, journal=journal, guard=guard, wd=wd,
            shutdown=shutdown, sim_factory=sim_factory,
            reshape_poll=reshape_poll,
        )
    except BaseException as exc:
        # A watchdog expiry unwinds as KeyboardInterrupt (the monitor's
        # interrupt_main, possibly re-raised through the shutdown
        # listener); surface it as the classified hang it is.
        if (wd is not None and wd.expired is not None
                and isinstance(exc, KeyboardInterrupt)):
            wd.check()  # raises HangError with the expired phase/step
        raise
    finally:
        shutdown.uninstall()
        if wd is not None:
            wd.stop()
        # The trace file must be valid after EVERY attempt — a
        # supervised multi-restart run flushes here between attempts,
        # and the atomic rewrite keeps the on-disk JSON well-formed
        # even if the next attempt dies mid-span.
        from .obs.trace import get_tracer

        try:
            get_tracer().flush()
        except OSError as e:
            import sys

            print(f"gray-scott: warning: could not write trace "
                  f"({e})", file=sys.stderr)


def _run_once_inner(
    settings: Settings,
    *,
    n_devices: Optional[int],
    seed: int,
    context,
    plan,
    journal,
    guard,
    wd,
    shutdown,
    sim_factory=None,
    reshape_poll=None,
):
    import jax

    from .obs import events as obs_events
    from .obs import metrics as obs_metrics
    from .obs.trace import ProfileWindow, get_tracer
    from .resilience.faults import (
        GracefulShutdown,
        InjectedKernelError,
        PreemptionError,
        injected_hang_wait,
    )

    # Observability sinks (docs/OBSERVABILITY.md): process-wide
    # singletons, so a supervised run's restart attempts share one
    # trace, one event stream, and one metrics registry — the unified
    # timeline is the point. All of them are no-ops unless their env
    # knob (GS_TRACE / GS_EVENTS / GS_METRICS / GS_PROFILE) is set, and
    # none of them touch the jitted programs: trajectories are bitwise
    # identical obs on or off (asserted in tier-1).
    tracer = get_tracer()
    evs = obs_events.get_events()
    metrics = obs_metrics.get_metrics(settings)
    profile = ProfileWindow.from_env()
    attempt = context.attempt if context is not None else 0

    def _mark(phase, step=None):
        """One driver phase boundary: the watchdog heartbeat (which
        itself emits the trace span edge) or, on an unwatched run, the
        edge directly — same timeline either way."""
        if wd is not None:
            wd.heartbeat(phase, step)
        else:
            tracer.edge(phase, step)

    _mark("compile")
    ens = getattr(settings, "ensemble", None)
    if sim_factory is not None:
        # The serve worker's warm-ensemble seam: the factory may hand
        # back an already-compiled engine rebound to this launch.
        sim = sim_factory(settings, n_devices=n_devices, seed=seed)
    elif ens is not None:
        # Batched ensemble run (docs/ENSEMBLE.md): one compiled launch
        # advances every member; stores are member-indexed.
        from .ensemble.engine import EnsembleSimulation

        sim = EnsembleSimulation(settings, n_devices=n_devices, seed=seed)
    else:
        sim = Simulation(settings, n_devices=n_devices, seed=seed)
    log = Logger(verbose=settings.verbose)
    proc, nprocs = jax.process_index(), jax.process_count()

    restart_step = 0
    if settings.restart:
        # Elastic restore (docs/RESHARD.md): the checkpoint's recorded
        # layout is compared against the mesh THIS run adopted; a
        # mismatch reshards via per-new-shard selection reads (and, for
        # ensembles, grows/shrinks the member set) with a `reshard`
        # event on the journal and the unified stream.
        from .reshard.restore import restore_run

        restart_step, _plan = restore_run(
            sim, settings, log=log, journal=journal
        )
        if ens is not None:
            log.info(
                f"Restarted {ens.n} ensemble members from "
                f"{settings.restart_input} member stores at step "
                f"{restart_step}"
            )
        else:
            log.info(
                f"Restarted from {settings.restart_input} at step "
                f"{restart_step}"
            )

    # Lossy snapshot codec (docs/PRECISION.md): resolved at Simulation
    # construction (misconfigurations fail there); ensembles keep exact
    # output — per-member quantization ranges are a member-axis
    # reduction the fused probe family does not carry yet, and a codec
    # that silently changed meaning per member would be worse than
    # refusing. Loud, not silent.
    codec = sim.snapshot_codec
    if ens is not None and codec.enabled:
        log.warn(
            "snapshot_bits ignored for ensemble runs (member stores "
            "stay exact); lossy output is a solo-run codec"
        )
        from .io.codec import CodecConfig

        codec = CodecConfig({}, {})
    #: field-index -> bits spec for snapshot_async's fused encoder.
    enc_spec = {
        i: codec.output[n.lower()]
        for i, n in enumerate(sim.model.field_names)
        if n.lower() in codec.output
    }
    ckpt_lossy = bool(codec.ckpt)

    if ens is not None:
        from .ensemble.io import EnsembleCheckpointWriter, EnsembleStream

        stream_cls, ckpt_cls = EnsembleStream, EnsembleCheckpointWriter
    else:
        from .io.checkpoint import CheckpointWriter
        from .io.stream import SimStream

        stream_cls, ckpt_cls = SimStream, CheckpointWriter

    stream_kw = {"codec": codec.output or None} if ens is None else {}
    ckpt_kw = {"codec": codec.ckpt or None} if ens is None else {}
    stream = stream_cls(
        settings, sim.domain, sim.dtype, writer_id=proc, nwriters=nprocs,
        resume_step=restart_step if settings.restart else None,
        **stream_kw,
    )
    ckpt = (
        ckpt_cls(
            settings, sim.dtype, writer_id=proc, nwriters=nprocs,
            resume_step=restart_step if settings.restart else None,
            # Elastic-resume metadata (docs/RESHARD.md): fresh stores
            # record the writing run's layout so a future restore can
            # plan an old->new reshard.
            layout=sim.layout(),
            **ckpt_kw,
        )
        if settings.checkpoint
        else None
    )

    from .io.async_writer import AsyncStepWriter
    from .utils.profiler import RunStats, trace

    # Auto-dispatch provenance: which kernel the ICI model picked and
    # why (None for an explicitly pinned language); after a supervisor
    # degradation, also which language the run fell back FROM.
    selection = sim.kernel_selection
    if context is not None and context.degraded is not None:
        selection = {**(selection or {}), **context.degraded}
    from .config.settings import resolve_autotune

    stats = RunStats(settings.L, tracer=tracer, config={
        "attempt": attempt,
        "model": sim.model.name,
        "fields": list(sim.model.field_names),
        "mesh_dims": list(sim.domain.dims),
        "padded_storage": (
            list(sim.domain.storage_shape) if sim.sharded
            and sim.domain.padded else None
        ),
        "kernel_language": sim.kernel_language,
        "kernel_selection": selection,
        "precision": settings.precision,
        # Mixed-precision + codec postures (docs/PRECISION.md): what
        # the run actually materialized — the tuner may have adopted
        # bf16 under an authorizing posture, and every artifact reader
        # must be able to tell.
        "compute_precision": sim.compute_precision,
        "snapshot_codec": codec.describe(),
        "n_devices": sim.domain.n_blocks,
        "n_processes": nprocs,
        "comm_overlap": sim.comm_overlap,
        "halo_depth": sim.halo_depth,
        # Elastic-restore provenance: the old->new plan when this
        # attempt resumed a checkpoint written on a different layout
        # (mesh change, process-count change, ensemble grow); None
        # otherwise. docs/RESHARD.md.
        "reshard": sim.reshard,
        "compile_cache": sim.compile_cache_dir,
        # The resolved tuner mode rides in the config echo even for
        # explicitly-pinned kernel languages (where no tuning runs):
        # a stats reader can tell "not tuned" from "tuner off".
        "autotune_mode": resolve_autotune(settings),
        "process_index": proc,
        "ensemble": (
            {"members": ens.n, "member_shards": sim.member_shards}
            if ens is not None else None
        ),
    })
    if ens is not None:
        # Per-member section: params + resolved seeds up front; the
        # latest per-member health lands here at each probed boundary.
        stats.record_ensemble({
            **ens.describe(),
            "member_shards": sim.member_shards,
            "seeds": list(sim.member_seeds),
        })
    if context is not None:
        # Hand the live stats to the supervisor: a failing attempt's
        # phase accumulation becomes an attempt-tagged journal event
        # (``attempt_phases``) instead of dying with the attempt.
        context.stats = stats
    from .parallel import icimodel

    comm = icimodel.comm_report(sim)
    stats.record_comm(comm)
    stats.record_watchdog(
        {**wd.describe(), "attempt": attempt} if wd is not None
        else {"enabled": False}
    )

    # Metrics instruments, registered once per attempt (get-or-create:
    # restarted attempts find the same objects) and labeled by the
    # run's resolved config so one scrape distinguishes models/meshes/
    # kernels sharing a host. Off means the shared null instrument —
    # the loop below pays a no-op call, nothing else.
    mlabels = sim.metrics_labels()
    m_step_us = metrics.histogram("step_latency_us", **mlabels)
    m_rounds = metrics.counter("step_rounds", **mlabels)
    m_steps = metrics.counter("steps", **mlabels)
    # In-graph numerics telemetry (obs/numerics.py, GS_NUMERICS):
    # "boundary" fuses the per-field min/max/mean/L2/finite reductions
    # into the snapshot-copy jit at write boundaries; "every_round"
    # additionally probes after every fused round. Off builds nothing —
    # the loop below pays one `is not None` check.
    from .obs import numerics as obs_numerics
    from .resilience.health import DriftGate

    num_mode = obs_numerics.resolve_numerics(settings)
    num_recorder = (
        obs_numerics.NumericsRecorder(
            sim.model.field_names, metrics=metrics, events=evs,
            gate=DriftGate.from_env(settings), log=log, labels=mlabels,
            journal=journal,
        )
        if num_mode != "off" else None
    )
    stats.config["numerics"] = num_mode
    # Data-integrity layer (resilience/integrity.py,
    # docs/RESILIENCE.md): GS_CKPT_VERIFY=full fuses the device-side
    # field checksum into the snapshot-copy jit (verified host-side
    # before any store write; single-process only — the host can only
    # recompute over its local shards); GS_SCRUB arms the boundary
    # scrubber over every checkpoint replica.
    from .resilience import integrity as integ

    icfg = integ.resolve_config(settings)
    stats.config["integrity"] = dict(icfg)
    snapshot_checksum = icfg["verify"] == "full" and nprocs == 1
    scrubber = (
        integ.Scrubber(settings, journal=journal,
                       every=icfg["scrub_every"])
        if icfg["scrub"] and ckpt is not None else None
    )
    # Compute-path SDC screening (resilience/sdc.py, docs/RESILIENCE.md
    # "Silent data corruption"): GS_SDC_CHECK=spot|shadow replays the
    # rounds since the previous boundary from a retained anchor and
    # compares exact in-graph checksums — a mismatch is attributed to a
    # device and unwinds as SDCError before any store write.
    # Single-process only, like the snapshot checksum above (the
    # screener compares addressable shards for attribution).
    from .resilience import sdc as sdc_mod

    scfg = sdc_mod.resolve_sdc(settings)
    screener = (
        sdc_mod.Screener(
            sim, mode=scfg["mode"], every=scfg["every"],
            journal=journal, log=log.info,
        )
        if scfg["mode"] != "off" and nprocs == 1 else None
    )
    if screener is not None:
        screener.rearm(restart_step)
    stats.config["sdc"] = dict(scfg)
    m_sdc_checks = metrics.counter("sdc_checks", **mlabels)
    # The reference side of the live model-vs-measured residual gauge:
    # what the ICI model projects one step should cost on this exact
    # config. Computed once — the observed p50 moves, the projection
    # does not.
    proj_us = icimodel.projected_step_us_for(sim)
    metrics.gauge("comm_hidden_us_per_step", **mlabels).set(
        comm.get("hidden_us")
    )
    metrics.gauge("comm_exposed_us_per_step", **mlabels).set(
        comm.get("exposed_us")
    )
    # s-step exchange visibility (docs/TEMPORAL.md): exchanges and
    # ghost bytes per step make the halo_depth amortization legible on
    # the same scrape that carries the hidden/exposed comm split.
    metrics.gauge("comm_exchanges_per_step", **mlabels).set(
        comm.get("exchanges_per_step")
    )
    metrics.gauge("comm_halo_bytes_per_step", **mlabels).set(
        comm.get("halo_bytes_per_step")
    )

    def _refresh_device_gauges():
        """Per-device allocator gauges; only refreshed when a metrics
        record is actually about to flush (the PJRT query is not
        boundary-cheap)."""
        for ms in sim.device_memory_stats():
            metrics.gauge(
                "device_bytes_in_use", device=ms["device"]
            ).set(ms["bytes_in_use"])
            metrics.gauge(
                "device_peak_bytes_in_use", device=ms["device"]
            ).set(ms["peak_bytes_in_use"])
        # Model-vs-measured residual (docs/OBSERVABILITY.md): observed
        # step-latency p50 minus the icimodel projection — calibration
        # drift, live on the same scrape as the latency itself.
        if proj_us is not None and hasattr(m_step_us, "percentile"):
            p50 = m_step_us.percentile(50)
            if p50 is not None:
                metrics.gauge(
                    "model_projected_step_us", **mlabels
                ).set(round(proj_us, 1))
                metrics.gauge(
                    "model_vs_measured_residual_us", **mlabels
                ).set(round(p50 - proj_us, 1))

    evs.emit(
        "run_start", step=restart_step, attempt=attempt,
        model=sim.model.name, L=settings.L, steps=settings.steps,
        kernel=sim.kernel_language, mesh=list(sim.domain.dims),
        restart=bool(settings.restart),
    )
    # The watchdog's drain heartbeat: while close() drains K queued
    # steps, each completed write re-arms the "drain" deadline (touch
    # only re-arms the currently armed phase, so mid-run worker writes
    # never mask a wedged driver).
    pipe = AsyncStepWriter(
        stats=stats, metrics=metrics,
        progress=(lambda s: wd.touch("drain", s)) if wd is not None else None,
    )
    stats.config["async_io_depth"] = pipe.depth
    step = restart_step
    first_round = True
    # Quarantine poll state: only a CHANGED blocklist pays the overlap
    # check + reshape attempt, so a refused move warns once, not every
    # round.
    quarantine_handled: frozenset = frozenset()

    def _graceful(at_step: int, ckpt_written: bool):
        """The preemption grace path: checkpoint NOW (off-schedule if
        needed), drain every accepted step durably, journal the resume
        marker, and exit via GracefulShutdown — the distinct
        EXIT_PREEMPTED code upstream tells the relauncher 'resume me'.
        """
        ckpt_step = None
        if ckpt is not None:
            if not ckpt_written:
                _mark("checkpoint", at_step)
                # A ckpt-lossy store's variables are uint — every save
                # (grace checkpoints included) must go through the
                # codec; the default exact store takes exact copies.
                snap = sim.snapshot_async(
                    encode=enc_spec if ckpt_lossy else None,
                    exact=not ckpt_lossy,
                )
                pipe.submit(at_step, snap, [("checkpoint", ckpt.save)])
                stats.count("checkpoints")
                log.info(
                    f"Graceful-shutdown checkpoint accepted at step {at_step}"
                )
            ckpt_step = at_step
        journal.record(
            event="graceful_shutdown", signal=shutdown.signum,
            step=at_step, checkpoint_step=ckpt_step,
        )
        _mark("drain", at_step)
        pipe.close()
        raise GracefulShutdown(shutdown.signum, at_step, ckpt_step)

    m_reshards = metrics.counter("reshards", **mlabels)
    m_reshard_wall = metrics.gauge("reshard_wall_s", **mlabels)

    def _apply_reshape(req) -> bool:
        """Between-rounds live reshape (docs/RESHARD.md "In-job
        reshapes"): move the LIVE state onto the target mesh with
        :func:`~.reshard.restore.reshape_live` — no kill, no checkpoint
        round-trip, continuation bitwise-identical — then swap in
        stores that append at the current step on the new layout."""
        nonlocal sim, stream, ckpt, first_round
        from .reshard.plan import ReshardError
        from .reshard.restore import reshape_live

        dims = _resolve_reshape_dims(req, sim)
        if dims is None:
            return False
        # The reshape pays a target compile plus the device-path move —
        # its own watchdog phase (GS_WATCHDOG_RESHAPE_S) so a wedged
        # move cannot hide under the looser compile budget forever.
        _mark("reshape", step)
        # Retire in-flight writes against the OLD stores before the
        # swap; the pipeline itself stays up.
        pipe.drain()
        try:
            new_sim, rplan = reshape_live(
                sim, mesh_dims=dims, seed=seed, log=log,
                journal=journal,
            )
        except ReshardError as e:
            log.warn(f"live reshape refused: {e}")
            return False
        if not rplan.changed:
            return False
        stream.close()
        if ckpt is not None:
            ckpt.close()
        sim = new_sim
        if screener is not None:
            # The screener's anchor/checksum closures are bound to the
            # old mesh; rebind and re-anchor on the adopted layout (the
            # move is bitwise-transparent, so the next replay segment
            # simply starts here).
            screener.rebind(sim)
            screener.rearm(step)
        # The rebuilt stores must APPEND at the current step: the
        # stores only open in append mode under settings.restart, and
        # a fresh (non-restarted) run that reshapes mid-life would
        # otherwise truncate every snapshot written before the move.
        # Per-step block boxes make mixed layouts in one store legal.
        resumed = copy.copy(settings)
        resumed.restart = True
        stream = stream_cls(
            resumed, sim.domain, sim.dtype, writer_id=proc,
            nwriters=nprocs, resume_step=step, **stream_kw,
        )
        if ckpt is not None:
            ckpt = ckpt_cls(
                resumed, sim.dtype, writer_id=proc, nwriters=nprocs,
                resume_step=step, layout=sim.layout(), **ckpt_kw,
            )
        # Config echo + comm model follow the adopted layout so every
        # artifact written after the move describes the mesh the run is
        # actually on; the reshard record carries the old one.
        stats.config["reshard"] = sim.reshard
        stats.config["mesh_dims"] = list(sim.domain.dims)
        stats.config["n_devices"] = sim.domain.n_blocks
        stats.record_comm(icimodel.comm_report(sim))
        m_reshards.inc()
        if sim.reshard is not None:
            m_reshard_wall.set(sim.reshard.get("wall_s"))
        first_round = True
        return True

    t0 = time.perf_counter()
    if profile is not None:
        profile.on_boundary(step)
    try:
        with trace(), pipe:
            while step < settings.steps:
                if reshape_poll is not None:
                    # Between-rounds elastic hook: the poll is cheap
                    # (a dict read under the serve scheduler's lock);
                    # only a truthy request pays the reshape.
                    req = reshape_poll()
                    if req:
                        _apply_reshape(req)
                # Quarantine poll (resilience/sdc.py): when a device
                # this run computes on lands in the blocklist — this
                # worker's own screener via the supervisor, a fleet
                # peer's quarantine doc, or an operator export — move
                # the live state onto the surviving inventory between
                # rounds, the live-path analog of the supervisor's
                # restart-with-exclusion.
                blocked = sdc_mod.resolve_blocklist()
                if blocked and blocked != quarantine_handled:
                    in_use = {
                        sdc_mod.device_name(d) for d in (
                            sim.mesh.devices.flat
                            if sim.mesh is not None else (sim.device,)
                        )
                    }
                    if blocked & in_use:
                        shards_per = max(
                            1, int(getattr(sim, "member_shards", 1))
                        )
                        dims = sdc_mod.feasible_dims(
                            len(sdc_mod.usable_devices()) // shards_per,
                            settings.L,
                        )
                        moved = dims is not None and _apply_reshape(
                            {"mesh_dims": dims}
                        )
                        if not moved:
                            log.warn(
                                "quarantined device(s) "
                                f"{sorted(blocked & in_use)} in use but "
                                "no feasible reshape target — continuing "
                                "on the current mesh"
                            )
                    quarantine_handled = blocked
                # The first round pays jit (and, under Auto, any
                # remaining autotune measurement) — its budget is
                # the compile deadline, every later round the much
                # tighter step_round one.
                _mark("compile" if first_round else "step_round", step)
                boundary = min(
                    _next_boundary(step, settings.plotgap, settings.steps),
                    _next_boundary(
                        step,
                        settings.checkpoint_freq if ckpt is not None else 0,
                        settings.steps,
                    ),
                )
                if sim.kernel_language == "pallas":
                    # Planned Mosaic runtime failure: armed only while
                    # Pallas is the resolved language (the supervisor's
                    # recovery degrades to XLA, where it cannot recur).
                    fault = plan.take("kernel", boundary)
                    if fault is not None:
                        journal.record(
                            event="injected", kind="kernel",
                            step=boundary, planned_step=fault.step,
                        )
                        raise InjectedKernelError(fault.step)
                fault = plan.take("sdc", boundary)
                if fault is not None:
                    # Compute-path corruption (faults.py kind catalog):
                    # flip one live cell on the named device BEFORE the
                    # round runs, so the corruption is an INPUT to the
                    # step program — unlike `bitflip`, which hits only
                    # the write-path snapshot copy. GS_SDC_CHECK replays
                    # from the pre-poison anchor and must diverge.
                    name = sim.poison_sdc(
                        device=sdc_mod.resolve_fault_device(settings)
                    )
                    journal.record(
                        event="injected", kind="sdc", step=step,
                        planned_step=fault.step, device=name,
                    )
                t_round = time.perf_counter()
                with stats.phase("compute", step=step):
                    sim.iterate(boundary - step)
                    # iterate() only dispatches; block so the phase
                    # measures device execution, not async enqueue time.
                    sim.block_until_ready()
                # Step-latency distribution: one sample per fused round
                # (per-step mean of the round — the host cannot see
                # individual steps inside the jitted chunk), feeding
                # the p50/p95/p99 the stats file and bench rows report.
                m_step_us.observe(
                    (time.perf_counter() - t_round)
                    / (boundary - step) * 1e6
                )
                m_rounds.inc()
                m_steps.inc(boundary - step)
                stats.count("steps", boundary - step)
                step = boundary
                first_round = False
                if num_recorder is not None and num_mode == "every_round":
                    # Probe-only reduction over the live fields: every
                    # fused round is covered, write boundaries
                    # included ("boundary" mode instead fuses the probe
                    # into the snapshot copy below — one HBM pass for
                    # copy, health, and numerics together).
                    num_recorder.observe(step, sim.numerics_stats())
                if profile is not None:
                    profile.on_boundary(step)

                if screener is not None:
                    # Screen BEFORE this boundary's poison faults (an
                    # injected nan/drift is a modeled failure the
                    # health/drift gates own, not compute-path SDC) and
                    # BEFORE any store write, so a mismatch unwinds as
                    # SDCError without persisting a single corrupt byte.
                    if screener.check(step):
                        m_sdc_checks.inc()
                fault = plan.take("nan", step)
                if fault is not None:
                    journal.record(
                        event="injected", kind="nan", step=step,
                        planned_step=fault.step,
                    )
                    sim.poison_nan()
                fault = plan.take("drift", step)
                if fault is not None:
                    # Finite-but-wrong excursion (docs/PRECISION.md):
                    # the health guard stays green, the numerics drift
                    # gate (GS_DRIFT_POLICY) must catch it.
                    journal.record(
                        event="injected", kind="drift", step=step,
                        planned_step=fault.step,
                    )
                    sim.poison_drift()
                fault = plan.take("preempt", step)
                if fault is not None:
                    # Fires BEFORE this boundary's writes: the
                    # SIGTERM-mid-compute shape. Steps already accepted
                    # by the pipeline still drain durably on the abort
                    # path (AsyncStepWriter.__exit__), like a
                    # grace-window shutdown.
                    journal.record(
                        event="injected", kind="preempt", step=step,
                        planned_step=fault.step,
                    )
                    raise PreemptionError(
                        f"injected preemption at step {step} "
                        f"(planned step {fault.step})"
                    )
                fault = plan.take("hang", step)
                if fault is not None:
                    # The wedged-collective / dead-tunnel shape: stall
                    # the driver thread at the boundary. Under an armed
                    # watchdog the step_round deadline expires
                    # mid-stall and the stall unwinds as HangError;
                    # unwatched, the bounded stall resolves and the run
                    # continues (faults change WHEN, never WHAT).
                    journal.record(
                        event="injected", kind="hang", step=step,
                        planned_step=fault.step,
                    )
                    injected_hang_wait(watchdog=wd, shutdown=shutdown)

                if screener is not None:
                    # Re-anchor every boundary (a device-side copy, no
                    # D2H) AFTER the poison takes above, so an injected
                    # nan/drift lands inside the anchor and the next
                    # replay segment reproduces it — faults change
                    # WHEN, never WHAT the screener compares.
                    screener.rearm(step)

                at_plot = (
                    settings.plotgap > 0 and step % settings.plotgap == 0
                )
                at_ckpt = (
                    ckpt is not None
                    and settings.checkpoint_freq > 0
                    and step % settings.checkpoint_freq == 0
                )
                if not (at_plot or at_ckpt):
                    if shutdown.requested:
                        _graceful(step, ckpt_written=False)
                    continue
                _mark("io", step)
                targets = []
                if at_plot:
                    log.info(
                        f"Simulation at step {step} writing output step "
                        f"{step // settings.plotgap}"
                    )
                    targets.append(("output", stream.write_step))
                if at_ckpt:
                    targets.append(("checkpoint", ckpt.save))
                if plan.pending("io_error"):
                    targets = [
                        (phase, _with_io_fault(plan, journal, fn))
                        for phase, fn in targets
                    ]
                # The bitflip fault corrupts THIS boundary's snapshot
                # copy on device (write-path silent corruption; the
                # live trajectory is untouched) — the device-side
                # checksum must catch it before anything is written.
                bitflip = None
                fault = plan.take("bitflip", step)
                if fault is not None:
                    journal.record(
                        event="injected", kind="bitflip", step=step,
                        planned_step=fault.step,
                    )
                    bitflip = True
                # Codec routing (docs/PRECISION.md): coded targets get
                # the fused device-side quantization; the exact copies
                # are captured only when some target needs them — a
                # lossy-output-only boundary moves ONLY the compressed
                # payload over D2H (the volume win).
                want_enc = bool(enc_spec) and (
                    at_plot or (at_ckpt and ckpt_lossy)
                )
                want_exact = (
                    (at_ckpt and not ckpt_lossy)
                    or (at_plot and not enc_spec)
                )
                with stats.phase("device_to_host", step=step):
                    snap = sim.snapshot_async(
                        health=guard.enabled,
                        numerics=num_mode == "boundary",
                        checksum=snapshot_checksum and want_exact,
                        bitflip=bitflip,
                        encode=enc_spec if want_enc else None,
                        exact=want_exact,
                    )
                    if pipe.synchronous:
                        # Depth 0 reproduces the reference's flow
                        # exactly: D2H resolves here, writes run inline
                        # in submit.
                        snap.blocks()
                if snap.has_checksums():
                    # Stamp the boundary's device checksums into the
                    # stores' integrity sidecars (per-step, per-field
                    # provenance next to the block CRCs).
                    cksums = snap.checksum_report()
                    targets = [
                        (phase, _with_checksums(fn, cksums))
                        for phase, fn in targets
                    ]
                if guard.enabled:
                    # Unhealthy + abort/rollback raises BEFORE the
                    # poisoned step is submitted — it never reaches the
                    # stores; warn records and writes anyway.
                    report = snap.health_report()
                    if ens is not None and report is not None:
                        stats.record_member_health(step, report)
                    try:
                        event = guard.check(step, report, log=log,
                                            metrics=metrics)
                    except Exception:
                        # Journal the failing report BEFORE unwinding:
                        # for ensembles this is where the non-finite
                        # member indices reach the FaultJournal.
                        journal.record(
                            event="health", kind="health", step=step,
                            policy=guard.policy, action=guard.policy,
                            **report.describe(),
                        )
                        raise
                    if event is not None:
                        journal.record(**event)
                gate_first = (
                    num_mode == "boundary"
                    and num_recorder.gate is not None
                    and getattr(num_recorder.gate, "raising", False)
                )
                if gate_first:
                    # A raising drift policy (abort/rollback,
                    # docs/PRECISION.md) mirrors the health guard: the
                    # DriftError must unwind BEFORE the drifted
                    # boundary is submitted, so the poisoned step
                    # never reaches the stores and the supervisor
                    # resumes from the last HEALTHY checkpoint.
                    num_recorder.observe(
                        step, snap.numerics_report(), boundary=True
                    )
                pipe.submit(step, snap, targets)
                if num_mode == "boundary" and not gate_first:
                    # After submit — the resolution blocks only on the
                    # probe's scalars, never delays the write pipeline.
                    num_recorder.observe(
                        step, snap.numerics_report(), boundary=True
                    )
                if at_plot:
                    stats.count("output_steps")
                    evs.emit("output", phase="io", step=step,
                             output_step=step // settings.plotgap)
                if at_ckpt:
                    stats.count("checkpoints")
                    evs.emit("checkpoint", phase="io", step=step)
                    log.info(f"Checkpoint accepted at step {step}")
                # The ckpt_corrupt fault flips one payload byte of the
                # latest DURABLE checkpoint entry in the primary store
                # (CRCs untouched — exactly the silent corruption the
                # verify/scrub/failover machinery exists to catch).
                fault = plan.take("ckpt_corrupt", step)
                if fault is not None and ckpt is not None:
                    info = integ.corrupt_store_byte(
                        integ.primary_checkpoint_path(settings)
                    )
                    journal.record(
                        event="injected", kind="ckpt_corrupt",
                        step=step, planned_step=fault.step,
                        **(info or {"corrupted": False}),
                    )
                if scrubber is not None and at_ckpt:
                    scrubber.maybe_scrub(step)
                # Interval metrics record (metrics_interval_s TOML /
                # GS_METRICS_INTERVAL_S): boundary-time only, with the
                # expensive device gauges refreshed just-in-time.
                metrics.maybe_flush(on_flush=_refresh_device_gauges)
                if shutdown.requested:
                    # After this boundary's scheduled writes so the
                    # resumed run reproduces the uninterrupted output
                    # stream byte-for-byte.
                    _graceful(step, ckpt_written=at_ckpt)

            # Drain INSIDE the timed region: the run is complete only
            # once every accepted step is durable (close re-raises a
            # writer failure with the failing step identified).
            _mark("drain", step)
            pipe.close()

        if screener is not None:
            # Echo what the screener actually did into the stats
            # artifact (boundaries seen, checks run, last verified
            # step) next to its resolved config.
            stats.config["sdc"].update(screener.describe())
        elapsed = time.perf_counter() - t0
        # Idle pack slots never count toward the work actually served
        # (docs/SERVICE.md): only ACTIVE members scale the aggregate.
        members = ens.active_n if ens is not None else 1
        cells = settings.L**3 * (settings.steps - restart_step) * members
        if ens is not None:
            log.info(
                f"Completed {settings.steps - restart_step} steps for "
                f"{members} ensemble members in {elapsed:.3f}s "
                f"({cells / max(elapsed, 1e-9):.3e} aggregate "
                "cell-updates/s)"
            )
        else:
            log.info(
                f"Completed {settings.steps - restart_step} steps in "
                f"{elapsed:.3f}s "
                f"({cells / max(elapsed, 1e-9):.3e} cell-updates/s)"
            )
        io_stats = pipe.overlap_stats()
        stats.record_io(io_stats)
        metrics.gauge("io_hidden_s").set(
            round(sum(io_stats["hidden_s"].values()), 6)
        )
        metrics.gauge("io_exposed_s").set(
            round(sum(io_stats["exposed_s"].values()), 6)
        )
        if wd is not None:
            # Re-record with the final heartbeat count (the pre-loop
            # record only captured the armed deadlines).
            stats.record_watchdog({**wd.describe(), "attempt": attempt})
        if scrubber is not None:
            # Scrub provenance next to the armed knobs: how many
            # audits ran and whether anything was quarantined.
            stats.config["integrity"].update(scrubber.describe())
        if journal.events:
            stats.record_faults(journal.events)
        if profile is not None:
            profile.finish()
        evs.emit(
            "run_complete", step=step, attempt=attempt,
            wall_s=round(elapsed, 3),
            steps=settings.steps - restart_step,
        )
        _refresh_device_gauges()
        metrics.maybe_flush(force=True)
        prom = env_raw("GS_METRICS_PROM")
        if prom:
            metrics.write_prometheus(prom)
        if metrics.enabled:
            stats.record_metrics(metrics.snapshot())
        if tracer.enabled or evs.enabled or metrics.enabled:
            stats.record_obs({
                "trace": tracer.describe(),
                "events": evs.describe(),
                "metrics": metrics.describe(),
            })
        if num_recorder is not None:
            stats.record_numerics(
                {"mode": num_mode, **num_recorder.describe()}
            )
        if sim.xstats_enabled:
            # Executable analytics (obs/xstats.py): the per-compile
            # records captured by the runner registrations, plus the
            # model-vs-measured residual so a stats reader sees the
            # calibration drift the gauge showed live.
            from .obs import xstats as obs_xstats

            xinfo = obs_xstats.summarize(sim.executables)
            xinfo["records"] = list(sim.executables)
            xinfo["model_projected_step_us"] = (
                round(proj_us, 1) if proj_us is not None else None
            )
            p50 = (
                m_step_us.percentile(50)
                if hasattr(m_step_us, "percentile") else None
            )
            xinfo["observed_p50_us"] = p50
            xinfo["model_vs_measured_residual_us"] = (
                round(p50 - proj_us, 1)
                if p50 is not None and proj_us is not None else None
            )
            stats.record_executables(xinfo)
        stats.maybe_write()
        if settings.verbose:
            log.info(f"run stats: {stats.summary()}")

        stream.close()
        if ckpt is not None:
            ckpt.close()
    except BaseException as exc:
        # Failure path (async-writer re-raise, preemption, health trip,
        # injected kernel error, KeyboardInterrupt): the stores MUST
        # still be closed — an open store leaks file handles and, after
        # a rollback, leaves the sidecar marker pointing at steps that
        # were never committed. Best-effort: never mask the in-flight
        # exception with a secondary close error.
        if profile is not None:
            profile.finish()
        if not isinstance(exc, GracefulShutdown):
            # GracefulShutdown already journaled its own marker (which
            # the stream mirrors); everything else gets the live error
            # notice here. emit() is best-effort by contract.
            evs.emit(
                "run_error", step=step, attempt=attempt,
                error=f"{type(exc).__name__}: {exc}",
            )
        _close_quietly(stream)
        if ckpt is not None:
            _close_quietly(ckpt)
        raise
    finalize()
    return sim
