"""Simulation driver: the reference's ``GrayScott.main`` step loop.

Flow (``src/GrayScott.jl:68-103``): initialization -> output stream init ->
step loop -> write every ``plotgap`` -> close -> finalize; plus what the
reference only declares (SURVEY defect #4): checkpoint every
``checkpoint_freq`` and restart from ``restart_input``.

Idiomatic-JAX difference: the loop advances in fused chunks — the number of
steps to the next output/checkpoint boundary runs as one jitted
``lax.fori_loop`` on device (halo exchange included), with host contact
only at the boundaries. The reference instead crosses the host boundary
every single step (``public.jl:45-71``).

Output is overlapped with compute: each boundary captures an async
:class:`~.simulation.FieldSnapshot` (non-blocking D2H) and submits it to
the bounded background writer (``io/async_writer.py``), so
serialization/VTK/disk for step N drain while steps N+1.. compute.
``GS_ASYNC_IO_DEPTH`` bounds the in-flight steps (0 = the reference's
synchronous flow); the pipeline preserves step order, applies
backpressure when full, surfaces writer errors on this thread, and is
drained before the run is declared complete.
"""

from __future__ import annotations

import time
from typing import List, Optional

from .config.settings import get_settings
from .simulation import Simulation, finalize
from .utils.log import Logger


def _next_boundary(step: int, period: int, limit: int) -> int:
    """Next multiple of ``period`` after ``step``, capped at ``limit``."""
    if period <= 0:
        return limit
    return min(limit, (step // period + 1) * period)


def maybe_initialize_distributed() -> None:
    """Multi-host bring-up (replaces the reference's ``MPI.Init``,
    ``communication.jl:20``).

    Activated by ``GS_TPU_COORDINATOR`` (host:port) +
    ``GS_TPU_NUM_PROCESSES`` + ``GS_TPU_PROCESS_ID`` for explicit launch
    (works on CPU for testing), or ``GS_TPU_DISTRIBUTED=auto`` for
    TPU-pod autodetection via ``jax.distributed.initialize()``.
    """
    import os

    import jax

    coord = os.environ.get("GS_TPU_COORDINATOR")
    if coord:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ["GS_TPU_NUM_PROCESSES"]),
            process_id=int(os.environ["GS_TPU_PROCESS_ID"]),
        )
    elif os.environ.get("GS_TPU_DISTRIBUTED") == "auto":
        jax.distributed.initialize()


def main(args: List[str], *, n_devices: Optional[int] = None, seed: int = 0):
    """Run a full simulation from CLI args (reference ``GrayScott.main``)."""
    settings = get_settings(list(args))
    maybe_initialize_distributed()

    import jax

    sim = Simulation(settings, n_devices=n_devices, seed=seed)
    log = Logger(verbose=settings.verbose)
    proc, nprocs = jax.process_index(), jax.process_count()

    restart_step = 0
    if settings.restart:
        from .io.checkpoint import open_checkpoint

        reader, last, restart_step = open_checkpoint(
            settings.restart_input, settings, settings.restart_step
        )
        sim.restore_from_reader(reader, last, restart_step)
        reader.close()
        log.info(f"Restarted from {settings.restart_input} at step {restart_step}")

    from .io.checkpoint import CheckpointWriter
    from .io.stream import SimStream

    stream = SimStream(
        settings, sim.domain, sim.dtype, writer_id=proc, nwriters=nprocs,
        resume_step=restart_step if settings.restart else None,
    )
    ckpt = (
        CheckpointWriter(
            settings, sim.dtype, writer_id=proc, nwriters=nprocs,
            resume_step=restart_step if settings.restart else None,
        )
        if settings.checkpoint
        else None
    )

    from .io.async_writer import AsyncStepWriter
    from .utils.profiler import RunStats, trace

    stats = RunStats(settings.L, config={
        "mesh_dims": list(sim.domain.dims),
        "padded_storage": (
            list(sim.domain.storage_shape) if sim.sharded
            and sim.domain.padded else None
        ),
        "kernel_language": sim.kernel_language,
        # Auto-dispatch provenance: which kernel the ICI model picked
        # and why (None for an explicitly pinned language).
        "kernel_selection": sim.kernel_selection,
        "precision": settings.precision,
        "n_devices": sim.domain.n_blocks,
        "n_processes": nprocs,
    })
    pipe = AsyncStepWriter(stats=stats)
    stats.config["async_io_depth"] = pipe.depth
    step = restart_step
    t0 = time.perf_counter()
    with trace(), pipe:
        while step < settings.steps:
            boundary = min(
                _next_boundary(step, settings.plotgap, settings.steps),
                _next_boundary(
                    step,
                    settings.checkpoint_freq if ckpt is not None else 0,
                    settings.steps,
                ),
            )
            with stats.phase("compute"):
                sim.iterate(boundary - step)
                # iterate() only dispatches; block so the phase measures
                # device execution, not async enqueue time.
                sim.block_until_ready()
            stats.count("steps", boundary - step)
            step = boundary

            at_plot = settings.plotgap > 0 and step % settings.plotgap == 0
            at_ckpt = (
                ckpt is not None
                and settings.checkpoint_freq > 0
                and step % settings.checkpoint_freq == 0
            )
            if not (at_plot or at_ckpt):
                continue
            targets = []
            if at_plot:
                log.info(
                    f"Simulation at step {step} writing output step "
                    f"{step // settings.plotgap}"
                )
                targets.append(("output", stream.write_step))
            if at_ckpt:
                targets.append(("checkpoint", ckpt.save))
            with stats.phase("device_to_host"):
                snap = sim.snapshot_async()
                if pipe.synchronous:
                    # Depth 0 reproduces the reference's flow exactly:
                    # D2H resolves here, writes run inline in submit.
                    snap.blocks()
            pipe.submit(step, snap, targets)
            if at_plot:
                stats.count("output_steps")
            if at_ckpt:
                stats.count("checkpoints")
                log.info(f"Checkpoint accepted at step {step}")

        # Drain INSIDE the timed region: the run is complete only once
        # every accepted step is durable (close re-raises a writer
        # failure with the failing step identified).
        pipe.close()

    elapsed = time.perf_counter() - t0
    cells = settings.L**3 * (settings.steps - restart_step)
    log.info(
        f"Completed {settings.steps - restart_step} steps in {elapsed:.3f}s "
        f"({cells / max(elapsed, 1e-9):.3e} cell-updates/s)"
    )
    stats.record_io(pipe.overlap_stats())
    stats.maybe_write()
    if settings.verbose:
        log.info(f"run stats: {stats.summary()}")

    stream.close()
    if ckpt is not None:
        ckpt.close()
    finalize()
    return sim
