"""Mosaic kernel generator: fused Pallas kernels from Model declarations.

Any registered model (``models/base.Model``) whose pure ``reaction``
traces to elementwise JAX — purity is machine-checked by gslint's
``purity`` pass, elementwise-ness is checked here — gets the fused
stencil+reaction+noise Pallas TPU kernel (``ops/pallas_stencil``): the
n-field VMEM-resident slab pipeline, the in-kernel temporal chain,
per-field frozen-ghost boundary constants, and f32 accumulation under
the bf16 posture. There is no source codegen: the model's ``reaction``
is *trace-inlined* into the kernel body — calling it on the in-kernel
window values emits its arithmetic directly into the Mosaic program,
the same mechanism by which the XLA path (``stencil.reaction_update``)
stays model-generic. The kernel-from-declaration approach follows the
stencil-DSL lowering literature (arxiv 2309.04671, 2404.02218): the
declaration carries exactly the four things the generator needs —
field count, boundary constants, parameter declarations, and the pure
update form.

Feasibility is a *property of the reaction's jaxpr*, not of the model's
name: :func:`generation_gate_reason` traces the reaction once over
dummy block-shaped operands and refuses (with a reason string that
rides into ``kernel_selection`` provenance as the ``kernel_gate``
record) when the trace fails, the output arity/shape is wrong, or the
jaxpr contains a non-elementwise primitive (a reduction, a gather, a
convolution — anything whose value at a cell depends on other cells
would silently change meaning inside the slab pipeline, where the
reaction only ever sees a local window). Everything else — VMEM slab
fit, Mosaic lane alignment, f64 — stays a *shape* gate in
``pallas_stencil`` / ``icimodel``, orthogonal to the model.

:class:`KernelSpec` is the generator's contract with the kernel: a
frozen, identity-hashed view of the declaration that rides through
``jax.jit`` as a static argument. Specs are memoized per model object
(:func:`get_spec`) so repeated dispatches reuse the jit cache.

Equality fine print (docs/KERNELGEN.md): the generated kernel inlines
the reaction with the SAME operand association as the XLA path — noise
is passed pre-scaled into ``reaction`` exactly like
``stencil.reaction_update`` does — so for Gray-Scott the generated
program is operation-for-operation the hand-written kernel it replaced,
and the trajectory is bitwise-identical (asserted against
``tests/golden/pallas_hand_kernel.npz``, captured from the last
hand-written build).

s-step exchange rounds (docs/TEMPORAL.md): the generated kernel's
in-kernel chain at depth k IS an s-step round — one (d x k)-deep
corner-propagated frame in, d*k Euler steps over progressively
shrinking VMEM-resident valid regions, full width restored at the next
exchange. ``halo_depth=k`` at fuse=d therefore lowers to the SAME
traced program as ``halo_depth=1`` at fuse=k*d (simulation.py chain
dispatch), which is what makes the program-identity contract bitwise
for every generated model; feasibility of the deepened working set is
the VMEM slab ledger (``pallas_stencil.max_feasible_chain_depth``).
GENERATOR_VERSION is unchanged by that schedule: the generated program
family is the same, only the dispatch-selected depth moved.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional, Tuple

#: Version of the generated-kernel contract. Bump when the generated
#: program changes in any observable way (operation order, noise
#: association, mid-stage rounding): the tune cache keys on it (schema
#: v7 ``kernel_generator``) so winners measured against one generator's
#: kernels are never adopted by another's, and ``kernel_selection``
#: provenance records it so artifacts can tell generated-kernel eras
#: apart.
GENERATOR_VERSION = 1

#: Primitives the generator accepts in a reaction jaxpr: elementwise
#: arithmetic (plus the broadcasts/casts jnp scalar-mixing inserts).
#: Anything outside this set couples cells and cannot be inlined into
#: the slab pipeline, where the reaction sees one local window at a
#: time. Conservative by design — extend it only with ops that are
#: provably per-cell.
_ELEMENTWISE_PRIMS = frozenset({
    "add", "sub", "mul", "div", "rem", "neg", "sign", "abs",
    "max", "min", "pow", "integer_pow", "sqrt", "rsqrt", "cbrt",
    "exp", "exp2", "expm1", "log", "log1p", "logistic", "tanh",
    "sin", "cos", "tan", "sinh", "cosh", "erf", "erfc", "square",
    "floor", "ceil", "round", "clamp", "is_finite", "nextafter",
    "eq", "ne", "ge", "gt", "le", "lt", "and", "or", "not", "xor",
    "select_n", "convert_element_type", "broadcast_in_dim", "copy",
    "stop_gradient", "reshape", "squeeze", "expand_dims",
})

#: Call-like primitives whose inner jaxpr is walked recursively.
_CALL_PRIMS = frozenset({
    "pjit", "closed_call", "core_call", "custom_jvp_call",
    "custom_vjp_call", "custom_jvp_call_jaxpr", "remat", "remat2",
    "checkpoint",
})


class KernelGenError(ValueError):
    """A model declaration the generator cannot lower; ``str(exc)`` is
    the feasibility reason recorded in ``kernel_gate`` provenance."""


@dataclasses.dataclass(frozen=True, eq=False)
class KernelSpec:
    """Static view of a Model declaration for the generated kernel.

    ``eq=False`` keeps dataclass identity hashing: a spec is a valid
    ``jax.jit`` static argument, and :func:`get_spec` memoization makes
    repeated dispatches hit the jit cache. ``model`` is the declaration
    object itself (duck-typed — ops/ imports no model module); the
    XLA fallbacks hand it to ``stencil.reaction_update`` unchanged.
    """

    name: str
    n_fields: int
    field_names: Tuple[str, ...]
    boundaries: Tuple[float, ...]
    param_fields: Tuple[str, ...]
    params_cls: type
    reaction: Callable
    model: object
    version: int = GENERATOR_VERSION


def _walk_jaxpr(jaxpr, bad):
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in _CALL_PRIMS:
            for v in eqn.params.values():
                inner = getattr(v, "jaxpr", None)
                if inner is not None:
                    _walk_jaxpr(inner, bad)
        elif name not in _ELEMENTWISE_PRIMS:
            bad.add(name)


def generation_gate_reason(model) -> Optional[str]:
    """Why the generator cannot lower ``model``'s reaction into the
    fused kernel, or ``None`` when it can.

    ONE statement of the model-side Pallas gate, shared by explicit
    ``kernel_language = "Pallas"`` validation, the Auto branch, and the
    autotuner's shortlist (``pallas_allowed``) — all three must agree,
    and the reason string is what lands in ``kernel_gate`` provenance.
    Purely abstract: traces over shaped dummies, never touches a
    device buffer.
    """
    import jax
    import jax.numpy as jnp

    shape = (4, 4, 4)
    n = len(model.field_names)
    dummies = tuple(
        jax.ShapeDtypeStruct(shape, jnp.float32) for _ in range(n)
    )
    noise = jax.ShapeDtypeStruct(shape, jnp.float32)
    params = model.params_cls(*(
        jax.ShapeDtypeStruct((), jnp.float32)
        for _ in model.params_cls._fields
    ))
    try:
        jaxpr, shapes = jax.make_jaxpr(model.reaction, return_shape=True)(
            dummies, dummies, noise, params
        )
    except Exception as e:  # noqa: BLE001 — the reason IS the product
        return f"reaction failed to trace: {type(e).__name__}: {e}"
    if not isinstance(shapes, (tuple, list)) or len(shapes) != n:
        got = len(shapes) if isinstance(shapes, (tuple, list)) else 1
        return (
            f"reaction returned {got} derivative(s) for {n} field(s)"
        )
    for fname, s in zip(model.field_names, shapes):
        if tuple(s.shape) != shape:
            return (
                f"derivative for field {fname!r} has shape "
                f"{tuple(s.shape)}, expected the field shape {shape}"
            )
    bad = set()
    _walk_jaxpr(jaxpr.jaxpr, bad)
    if bad:
        return (
            "reaction uses non-elementwise primitive(s) "
            f"{sorted(bad)}; the slab pipeline only sees a local "
            "window, so cross-cell ops cannot be inlined"
        )
    return None


def build_spec(model) -> KernelSpec:
    """Spec for ``model``, or :class:`KernelGenError` naming the reason
    when generation is infeasible (callers wanting a non-raising check
    use :func:`generation_gate_reason` directly)."""
    reason = generation_gate_reason(model)
    if reason is not None:
        raise KernelGenError(
            f"cannot generate a Pallas kernel for model "
            f"{model.name!r}: {reason}"
        )
    return KernelSpec(
        name=model.name,
        n_fields=len(model.field_names),
        field_names=tuple(model.field_names),
        boundaries=tuple(float(b) for b in model.boundaries),
        param_fields=tuple(model.params_cls._fields),
        params_cls=model.params_cls,
        reaction=model.reaction,
        model=model,
    )


#: Memoized specs keyed on the model object — identity matters: the
#: spec is a jit static argument, so handing the SAME object back on
#: every dispatch is what makes the jit cache hit.
_SPECS: dict = {}


def get_spec(model) -> KernelSpec:
    key = (model.name, id(model))
    spec = _SPECS.get(key)
    if spec is None:
        spec = _SPECS[key] = build_spec(model)
    return spec
