"""Hand-fused Pallas TPU kernel for the Gray-Scott update.

``kernel_language = "Pallas"`` — the TPU-native re-design of the
reference's hand-written GPU kernels (``ext/CUDAExt.jl:127-187``,
``Simulation_KA.jl:160-236``): where those launch a 2D (k,j) thread grid
with a serial i loop per thread, this kernel walks the outermost (x) axis
as a sequential TPU grid, processing one full (y, z) plane per program with
both fields' diffusion + reaction fused into a single VMEM-resident pass.

Layout: fields are C-order ``[x, y, z]`` so z is the 128-lane dimension and
y the sublane dimension; in-plane shifts are vector ops, and the x-axis
neighbor planes arrive as separate blocks (``x-1``, ``x``, ``x+1``) of the
same ghost-padded operand. HBM traffic per step: 3 reads + 1 write per
field per cell (vs the XLA path's materialized pad + 6 shifted-slice
reads), plus the optional noise field.

Numerics are identical to ``ops/stencil.reaction_update`` (same op order,
same dtype); the noise field is generated *outside* the kernel with the
same ``jax.random`` stream, so XLA- and Pallas-kernel runs are bit-
comparable (asserted by ``tests/unit/test_pallas.py``).

On non-TPU backends the kernel runs in Pallas interpret mode (tests); the
Float64 + TPU combination falls back to the XLA kernel (Mosaic has no f64
vector path — the reference has the same asymmetry: its AMDGPU backend
disables noise rather than supporting it, ``AMDGPUExt.jl:195-201``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import stencil


def _plane_kernel(p_ref, um, uc, up, vm, vc, vp, nz, u_out, v_out):
    """One (y, z) plane of the fused update.

    ``um/uc/up`` are the x-1/x/x+1 ghost-padded planes of u, shape
    (1, ny+2, nz+2); ``nz`` is the pre-scaled noise plane (1, ny, nz) or
    None; outputs are interior planes (1, ny, nz).
    """
    dtype = uc.dtype
    six = jnp.asarray(6.0, dtype)
    one = jnp.asarray(1.0, dtype)
    Du, Dv, F, K, dt = (p_ref[i] for i in range(5))

    # 7-point Laplacian on the plane interior (Common.jl:13-18): x-axis
    # neighbors come from the um/up planes, y/z neighbors from in-plane
    # shifts of the center plane.
    u_c = uc[0]
    v_c = vc[0]
    lap_u = (
        um[0, 1:-1, 1:-1]
        + up[0, 1:-1, 1:-1]
        + u_c[:-2, 1:-1]
        + u_c[2:, 1:-1]
        + u_c[1:-1, :-2]
        + u_c[1:-1, 2:]
        - six * u_c[1:-1, 1:-1]
    ) / six
    lap_v = (
        vm[0, 1:-1, 1:-1]
        + vp[0, 1:-1, 1:-1]
        + v_c[:-2, 1:-1]
        + v_c[2:, 1:-1]
        + v_c[1:-1, :-2]
        + v_c[1:-1, 2:]
        - six * v_c[1:-1, 1:-1]
    ) / six

    u = u_c[1:-1, 1:-1]
    v = v_c[1:-1, 1:-1]
    uvv = u * v * v
    du = Du * lap_u - uvv + F * (one - u) + (nz[0] if nz is not None else 0.0)
    dv = Dv * lap_v + uvv - (F + K) * v
    u_out[0] = u + du * dt
    v_out[0] = v + dv * dt


def _plane_kernel_nonoise(p_ref, um, uc, up, vm, vc, vp, u_out, v_out):
    _plane_kernel(p_ref, um, uc, up, vm, vc, vp, None, u_out, v_out)


@functools.partial(jax.jit, static_argnames=("use_noise",))
def _call(u_pad, v_pad, noise_u, params_vec, *, use_noise: bool):
    nxp, nyp, nzp = u_pad.shape
    nx, ny, nz = nxp - 2, nyp - 2, nzp - 2
    dtype = u_pad.dtype

    plane = lambda off: pl.BlockSpec(  # noqa: E731 — x-1/x/x+1 planes
        (1, nyp, nzp), lambda i, o=off: (i + o, 0, 0)
    )
    interior = pl.BlockSpec((1, ny, nz), lambda i: (i, 0, 0))

    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),  # params vector
        plane(0), plane(1), plane(2),  # u planes x-1, x, x+1
        plane(0), plane(1), plane(2),  # v planes
    ]
    operands = [params_vec, u_pad, u_pad, u_pad, v_pad, v_pad, v_pad]
    if use_noise:
        in_specs.append(interior)
        operands.append(noise_u)
        kernel = _plane_kernel
    else:
        kernel = _plane_kernel_nonoise

    out_shape = [
        jax.ShapeDtypeStruct((nx, ny, nz), dtype),
        jax.ShapeDtypeStruct((nx, ny, nz), dtype),
    ]
    return pl.pallas_call(
        kernel,
        grid=(nx,),
        in_specs=in_specs,
        out_specs=[interior, interior],
        out_shape=out_shape,
        interpret=jax.default_backend() != "tpu",
    )(*operands)


def reaction_update(u_pad, v_pad, noise_u, params):
    """Drop-in replacement for ``stencil.reaction_update`` (same signature:
    ghost-padded inputs, interior outputs)."""
    dtype = u_pad.dtype
    if dtype == jnp.float64 and jax.default_backend() == "tpu":
        # Mosaic has no f64 path; keep Float64 configs correct via XLA.
        return stencil.reaction_update(u_pad, v_pad, noise_u, params)
    params_vec = jnp.stack(
        [params.Du, params.Dv, params.F, params.k, params.dt]
    ).astype(dtype)
    use_noise = getattr(noise_u, "ndim", 0) > 0
    if not use_noise:
        noise_u = None
    return _call(u_pad, v_pad, noise_u, params_vec, use_noise=use_noise)
