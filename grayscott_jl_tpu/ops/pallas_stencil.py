"""Generated fused Pallas TPU kernel for registered reaction models.

``kernel_language = "Pallas"`` — the TPU-native re-design of the
reference's hand-written GPU kernels (``ext/CUDAExt.jl:127-187``,
``Simulation_KA.jl:160-236``). Where those launch a 2D (k,j) thread grid
with a serial i loop per thread, this kernel is a single program that
walks the outermost (x) axis in ``BX``-plane slabs with a manually
double-buffered HBM->VMEM DMA pipeline, computing every field's
diffusion + reaction + noise in one fused VMEM-resident pass per slab.

The kernel is **generated, not hand-written per model**: the slab
pipeline below is model-independent, and the model's pure ``reaction``
is trace-inlined into the stage compute from the declaration's
:class:`~..ops.kernelgen.KernelSpec` (field count, frozen-ghost
boundary constants, parameter declarations) — see ``ops/kernelgen.py``
and docs/KERNELGEN.md. Gray-Scott is the flagship instance: the
generated program is operation-for-operation the hand kernel it
replaced, bitwise-checked in tests/golden/pallas_hand_kernel.npz.

The stencil is memory-bound (~30 flops vs 16 bytes minimum traffic per
cell per step for two f32 fields), so the kernel is designed around HBM
traffic:

* operands are the **interior-shaped** ``(L, L, L)`` fields — no
  materialized ghost pad (a blocked-``pallas_call`` or XLA version spends
  a full extra read+write per field on ``jnp.pad``, and the padded
  ``L+2`` lane dimension rounds up to the next 128-lane tile, wasting up
  to ~50% of the vector work at L=256);
* x-neighbor planes come from overlapping slab DMAs — ``(BX+2h)/BX``
  reads per cell (h = halo width) instead of 3 reads with the
  three-plane-operand trick;
* y/z neighbors are in-VMEM shifts (``pltpu.roll``) with the wrapped
  boundary row/column repaired by a masked select — ghost cells never
  exist in memory. On the global edge the mask substitutes the model's
  frozen boundary value (the reference's ``MPI.PROC_NULL`` ghost
  semantics, ``Simulation_CPU.jl:23-24``); on an interior shard edge it
  substitutes the neighbor face delivered by the ``ppermute`` halo
  exchange (``parallel/halo.exchange_faces``);
* **temporal blocking** (``fuse=k``): each slab pass advances k
  timesteps through a chain of shrinking windows — stage s computes
  step n+1+s on a (BX+2(k-1-s))-plane window, recomputing one overlap
  plane per side per stage — so HBM traffic per *step* drops to
  ~((BX+2k)/BX + 1)/k passes (~5 bytes/cell at BX=16, k=4, f32), far
  below the 1-read-1-write "roofline" of any single-step schedule.
  Multi-block slabs fuse too (any BX >= k, the production shape at
  L=128+). With faces, fusion crosses the shard boundary in the
  1D-x-sharded **x-chain** mode (two fuse-wide x faces per field; r3);
  only the full-faces 3D-sharded mode requires fuse=1 (y/z halos break
  Mosaic lane alignment).
  Measured on the v5e, the slab DMA pipeline has a hard per-pass
  envelope (~2 ms at L=256 f32) that is flat in compute content, so
  per-step time scales ~1/k until the k-fold stage compute fills the
  envelope (k≈4 at full clock);
* per-cell uniform noise is generated *inside* the kernel from the
  framework's position-keyed counter-hash stream (``ops/noise.py``),
  keyed on ``(key, absolute step, global cell coordinates)`` — so the
  stream is invariant under restarts, step chunking, slab size, shard
  layout, and temporal fusion (slab-overlap recomputation reproduces
  identical noise), and it is the *same* stream the XLA kernel draws
  from, making the cross-kernel-language oracle exact for noisy runs.
  The hash is pure vector integer ALU (xor/shift/mul) — essentially
  free in a memory-bound kernel — and, unlike the TPU hardware PRNG
  (``pltpu.prng_random_bits``), it is modeled faithfully by the
  interpret-mode tests (the interpreter stubs the hardware PRNG to
  zeros) and needs no per-shard seeding machinery.

The Float64 + TPU combination falls back to the XLA kernel (Mosaic has no
f64 vector path — the reference has the same asymmetry: its AMDGPU
backend disables noise rather than supporting it, ``AMDGPUExt.jl:195-201``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import stencil
from ..config.env import env_raw, env_str
from .noise import _u32, block_bits, plane_seed, uniform_pm1_block

# Name compat across jax releases: CompilerParams/InterpretParams are
# the jax >= 0.6 spellings; older releases export TPUCompilerParams and
# may lack the TPU-semantics interpreter entirely (``None`` here), in
# which case interpret-mode kernels run on the generic HLO interpreter
# and DMA/compute race detection is unavailable.
_COMPILER_PARAMS = getattr(
    pltpu, "CompilerParams", None
) or pltpu.TPUCompilerParams
_INTERPRET_PARAMS = getattr(pltpu, "InterpretParams", None) or getattr(
    pltpu, "TPUInterpretParams", None
)


def interpret_supports_race_detection() -> bool:
    """Whether this jax ships the TPU-semantics interpreter with the
    DMA/compute race detector (``detect_races`` is silently meaningless
    on the generic HLO interpreter, so callers gate on this)."""
    import inspect

    return (
        _INTERPRET_PARAMS is not None
        and "detect_races"
        in inspect.signature(_INTERPRET_PARAMS).parameters
    )


def _interpret_arg(detect_races: bool):
    """The ``pallas_call(interpret=...)`` value for interpret mode on
    this jax: the TPU-semantics interpreter when available (eager DMA
    so tests see deterministic copies), else plain ``True``."""
    if _INTERPRET_PARAMS is None:
        return True
    import inspect

    params = inspect.signature(_INTERPRET_PARAMS).parameters
    kw = {}
    if "dma_execution_mode" in params:
        kw["dma_execution_mode"] = "eager"
    if "detect_races" in params:
        kw["detect_races"] = detect_races
    return _INTERPRET_PARAMS(**kw)

#: VMEM scratch budget for slab buffers, keyed on the device generation:
#: v4/v5/v6 cores carry 128 MiB of VMEM — 96 lets fuse=4 keep bx=16
#: (read amplification (bx+2k)/bx = 1.5 rather than 2 at bx=8) while
#: leaving the compiler headroom; older/unknown parts get a conservative
#: share of their 16 MiB. Resolved lazily (first backend touch).
_VMEM_BUDGETS = {True: 96 * 1024 * 1024, False: 12 * 1024 * 1024}
_VMEM_BUDGET = None

#: Messages already emitted by :func:`_warn_once` (one line per distinct
#: silent-fallback condition per process — benchmark users must see when
#: "Pallas" is measuring the XLA kernel).
_WARNED: set = set()


def _warn_once(msg: str) -> None:
    # Deliberately fires at trace time: gate/fallback decisions are
    # made while building the kernel call, and the operator must see
    # them exactly once per process.
    if msg not in _WARNED:
        _WARNED.add(msg)
        import sys

        print(f"gray-scott: warning: {msg}",  # gslint: disable=trace-safety
              file=sys.stderr)


def _vmem_budget() -> int:
    global _VMEM_BUDGET
    if _VMEM_BUDGET is None:
        try:
            kind = jax.devices()[0].device_kind.lower()
        except Exception:
            kind = ""
        big = any(t in kind for t in ("v4", "v5", "v6", "cpu"))
        _VMEM_BUDGET = _VMEM_BUDGETS[big]
    return _VMEM_BUDGET


def _mid_layout(bx: int, fuse: int):
    """(buffer count, plane width) of the temporal-blocking mid scratch —
    ONE definition shared by the VMEM estimate and the allocation."""
    nbuf = 0 if fuse == 1 else (1 if fuse == 2 else 2)
    return nbuf, bx + 2 * (fuse - 1)


def _compute_dtype(dtype):
    """In-kernel compute dtype: bf16 fields are stored bf16 (the HBM
    traffic win) but computed in f32 — Mosaic's rotate has no 16-bit
    path, and f32 accumulate is the accuracy-correct choice anyway.
    ONE definition shared by the kernel body and the VMEM estimate."""
    return jnp.float32 if dtype == jnp.bfloat16 else dtype


def _mid_store_dtype(dtype, mid_bf16: bool):
    """Storage dtype of the temporal-blocking mid buffers.

    bf16 fields ALWAYS store mids as bf16: the exact chain already
    rounds every mid stage through the field dtype (``_round``), so a
    bf16 store + f32 read-back is bitwise-identical to the old
    f32-store-of-rounded-values — at half the VMEM movement, which the
    r3 envelope probe showed is the kernel's binding cost. f32 fields
    store mids as bf16 only under ``GS_MID_BF16=1`` (``mid_bf16``): an
    opt-in speed/accuracy trade that BREAKS bitwise equality with the
    stepwise trajectory (mid stages round to 8-bit mantissas), for
    benchmark A/B on hardware. f64 mids stay f64."""
    if dtype == jnp.bfloat16:
        return jnp.bfloat16
    if mid_bf16 and dtype == jnp.float32:
        return jnp.bfloat16
    return _compute_dtype(dtype)


def _slab_fits(bx: int, nx: int, ny: int, nz: int, itemsize: int,
               fuse: int, mid_itemsize: int, budget: int,
               n_fields: int = 2) -> bool:
    """ONE statement of the slab-depth VMEM feasibility gate, shared by
    the dispatch pick (:func:`pick_block_planes`) and the autotuner's
    candidate enumeration (:func:`feasible_block_planes`). Scratch
    scales linearly in the model's field count (``n_fields``)."""
    if nx % bx:
        return False
    if bx < nx and bx < fuse:
        # Interior slabs read [b*bx - fuse, b*bx + bx + fuse); with
        # bx < halo the slab next to the boundary would read out of
        # bounds. (Single-block nx == bx has no interior slabs.)
        return False
    # A whole-block slab (nblocks == 1) only ever touches buffer
    # slot 0 — no double buffering to charge for.
    nio = 1 if bx == nx else 2
    in_bytes = n_fields * nio * (bx + 2 * fuse) * ny * nz * itemsize
    nbuf, mid_planes = _mid_layout(bx, fuse)
    mid_bytes = n_fields * nbuf * mid_planes * ny * nz * mid_itemsize
    out_bytes = n_fields * nio * bx * ny * nz * itemsize
    return in_bytes + mid_bytes + out_bytes <= budget


def feasible_block_planes(
    nx: int, ny: int, nz: int, itemsize: int, fuse: int = 1,
    mid_itemsize: int = None, n_fields: int = 2,
) -> list:
    """EVERY slab depth BX the VMEM gate admits for this shape, largest
    first — the ``bx`` axis of the measured autotuner's candidate space
    (``tune/candidates``). :func:`pick_block_planes` picks one of these
    by a fixed preference order; which one actually runs fastest is a
    DMA-pipeline question the analytic gate cannot answer, so the tuner
    measures the alternatives (``GS_BX`` pins the winner)."""
    budget = _vmem_budget()
    if mid_itemsize is None:
        mid_itemsize = max(itemsize, 4)
    out = [bx for bx in range(nx, 0, -1)
           if _slab_fits(bx, nx, ny, nz, itemsize, fuse, mid_itemsize,
                         budget, n_fields)]
    return out


def pick_block_planes(
    nx: int, ny: int, nz: int, itemsize: int, fuse: int = 1,
    mid_itemsize: int = None, n_fields: int = 2,
) -> int:
    """Largest slab depth BX (dividing nx) whose double-buffered
    per-field in/mid/out scratch fits the VMEM budget; 0 if even BX=1
    does not fit. ``fuse`` is the temporal-blocking depth (input halo
    width); ``mid_itemsize`` the mid-buffer element size (defaults to
    the conservative f32 floor; bf16-mid configs pass 2); ``n_fields``
    the model's field count.
    ``GS_BX`` forces a specific depth (benchmark sweeps) when it divides
    ``nx`` and fits; otherwise it is ignored with a warning."""
    budget = _vmem_budget()
    if mid_itemsize is None:
        mid_itemsize = max(itemsize, 4)

    def fits(bx: int) -> bool:
        return _slab_fits(bx, nx, ny, nz, itemsize, fuse, mid_itemsize,
                          budget, n_fields)

    override = env_str("GS_BX", "")
    if override:
        try:
            bx = int(override)
        except ValueError:
            bx = -1
        if bx > 0 and fits(bx):
            return bx
        _warn_once(
            f"GS_BX={override!r} does not fit "
            f"(nx={nx}, fuse={fuse}); using automatic slab depth"
        )
    # Candidate order: the pipelined power-of-two depths first (slab
    # overlap needs nblocks >= 2), then the whole block as a last
    # resort — the only option with a fused chain when nx is odd (the
    # uneven-pod pad shapes, e.g. local nx = 9 for L=26 over 3), where
    # no power-of-two divides nx but a single slab has no divisibility
    # or bx >= fuse constraint at all.
    for bx in (16, 8, 4, 2, 1, nx):
        if fits(bx):
            return bx
    return 0


def mid_itemsize_for(dtype) -> int:
    """Mid-buffer element size for dispatch-time feasibility checks —
    reads ``GS_MID_BF16`` exactly the way :func:`fused_step` does, so
    the dispatch-side depth cap agrees with the kernel-side fit (bf16
    mids halve the mid scratch and can admit a deeper chain)."""
    dt = jnp.dtype(dtype)
    mid_bf16 = env_raw("GS_MID_BF16") == "1" and dt == jnp.float32
    return jnp.dtype(_mid_store_dtype(dt, mid_bf16)).itemsize


def mosaic_gate_reason(local, itemsize: int):
    """Why this local block can NEVER run the fused kernel on TPU, or
    None when it can (subject to the VMEM checks below). ONE statement
    of the dispatch-level gates in :func:`fused_step` (f64 fallback,
    128-lane tiling of the z extent) shared with the ICI model's Auto
    dispatch (``parallel/icimodel.py``) — the model must never promise
    a schedule the kernel would silently decline. The y-sublane gate is
    not here: chain operands arrive y-extended and sublane-rounded, and
    a 128-aligned cubic block satisfies it by construction. Model-side
    feasibility (can the reaction be inlined at all?) is
    ``kernelgen.generation_gate_reason`` — orthogonal to this shape
    gate."""
    nz = local[2]
    if itemsize == 8:
        return "float64 runs the Pallas kernel's XLA fallback on TPU"
    if nz % 128:
        return (f"local z extent {nz} misses Mosaic's 128-lane "
                "alignment; the Pallas kernel would fall back to XLA")
    return None


def max_feasible_fuse(nx: int, ny: int, nz: int, itemsize: int,
                      fuse: int, mid_itemsize: int = None,
                      n_fields: int = 2) -> int:
    """Deepest chain depth <= ``fuse`` whose slab scratch fits the VMEM
    budget (:func:`pick_block_planes` > 0); 0 if not even ``fuse=1``
    fits. Dispatch-time guard for the in-kernel chain modes: the
    exchange width must match a depth Mosaic can actually serve, or the
    kernel silently degrades to its XLA fallback (e.g. the v5p-16 pod
    shape 64x512x512 f32 fits fuse=3 at bx=4 but not fuse=5)."""
    for k in range(fuse, 0, -1):
        if pick_block_planes(nx, ny, nz, itemsize, k,
                             mid_itemsize=mid_itemsize,
                             n_fields=n_fields) > 0:
            return k
    return 0


def max_feasible_fuse_ypad(nx: int, ny: int, nz: int, itemsize: int,
                           fuse: int, sublane: int = 8,
                           mid_itemsize: int = None,
                           n_fields: int = 2) -> int:
    """:func:`max_feasible_fuse` for the xy-chain mode, where the
    operand arrives y-extended: depth k widens every plane to
    ``ny + 2k`` rows rounded up to the sublane tile, so feasibility
    must be judged on the padded shape."""
    for k in range(fuse, 0, -1):
        ny_ext = ny + 2 * k
        ny_ext += (-ny_ext) % sublane
        if pick_block_planes(nx, ny_ext, nz, itemsize, k,
                             mid_itemsize=mid_itemsize,
                             n_fields=n_fields) > 0:
            return k
    return 0


def max_feasible_chain_depth(local, dims, itemsize: int, depth: int,
                             sublane: int = 8, mid_itemsize: int = None,
                             n_fields: int = 2) -> int:
    """Deepest in-kernel chain depth <= ``depth`` the SHARDED chain
    dispatch for mesh ``dims`` can serve on local block ``local`` —
    the runner's own geometry caps (x-chain: depth <= nx; xy-chain:
    depth <= nx, ny, and nz // 2 when z is sharded) composed with the
    VMEM slab ledger (:func:`max_feasible_fuse` /
    :func:`max_feasible_fuse_ypad`). The ONE statement of chain-depth
    feasibility shared by the s-step ``halo_depth`` gate
    (``simulation.py``) and the autotune shortlist
    (``tune/candidates.py``), so neither ever promises a depth the
    kernel would decline; 0 when not even depth 1 fits."""
    nx, ny, nz = local
    if dims[1] == 1 and dims[2] == 1:
        cap = min(depth, nx)
        if cap < 1:
            return 0
        return max_feasible_fuse(nx, ny, nz, itemsize, cap,
                                 mid_itemsize=mid_itemsize,
                                 n_fields=n_fields)
    cap = min(depth, nx, ny)
    if dims[2] > 1:
        cap = min(cap, nz // 2)
    if cap < 1:
        return 0
    return max_feasible_fuse_ypad(nx, ny, nz, itemsize, cap, sublane,
                                  mid_itemsize=mid_itemsize,
                                  n_fields=n_fields)


def _kernel_pm1(bits, dtype):
    """uint32 bits -> uniform [-1, 1), Mosaic form of
    ``noise.bits_to_pm1`` (``pltpu.bitcast`` instead of lax bitcast)."""
    f12 = pltpu.bitcast(
        jnp.uint32(0x3F800000) | (bits >> jnp.uint32(9)), jnp.float32
    )
    return (f12 * 2.0 - 3.0).astype(dtype)


def _edge_masks(ny, nz):
    """The four wrapped-row/column boolean masks for a (n, ny, nz)
    window, shaped to broadcast over any plane count n — computed once
    per kernel invocation and shared across fields and stages (an
    iota + compare per ``_shifted`` call is pure VPU overhead in a
    stage-compute-bound pass)."""
    iy = lax.broadcasted_iota(jnp.int32, (1, ny, 1), 1)
    iz = lax.broadcasted_iota(jnp.int32, (1, 1, nz), 2)
    return {
        (1, 1): iy == 0,
        (1, -1): iy == ny - 1,
        (2, 1): iz == 0,
        (2, -1): iz == nz - 1,
    }


def _shifted(block, axis, shift, edge_value, masks):
    """Neighbor values along a VMEM-resident axis (1 = y, 2 = z):
    circular shift with the wrapped boundary row/column replaced by
    ``edge_value`` (a scalar boundary constant or a broadcastable face
    slab); ``masks`` are the shared precomputed edge masks
    (:func:`_edge_masks`)."""
    n = block.shape[axis]
    # roll(x, s)[i] = x[i - s]; a backward (-1) shift is circularly n-1.
    rolled = pltpu.roll(block, shift if shift > 0 else n - 1, axis)
    return jnp.where(masks[(axis, shift)], edge_value, rolled)


def _make_kernel(spec, nblocks, bx, nx, ny, nz, dtype, use_noise,
                 with_faces, fuse, mid_bf16=False):
    """Build the fused single-program kernel body for ``spec``'s model;
    see module docstring. The pipeline is model-independent; the stage
    compute TRACE-INLINES ``spec.reaction`` over the window interiors
    (ops/kernelgen.py), with per-field boundary constants from
    ``spec.boundaries``.

    Two faces modes: ``with_faces`` with ``fuse == 1`` takes the full
    6-per-field face tuple of a 3D-sharded block; ``with_faces`` with
    ``fuse >= 2`` is the 1D-x-sharded temporal chain — ONLY the
    2-per-field x faces, each ``fuse`` planes wide, feeding the
    in-kernel k-stage chain (y/z stay global frozen boundaries), with
    mid-stage out-of-domain pinning keyed on GLOBAL x coordinates so
    interior shards recompute the neighbor ring instead of freezing it.

    Ref order, for an n-field model (mid scratch present only when
    ``fuse >= 2``):
      params(SMEM f32[n_params]; f64 for f64 fields — never bf16,
      Mosaic SMEM support for bf16 scalars is shaky),
      seeds(SMEM i32[7] = key lo, key hi, step, x/y/z global offset,
      global row length L — the position-keyed noise coordinates),
      f_0 .. f_{n-1} (ANY/HBM, (nx, ny, nz)),
      [f_0_xlo, f_0_xhi, .., f_{n-1}_xhi (ANY, (fuse, ny, nz)),
       fuse==1 only: per-field y faces (VMEM, (nx, 1, nz)),
                     per-field z faces (VMEM, (nx, ny, 1))],
      f_0_out .. f_{n-1}_out (ANY/HBM),
      scratch: in_0 .. in_{n-1} (VMEM (2, bx+2*fuse, ny, nz)),
               [mid_0 .. mid_{n-1} (VMEM (nbuf, bx+2(fuse-1), ny, nz))],
               out_0 .. out_{n-1} (VMEM (2, bx, ny, nz)),
               in_sems (DMA (2, n)), out_sems (DMA (2, n)),
               [face_sems (DMA (2, n, 2))]
    """
    halo = fuse
    win_n = bx + 2 * halo
    x_chain = with_faces and fuse >= 2
    n_f = spec.n_fields

    def kernel(params, seeds, *rest):
        rest = list(rest)

        def take(k):
            out = rest[:k]
            del rest[:k]
            return out

        field_refs = take(n_f)
        x_faces = y_faces = z_faces = None
        if with_faces:
            xf = take(2 * n_f)
            x_faces = [(xf[2 * i], xf[2 * i + 1]) for i in range(n_f)]
            if not x_chain:
                yf = take(2 * n_f)
                zf = take(2 * n_f)
                y_faces = [(yf[2 * i], yf[2 * i + 1]) for i in range(n_f)]
                z_faces = [(zf[2 * i], zf[2 * i + 1]) for i in range(n_f)]
        field_outs = take(n_f)
        ins = take(n_f)
        mids = take(n_f) if fuse >= 2 else None
        out_scr = take(n_f)
        in_sems, out_sems = take(2)
        face_sems = rest[0] if with_faces else None

        # cdt == dtype except bf16, which computes in f32 (_compute_dtype).
        cdt = _compute_dtype(dtype)
        bvs = tuple(jnp.asarray(b, cdt) for b in spec.boundaries)
        # Params land in SMEM at >= f32 (see ref order above); rebuild
        # the model's params namedtuple with every scalar cast to the
        # compute dtype, so the inlined reaction sees exactly the
        # argument types the XLA path feeds it.
        p_c = spec.params_cls(
            *(params[j].astype(cdt)
              for j in range(len(spec.param_fields)))
        )
        dt = p_c.dt
        noise = p_c.noise
        inv_six = jnp.asarray(1.0 / 6.0, cdt)

        def slab_io(slot, b, start):
            """Start (or wait for) all input DMAs of slab ``b``.

            An interior slab reads planes [b*bx-halo, b*bx+bx+halo); the
            first and last slabs read ``halo`` planes fewer (the missing
            ghost plane is filled from the boundary constant or the x
            halo face; for fuse=2 the outermost missing plane is filled
            with the boundary too — its value is masked out of stage A,
            the fill just keeps scratch deterministic). Descriptors are
            constructed lazily inside their branch — an unused
            descriptor is an error.
            """

            def go(make):
                d = make()
                (d.start if start else d.wait)()

            for tag in range(n_f):
                field_ref, scr, bv = field_refs[tag], ins[tag], bvs[tag]
                sem = in_sems.at[slot, tag]
                if nblocks == 1:
                    go(lambda: pltpu.make_async_copy(
                        field_ref, scr.at[slot, pl.ds(halo, bx)], sem))
                else:
                    lo, hi = b == 0, b == nblocks - 1

                    @pl.when(lo)
                    def _():
                        go(lambda: pltpu.make_async_copy(
                            field_ref.at[pl.ds(0, bx + halo)],
                            scr.at[slot, pl.ds(halo, bx + halo)], sem))

                    @pl.when(hi)
                    def _():
                        go(lambda: pltpu.make_async_copy(
                            field_ref.at[pl.ds(b * bx - halo, bx + halo)],
                            scr.at[slot, pl.ds(0, bx + halo)], sem))

                    @pl.when(jnp.logical_not(lo | hi))
                    def _():
                        go(lambda: pltpu.make_async_copy(
                            field_ref.at[pl.ds(b * bx - halo, win_n)],
                            scr.at[slot], sem))

                # Ghost x-planes on the slab's outer side(s): DMA'd from
                # the face operands (``halo`` planes wide — 1 for the
                # 3D-sharded mode, ``fuse`` for the x-chain mode), or
                # filled with the frozen boundary constant.
                for which, cond in ((0, b == 0), (1, b == nblocks - 1)):
                    if with_faces:
                        xref = x_faces[tag][which]
                        plane = 0 if which == 0 else bx + halo

                        @pl.when(cond)
                        def _():
                            go(lambda: pltpu.make_async_copy(
                                xref,
                                scr.at[slot, pl.ds(plane, halo)],
                                face_sems.at[slot, tag, which]))
                    elif start:
                        planes = (
                            range(halo) if which == 0
                            else range(bx + halo, win_n)
                        )

                        @pl.when(cond)
                        def _():
                            for p in planes:
                                scr[slot, p] = jnp.full((ny, nz), bv, dtype)

        def out_dma(slot, b, tag):
            return pltpu.make_async_copy(
                out_scr[tag].at[slot],
                field_outs[tag].at[pl.ds(b * bx, bx)],
                out_sems.at[slot, tag],
            )

        masks = _edge_masks(ny, nz)

        def lap(win, c, edges):
            """7-point Laplacian over the window interior ``c``
            (``Common.jl:13-18``), in the same ``sum * (1/6) - center``
            form and neighbor order as ``stencil.laplacian`` — the
            per-cell divide of the literal ``(sum - 6c)/6`` was
            measurable VPU time in the fused pass."""
            n = c.shape[0]
            ylo, yhi, zlo, zhi = edges
            return (
                win[0:n] + win[2:n + 2]
                + _shifted(c, 1, 1, ylo, masks)
                + _shifted(c, 1, -1, yhi, masks)
                + _shifted(c, 2, 1, zlo, masks)
                + _shifted(c, 2, -1, zhi, masks)
            ) * inv_six - c

        def noise_block(step_idx, g0, w, iota_w=None):
            """Pre-scaled noise for ``w`` consecutive local x-planes
            starting at ``g0`` — one 3D evaluation of the identical
            per-plane stream (the (w,1,1) seed vector broadcasts into
            the (1,ny,nz) cell counter exactly as the scalar per-plane
            seed does), replacing w unrolled plane hashes + stores.
            ``iota_w`` lets the caller share its plane iota."""
            if iota_w is None:
                iota_w = lax.broadcasted_iota(jnp.int32, (w, 1, 1), 0)
            gx = seeds[3] + g0 + iota_w
            seed = plane_seed(seeds[0], seeds[1], step_idx, gx)
            iy = (lax.broadcasted_iota(jnp.uint32, (1, ny, 1), 1)
                  + _u32(seeds[4]))
            iz = (lax.broadcasted_iota(jnp.uint32, (1, 1, nz), 2)
                  + _u32(seeds[5]))
            bits = block_bits(seed, iy, iz, seeds[6])
            return noise * _kernel_pm1(bits, cdt)

        const_edges = tuple((bv,) * 4 for bv in bvs)

        def react(wins, edges, step_idx, g0, w, iota_w=None):
            """One stage of every field: slice the window interiors,
            form the Laplacians, and trace-inline ``spec.reaction``
            over them. Noise is passed pre-scaled INTO the reaction,
            exactly like the XLA path (``stencil.reaction_update``), so
            the two kernel languages agree to float roundoff even with
            noise on — and, for Gray-Scott, the inlined program is
            operation-for-operation the old hand-written kernel."""
            m = wins[0].shape[0] - 2
            centers = tuple(w_[1:m + 1] for w_ in wins)
            laps = tuple(
                lap(w_, c, e) for w_, c, e in zip(wins, centers, edges)
            )
            if use_noise:
                noise_term = noise_block(step_idx, g0, w, iota_w)
            else:
                noise_term = jnp.asarray(0.0, cdt)
            derivs = spec.reaction(centers, laps, noise_term, p_c)
            return centers, derivs

        def compute1(slot, b):
            wins = tuple(ins[i][slot].astype(cdt) for i in range(n_f))
            if with_faces:
                def rows(f):
                    return f[pl.ds(b * bx, bx)].astype(cdt)

                edges = tuple(
                    (rows(y_faces[i][0]), rows(y_faces[i][1]),
                     rows(z_faces[i][0]), rows(z_faces[i][1]))
                    for i in range(n_f)
                )
            else:
                edges = const_edges
            centers, derivs = react(wins, edges, seeds[2], b * bx, bx)
            for i in range(n_f):
                out_scr[i][slot] = (
                    centers[i] + derivs[i] * dt
                ).astype(dtype)

        def compute_k(slot, b):
            """``fuse``-stage temporal blocking: stage s advances step
            n+1+s on a window that shrinks by one plane per side per
            stage — the outermost recomputed ring planes reproduce their
            owner slab's values exactly (same inputs, position-keyed
            noise), so the chain equals ``fuse`` single steps bitwise.
            Stage 0 reads the (bx+2*fuse)-plane input slab; stages
            0..fuse-2 write ping-pong mid buffers with out-of-domain
            planes pinned to the frozen boundary value; the last stage
            writes the bx output planes."""
            k = fuse
            if x_chain:
                # xy-chain support: when the operand is y-extended (its
                # rows cover global [seeds[4], seeds[4]+ny), which may
                # start negative or cross L), mid-stage rows outside the
                # GLOBAL domain pin to the boundary value exactly like
                # out-of-domain x planes — while in-domain rows of the
                # y pad ring-recompute the y neighbor's values, the
                # property that lets the chain cross a y shard boundary.
                # In the 1D x-chain (block spans full L in y) every row
                # is in-domain and this mask is all-true.
                gy = (lax.broadcasted_iota(jnp.int32, (1, ny, 1), 1)
                      + seeds[4])
                valid_y = (gy >= 0) & (gy < seeds[6])
                # z likewise (non-divisible L stores pad cells past the
                # true domain inside the block; they must read back as
                # the boundary value at every stage). All-true for
                # divisible L.
                gz = (lax.broadcasted_iota(jnp.int32, (1, 1, nz), 2)
                      + seeds[5])
                valid_yz = valid_y & ((gz >= 0) & (gz < seeds[6]))
            for s in range(k):
                w_out = bx + 2 * (k - 1 - s)
                if s == 0:
                    wins = tuple(
                        ins[i][slot].astype(cdt) for i in range(n_f)
                    )
                else:
                    # Mid buffers hold _mid_store_dtype values (bf16 for
                    # bf16 fields / GS_MID_BF16); widen to the compute
                    # dtype BEFORE any roll (no 16-bit rotate path).
                    buf = (s - 1) % 2 if k > 2 else 0
                    wins = tuple(
                        mids[i][buf, pl.ds(0, w_out + 2)].astype(cdt)
                        for i in range(n_f)
                    )
                step_s = seeds[2] + s
                if s == k - 1:
                    centers, derivs = react(
                        wins, const_edges, step_s, b * bx, bx
                    )
                    for i in range(n_f):
                        out_scr[i][slot] = (
                            centers[i] + derivs[i] * dt
                        ).astype(dtype)
                else:
                    g0 = b * bx - (k - 1 - s)
                    iota_w = lax.broadcasted_iota(
                        jnp.int32, (w_out, 1, 1), 0
                    )
                    centers, derivs = react(
                        wins, const_edges, step_s, g0, w_out, iota_w
                    )
                    buf = s % 2 if k > 2 else 0
                    # Ring planes outside the domain stay at the frozen
                    # boundary value. In the x-chain (1D-sharded) mode
                    # "domain" is the GLOBAL grid: interior shards own
                    # no global edge, so their rings recompute neighbor
                    # values (from the face data) instead of freezing —
                    # the bitwise ring-recompute property that makes
                    # fuse=k equal k exchanged single steps.
                    gx = g0 + iota_w
                    if x_chain:
                        gxg = seeds[3] + gx
                        valid = ((gxg >= 0) & (gxg < seeds[6])) & valid_yz
                    else:
                        valid = (gx >= 0) & (gx < nx)

                    ms = _mid_store_dtype(dtype, mid_bf16)
                    if ms == cdt:
                        # Exact f32/f64 path: mid stages round through
                        # the FIELD dtype so fuse=k stays bitwise equal
                        # to k single steps (each of which stores the
                        # field).
                        def _store(x):
                            return x.astype(dtype).astype(cdt)
                    else:
                        # bf16 mid storage: the astype IS the rounding
                        # (bitwise-identical to the old round-trip for
                        # bf16 fields; the opt-in approximation for
                        # f32 + GS_MID_BF16).
                        def _store(x):
                            return x.astype(ms)

                    for i in range(n_f):
                        mids[i][buf, pl.ds(0, w_out)] = _store(
                            jnp.where(
                                valid, centers[i] + derivs[i] * dt, bvs[i]
                            )
                        )

        compute = compute_k if fuse >= 2 else compute1

        # ---- pipeline: prologue, steady-state loop, epilogue ----
        # Buffer count matches the scratch allocation: single-slab runs
        # carry one slot (slot/nxt stay 0 — the prefetch branch never
        # fires), multi-slab runs double-buffer.
        nio = 1 if nblocks == 1 else 2
        slab_io(0, jnp.int32(0), start=True)

        def body(b, _):
            slot = lax.rem(b, nio)
            nxt = lax.rem(b + 1, nio)

            @pl.when(b + 1 < nblocks)
            def _():
                slab_io(nxt, b + 1, start=True)

            slab_io(slot, b, start=False)

            @pl.when(b >= 2)
            def _():
                for tag in range(n_f):
                    out_dma(slot, b - 2, tag).wait()

            compute(slot, b)
            for tag in range(n_f):
                out_dma(slot, b, tag).start()
            return 0

        lax.fori_loop(0, nblocks, body, 0)

        for tail_b in (nblocks - 2, nblocks - 1):
            if tail_b >= 0:
                slot = tail_b % nio
                b = jnp.int32(tail_b)
                for tag in range(n_f):
                    out_dma(slot, b, tag).wait()

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("spec", "bx", "use_noise", "interpret", "fuse",
                     "detect_races", "mid_bf16"),
)
def _fused_call(fields, params_vec, seeds, faces, *, spec, bx, use_noise,
                interpret, fuse, detect_races=False, mid_bf16=False):
    n_f = spec.n_fields
    nx, ny, nz = fields[0].shape
    dtype = fields[0].dtype
    nblocks = nx // bx
    with_faces = faces is not None

    any_spec = pl.BlockSpec(memory_space=pl.ANY)
    vmem_spec = pl.BlockSpec(memory_space=pltpu.VMEM)
    smem_spec = pl.BlockSpec(memory_space=pltpu.SMEM)

    in_specs = [smem_spec, smem_spec] + [any_spec] * n_f
    operands = [params_vec, seeds, *fields]
    if with_faces:
        # x faces ride DMA from HBM (ANY); y/z faces (full-faces mode
        # only) are small -> VMEM. The 2-per-field tuple is the x-chain
        # mode: fuse-wide x slabs, no y/z faces.
        in_specs += [any_spec] * (2 * n_f)
        if len(faces) == 6 * n_f:
            in_specs += [vmem_spec] * (4 * n_f)
        operands += list(faces)

    # Single-slab runs (nblocks == 1) only ever use buffer slot 0;
    # allocating the second slot would double the scratch for nothing
    # (pick_block_planes budgets the same way).
    nio = 1 if nblocks == 1 else 2
    scratch_shapes = [
        pltpu.VMEM((nio, bx + 2 * fuse, ny, nz), dtype)
        for _ in range(n_f)
    ]
    if fuse >= 2:
        nbuf, mid_planes = _mid_layout(bx, fuse)
        mid_dtype = _mid_store_dtype(dtype, mid_bf16)
        scratch_shapes += [
            pltpu.VMEM((nbuf, mid_planes, ny, nz), mid_dtype)
            for _ in range(n_f)
        ]
    scratch_shapes += [
        pltpu.VMEM((nio, bx, ny, nz), dtype) for _ in range(n_f)
    ]
    scratch_shapes += [
        pltpu.SemaphoreType.DMA((nio, n_f)),
        pltpu.SemaphoreType.DMA((nio, n_f)),
    ]
    if with_faces:
        scratch_shapes.append(pltpu.SemaphoreType.DMA((nio, n_f, 2)))

    out = pl.pallas_call(
        _make_kernel(
            spec, nblocks, bx, nx, ny, nz, dtype, use_noise, with_faces,
            fuse, mid_bf16,
        ),
        in_specs=in_specs,
        out_specs=[any_spec] * n_f,
        out_shape=[
            jax.ShapeDtypeStruct((nx, ny, nz), dtype) for _ in range(n_f)
        ],
        scratch_shapes=scratch_shapes,
        # Mosaic's default scoped-VMEM cap is well below the slab budget;
        # without an explicit limit L=256 f32 OOMs at kernel-stack
        # allocation even though the scratch fits physical VMEM.
        compiler_params=_COMPILER_PARAMS(
            vmem_limit_bytes=_vmem_budget() + 16 * 1024 * 1024,
        ),
        # The TPU-semantics interpreter (not the generic HLO one) models
        # SMEM/semaphores/DMA on CPU for tests. ``detect_races`` is a
        # static jit argument so toggling it cannot be swallowed by the
        # jit cache (it is part of the cache key).
        interpret=_interpret_arg(detect_races) if interpret else False,
    )(*operands)
    return tuple(out)


def fused_step(fields, params, seeds, faces=None, *, spec, use_noise=True,
               allow_interpret=True, fuse=1, detect_races=False,
               offsets=None, row=None):
    """``fuse`` fused steps of ``spec``'s model on interior-shaped
    fields (an n-tuple of (nx, ny, nz) arrays in declaration order).

    ``spec`` is the model's generated-kernel spec
    (``kernelgen.get_spec(model)``) — a static argument carrying the
    reaction to inline, the per-field boundary constants, and the
    parameter layout. ``seeds`` is an int32[3] vector (PRNG key data
    lo/hi, absolute step index) keying the in-kernel noise stream;
    ``offsets`` (optional, int32[3]) is the block's global origin and
    ``row`` the global grid side L — together they make the noise
    position-keyed across shard layouts (defaults: zero origin, row =
    local nz — the single-block case). ``faces`` takes one of two
    forms (n = field count):

    * 6n-tuple (fuse=1 only) — resolved halo faces of a 3D-sharded
      block, axis-major then field-major then lo/hi, e.g. for two
      fields u, v: ``(u_xlo, u_xhi, v_xlo, v_xhi, u_ylo, u_yhi, v_ylo,
      v_yhi, u_zlo, u_zhi, v_zlo, v_zhi)`` with x faces shaped
      (1, ny, nz), y faces (nx, 1, nz), z faces (nx, ny, 1);
    * 2n-tuple ``(f0_xlo, f0_xhi, f1_xlo, f1_xhi, ...)`` with
      fuse >= 2, each shaped (fuse, ny, nz) — the x-sharded **x-chain**
      mode: the fuse-wide x slabs feed the in-kernel temporal chain
      across the shard boundary (z stays a global frozen boundary, and
      mid-stage ring pinning uses GLOBAL x *and y* coordinates so
      interior shards recompute the neighbor ring bitwise instead of
      freezing it).
      The **xy-chain** is the same mode with a y-extended operand
      (``parallel/temporal.xy_chain``): rows cover global
      ``[offsets[1], offsets[1] + ny)`` including a fuse-deep exchanged
      y halo (plus sublane-alignment filler rows at the high end), so
      the chain also crosses y shard boundaries — in-domain pad rows
      ring-recompute the y neighbor's values, out-of-domain rows pin to
      the boundary constant, and the caller slices the y interior from
      the result. y is the sublane dim (8/16-granularity tiling), which
      is what makes this extension Mosaic-cheap, unlike the 128-lane z.

    ``fuse=k`` temporal blocking advances k steps per HBM pass
    (single- or multi-block; with faces only in the 2n-tuple x-chain
    form). ``detect_races`` (interpret
    mode only) runs the TPU interpreter's DMA/compute race detector; it
    is a static jit argument, so toggling it recompiles rather than
    reusing a stale cache entry.

    Noise comes from *inside* the kernel, drawn from the shared
    position-keyed stream (``ops/noise.py``) — the same code path and
    the same values on hardware and under the interpreter, and the same
    stream as the XLA kernel.

    Returns the updated field tuple. Falls back to the XLA kernel when
    Mosaic cannot serve the dtype (f64 on TPU), the shape would
    overflow VMEM, or — off TPU with ``allow_interpret=False`` — when
    the caller is inside ``shard_map``: the interpret-mode TPU model
    keeps *global* semaphore state, and concurrent per-shard
    interpreter instances deadlock each other (reproduced at
    nblocks >= 2 on an 8-device CPU mesh). The sharded kernel path is
    instead covered by the single-device with-faces interpret test plus
    the TPU hardware tests.
    """
    fields = tuple(fields)
    n_f = spec.n_fields
    if len(fields) != n_f:
        raise ValueError(
            f"model {spec.name!r} declares {n_f} field(s); "
            f"got {len(fields)}"
        )
    x_chain = faces is not None and len(faces) == 2 * n_f
    if faces is not None and not x_chain and len(faces) != 6 * n_f:
        raise ValueError(
            f"faces for the {n_f}-field model {spec.name!r} must be the "
            f"{2 * n_f}-tuple x-chain form or the {6 * n_f}-tuple 3D "
            f"form; got {len(faces)}"
        )
    if fuse > 1 and faces is not None and not x_chain:
        raise ValueError(
            "temporal blocking with faces requires the x-chain mode "
            "(1D-sharded, two fuse-wide x faces per field); the "
            "full-faces 3D mode is fuse=1 only"
        )
    if x_chain and fuse < 2:
        raise ValueError("the x-chain faces mode requires fuse >= 2")
    nx, ny, nz = fields[0].shape
    dtype = fields[0].dtype
    on_tpu = jax.default_backend() == "tpu"
    seeds = jnp.asarray(seeds, jnp.int32)
    if offsets is None:
        offsets = jnp.zeros((3,), jnp.int32)
    offsets = jnp.asarray(offsets, jnp.int32)
    row = jnp.asarray(nz if row is None else row, jnp.int32)
    if x_chain:
        for f in faces:
            if f.shape != (fuse, ny, nz):
                raise ValueError(
                    f"x-chain faces must be ({fuse}, {ny}, {nz}); "
                    f"got {f.shape}"
                )

    # GS_MID_BF16=1: store f32 configs' mid buffers as bf16 — an opt-in
    # speed/accuracy trade for benchmark A/B (see _mid_store_dtype; the
    # envelope probe showed mid-buffer VMEM movement is the kernel's
    # binding cost). bf16 fields get bf16 mids unconditionally (bitwise
    # identical to the old rounded f32 storage).
    mid_bf16 = (
        env_raw("GS_MID_BF16") == "1" and dtype == jnp.float32
    )
    mid_item = jnp.dtype(_mid_store_dtype(dtype, mid_bf16)).itemsize
    bx = pick_block_planes(nx, ny, nz, dtype.itemsize, fuse,
                           mid_itemsize=mid_item, n_fields=n_f)
    if bx == 0 and fuse > 1 and not x_chain:
        # The requested depth overflows VMEM for this shape, but a
        # shallower chain may still fit — step down rather than losing
        # the Pallas kernel entirely (large grids are exactly where the
        # kernel matters most).
        shallower = max_feasible_fuse(nx, ny, nz, dtype.itemsize,
                                      fuse - 1, mid_itemsize=mid_item,
                                      n_fields=n_f)
        if shallower:
            done = 0
            while done < fuse:
                k = min(shallower, fuse - done)
                fields = fused_step(
                    fields, params,
                    seeds.at[2].add(done) if done else seeds, faces,
                    spec=spec, use_noise=use_noise,
                    allow_interpret=allow_interpret,
                    fuse=k, detect_races=detect_races,
                    offsets=offsets, row=row,
                )
                done += k
            return fields
    # Mosaic tiles VMEM as (sublane, 128-lane) over the trailing two dims
    # and rejects the kernel's sliced scratch views unless the lane dim is
    # a whole number of tiles (measured on v5e: L=64 f32 fails "Slice
    # shape along dimension 2 must be aligned to tiling (128)"; L=128
    # compiles). Unaligned shapes take the XLA kernel, which handles any L.
    sublane = 16 if dtype == jnp.bfloat16 else 8
    aligned = nz % 128 == 0 and ny % sublane == 0
    if on_tpu and not aligned:
        _warn_once(
            f"Pallas kernel requested but the local grid "
            f"({nx}x{ny}x{nz}, {dtype}) is not Mosaic-tile-aligned "
            f"(needs nz % 128 == 0 and ny % {sublane} == 0); "
            "running the XLA kernel instead"
        )
    if (dtype == jnp.float64 and on_tpu) or bx == 0 or (
        on_tpu and not aligned
    ) or (
        not on_tpu and not allow_interpret
    ):
        if x_chain:
            if on_tpu and bx == 0:
                # On hardware this is a silent perf cliff, not a
                # correctness issue — make it visible (the module's
                # stated invariant: benchmark users must see when
                # "Pallas" is measuring the XLA kernel). Callers should
                # cap the chain depth with max_feasible_fuse so the
                # exchange width matches a depth Mosaic can serve.
                _warn_once(
                    f"x-chain fuse={fuse} does not fit VMEM for local "
                    f"grid {nx}x{ny}x{nz} ({dtype}); running the XLA "
                    "x-chain fallback — cap the depth with "
                    "max_feasible_fuse"
                )
            return _xla_xchain_fallback(
                fields, params, seeds, faces, spec=spec, fuse=fuse,
                use_noise=use_noise, offsets=offsets, row=row,
            )
        for s in range(fuse):
            fields = _xla_fallback(
                fields, params, seeds.at[2].add(s) if s else seeds,
                faces, spec=spec, use_noise=use_noise, offsets=offsets,
                row=row,
            )
        return fields

    # SMEM scalars stay >= f32 (bf16 scalars in SMEM are a shaky Mosaic
    # combination); the kernel casts them to the field dtype at use.
    smem_dtype = jnp.promote_types(dtype, jnp.float32)
    params_vec = jnp.stack(
        [getattr(params, f_) for f_ in spec.param_fields]
    ).astype(smem_dtype)
    seeds7 = jnp.concatenate([seeds, offsets, row[None]])
    return _fused_call(
        fields, params_vec, seeds7,
        tuple(faces) if faces is not None else None,
        spec=spec, bx=bx, use_noise=use_noise, interpret=not on_tpu,
        fuse=fuse, detect_races=detect_races and not on_tpu,
        mid_bf16=mid_bf16,
    )


def _xla_xchain_fallback(fields, params, seeds, faces, *, spec, fuse,
                         use_noise, offsets, row):
    """XLA form of the in-kernel x-chain (1D-sharded temporal blocking):
    ``fuse`` stages on an x-extended window seeded by the fuse-wide x
    faces, with z frozen at the global boundary and out-of-global-domain
    x planes AND y rows pinned per stage — the y pinning is the xy-chain
    mode, where the operand arrives y-extended (rows covering global
    [offsets[1], offsets[1]+ny)) and in-domain pad rows ring-recompute
    the y neighbor's values (it is an all-true no-op for the 1D x-chain,
    whose block spans the full L in y). Bitwise-equal to the Mosaic
    chain for f32/f64 (same op order, same position-keyed noise) —
    the CPU-mesh / f64 / lane-misaligned path of the same design."""
    n_f = spec.n_fields
    nx, ny, nz = fields[0].shape
    dtype = fields[0].dtype
    # The chain carries the STORAGE dtype between stages, but the
    # params carry the compute posture: under bf16_f32acc their f32
    # would promote the whole update (a carry-dtype crash in
    # run_chain_rounds), so each stage accumulates in the params'
    # dtype and rounds back. In the matched postures (f32/f64,
    # pure-bf16) this resolves to the no-cast fast path, keeping the
    # fallback bitwise-equal to single-device stepwise Plain.
    pdt = jnp.asarray(params.noise).dtype
    acc = None if pdt == dtype else pdt
    k = fuse
    bvs = tuple(jnp.asarray(b, dtype) for b in spec.boundaries)
    wins = [
        jnp.concatenate([faces[2 * i], fields[i], faces[2 * i + 1]],
                        axis=0)
        for i in range(n_f)
    ]
    gy = offsets[1] + jnp.arange(ny)
    valid_y = ((gy >= 0) & (gy < row))[None, :, None]
    gz = offsets[2] + jnp.arange(nz)
    valid_yz = valid_y & ((gz >= 0) & (gz < row))[None, None, :]

    def pad_yz(x, bv):
        return jnp.pad(
            x, ((0, 0), (1, 1), (1, 1)), constant_values=bv
        )

    for s in range(k):
        m_out = k - 1 - s
        w_out = nx + 2 * m_out
        if use_noise:
            offs_w = jnp.stack(
                [offsets[0] - m_out, offsets[1], offsets[2]]
            )
            unit = uniform_pm1_block(
                seeds[:2], seeds[2] + s, offs_w, (w_out, ny, nz), row,
                dtype,
            )
            nz_field = params.noise * unit
        else:
            nz_field = jnp.asarray(0.0, dtype)
        wins = list(stencil.reaction_update(
            tuple(pad_yz(w, bv) for w, bv in zip(wins, bvs)), nz_field,
            params, spec.model,
            compute_dtype=acc,
        ))
        if s == k - 1:
            # Mirror the kernel: the final stage writes its output
            # unpinned (out-of-domain y pad rows hold computed ring
            # garbage in both implementations; callers slice the y
            # interior). In the 1D x-chain the output is entirely
            # in-domain and this changes nothing.
            break
        gx = offsets[0] - m_out + jnp.arange(w_out)
        valid = ((gx >= 0) & (gx < row))[:, None, None] & valid_yz
        wins = [jnp.where(valid, w, bv) for w, bv in zip(wins, bvs)]
    return tuple(wins)


def _xla_fallback(fields, params, seeds, faces, *, spec, use_noise,
                  offsets=None, row=None):
    """XLA-path step with the same call contract as ``fused_step``,
    drawing from the same position-keyed noise stream."""
    n_f = spec.n_fields
    if faces is None:
        pads = tuple(
            stencil.pad_with_boundary(f, bv)
            for f, bv in zip(fields, spec.boundaries)
        )
    else:
        pads = tuple(
            _pad_from_faces(
                fields[i], faces[2 * i], faces[2 * i + 1],
                faces[2 * n_f + 2 * i], faces[2 * n_f + 2 * i + 1],
                faces[4 * n_f + 2 * i], faces[4 * n_f + 2 * i + 1],
            )
            for i in range(n_f)
        )
    shape = fields[0].shape
    dtype = fields[0].dtype
    if use_noise:
        seeds = jnp.asarray(seeds, jnp.int32)
        if offsets is None:
            offsets = jnp.zeros((3,), jnp.int32)
        unit = uniform_pm1_block(
            seeds[:2], seeds[2], offsets, shape,
            shape[2] if row is None else row, dtype,
        )
        nz_field = params.noise * unit
    else:
        nz_field = jnp.asarray(0.0, dtype)
    # Accumulate in the params' dtype only when the posture splits
    # storage from compute (bf16_f32acc) — see _xla_xchain_fallback.
    pdt = jnp.asarray(params.noise).dtype
    return stencil.reaction_update(
        pads, nz_field, params, spec.model,
        compute_dtype=None if pdt == dtype else pdt,
    )


def _pad_from_faces(x, xlo, xhi, ylo, yhi, zlo, zhi):
    """Ghost-pad an interior block using resolved halo faces (corner and
    edge ghosts get zeros — the 7-point stencil never reads them)."""
    x = jnp.concatenate([xlo, x, xhi], axis=0)
    ylo = jnp.pad(ylo, ((1, 1), (0, 0), (0, 0)))
    yhi = jnp.pad(yhi, ((1, 1), (0, 0), (0, 0)))
    x = jnp.concatenate([ylo, x, yhi], axis=1)
    zlo = jnp.pad(zlo, ((1, 1), (1, 1), (0, 0)))
    zhi = jnp.pad(zhi, ((1, 1), (1, 1), (0, 0)))
    return jnp.concatenate([zlo, x, zhi], axis=2)
