"""XLA-path stencil ops — the model-generic compute core.

The 7-point Laplacian matches the reference math core
(``src/simulation/Common.jl:13-18``):

    lap(x) = (sum of 6 face neighbors - 6*center) / 6

including the ``/6`` normalization. The reference evaluates the Laplacian in
Float64 even for Float32 fields (Julia's ``6.0 *`` literal promotes); we
compute in the field dtype — on TPU this keeps the kernel on the fast path.
The numerical delta is below the explicit-Euler truncation error (verified by
``tests/unit/test_model.py::test_single_device_matches_oracle``, which
compares the Float32 path against a Float64-Laplacian NumPy oracle at
rtol 2e-5 over 10 steps).

Arrays here are ghost-padded ``(nx+2, ny+2, nz+2)`` blocks; functions return
interior-shaped ``(nx, ny, nz)`` results. :func:`reaction_update` is
n-field and model-generic: field extraction and Laplacians happen here,
the time derivatives come from the model's declared ``reaction``
(``models/base.Model``), and the explicit-Euler update closes the step —
so a new model touches this file not at all. XLA fuses the shifted
slices, the reaction terms, and the noise into a small number of HBM
passes; the Pallas kernel (``ops/pallas_stencil.py``) is the hand-fused
Gray-Scott-specific alternative.

This module contains no model-specific constants: boundary values and
seeds are model declarations (``models/``), threaded in by callers.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp


def pad_with_boundary(x: jnp.ndarray, value: float) -> jnp.ndarray:
    """Add a 1-cell ghost shell holding the frozen boundary ``value``."""
    return jnp.pad(x, 1, mode="constant", constant_values=value)


def laplacian(padded: jnp.ndarray) -> jnp.ndarray:
    """7-point Laplacian of a ghost-padded block (``Common.jl:13-18``).

    Evaluated as ``sum(neighbors) * (1/6) - center`` — algebraically the
    reference's ``(sum - 6*center) / 6`` with the division folded into a
    constant multiply (the per-cell divide is measurable VPU time in the
    fused TPU kernel; the delta is ulp-level, far below the explicit-Euler
    truncation error the oracle tolerance already absorbs). The Pallas
    kernel (``ops/pallas_stencil.py``) uses the identical form and
    neighbor-summation order so the two kernel languages keep agreeing to
    float roundoff.
    """
    center = padded[1:-1, 1:-1, 1:-1]
    inv6 = jnp.asarray(1.0 / 6.0, dtype=padded.dtype)
    total = (
        padded[:-2, 1:-1, 1:-1]
        + padded[2:, 1:-1, 1:-1]
        + padded[1:-1, :-2, 1:-1]
        + padded[1:-1, 2:, 1:-1]
        + padded[1:-1, 1:-1, :-2]
        + padded[1:-1, 1:-1, 2:]
    )
    return total * inv6 - center


def reaction_update(
    fields_pad: Sequence[jnp.ndarray],
    noise_term,
    params,
    model,
    compute_dtype=None,
) -> Tuple[jnp.ndarray, ...]:
    """One explicit-Euler step of ``model`` on ghost-padded fields.

        f_i' = f_i + d_i * dt   with   (d_1..d_n) = model.reaction(...)

    The per-field slice extraction and Laplacians are computed here in
    field order, the model's pure ``reaction`` supplies the derivatives,
    and ``params.dt`` closes the Euler update — the same dataflow graph
    the pre-framework Gray-Scott update lowered to, which is what keeps
    its trajectories byte-identical (``tests/golden/``).

    ``noise_term`` is the pre-scaled noise field ``noise * U(-1,1)`` (or
    a 0.0 scalar on the noiseless path); which derivative receives it is
    the model's choice inside ``reaction``.

    ``compute_dtype`` (docs/PRECISION.md, the ``bf16_f32acc`` posture)
    widens the accumulation: the ghost-padded fields are upcast ONCE,
    Laplacian + reaction + Euler update all run at the wide dtype, and
    only the final result rounds back to the storage dtype — one
    rounding per step, exactly like a hardware MXU bf16xbf16->f32
    pipeline. ``None`` (and a matching dtype) leave the historical
    dataflow untouched, bit for bit.

    Returns interior-shaped updated fields, in declaration order.
    """
    fields_pad = tuple(fields_pad)
    store_dtype = fields_pad[0].dtype
    if compute_dtype is not None and compute_dtype != store_dtype:
        fields_pad = tuple(f.astype(compute_dtype) for f in fields_pad)
        noise_term = jnp.asarray(noise_term).astype(compute_dtype)
    else:
        compute_dtype = None  # fast path: no casts traced at all
    fields = tuple(f[1:-1, 1:-1, 1:-1] for f in fields_pad)
    laps = tuple(laplacian(f) for f in fields_pad)
    derivs = model.reaction(fields, laps, noise_term, params)
    out = tuple(
        f + d * params.dt for f, d in zip(fields, derivs)
    )
    if compute_dtype is not None:
        out = tuple(f.astype(store_dtype) for f in out)
    return out
