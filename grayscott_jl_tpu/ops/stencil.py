"""XLA-path stencil ops for the Gray-Scott system.

The 7-point Laplacian matches the reference math core
(``src/simulation/Common.jl:13-18``):

    lap(x) = (sum of 6 face neighbors - 6*center) / 6

including the ``/6`` normalization. The reference evaluates the Laplacian in
Float64 even for Float32 fields (Julia's ``6.0 *`` literal promotes); we
compute in the field dtype — on TPU this keeps the kernel on the fast path.
The numerical delta is below the explicit-Euler truncation error (verified by
``tests/unit/test_model.py::test_single_device_matches_oracle``, which
compares the Float32 path against a Float64-Laplacian NumPy oracle at
rtol 2e-5 over 10 steps).

Arrays here are ghost-padded ``(nx+2, ny+2, nz+2)`` blocks; functions return
interior-shaped ``(nx, ny, nz)`` results. XLA fuses the shifted slices, the
reaction terms, and the noise into a small number of HBM passes; the Pallas
kernel (``ops/pallas_stencil.py``) is the hand-fused alternative.
"""

from __future__ import annotations

import jax.numpy as jnp

#: Ghost-cell boundary values. In the reference, ghost layers are initialized
#: to u=1, v=0 (``Simulation_CPU.jl:23-24``) and — with no neighbor to
#: exchange with (``MPI.PROC_NULL``) — stay frozen, acting as Dirichlet
#: boundary data on the global domain edge.
U_BOUNDARY = 1.0
V_BOUNDARY = 0.0


def pad_with_boundary(x: jnp.ndarray, value: float) -> jnp.ndarray:
    """Add a 1-cell ghost shell holding the frozen boundary ``value``."""
    return jnp.pad(x, 1, mode="constant", constant_values=value)


def laplacian(padded: jnp.ndarray) -> jnp.ndarray:
    """7-point Laplacian of a ghost-padded block (``Common.jl:13-18``).

    Evaluated as ``sum(neighbors) * (1/6) - center`` — algebraically the
    reference's ``(sum - 6*center) / 6`` with the division folded into a
    constant multiply (the per-cell divide is measurable VPU time in the
    fused TPU kernel; the delta is ulp-level, far below the explicit-Euler
    truncation error the oracle tolerance already absorbs). The Pallas
    kernel (``ops/pallas_stencil.py``) uses the identical form and
    neighbor-summation order so the two kernel languages keep agreeing to
    float roundoff.
    """
    center = padded[1:-1, 1:-1, 1:-1]
    inv6 = jnp.asarray(1.0 / 6.0, dtype=padded.dtype)
    total = (
        padded[:-2, 1:-1, 1:-1]
        + padded[2:, 1:-1, 1:-1]
        + padded[1:-1, :-2, 1:-1]
        + padded[1:-1, 2:, 1:-1]
        + padded[1:-1, 1:-1, :-2]
        + padded[1:-1, 1:-1, 2:]
    )
    return total * inv6 - center


def reaction_update(u_pad, v_pad, noise_u, params):
    """One explicit-Euler Gray-Scott update on ghost-padded fields.

    Mirrors the reference update (``Simulation_CPU.jl:92-112``):

        du = Du*lap(u) - u*v^2 + F*(1-u) + noise*U(-1,1)
        dv = Dv*lap(v) + u*v^2 - (F+k)*v
        u' = u + du*dt ;  v' = v + dv*dt

    ``noise_u`` is the pre-scaled noise field ``noise * U(-1,1)`` (or 0.0 for
    the noiseless path); only ``du`` receives noise, as in the reference.

    Returns interior-shaped (u', v').
    """
    u = u_pad[1:-1, 1:-1, 1:-1]
    v = v_pad[1:-1, 1:-1, 1:-1]
    dtype = u.dtype
    one = jnp.asarray(1.0, dtype)

    lap_u = laplacian(u_pad)
    lap_v = laplacian(v_pad)

    uvv = u * v * v
    du = params.Du * lap_u - uvv + params.F * (one - u) + noise_u
    dv = params.Dv * lap_v + uvv - (params.F + params.k) * v

    return u + du * params.dt, v + dv * params.dt
