"""Compute kernels for the stencil update.

Two kernel languages (the reference's Plain/KernelAbstractions pair,
``Inputs.jl:110-120``, re-imagined for TPU):

* ``"xla"``    — jnp/lax ops, fused by the XLA compiler (default; legacy
  config values "Plain" and "KernelAbstractions" alias here).
* ``"pallas"`` — hand-fused Pallas TPU kernel (``kernel_language = "Pallas"``).

The two languages have *different* call contracts (deliberately — the
Pallas kernel's whole advantage is consuming interior arrays + halo faces
with in-kernel RNG, while the XLA kernel consumes ghost-padded arrays +
a pre-generated noise field), so there is no uniform kernel callable:
``Simulation._local_run`` branches on the language explicitly.
``validate_kernel_language`` front-loads the import/availability check so
a bad config fails at construction, not at first ``iterate`` (the
reference defers dispatch errors to runtime fallbacks,
``public.jl:31-32, 77-78``).
"""

from __future__ import annotations

from . import stencil  # noqa: F401 — re-exported compute core


def validate_kernel_language(lang: str) -> None:
    """Raise if ``lang`` is unknown or its kernel module cannot load."""
    if lang == "xla":
        return
    if lang in ("pallas", "auto"):
        # "auto" may resolve to the Pallas path, so its kernel module
        # must load too — a broken install fails at construction either
        # way, not at dispatch.
        from . import pallas_stencil  # noqa: F401 — import is the check

        return
    raise ValueError(f"Unknown kernel language: {lang!r}")
