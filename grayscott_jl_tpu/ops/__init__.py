"""Compute kernels for the stencil update.

Two kernel languages (the reference's Plain/KernelAbstractions pair,
``Inputs.jl:110-120``, re-imagined for TPU):

* ``"xla"``    — jnp/lax ops, fused by the XLA compiler (default; legacy
  config values "Plain" and "KernelAbstractions" alias here).
* ``"pallas"`` — hand-fused Pallas TPU kernel (``kernel_language = "Pallas"``).

Both share the signature ``kernel(u_pad, v_pad, noise_u, params) -> (u, v)``
with ghost-padded inputs and interior-shaped outputs.
"""

from __future__ import annotations

from . import stencil


def get_kernel(lang: str):
    if lang == "xla":
        return stencil.reaction_update
    if lang == "pallas":
        from . import pallas_stencil

        return pallas_stencil.reaction_update
    raise ValueError(f"Unknown kernel language: {lang!r}")
