"""Position-keyed noise stream shared by every kernel and layout.

One stream for the whole framework: each global cell's draw at each step
is a pure function of ``(key, step, global x, global y, global z)``,
computed with a counter-based integer hash (lowbias32). Consequences,
all load-bearing for correctness tests:

* **chunk invariance** — iterating 10 steps in one jitted chunk equals
  two chunks of 5 (the step index is absolute);
* **layout invariance** — a sharded run draws the same noise as a
  single-device run for every global cell (the key is shared, the
  coordinates are global), so sharded == single-device holds bitwise
  even with noise on;
* **fusion invariance** — temporal blocking recomputes neighbor-owned
  ring cells locally; position-keyed draws make the recomputed values
  identical to what the owner computed, so ``fuse=2`` equals two single
  steps exactly;
* **kernel-language agreement** — the XLA path (:func:`uniform_pm1_block`)
  and the Pallas kernel (same hash on 2D planes,
  ``ops/pallas_stencil.py``) produce identical bits, so the
  cross-kernel-language oracle tests are exact for noisy runs too —
  strictly stronger than the reference, whose CPU and CUDA backends draw
  from unrelated streams (``Simulation_CPU.jl:101-103`` vs
  ``CUDAExt.jl:149-151``).

The reference's noise is ``rand(Distributions.Uniform(-1,1))`` from a
global RNG — not reproducible across thread schedules, let alone across
backends. This design trades its statistical pedigree (threefry) for a
fast avalanche hash; the noise term is a forcing perturbation, not a
Monte-Carlo estimator, and the uniformity/independence the tests assert
(mean, variance, step-to-step decorrelation) hold.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def hash32(x):
    """lowbias32 integer finalizer (32-bit avalanche hash); uint32
    arithmetic wraps modulo 2**32 by construction."""
    x = x ^ (x >> jnp.uint32(16))
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> jnp.uint32(15))
    x = x * jnp.uint32(0x846CA68B)
    return x ^ (x >> jnp.uint32(16))


def _u32(x):
    """Reinterpret an int32 scalar/array as uint32 (no value checks —
    negative step offsets at global edges wrap, which is fine: the wrap
    is deterministic and those draws land on masked ghost cells)."""
    return jnp.asarray(x).astype(jnp.uint32)


def plane_seed(k0, k1, step, gx):
    """Per-(key, step, global x-plane) seed — the contract shared with
    the Pallas kernel's in-kernel ``noise_block``. ``gx`` may be an
    array (hash32 is elementwise), which is how the 3D block forms
    vectorize over planes."""
    return hash32(
        hash32(hash32(_u32(k0)) ^ _u32(k1))
        ^ hash32(hash32(_u32(step)) ^ _u32(gx))
    )


def cell_hash(iy, iz, row):
    """Avalanche hash of the per-cell (y, z) counter — a pure function of
    the global cell column, independent of key/step/plane. Broadcast
    shapes keep this a 2D computation: for (1, ny, 1) x (1, 1, nz)
    inputs the result is (1, ny, nz), so in the fused kernel the counter
    hash costs ny*nz lanes once per draw instead of nx*ny*nz."""
    return hash32(iy * _u32(row) + iz)


def block_bits(seed, iy, iz, row):
    """uint32 noise bits for cells at broadcastable global y/z
    coordinate arrays ``iy``/``iz`` (uint32); ``row`` is the global row
    length (grid side L), making the per-cell counter a global
    coordinate. ONE definition of the seed/counter mix — the XLA block
    form and the Pallas in-kernel form must produce identical bits.

    Split as ``hash32(cell_hash(y, z) ^ seed)`` so only one of the two
    avalanche rounds runs at full 3D rank (``seed`` carries the x/step
    variation at (nx, 1, 1)): per-cell noise cost is one hash32 + xor,
    with the counter hash amortized over the x axis."""
    return hash32(cell_hash(iy, iz, row) ^ seed)


def bits_to_pm1(bits, dtype):
    """Map uint32 bits to uniform [-1, 1): 23 mantissa bits over exponent
    0 -> float in [1, 2), then affine-map."""
    f12 = lax.bitcast_convert_type(
        jnp.uint32(0x3F800000) | (bits >> jnp.uint32(9)), jnp.float32
    )
    return (f12 * 2.0 - 3.0).astype(dtype)


def uniform_pm1_block(key_i32, step, offsets, shape, row, dtype):
    """Uniform [-1, 1) noise for a 3D block at global ``offsets``.

    ``key_i32`` is the int32[2] raw key data (bitcast of a PRNG key),
    ``step`` the absolute step index, ``offsets`` the block's global
    (x, y, z) origin (python ints or traced scalars), ``row`` the global
    grid side L. Identical values to the Pallas kernel's per-plane draws
    for the same global cells.
    """
    gx = (lax.broadcasted_iota(jnp.uint32, (shape[0], 1, 1), 0)
          + _u32(offsets[0]))
    seed = plane_seed(key_i32[0], key_i32[1], step, gx)
    iy = (lax.broadcasted_iota(jnp.uint32, (1, shape[1], 1), 1)
          + _u32(offsets[1]))
    iz = (lax.broadcasted_iota(jnp.uint32, (1, 1, shape[2]), 2)
          + _u32(offsets[2]))
    bits = block_bits(seed, iy, iz, row)
    return bits_to_pm1(bits, dtype)
