"""Streaming PDF analysis of simulation output — completed for real.

The reference's companion analysis (``src/analysis/pdfcalc.jl``) is
unfinished: the read loop stops at a ``# Calculate`` comment
(``pdfcalc.jl:147``), ``_compute_pdf`` never zero-initializes its histogram
and has no return on the main path (``pdfcalc.jl:15-48``), and the ADIOS2
import is commented out (SURVEY defect #5). This module implements the
intended workflow end to end:

* open the simulation output as a *streaming* reader —
  ``begin_step(timeout=10)``, sleep-and-retry on NOT_READY, stop otherwise
  (``pdfcalc.jl:112-123``) — so it can run concurrently with a live
  simulation (in-situ coupling) or over a finished store;
* per step, for each x-slice of U and V, compute an ``nbins``-bin histogram
  of the slice's values between its min and max (``pdfcalc.jl:14-49``,
  with the counting bug fixed: zero-initialized, returned, and vectorized
  with numpy instead of a triple loop);
* split slices across workers along the slowest dimension with the
  remainder to the last worker (``pdfcalc.jl:132-139``);
* write ``U/pdf``, ``U/bins``, ``V/pdf``, ``V/bins`` (+ optionally the
  original U/V) to an output store per step.

CLI (``python -m grayscott_jl_tpu.analysis.pdfcalc``) mirrors the
reference's arguments: input, output, nbins (default 1000),
output_inputdata (default False) (``pdfcalc.jl:51-84``).
"""

from __future__ import annotations

import argparse
import time
from typing import List, Optional, Tuple

import numpy as np

from ..io import open_reader, open_writer
from ..io.bplite import StepStatus

_EPS = 1.0e-20  # reference ``_epsilon`` threshold (pdfcalc.jl:5-7)


def compute_pdf(
    data: np.ndarray, nbins: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Per-slice histograms of a (nslices, ny, nz) block.

    Returns ``(pdf, bins)`` with shapes ``(nslices, nbins)`` and
    ``(nbins,)``: counts of values in ``nbins`` equal bins spanning
    [min, max] of the whole block, lower-edge bin convention with the top
    value folded into the last bin (``pdfcalc.jl:41-44``). Degenerate
    windows (single bin, or max-min below epsilon) fill ``slice_size``
    per bin, matching the reference's special case (``pdfcalc.jl:24-27``).
    """
    nslices = data.shape[0]
    slice_size = int(np.prod(data.shape[1:], dtype=np.int64))
    lo = float(data.min())
    hi = float(data.max())
    bin_width = (hi - lo) / nbins
    bins = (lo + np.arange(nbins) * bin_width).astype(data.dtype)

    if nbins == 1 or (hi - lo) < _EPS or bin_width < _EPS:
        pdf = np.full((nslices, nbins), slice_size, dtype=data.dtype)
        return pdf, bins

    idx = np.floor((data.reshape(nslices, -1) - lo) / bin_width).astype(np.int64)
    np.clip(idx, 0, nbins - 1, out=idx)
    pdf = np.zeros((nslices, nbins), dtype=np.int64)
    rows = np.repeat(np.arange(nslices), slice_size)
    np.add.at(pdf, (rows, idx.ravel()), 1)
    return pdf.astype(data.dtype), bins


def split_slowest_dim(n: int, size: int, rank: int) -> Tuple[int, int]:
    """(start, count) of worker ``rank``'s share of ``n`` slices: floor
    division with the remainder going to the last worker
    (``pdfcalc.jl:132-139``)."""
    count = n // size
    start = count * rank
    if rank == size - 1:
        count = n - count * (size - 1)
    return start, count


def parse_arguments(args: List[str]) -> argparse.Namespace:
    """Reference CLI contract (``pdfcalc.jl:51-84``)."""
    p = argparse.ArgumentParser(
        prog="pdfcalc",
        description="gray-scott workflow pdf generator, TPU-native version",
    )
    p.add_argument("input", help="Name of the input file handle for reading data")
    p.add_argument(
        "output", help="Name of the output file to which data must be written"
    )
    p.add_argument(
        "N",
        nargs="?",
        type=int,
        default=1000,
        help="Number of bins for the PDF calculation, default = 1000",
    )
    p.add_argument(
        "output_inputdata",
        nargs="?",
        type=lambda s: s.lower() in ("yes", "true", "1"),
        default=False,
        help="YES will write the original variables besides the analysis results",
    )
    return p.parse_args(args)


def read_data_write_pdf(
    in_filename: str,
    out_filename: str,
    nbins: int = 1000,
    write_inputvars: bool = False,
    *,
    rank: int = 0,
    size: int = 1,
    timeout: float = 10.0,
    max_not_ready: Optional[int] = None,
    verbose: bool = False,
) -> int:
    """Streaming read -> per-slice PDF -> write loop. Returns steps processed.

    ``rank``/``size`` split the slowest (x) dimension across workers; with
    one worker the whole volume is processed. ``max_not_ready`` bounds the
    NOT_READY retries (None = retry forever, the reference behavior).
    """
    # open_reader dispatches on the store format: BP-lite from this
    # framework's runs, or — when the adios2 bindings are importable — a
    # real ADIOS2 BP store (including the reference's own output).
    # live=True: this is the streaming coupling — the simulation may
    # still be in its first-step compile window, so the store is allowed
    # to not exist yet (begin_step polls NOT_READY until it appears).
    reader = open_reader(in_filename, live=True)
    # All workers cooperate on ONE output store (the reference's
    # MPI-parallel pdfcalc writes a single output.bp the same way).
    writer = open_writer(out_filename, writer_id=rank, nwriters=size)

    defined = False
    not_ready = 0
    steps_done = 0
    while True:
        status = reader.begin_step(timeout=timeout)
        if status == StepStatus.NOT_READY:
            not_ready += 1
            if max_not_ready is not None and not_ready > max_not_ready:
                break
            time.sleep(1.0)  # pdfcalc.jl:117-118
            continue
        if status != StepStatus.OK:
            break
        not_ready = 0

        var_u = reader.inquire_variable("U")
        shape = var_u.shape
        start_x, count_x = split_slowest_dim(shape[0], size, rank)
        sel_start = (start_x, 0, 0)
        sel_count = (count_x, shape[1], shape[2])
        reader.set_selection("U", sel_start, sel_count)
        reader.set_selection("V", sel_start, sel_count)

        u = reader.get("U")
        v = reader.get("V")
        sim_step = int(reader.get("step"))
        reader.end_step()

        if not defined:
            dt = var_u.dtype.name
            writer.define_attribute("nbins", nbins)
            writer.define_attribute("input", in_filename)
            writer.define_variable("step", np.int32)
            writer.define_variable("U/pdf", dt, (shape[0], nbins))
            writer.define_variable("U/bins", dt, (nbins,))
            writer.define_variable("V/pdf", dt, (shape[0], nbins))
            writer.define_variable("V/bins", dt, (nbins,))
            if write_inputvars:
                writer.define_variable("U", dt, shape)
                writer.define_variable("V", dt, shape)
            defined = True

        u_pdf, u_bins = compute_pdf(u, nbins)
        v_pdf, v_bins = compute_pdf(v, nbins)

        writer.begin_step()
        writer.put("step", np.int32(sim_step))
        writer.put(
            "U/pdf", u_pdf, start=(start_x, 0), count=(count_x, nbins)
        )
        writer.put("U/bins", u_bins)
        writer.put(
            "V/pdf", v_pdf, start=(start_x, 0), count=(count_x, nbins)
        )
        writer.put("V/bins", v_bins)
        if write_inputvars:
            writer.put("U", u, start=sel_start, count=sel_count)
            writer.put("V", v, start=sel_start, count=sel_count)
        writer.end_step()
        steps_done += 1
        if verbose:
            print(f"pdfcalc: processed sim step {sim_step}", flush=True)

    writer.close()
    reader.close()
    return steps_done


def main(argv: Optional[List[str]] = None) -> int:
    """CLI entry. Parallel operation (the reference's pdfcalc is
    MPI-parallel, ``pdfcalc.jl:126-144``) uses the same environment
    contract as the simulation's multi-host launch: start
    ``GS_TPU_NUM_PROCESSES`` copies, each with its own
    ``GS_TPU_PROCESS_ID``; each worker reads its x-share via selection
    and writes its block into ONE shared multi-writer output store."""
    import sys

    from ..config.env import env_int

    ns = parse_arguments(sys.argv[1:] if argv is None else argv)
    rank = env_int("GS_TPU_PROCESS_ID", 0)
    size = env_int("GS_TPU_NUM_PROCESSES", 1)
    if not 0 <= rank < size:
        raise SystemExit(
            f"pdfcalc: GS_TPU_PROCESS_ID={rank} out of range for "
            f"GS_TPU_NUM_PROCESSES={size}"
        )
    read_data_write_pdf(
        ns.input, ns.output, ns.N, ns.output_inputdata,
        rank=rank, size=size, verbose=rank == 0,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
