"""Inspect / visualize the 3D domain decomposition.

Implements the reference's empty ``src/plot/decomp.jl`` stub: given a
device count and grid size, show how :func:`dims_create` factorizes the
mesh and which (sizes, offsets) block each shard owns.

CLI::

    python -m grayscott_jl_tpu.analysis.decomp 8 --L 256
"""

from __future__ import annotations

import argparse
from typing import List, Optional

from ..parallel.domain import CartDomain, dims_create


def describe(n_devices: int, L: int) -> str:
    dims = dims_create(n_devices)
    lines = [
        f"devices = {n_devices} -> mesh dims {dims} "
        f"(axes x,y,z; like MPI_Dims_create)",
        f"global grid {L}^3, "
        + (
            "equal blocks "
            + "x".join(str(L // d) for d in dims)
            if all(L % d == 0 for d in dims)
            else "UNEVEN blocks (sharded path requires divisibility)"
        ),
        f"{'rank':>4} {'coords':>10} {'sizes':>15} {'offsets':>15}",
    ]
    dom = CartDomain(L=L, dims=dims)
    for r in range(n_devices):
        c = dom.coords(r)
        lines.append(
            f"{r:>4} {str(c):>10} {str(dom.proc_sizes(c)):>15} "
            f"{str(dom.proc_offsets(c)):>15}"
        )
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="decomp")
    p.add_argument("n_devices", type=int)
    p.add_argument("--L", type=int, default=128)
    ns = p.parse_args(argv)
    print(describe(ns.n_devices, ns.L))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
