"""Plot simulation output: mid-plane slices and PDF curves.

The reference ships empty plotting stubs (``src/plot/gdsplot.jl`` and
``src/plot/decomp.jl`` are 0 bytes — SURVEY §2); this implements what they
were for: quick-look rendering of the ``.bp`` output.

CLI::

    python -m grayscott_jl_tpu.analysis.gdsplot out.bp [--var U] [--step -1]
        [--axis x] [--index mid] [--output slice.png]

Renders a 2D mid-plane (or chosen) slice of U or V at a given output step,
or — with ``--pdf`` on a pdfcalc output store — the per-slice PDF heatmap.
"""

from __future__ import annotations

import argparse
from typing import List, Optional

import numpy as np

from ..io import open_reader

_AXES = {"x": 0, "y": 1, "z": 2}


def load_slice(
    path: str,
    var: str = "U",
    step: int = -1,
    axis: str = "x",
    index: Optional[int] = None,
) -> np.ndarray:
    """A 2D slice of ``var`` at output step ``step`` (negative = from end)."""
    r = open_reader(path)
    n = r.num_steps()
    if n == 0:
        raise ValueError(f"{path} contains no steps")
    if step < 0:
        step = n + step
    data = r.get(var, step=step)
    ax = _AXES[axis]
    if index is None:
        index = data.shape[ax] // 2
    r.close()
    return np.take(data, index, axis=ax)


def plot_slice(
    path: str,
    var: str = "U",
    step: int = -1,
    axis: str = "x",
    index: Optional[int] = None,
    output: Optional[str] = None,
):
    """Render a slice with matplotlib; returns the output filename."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    sl = load_slice(path, var, step, axis, index)
    fig, ax_ = plt.subplots(figsize=(6, 5))
    im = ax_.imshow(sl.T, origin="lower", cmap="viridis")
    ax_.set_title(f"{var} slice ({axis}={index if index is not None else 'mid'})")
    other = [a for a in _AXES if a != axis]
    ax_.set_xlabel(other[0])
    ax_.set_ylabel(other[1])
    fig.colorbar(im, ax=ax_)
    out = output or f"{var.lower()}_slice.png"
    fig.savefig(out, dpi=120, bbox_inches="tight")
    plt.close(fig)
    return out


def plot_pdf(
    path: str,
    var: str = "U",
    step: int = -1,
    output: Optional[str] = None,
):
    """Heatmap of a pdfcalc output store's per-slice PDFs."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    r = open_reader(path)
    n = r.num_steps()
    if step < 0:
        step = n + step
    pdf = r.get(f"{var}/pdf", step=step)
    bins = r.get(f"{var}/bins", step=step)
    r.close()

    fig, ax = plt.subplots(figsize=(7, 4))
    im = ax.imshow(
        pdf,
        origin="lower",
        aspect="auto",
        extent=(float(bins[0]), float(bins[-1]), 0, pdf.shape[0]),
        cmap="magma",
    )
    ax.set_xlabel(f"{var} value")
    ax.set_ylabel("slice index")
    ax.set_title(f"{var} per-slice PDF")
    fig.colorbar(im, ax=ax, label="count")
    out = output or f"{var.lower()}_pdf.png"
    fig.savefig(out, dpi=120, bbox_inches="tight")
    plt.close(fig)
    return out


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="gdsplot")
    p.add_argument("input", help="BP-lite store (simulation or pdfcalc output)")
    p.add_argument("--var", default="U", choices=["U", "V"])
    p.add_argument("--step", type=int, default=-1)
    p.add_argument("--axis", default="x", choices=list(_AXES))
    p.add_argument("--index", type=int, default=None)
    p.add_argument("--pdf", action="store_true", help="plot pdfcalc output")
    p.add_argument("--output", default=None)
    ns = p.parse_args(argv)
    if ns.pdf:
        out = plot_pdf(ns.input, ns.var, ns.step, ns.output)
    else:
        out = plot_slice(ns.input, ns.var, ns.step, ns.axis, ns.index, ns.output)
    print(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
