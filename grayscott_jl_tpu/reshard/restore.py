"""Elastic resharding: executing the restore plan (docs/RESHARD.md).

Two execution paths, one plan:

* **Host checkpoint path** (:func:`restore_run`): each process
  selection-reads exactly its NEW shards from the global-indexed
  checkpoint store (``Simulation.restore_from_reader`` already reads
  per addressable shard, so no process ever materializes the full
  field), making the mesh shape a restore-time decision with zero data
  movement beyond what any restore pays. This remains the
  preemption-shaped path — a replacement slice boots from the durable
  store anyway.

* **Live device path** (:func:`device_all_to_all_restore`, driven by
  :func:`reshape_live`): re-slices LIVE mesh-A field buffers onto mesh
  B between step rounds with no checkpoint round-trip. The plan's
  ``overlapping_old_shards`` schedule is compiled into ONE device
  program: when both meshes span the same device set, a single jitted
  relayout whose ``out_shardings`` is the target placement (XLA GSPMD
  lowers exactly the plan's send/recv pairs to ICI collectives —
  ppermute/all-to-all on TPU); across device sets, a
  ``jax.device_put`` transfer tier; and a host-gather tier for
  backends without either. Tier choice is the ``GS_RESHARD_DEVICE``
  knob (``config.resolve_reshard_device``). Every tier moves the true
  L^3 values verbatim and reconstructs storage pad at the frozen
  boundary value, so the continuation is bitwise identical to the
  host-path restore of the same plan — and to a run that never moved.

The plan (``reshard/plan.py``) supplies validation and provenance;
this module supplies orchestration: plan -> move -> journal/event,
with ``path`` / ``bytes`` / ``wall_s`` timing provenance on every
``reshard`` record.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

from ..config.settings import (
    Settings,
    resolve_reshard,
    resolve_reshard_device,
)
from . import plan as plan_mod
from .plan import LayoutMeta, ReshardError, ReshardPlan

__all__ = [
    "device_all_to_all_restore",
    "layout_of",
    "reshape_live",
    "restore_run",
]


def layout_of(sim, *, process_count: Optional[int] = None) -> LayoutMeta:
    """The :class:`LayoutMeta` describing a live simulation — the
    record its checkpoints carry, and the "new" side of a restore plan.

    Deliberately the SPATIAL layout even for ensembles
    (``EnsembleSimulation.domain`` is the spatial decomposition):
    member stores must stay byte-identical to solo stores, so the
    member axis never enters the per-store attributes.
    """
    import jax

    return LayoutMeta(
        mesh_dims=tuple(int(d) for d in sim.domain.dims),
        process_count=int(
            jax.process_count() if process_count is None
            else process_count
        ),
        halo_depth=int(sim.halo_depth),
        chain_fuse=int(sim._fuse_base()),
        ensemble_size=1,
    )


def _move_bytes(plan: ReshardPlan, sim) -> int:
    """Bytes the plan's schedule re-slices: the sum of every new
    shard's true-domain selection box, over all fields (and members) —
    what the device program moves, and what a host restore reads."""
    import numpy as np

    cells = 0
    for _coords, _start, count in plan.boxes:
        vol = 1
        for c in count:
            vol *= int(c)
        cells += vol
    members = int(getattr(sim, "n_members", 1))
    itemsize = int(np.dtype(sim.dtype).itemsize)
    return cells * sim.model.n_fields * members * itemsize


def _announce(
    sim, plan: ReshardPlan, *, log=None, journal=None, prov=None
) -> None:
    """One ``reshard`` record on every observer: the unified event
    stream (GS_EVENTS), the fault journal (and through it the final
    RunStats ``faults`` section), and the console log. ``prov`` is the
    timing provenance (``path`` / ``bytes`` / ``wall_s``) the executing
    tier measured — every record carries it."""
    from ..obs import events as obs_events

    prov = prov or {}
    old = plan.old.describe() if plan.old is not None else None
    obs_events.get_events().emit(
        "reshard", step=sim.step,
        old_mesh=(old or {}).get("mesh_dims"),
        new_mesh=list(plan.new.mesh_dims),
        old_procs=(old or {}).get("process_count"),
        new_procs=plan.new.process_count,
        members=plan.members,
        path=prov.get("path"),
        bytes=prov.get("bytes"),
        wall_s=prov.get("wall_s"),
    )
    if journal is not None:
        journal.record(
            event="reshard", step=sim.step,
            old=old, new=plan.new.describe(), members=plan.members,
            path=prov.get("path"), bytes=prov.get("bytes"),
            wall_s=prov.get("wall_s"),
        )
    if log is not None:
        old_mesh = (
            "x".join(str(d) for d in plan.old.mesh_dims)
            if plan.old is not None else "?"
        )
        new_mesh = "x".join(str(d) for d in plan.new.mesh_dims)
        log.info(
            f"Resharded restore: layout {old_mesh} "
            f"({plan.old.process_count if plan.old else '?'} proc) -> "
            f"adopted {new_mesh} ({plan.new.process_count} proc) "
            f"at step {sim.step} via {prov.get('path', '?')} "
            f"({prov.get('bytes', '?')} B in "
            f"{prov.get('wall_s', '?')}s)"
        )


def restore_run(
    sim, settings: Settings, *, log=None, journal=None
) -> Tuple[int, ReshardPlan]:
    """Restore ``sim`` from its configured checkpoint store(s),
    resharding to the simulation's (already-built) mesh when the store
    was written on a different layout.

    Returns ``(restart_step, plan)``. Solo runs restore through
    per-shard selection reads; ensembles route through the elastic
    member restore (``ensemble/io.restore_ensemble`` — grow/shrink plus
    per-member spatial reshard). The adopting simulation records the
    plan as ``sim.reshard`` (None when the layout did not change) so
    the stats config echo says whether this attempt moved.
    """
    allow = resolve_reshard(settings)
    t0 = time.perf_counter()
    ens = getattr(settings, "ensemble", None)
    if ens is not None:
        from ..ensemble.io import restore_ensemble

        step, plan = restore_ensemble(sim, settings, allow=allow)
    else:
        from ..io.checkpoint import open_checkpoint, read_layout
        from ..resilience import integrity

        def restore_from(candidate):
            reader, idx, step = open_checkpoint(
                candidate, settings, settings.restart_step
            )
            try:
                old = read_layout(reader)
                plan = plan_mod.plan_restore(
                    old, layout_of(sim), L=settings.L, allow=allow
                )
                # The reshard IS these selection reads: each process
                # pulls exactly its NEW shards' (start, count) boxes
                # out of the global store — plan.boxes enumerates them.
                sim.restore_from_reader(reader, idx, step)
                return step, plan
            finally:
                reader.close()

        # Replica failover (docs/RESILIENCE.md "Data integrity"): a
        # corrupt or unreadable candidate — CRC mismatch mid-selection-
        # read included — fails over to the next replica in health
        # order; a sole corrupted store refuses loudly with the CRC
        # mismatch named instead of resuming wrong.
        step, plan = integrity.restore_with_failover(
            settings.restart_input, restore_from, journal=journal,
            log=log,
        )
    if plan.changed:
        prov = {
            "path": "ckpt",
            "bytes": _move_bytes(plan, sim),
            "wall_s": round(time.perf_counter() - t0, 6),
        }
        sim.reshard = {**plan.describe(), **prov}
        _announce(sim, plan, log=log, journal=journal, prov=prov)
    else:
        sim.reshard = None
    return step, plan


# --------------------------------------------------------------- live path


def _device_set(sim) -> frozenset:
    """The devices a simulation's field buffers live on."""
    mesh = getattr(sim, "mesh", None)
    if mesh is not None:
        return frozenset(mesh.devices.flat)
    return frozenset([sim.device])


def _target_sharding(target):
    import jax

    if getattr(target, "mesh", None) is not None:
        return target.field_sharding
    return jax.sharding.SingleDeviceSharding(target.device)


def _spatial_pads(target):
    L = target.settings.L
    return [(0, g - L) for g in target.domain.storage_shape]


def _relayout_fn(sim, target):
    """The pure old->new relayout the collective tier jits: slice to
    the true L^3 domain (dropping mesh A's storage pad), re-pad to
    mesh B's storage shape at the frozen boundary values, and — for
    ensembles — grow/shrink the member axis (grown members take the
    broadcast init block, the same state ``restore_ensemble`` gives a
    grown member). ``out_shardings`` = mesh B's placement turns this
    into the plan's send/recv schedule when XLA lowers it."""
    import jax.numpy as jnp

    L = sim.settings.L
    pads = _spatial_pads(target)
    padded = any(p[1] for p in pads)
    bvs = [float(b) for b in target.model.boundaries]
    if not getattr(sim, "is_ensemble", False):
        def move(fields, _init):
            out = []
            for f, bv in zip(fields, bvs):
                t = f[:L, :L, :L]
                if padded:
                    t = jnp.pad(t, pads, constant_values=bv)
                out.append(t)
            return tuple(out)

        return move

    old_n = int(sim.n_members)
    new_n = int(target.n_members)
    keep = min(old_n, new_n)
    mpads = [(0, 0)] + pads

    def move(fields, init_blocks):
        out = []
        for f, ib, bv in zip(fields, init_blocks, bvs):
            t = f[:keep, :L, :L, :L]
            if new_n > keep:
                grown = jnp.broadcast_to(
                    ib[None], (new_n - keep,) + ib.shape
                )
                t = jnp.concatenate([t, grown], axis=0)
            if padded:
                t = jnp.pad(t, mpads, constant_values=bv)
            out.append(t)
        return tuple(out)

    return move


def _init_blocks(sim, target):
    """Broadcast init blocks for grown ensemble members (zeros-shaped
    placeholders otherwise — the relayout never reads them then)."""
    import jax.numpy as jnp

    if (getattr(sim, "is_ensemble", False)
            and int(target.n_members) > int(sim.n_members)):
        return tuple(
            jnp.asarray(b, target.dtype)
            for b in target.member_init_fields()
        )
    L = sim.settings.L
    shape = (L, L, L)
    return tuple(
        jnp.zeros(shape, target.dtype)
        for _ in range(target.model.n_fields)
    )


def _collective_tier(sim, target) -> None:
    """Same-device-set relayout as ONE compiled program: the jit's
    ``out_shardings`` is mesh B's placement, so XLA GSPMD emits exactly
    the plan's overlap schedule as on-fabric collectives (ICI
    ppermute/all-to-all on TPU; shared-memory copies on CPU)."""
    import jax

    sharding = _target_sharding(target)
    move = _relayout_fn(sim, target)
    n = target.model.n_fields
    moved = jax.jit(move, out_shardings=(sharding,) * n)(
        sim.fields, _init_blocks(sim, target)
    )
    target.fields = tuple(moved)
    target.step = int(sim.step)


def _put_tier(sim, target) -> None:
    """Cross-device-set move: compute the relayout on mesh A's devices
    (one jit — slice, member grow/shrink, re-pad), then
    ``jax.device_put`` the result onto mesh B's placement. No host
    round-trip in user code; the runtime picks the cheapest transfer
    it supports."""
    import jax

    move = jax.jit(_relayout_fn(sim, target))
    staged = move(sim.fields, _init_blocks(sim, target))
    sharding = _target_sharding(target)
    target.fields = tuple(
        jax.device_put(f, sharding) for f in staged
    )
    target.step = int(sim.step)


def _host_tier(sim, target) -> None:
    """Backstop tier: gather the true-domain fields to host and
    re-place them through the same restore entrypoints the checkpoint
    path uses — still no checkpoint round-trip, just a D->H->D copy."""
    if getattr(sim, "is_ensemble", False):
        old = sim.get_fields()  # (N, L, L, L) per field, pad-stripped
        old_n, new_n = int(sim.n_members), int(target.n_members)
        blocks = []
        for i in range(new_n):
            if i < old_n:
                blocks.append(tuple(f[i] for f in old))
            else:
                blocks.append(target.member_init_fields())
        target.restore_members(blocks, int(sim.step))
    else:
        target.restore_fields(sim.get_fields(), int(sim.step))


def device_all_to_all_restore(
    sim, plan: ReshardPlan, target, *, mode: Optional[str] = None
) -> dict:
    """Move ``sim``'s LIVE field buffers onto ``target``'s layout per
    ``plan`` — the in-job device reshard (docs/RESHARD.md "The live
    device path"). No checkpoint round-trip; the continuation on
    ``target`` is bitwise identical to a host-path restore of the same
    plan (asserted in tests/unit/test_reshard_device.py).

    Tier selection (``mode``, default ``config.
    resolve_reshard_device``): ``collective`` compiles the plan's
    ``overlapping_old_shards`` schedule into one jitted program whose
    ``out_shardings`` is mesh B (same device set only — that is when
    the relayout is pure data movement XLA can lower to ICI
    collectives); ``put`` stages the relayout on mesh A and
    ``jax.device_put``s across device sets; ``host`` gathers and
    re-places through the restore entrypoints. ``auto`` picks
    collective when the device sets match, else put, degrading to host
    if the runtime refuses the transfer. Returns the timing provenance
    ``{"path", "bytes", "wall_s"}`` recorded on the ``reshard`` event.
    """
    import jax

    if mode is None:
        mode = resolve_reshard_device(sim.settings)
    if mode == "off":
        raise ReshardError(
            "live device resharding is disabled (GS_RESHARD_DEVICE="
            "off); use the checkpoint restore path "
            "(reshard.restore.restore_run)"
        )
    same_set = _device_set(sim) == _device_set(target)
    t0 = time.perf_counter()
    if mode == "collective" or (mode == "auto" and same_set):
        if not same_set:
            raise ReshardError(
                "GS_RESHARD_DEVICE=collective needs mesh A and mesh B "
                "to span the SAME device set (the one-program relayout "
                f"is a pure re-slice there); old spans "
                f"{len(_device_set(sim))} device(s), new "
                f"{len(_device_set(target))} — use auto/put/host"
            )
        _collective_tier(sim, target)
        path = "collective"
    elif mode == "put" or mode == "auto":
        try:
            _put_tier(sim, target)
            path = "put"
        except Exception:
            if mode == "put":
                raise
            # auto degrades to the host tier when the backend refuses
            # the cross-set transfer (jaxlib version / platform gaps).
            _host_tier(sim, target)
            path = "host"
    else:  # mode == "host"
        _host_tier(sim, target)
        path = "host"
    target.step = int(sim.step)
    jax.block_until_ready(target.fields)
    return {
        "path": path,
        "bytes": _move_bytes(plan, target),
        "wall_s": round(time.perf_counter() - t0, 6),
    }


def reshape_live(
    sim,
    *,
    mesh_dims: Optional[Tuple[int, int, int]] = None,
    settings: Optional[Settings] = None,
    seed: int = 0,
    mode: Optional[str] = None,
    log=None,
    journal=None,
):
    """In-job reshape: build the TARGET simulation on ``mesh_dims``
    (and/or a new ensemble spec via ``settings``) and move the live
    state onto it — the between-rounds hook the driver calls when the
    serve elastic policy (docs/SERVICE.md) grants or reclaims chips.

    Returns ``(target, plan)``; the caller swaps ``target`` in for
    ``sim`` and continues stepping. The target is constructed with the
    SOURCE's resolved kernel language pinned and the autotuner off —
    a reshape must not re-litigate tuning mid-run (the adopted mesh
    joins the tuning-cache key; a later run on this shape tunes
    normally). ``target.reshard`` carries the plan + timing provenance
    and the ``reshard`` event/journal record is emitted, so stats and
    reports attribute the move.
    """
    import dataclasses

    import jax

    settings = sim.settings if settings is None else settings
    dims = tuple(
        int(d) for d in (mesh_dims or sim.domain.dims)
    )
    allow = resolve_reshard(settings)
    ens = getattr(settings, "ensemble", None)
    member_shards = int(ens.member_shards) if ens is not None else 1
    n_devices = dims[0] * dims[1] * dims[2] * member_shards
    pinned = dataclasses.replace(
        settings,
        kernel_language=sim.kernel_language,
        autotune="off",
    )
    target = type(sim)(
        pinned, n_devices=n_devices, seed=seed, mesh_dims=dims
    )
    old = layout_of(sim)
    new = layout_of(target)
    plan = plan_mod.plan_restore(old, new, L=settings.L, allow=allow)
    old_n = int(getattr(sim, "n_members", 1))
    new_n = int(getattr(target, "n_members", 1))
    if old_n != new_n:
        if allow == "off":
            raise ReshardError(
                f"live member reshape {old_n} -> {new_n} refused: "
                "reshard='off' (set reshard='auto' / GS_RESHARD=auto)"
            )
        plan = dataclasses.replace(
            plan, changed=True, members={
                "restored": min(old_n, new_n),
                "grown": max(0, new_n - old_n),
                "new_n": new_n,
            },
        )
    prov = device_all_to_all_restore(sim, plan, target, mode=mode)
    jax.block_until_ready(target.fields)
    if plan.changed:
        target.reshard = {**plan.describe(), **prov}
        _announce(target, plan, log=log, journal=journal, prov=prov)
    return target, plan
