"""Elastic resharding: executing the restore plan (docs/RESHARD.md).

The host-side path is the one implemented here: each process
selection-reads exactly its NEW shards from the global-indexed
checkpoint store (``Simulation.restore_from_reader`` already reads per
addressable shard, so no process ever materializes the full field),
making the mesh shape a restore-time decision with zero data movement
beyond what any restore pays. The plan (``reshard/plan.py``) supplies
the validation and the provenance; this module supplies the
orchestration the driver calls: open -> read layout -> plan -> restore
-> journal/event.

The ICI all-to-all device path — reshuffling LIVE device buffers
between two meshes without a checkpoint round-trip — is a documented
seam (:func:`device_all_to_all_restore`), not an implementation: the
host path is correct and preemption-shaped (the replacement slice
boots from the durable store anyway), while the device path only pays
off for planned in-job reshapes, which need TPU hardware to validate.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..config.settings import Settings, resolve_reshard
from . import plan as plan_mod
from .plan import LayoutMeta, ReshardError, ReshardPlan

__all__ = [
    "device_all_to_all_restore",
    "layout_of",
    "restore_run",
]


def layout_of(sim, *, process_count: Optional[int] = None) -> LayoutMeta:
    """The :class:`LayoutMeta` describing a live simulation — the
    record its checkpoints carry, and the "new" side of a restore plan.

    Deliberately the SPATIAL layout even for ensembles
    (``EnsembleSimulation.domain`` is the spatial decomposition):
    member stores must stay byte-identical to solo stores, so the
    member axis never enters the per-store attributes.
    """
    import jax

    return LayoutMeta(
        mesh_dims=tuple(int(d) for d in sim.domain.dims),
        process_count=int(
            jax.process_count() if process_count is None
            else process_count
        ),
        halo_depth=int(sim.halo_depth),
        chain_fuse=int(sim._fuse_base()),
        ensemble_size=1,
    )


def _announce(sim, plan: ReshardPlan, *, log=None, journal=None) -> None:
    """One ``reshard`` record on every observer: the unified event
    stream (GS_EVENTS), the fault journal (and through it the final
    RunStats ``faults`` section), and the console log."""
    from ..obs import events as obs_events

    old = plan.old.describe() if plan.old is not None else None
    obs_events.get_events().emit(
        "reshard", step=sim.step,
        old_mesh=(old or {}).get("mesh_dims"),
        new_mesh=list(plan.new.mesh_dims),
        old_procs=(old or {}).get("process_count"),
        new_procs=plan.new.process_count,
        members=plan.members,
    )
    if journal is not None:
        journal.record(
            event="reshard", step=sim.step,
            old=old, new=plan.new.describe(), members=plan.members,
        )
    if log is not None:
        old_mesh = (
            "x".join(str(d) for d in plan.old.mesh_dims)
            if plan.old is not None else "?"
        )
        new_mesh = "x".join(str(d) for d in plan.new.mesh_dims)
        log.info(
            f"Resharded restore: checkpoint layout {old_mesh} "
            f"({plan.old.process_count if plan.old else '?'} proc) -> "
            f"adopted {new_mesh} ({plan.new.process_count} proc) "
            f"at step {sim.step}"
        )


def restore_run(
    sim, settings: Settings, *, log=None, journal=None
) -> Tuple[int, ReshardPlan]:
    """Restore ``sim`` from its configured checkpoint store(s),
    resharding to the simulation's (already-built) mesh when the store
    was written on a different layout.

    Returns ``(restart_step, plan)``. Solo runs restore through
    per-shard selection reads; ensembles route through the elastic
    member restore (``ensemble/io.restore_ensemble`` — grow/shrink plus
    per-member spatial reshard). The adopting simulation records the
    plan as ``sim.reshard`` (None when the layout did not change) so
    the stats config echo says whether this attempt moved.
    """
    allow = resolve_reshard(settings)
    ens = getattr(settings, "ensemble", None)
    if ens is not None:
        from ..ensemble.io import restore_ensemble

        step, plan = restore_ensemble(sim, settings, allow=allow)
    else:
        from ..io.checkpoint import open_checkpoint, read_layout
        from ..resilience import integrity

        def restore_from(candidate):
            reader, idx, step = open_checkpoint(
                candidate, settings, settings.restart_step
            )
            try:
                old = read_layout(reader)
                plan = plan_mod.plan_restore(
                    old, layout_of(sim), L=settings.L, allow=allow
                )
                # The reshard IS these selection reads: each process
                # pulls exactly its NEW shards' (start, count) boxes
                # out of the global store — plan.boxes enumerates them.
                sim.restore_from_reader(reader, idx, step)
                return step, plan
            finally:
                reader.close()

        # Replica failover (docs/RESILIENCE.md "Data integrity"): a
        # corrupt or unreadable candidate — CRC mismatch mid-selection-
        # read included — fails over to the next replica in health
        # order; a sole corrupted store refuses loudly with the CRC
        # mismatch named instead of resuming wrong.
        step, plan = integrity.restore_with_failover(
            settings.restart_input, restore_from, journal=journal,
            log=log,
        )
    sim.reshard = plan.describe() if plan.changed else None
    if plan.changed:
        _announce(sim, plan, log=log, journal=journal)
    return step, plan


def device_all_to_all_restore(sim, plan: ReshardPlan):
    """SEAM — the ICI device path for planned in-job reshapes.

    Contract (not yet implemented; the host selection-read path above
    is the production restore): given live device buffers laid out on
    mesh A and a plan targeting mesh B over the SAME device set, emit
    one ``jax.device_put``-free all-to-all that re-slices every shard
    on-fabric — ``plan.boxes`` with
    :func:`~.plan.overlapping_old_shards` is exactly the send/recv
    schedule. Needs TPU hardware to validate (the standing note in
    ROADMAP.md); on CPU the host path is measurably equivalent.
    """
    raise NotImplementedError(
        "the ICI all-to-all reshard path is a documented seam "
        "(docs/RESHARD.md); use the host-side checkpoint restore "
        "(reshard.restore.restore_run)"
    )
