"""Elastic resharding: checkpoint on N devices, resume on M.

``plan`` computes and validates the old->new layout plan from the
checkpoint store's layout attributes (pure host math, JAX-free);
``restore`` executes it host-side — per-shard selection reads of the
NEW decomposition against the global-indexed store — and leaves the
ICI all-to-all device path as a documented seam. See docs/RESHARD.md.
"""

from .plan import (  # noqa: F401
    LAYOUT_SCHEMA_VERSION,
    LayoutMeta,
    ReshardError,
    ReshardPlan,
    layout_attrs,
    member_map,
    plan_restore,
    read_layout,
    shard_boxes,
)
from .restore import layout_of, restore_run  # noqa: F401
