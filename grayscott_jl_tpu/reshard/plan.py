"""Elastic resharding: the old->new layout plan (docs/RESHARD.md).

The mesh shape is a RESTORE-time decision, not a checkpoint-time
constant: checkpoint stores are global-indexed (every block carries its
``(start, count)`` box in the L^3 domain, ``io/bplite.py``) and the
restore path selection-reads per shard, so the data itself never
depended on the writing decomposition. What was missing is the
*metadata* — which layout wrote the store, and whether the target
layout can legally adopt it — and that is this module: pure,
JAX-free planning over the layout attributes
:class:`~..io.checkpoint.CheckpointWriter` records
(:data:`LAYOUT_ATTRS`).

The plan is deliberately host-math only (boxes, member maps, loud
:class:`ReshardError` for infeasible targets); execution lives in
``reshard/restore.py``. The shape of the problem follows the adaptive
distributed-stencil literature (arXiv:2512.19851 treats the
decomposition as an adaptable runtime property; arXiv:2404.02218 puts
the relayout in the runtime layer, not user code).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from ..parallel.domain import block_size_offset

__all__ = [
    "LAYOUT_ATTRS",
    "LAYOUT_SCHEMA_VERSION",
    "LayoutMeta",
    "ReshardError",
    "ReshardPlan",
    "layout_attrs",
    "member_map",
    "plan_restore",
    "read_layout",
    "shard_boxes",
]

#: Version of the layout-attribute schema below. Bump when an attribute
#: changes meaning; readers treat a NEWER schema as best-effort (the
#: attributes below keep their meaning across versions by contract) and
#: a missing schema as "pre-elastic store" (layout unknown — restore is
#: still legal, the stores were always global-indexed).
LAYOUT_SCHEMA_VERSION = 1

#: The store attributes that make up the layout record, in write order.
LAYOUT_ATTRS = (
    "layout_schema",
    "mesh_dims",
    "axis_names",
    "process_count",
    "halo_depth",
    "chain_fuse",
    "ensemble_size",
)


class ReshardError(RuntimeError):
    """An infeasible or refused restore-time layout change.

    Raised LOUDLY (naming both layouts) instead of letting a mismatched
    restore limp along: a silently wrong decomposition would corrupt
    every downstream artifact that believes the stats' mesh echo.
    """


@dataclasses.dataclass(frozen=True)
class LayoutMeta:
    """One run layout, as recorded in (or derived for) a checkpoint
    store. ``mesh_dims`` is the SPATIAL decomposition — the member axis
    of an ensemble is deliberately absent (member stores are
    byte-identical to solo stores, ``ensemble/io.py``; the ensemble
    size is the count of member stores on disk, not an attribute)."""

    schema: int = LAYOUT_SCHEMA_VERSION
    mesh_dims: Tuple[int, ...] = (1, 1, 1)
    axis_names: Tuple[str, ...] = ("x", "y", "z")
    process_count: int = 1
    halo_depth: int = 1
    chain_fuse: int = 1
    ensemble_size: int = 1

    @property
    def n_devices(self) -> int:
        n = 1
        for d in self.mesh_dims:
            n *= int(d)
        return n

    def describe(self) -> dict:
        return {
            "schema": self.schema,
            "mesh_dims": list(self.mesh_dims),
            "process_count": self.process_count,
            "halo_depth": self.halo_depth,
            "chain_fuse": self.chain_fuse,
            "ensemble_size": self.ensemble_size,
        }


def layout_attrs(
    *,
    mesh_dims: Sequence[int],
    axis_names: Sequence[str] = ("x", "y", "z"),
    process_count: int = 1,
    halo_depth: int = 1,
    chain_fuse: int = 1,
    ensemble_size: int = 1,
) -> dict:
    """The attribute dict a checkpoint writer records (name -> value),
    one entry per :data:`LAYOUT_ATTRS` name."""
    return {
        "layout_schema": int(LAYOUT_SCHEMA_VERSION),
        "mesh_dims": [int(d) for d in mesh_dims],
        "axis_names": [str(a) for a in axis_names],
        "process_count": int(process_count),
        "halo_depth": int(halo_depth),
        "chain_fuse": int(chain_fuse),
        "ensemble_size": int(ensemble_size),
    }


def read_layout(attrs: dict) -> Optional[LayoutMeta]:
    """Parse a store's attribute dict into a :class:`LayoutMeta`, or
    None for a pre-elastic store (no ``layout_schema`` attribute).

    Tolerant by design: a store written by a NEWER schema still parses
    (the attribute names keep their meaning by contract), and damaged
    individual attributes fall back to the dataclass defaults — the
    layout record is advisory provenance for the plan, never a
    load-bearing input to the selection reads themselves.
    """
    if attrs is None or "layout_schema" not in attrs:
        return None

    def _ints(name, default):
        try:
            v = attrs[name]
            return tuple(int(x) for x in v)
        except (KeyError, TypeError, ValueError):
            return default

    def _int(name, default):
        try:
            return int(attrs[name])
        except (KeyError, TypeError, ValueError):
            return default

    return LayoutMeta(
        schema=_int("layout_schema", LAYOUT_SCHEMA_VERSION),
        mesh_dims=_ints("mesh_dims", (1, 1, 1)),
        axis_names=tuple(
            str(a) for a in attrs.get("axis_names", ("x", "y", "z"))
        ),
        process_count=_int("process_count", 1),
        halo_depth=_int("halo_depth", 1),
        chain_fuse=_int("chain_fuse", 1),
        ensemble_size=_int("ensemble_size", 1),
    )


def shard_boxes(
    L: int, dims: Sequence[int]
) -> List[Tuple[Tuple[int, ...], Tuple[int, ...], Tuple[int, ...]]]:
    """Every shard's ``(coords, start, count)`` box in the true L^3
    domain for a ``dims`` decomposition — the per-shard selection reads
    a restore on that mesh issues (count clipped to the true domain;
    storage pad cells are reconstructed at the boundary value, never
    read). Row-major coordinate order, matching ``CartDomain.coords``.
    """
    dims = tuple(int(d) for d in dims)
    out = []
    dx, dy, dz = dims
    for cx in range(dx):
        for cy in range(dy):
            for cz in range(dz):
                sizes, offsets = zip(*(
                    block_size_offset(L, d, c)
                    for d, c in zip(dims, (cx, cy, cz))
                ))
                out.append(((cx, cy, cz), tuple(offsets), tuple(sizes)))
    return out


def overlapping_old_shards(
    box: Tuple[Tuple[int, ...], Tuple[int, ...]],
    L: int,
    old_dims: Sequence[int],
) -> List[Tuple[int, ...]]:
    """Coordinates of the OLD shards whose boxes intersect one new
    shard's ``(start, count)`` box — the communication pattern of the
    future ICI all-to-all device path (``reshard/restore.py``), and a
    diagnostic for the plan's describe output."""
    start, count = box
    hits = []
    for coords, ostart, ocount in shard_boxes(L, old_dims):
        if all(
            os_ < s + c and s < os_ + oc
            for s, c, os_, oc in zip(start, count, ostart, ocount)
        ):
            hits.append(coords)
    return hits


@dataclasses.dataclass(frozen=True)
class ReshardPlan:
    """The old->new restore plan for one run.

    ``changed`` is the headline: False means the store's recorded
    layout (if any) matches the adopting run and the restore is a plain
    same-shape resume; True means the selection reads below re-slice
    the global arrays into a genuinely different decomposition.
    """

    old: Optional[LayoutMeta]
    new: LayoutMeta
    L: int
    changed: bool
    #: Every new shard's (coords, start, count) selection-read box.
    boxes: Tuple = ()
    #: Elastic-ensemble record (``restore_ensemble``):
    #: ``{"restored": k, "grown": g, "new_n": n}`` — None for solo runs.
    members: Optional[dict] = None

    def describe(self) -> dict:
        return {
            "changed": self.changed,
            "old": self.old.describe() if self.old is not None else None,
            "new": self.new.describe(),
            "n_shards": len(self.boxes),
            "members": self.members,
        }


def plan_restore(
    old: Optional[LayoutMeta],
    new: LayoutMeta,
    *,
    L: int,
    allow: str = "auto",
) -> ReshardPlan:
    """Compute (and validate) the restore plan adopting ``new``.

    ``allow`` is the resolved ``reshard`` knob
    (``config.resolve_reshard``): ``"off"`` refuses any layout change
    with a loud :class:`ReshardError` naming both sides — the operator
    contract for runs that must never silently move. Infeasible
    targets (a mesh axis owning no true-domain cells, a non-positive
    dim) are errors here even though ``Simulation`` would also refuse
    at construction — the plan is consulted on restore paths where the
    target simulation may already exist.
    """
    dims = tuple(int(d) for d in new.mesh_dims)
    if len(dims) != 3 or any(d < 1 for d in dims):
        raise ReshardError(
            f"target mesh {dims} is not a valid 3D decomposition"
        )
    for d in dims:
        if d > 1 and -(-L // d) * (d - 1) >= L:
            raise ReshardError(
                f"target mesh {dims} is infeasible for L={L}: a block "
                f"of axis size {d} would own no true-domain cells"
            )
    changed = old is not None and (
        tuple(old.mesh_dims) != dims
        or int(old.process_count) != int(new.process_count)
    )
    if changed and allow == "off":
        raise ReshardError(
            f"checkpoint was written on mesh "
            f"{'x'.join(str(d) for d in old.mesh_dims)} "
            f"({old.process_count} process(es)) but this run adopts "
            f"{'x'.join(str(d) for d in dims)} "
            f"({new.process_count} process(es)) and reshard='off' "
            "refuses restore-time layout changes; set reshard='auto' "
            "(or GS_RESHARD=auto) to allow elastic resume"
        )
    return ReshardPlan(
        old=old, new=new, L=int(L), changed=changed,
        boxes=tuple(shard_boxes(L, dims)),
    )


def member_map(
    present: Sequence[bool], new_n: int,
    active: Optional[Sequence[bool]] = None,
) -> List[Tuple[str, int]]:
    """The elastic ensemble member plan: ``[("restore"|"init", i)]``
    for each of the ``new_n`` members of the resuming run.

    ``present[i]`` says whether member ``i``'s checkpoint store holds a
    durable step. Grow (``new_n`` beyond the present prefix) initializes
    the new trailing members from their spec; shrink simply has fewer
    entries than there are stores (trailing old members are dropped,
    their stores left untouched). A GAP — a missing store *before* a
    present one — is a loud :class:`ReshardError`: that is a lost or
    corrupt member, not a grow, and silently re-initializing it would
    fork the ensemble's history.

    ``active`` masks IDLE pack slots (``serve/scheduler.py`` padding,
    docs/SERVICE.md): an idle slot deliberately wrote no store, so its
    absence is never a gap and its action is always ``"init"`` — a
    requeued packed batch resumes its real members from the store
    quorum while the padding just re-initializes.
    """
    present_l = [bool(p) for p in present[:new_n]]
    present_l += [False] * (new_n - len(present_l))
    if active is None:
        active_l = [True] * new_n
    else:
        active_l = [bool(a) for a in list(active)[:new_n]]
        active_l += [True] * (new_n - len(active_l))
    eff = [p and a for p, a in zip(present_l, active_l)]
    if not any(eff):
        raise ReshardError(
            "no member checkpoint store holds a durable step — nothing "
            "to resume (delete restart=true to start from scratch)"
        )
    last_present = max(i for i, e in enumerate(eff) if e)
    missing = [
        i for i in range(last_present)
        if active_l[i] and not present_l[i]
    ]
    if missing:
        raise ReshardError(
            f"member checkpoint stores {missing} are missing or hold no "
            f"durable step while later members exist — a gap is a lost "
            "member, not an ensemble grow; restore it or roll the whole "
            "ensemble back"
        )
    return [
        ("restore" if eff[i] else "init", i)
        for i in range(new_n)
    ]
