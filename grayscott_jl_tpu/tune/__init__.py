"""Measured autotuner behind ``kernel_language = "Auto"`` dispatch.

The analytic ICI model (``parallel/icimodel.select_kernel``) projects a
kernel schedule from hand-calibrated constants; this package *measures*
the shortlist of plausible schedules on the real step function and
remembers the winner (docs/TUNING.md):

* :mod:`~.candidates` — top-N config shortlist (kernel mode x block
  planes x chain depth x comm_overlap) from the icimodel's projections,
  pruned by the SAME Mosaic feasibility gates the kernel dispatch
  applies;
* :mod:`~.measure` — compile-and-time each candidate with the repo's
  one timing discipline (``utils/benchmark.time_sim_rounds``: warmup
  chunk, completion sync, median-of-rounds) under a
  ``GS_AUTOTUNE_BUDGET_S`` wall budget;
* :mod:`~.cache` — persistent, versioned, atomically-written tuning
  cache keyed by (schema, device kind, platform, mesh, L, dtype,
  noise, jax version);
* :mod:`~.autotuner` — the mode knob (``GS_AUTOTUNE`` /
  ``autotune`` TOML key: off | cached | quick | full) and the decision
  record that lands in RunStats ``kernel_selection`` provenance.

Default mode is ``cached``: a cache hit applies the measured winner
with zero measurement; a miss falls back to the analytic pick
*unchanged*, so default behavior is bit-identical to a tuner-less
build (asserted in tests/unit/test_autotune.py).
"""

from .autotuner import TuneDecision, autotune, resolve_budget_s  # noqa: F401
from .cache import SCHEMA_VERSION, cache_key  # noqa: F401
