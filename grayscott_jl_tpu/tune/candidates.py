"""Candidate shortlist for the measured autotuner.

The search space is every knob the runner resolves at construction
time: kernel mode (Pallas chain vs XLA window chain), chain/fuse depth
(``GS_FUSE``), split-phase exchange on/off (``GS_COMM_OVERLAP``), and —
for the Pallas kernel — the DMA slab depth (``GS_BX``). Enumerating it
raw would be hundreds of compiles, so candidates are (a) pruned by the
SAME Mosaic feasibility gates the kernel dispatch applies
(``pallas_stencil.mosaic_gate_reason`` / ``max_feasible_fuse*`` /
``feasible_block_planes`` — the tuner must never time a schedule the
kernel would silently decline into its fallback) and (b) ranked by the
analytic ICI model (``icimodel.projected_step_us``) so the measured
top-N starts from the model's best guesses. The analytic pick itself is
ALWAYS in the shortlist: the measured-vs-model delta in the provenance
is only meaningful when both were timed under the same conditions.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

from ..parallel import icimodel
from ..parallel.domain import dims_create


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One concrete schedule the tuner can pin and time."""

    kernel: str  # "pallas" | "xla"
    fuse: int  # chain / temporal-blocking depth (GS_FUSE)
    comm_overlap: bool  # split-phase exchange armed (GS_COMM_OVERLAP)
    #: s-step exchange depth (GS_HALO_DEPTH, docs/TEMPORAL.md): one
    #: (fuse x halo_depth)-deep exchange per halo_depth chain rounds —
    #: the XLA window chain and the generated Pallas chains both run
    #: it (a Pallas candidate realizes k as the fuse*k in-kernel
    #: chain, VMEM-ledger-gated).
    halo_depth: int = 1
    bx: Optional[int] = None  # Pallas slab depth (GS_BX); None = auto
    projected_step_us: Optional[float] = None  # model rank, None = unscored
    analytic: bool = False  # this is the model's own pick
    #: Ensemble-only (docs/ENSEMBLE.md): the member-axis mesh split
    #: this candidate devotes to batching (None = not an ensemble run /
    #: keep the configured split), plus the spatial mesh that split
    #: implies (None = the run's own mesh). Together they span the
    #: batch-size-per-device x block-shape trade-off.
    member_shards: Optional[int] = None
    mesh: Optional[tuple] = None
    #: Mixed-precision compute posture this candidate runs at
    #: (docs/PRECISION.md): "f32" or "bf16_f32acc". The axis is only
    #: enumerated under an authorizing ``bf16_f32acc`` run posture —
    #: the winner decides, per config, whether bf16 actually pays.
    compute_precision: str = "f32"

    def label(self) -> str:
        parts = [self.kernel, f"fuse={self.fuse}",
                 "overlap" if self.comm_overlap else "fused"]
        if self.compute_precision != "f32":
            parts.insert(1, "bf16")
        if self.halo_depth != 1:
            parts.append(f"sk={self.halo_depth}")
        if self.bx is not None:
            parts.append(f"bx={self.bx}")
        if self.member_shards is not None:
            parts.append(f"mshards={self.member_shards}")
        return "/".join(parts)

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        if d["projected_step_us"] is not None:
            d["projected_step_us"] = round(d["projected_step_us"], 1)
        return d


def from_dict(d: dict) -> Candidate:
    """Inverse of :meth:`Candidate.as_dict` for cache records; unknown
    keys (a newer writer) are dropped rather than rejected."""
    fields = {f.name for f in dataclasses.fields(Candidate)}
    out = {k: v for k, v in d.items() if k in fields}
    if out.get("mesh") is not None:
        out["mesh"] = tuple(int(x) for x in out["mesh"])
    return Candidate(**out)


def _pallas_depths(local, itemsize: int, dims, kmax: int,
                   n_fields: int = 2) -> List[int]:
    """Chain depths the Mosaic gates admit for this block on this mesh
    — mirrors the caps the runner itself applies (``simulation.py``
    x-chain / xy-chain dispatch), restricted to depths the cost model
    can rank (measured fuse ratios)."""
    from ..ops import pallas_stencil as ps

    if min(local) < 2 or ps.mosaic_gate_reason(local, itemsize):
        return []
    n, m, p = dims
    sharded = n * m * p > 1
    if not sharded:
        cap = ps.max_feasible_fuse(*local, itemsize,
                                   max(icimodel.FUSE_COST_RATIO),
                                   n_fields=n_fields)
        lo = 1
    elif m == 1 and p == 1:
        cap = min(kmax, local[0])
        cap = ps.max_feasible_fuse(*local, itemsize, max(cap, 1),
                                   n_fields=n_fields)
        lo = 2
    else:
        cap = min(kmax, local[0], local[1])
        if p > 1:
            cap = min(cap, local[2] // 2)
        sublane = 16 if itemsize == 2 else 8
        cap = ps.max_feasible_fuse_ypad(*local, itemsize, max(cap, 1),
                                        sublane, n_fields=n_fields)
        lo = 2
    return [k for k in sorted(icimodel.FUSE_COST_RATIO)
            if lo <= k <= cap]


def _xla_depths(local, dims, kmax: int) -> List[int]:
    n, m, p = dims
    if n * m * p == 1:
        # The single-device XLA path is a plain per-step loop; depth is
        # not a knob there.
        return [1]
    return list(range(1, max(1, min(kmax, min(local))) + 1))


def generate(
    *,
    dims,
    L: int,
    platform: str,
    itemsize: int,
    fuse_cap: int,
    analytic_kernel: str,
    analytic_fuse: int,
    comm_overlap: bool,
    overlap_toggle: bool,
    link_gbps: float = 90.0,
    links: int = 6,
    top_n: int = 4,
    bx_variants: int = 0,
    ensemble: int = 1,
    member_shards: int = 1,
    pallas_allowed: bool = True,
    halo_depth: int = 0,
    compute_precision: str = "f32",
    n_fields: int = 2,
) -> List[Candidate]:
    """The ranked measurement shortlist for one run config.

    ``overlap_toggle`` widens the search across the split-phase knob
    (only when the operator left ``comm_overlap = "auto"`` — a pinned
    setting is respected, not searched). ``bx_variants`` adds up to
    that many alternative Pallas slab depths per surviving Pallas
    candidate (full mode only — each one is an extra compile).
    Off-TPU the Pallas rows are excluded outright: the interpret-mode
    path is a correctness tool ~1000x off, and timing it would burn the
    whole budget saying so.

    ``halo_depth`` is the s-step-exchange pin: 0 (auto) widens BOTH
    languages across k in {1, 2, 4} wherever the schedule is feasible;
    an explicit value is respected, not searched. XLA combinations are
    pruned by the same geometry rule ``simulation.py`` validates with
    a SettingsError (fuse x k <= min local extent); Pallas
    combinations by the same chain-dispatch geometry + VMEM slab
    ledger the runner's gate applies
    (``pallas_stencil.max_feasible_chain_depth`` — the generated
    kernel realizes k as the fuse*k in-kernel chain, so the deepened
    working set must fit VMEM).

    ``compute_precision`` is the run's posture (docs/PRECISION.md):
    ``bf16_f32acc`` arms the precision AXIS — every (kernel, depth,
    overlap, k) point is enumerated at BOTH precisions, the bf16
    variants priced with halved halo bytes (itemsize 2) and the
    :data:`~..parallel.icimodel.BF16_COMPUTE_RATIO` anchor discount —
    while ``f32``/``equality`` runs never see a bf16 candidate.

    Ensemble runs (``ensemble > 1``, ``member_shards`` the configured
    member-axis split) additionally search the batch-size x block-shape
    trade-off: every alternative split m' of the same device pool
    (m' | gcd(members, devices)) trades members-per-device-group
    against spatial block size — a candidate at m' carries its implied
    spatial mesh, and its score is the per-step projection scaled by
    the N/m' members each group advances.
    """
    n, m, p = dims
    sharded = n * m * p > 1
    local = tuple(-(-L // d) for d in dims)
    overlaps = [comm_overlap]
    if sharded and overlap_toggle:
        overlaps.append(not comm_overlap)

    # Precision axis (docs/PRECISION.md): only an authorizing
    # bf16_f32acc posture widens the search — the posture's own
    # precision is the analytic default, and the f32 variant rides
    # along so the measurement decides per config. f32/equality
    # postures never see a bf16 candidate (and a bf16-measured winner
    # is unreachable anyway — the posture is in the cache key).
    analytic_cp = (
        "bf16_f32acc" if compute_precision == "bf16_f32acc" else "f32"
    )
    precisions = (
        ["bf16_f32acc", "f32"] if compute_precision == "bf16_f32acc"
        else ["f32"]
    )

    def _isz(cp: str) -> int:
        return 2 if cp == "bf16_f32acc" else itemsize

    def _langs(cp: str) -> dict:
        out = {"xla": _xla_depths(local, dims, fuse_cap)}
        if platform == "tpu" and pallas_allowed:
            # pallas_allowed is the generator-feasibility gate
            # (``kernelgen.generation_gate_reason``): the fused kernel
            # is generated from the model's reaction, and the tuner
            # must never time — or cache a winner for — a Pallas
            # schedule the generator refuses to build. Feasibility is
            # re-gated per precision: bf16 halves the slab bytes and
            # can admit deeper chains; ``n_fields`` scales the slab
            # bytes the VMEM gates price.
            depths = _pallas_depths(local, _isz(cp), dims, fuse_cap,
                                    n_fields=n_fields)
            if depths:
                out["pallas"] = depths
        return out

    def score(kernel, fuse, ov, sk=1, cp="f32"):
        us = icimodel.projected_step_us(
            kernel, dims, L, fuse, itemsize=_isz(cp), links=links,
            link_gbps=link_gbps, local=local,
            overlap="auto" if ov else 0.0, halo_depth=sk,
            compute_precision=cp, n_fields=n_fields,
        )
        if us is not None and ensemble > 1:
            # Rank ensembles by the batch each device group carries so
            # alternative member-shard splits compare on aggregate.
            us = us * (ensemble / max(member_shards, 1))
        return us

    analytic_sk = max(1, int(halo_depth)) if halo_depth else 1

    def sstep_depths(kernel, fuse, cp="f32"):
        """s-step depths to enumerate for one (kernel, fuse):
        single-device runs have no s-step schedule; sharded candidates
        in BOTH languages search {1, 2, 4} (or honor the pin) within
        the same feasibility rule the runner validates — XLA's
        geometry bound (fuse x k <= min local extent), or the Pallas
        chain-dispatch caps + VMEM slab ledger on the fuse*k-deep
        working set (``max_feasible_chain_depth``)."""
        if not sharded:
            return [1]
        ks = [halo_depth] if halo_depth else [1, 2, 4]
        if kernel != "xla":
            from ..ops import pallas_stencil as ps

            isz = _isz(cp)
            sublane = 16 if isz == 2 else 8
            return [k for k in ks if ps.max_feasible_chain_depth(
                local, dims, isz, fuse * k, sublane,
                n_fields=n_fields,
            ) == fuse * k] or [1]
        return [k for k in ks if fuse * k <= min(local)] or [1]

    ens_tag = member_shards if ensemble > 1 else None
    out = []
    for cp in precisions:
        for kernel, depths in _langs(cp).items():
            for fuse in depths:
                for ov in overlaps if sharded else [False]:
                    for sk in sstep_depths(kernel, fuse, cp):
                        out.append(Candidate(
                            kernel=kernel, fuse=fuse, comm_overlap=ov,
                            halo_depth=sk,
                            projected_step_us=score(
                                kernel, fuse, ov, sk, cp
                            ),
                            analytic=(kernel == analytic_kernel
                                      and fuse == analytic_fuse
                                      and ov == comm_overlap
                                      and sk == analytic_sk
                                      and cp == analytic_cp),
                            member_shards=ens_tag,
                            compute_precision=cp,
                        ))

    if ensemble > 1:
        # Batch-size x block-shape trade-off: alternative member-axis
        # splits of the SAME device pool. Each m' implies a spatial
        # mesh over devices/m' chips advancing ensemble/m' members per
        # group; ranked by the per-step projection scaled by the batch
        # each group carries (the aggregate-throughput proxy — the
        # measurement, not the model, decides).
        total = n * m * p * member_shards
        import math

        for m_alt in range(1, math.gcd(ensemble, total) + 1):
            if m_alt == member_shards or ensemble % m_alt or total % m_alt:
                continue
            alt_dims = dims_create(total // m_alt, 3)
            alt_local = tuple(-(-L // d) for d in alt_dims)
            if any(-(-L // d) * (d - 1) >= L for d in alt_dims):
                continue  # a block would own no true-domain cells
            alt_sharded = total // m_alt > 1
            for fuse in _xla_depths(alt_local, alt_dims, fuse_cap):
                proj = icimodel.projected_step_us(
                    "xla", alt_dims, L, fuse,
                    itemsize=_isz(analytic_cp),
                    links=links, link_gbps=link_gbps, local=alt_local,
                    overlap="auto" if (comm_overlap and alt_sharded)
                    else 0.0,
                    compute_precision=analytic_cp,
                )
                out.append(Candidate(
                    kernel="xla", fuse=fuse,
                    comm_overlap=comm_overlap and alt_sharded,
                    projected_step_us=(
                        proj * (ensemble / max(m_alt, 1))
                        if proj is not None else None
                    ),
                    member_shards=m_alt,
                    mesh=tuple(alt_dims),
                    compute_precision=analytic_cp,
                ))
    if not any(c.analytic for c in out):
        # The analytic pick fell outside the enumerable space (e.g. a
        # depth with no measured ratio): still measure it — the
        # model-vs-measured delta is the point of the exercise.
        out.append(Candidate(
            kernel=analytic_kernel, fuse=analytic_fuse,
            comm_overlap=comm_overlap if sharded else False,
            halo_depth=analytic_sk if sharded else 1,
            projected_step_us=score(
                analytic_kernel, analytic_fuse,
                comm_overlap if sharded else False,
                analytic_sk if sharded else 1,
                analytic_cp),
            analytic=True,
            member_shards=ens_tag,
            compute_precision=analytic_cp,
        ))

    big = float("inf")
    out.sort(key=lambda c: (not c.analytic,
                            c.projected_step_us
                            if c.projected_step_us is not None else big))
    short = out[:max(top_n, 1)]

    if bx_variants > 0:
        from ..ops import pallas_stencil as ps

        extra = []
        for c in [c for c in short if c.kernel == "pallas"]:
            opts = ps.feasible_block_planes(
                *local, itemsize, c.fuse,
                mid_itemsize=ps.mid_itemsize_for("float32"
                                                 if itemsize == 4
                                                 else "bfloat16"),
                n_fields=n_fields,
            )
            auto = ps.pick_block_planes(*local, itemsize, c.fuse,
                                        n_fields=n_fields)
            for bx in [b for b in opts if b != auto][:bx_variants]:
                extra.append(dataclasses.replace(
                    c, bx=bx, analytic=False))
        short += extra
    return short
