"""Persistent tuning cache: one JSON file per tuning key.

Layout: ``<cache_dir>/v<SCHEMA_VERSION>/<digest>.json`` where the
digest is a sha1 of the canonical key JSON. The key carries every knob
that changes what a measurement means — device kind, platform, mesh
dims, L, dtype, noise, jax version, schema version — so a config drift
is a cache *miss*, never a wrong hit; bumping :data:`SCHEMA_VERSION`
orphans every old entry at once (stale-key invalidation is structural:
old entries live under the old ``v<N>/`` directory and are simply
never consulted).

Failure containment mirrors ``io/sidecar.read_keep_base``: a corrupt,
truncated, or wrong-shape cache file degrades to a documented miss
with a one-line warning — tuning state must never be able to crash a
run. Writes are atomic (same-directory temp file + ``os.replace``), so
a crash mid-store leaves either the old entry or a ``*.tmp*`` orphan
that readers never look at.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
from typing import Optional

from ..config.env import env_str

#: Bump when the record layout or the meaning of a measurement changes;
#: every existing cache entry becomes invisible (they live under the
#: old version's subdirectory). v2: the key grew the ``ensemble``
#: member count — a batched N-member run and a solo run at the same
#: (mesh, L, dtype) are different schedules and must never share a
#: winner. v3: the key grew the ``model`` name and ``n_fields`` — a
#: Brusselator run must never adopt a Gray-Scott-measured winner (a
#: different reaction is a different program, and a different field
#: count moves different halo bytes); stale v2 entries degrade to the
#: analytic pick exactly like any other miss. v4: the key grew
#: ``halo_depth`` — the operator's s-step exchange pin (0 = auto;
#: docs/TEMPORAL.md): a run pinned to a given k measures a constrained
#: candidate space, so pinned and auto runs must never share winners;
#: stale v3 entries degrade to the analytic pick with the usual
#: warning. v5: the key grew the ADOPTED placement — ``member_shards``
#: (the ensemble member mesh axis) and ``procs`` (process count):
#: elastic resharding (docs/RESHARD.md) makes the mesh a restore-time
#: decision, so one config legitimately runs on different placements
#: across resumes, and a winner tuned on placement A must never be
#: applied on placement B; stale v4 entries are structurally invisible
#: and degrade to the warned analytic pick like any other miss. v6:
#: the key grew the ``compute_precision`` posture and the
#: ``snapshot_codec`` posture (docs/PRECISION.md): a bf16_f32acc-
#: measured winner moves half the halo/HBM bytes of an f32 run and
#: must never be adopted by one (the bf16 posture also arms the
#: precision candidate axis, so its measured space is wider), and a
#: lossy-output run's boundary program differs from an exact run's;
#: stale v5 entries are structurally invisible and degrade to the
#: warned analytic pick like any other miss. v7: the key grew
#: ``kernel_generator`` — the version of the kernel-generator contract
#: (``ops/kernelgen.GENERATOR_VERSION``) whose generated Pallas
#: kernels the shortlist measured: a generator bump may change the
#: generated program (operation order, noise association, mid-stage
#: rounding), so winners measured against one generator's kernels must
#: never be adopted by another's; stale v6 entries are structurally
#: invisible and degrade to the warned analytic pick like any other
#: miss. v8: ``halo_depth`` semantics became per-language — the
#: generated Pallas chains now run a real s-step schedule (the
#: fuse*k-deep VMEM-resident in-kernel chain, docs/TEMPORAL.md), so
#: the shortlist enumerates Pallas k > 1 and a winner's ``halo_depth``
#: now changes the Pallas program too; v7 winners were measured under
#: the blanket Pallas k=1 gate and must never apply to runs where
#: k > 1 is a live schedule — stale v7 entries are structurally
#: invisible and degrade to the warned analytic pick like any other
#: miss.
SCHEMA_VERSION = 8


def cache_dir() -> str:
    """Cache root: ``GS_AUTOTUNE_CACHE`` env, else
    ``~/.cache/grayscott_tune``."""
    raw = env_str("GS_AUTOTUNE_CACHE", "").strip()
    if raw:
        return os.path.expanduser(raw)
    return os.path.join(os.path.expanduser("~"), ".cache",
                        "grayscott_tune")


def cache_key(
    *,
    device_kind: str,
    platform: str,
    dims,
    L: int,
    dtype: str,
    noise: float,
    jax_version: str,
    ensemble: int = 1,
    model: str = "grayscott",
    n_fields: int = 2,
    halo_depth: int = 0,
    member_shards: int = 1,
    procs: int = 1,
    compute_precision: str = "f32",
    snapshot_codec: str = "off",
    kernel_generator: int = 0,
) -> dict:
    """The canonical tuning key. Every field participates in the
    digest; adding a field is a schema bump (old digests stop
    matching). ``ensemble`` is the member count of a batched run
    (``ensemble/engine.py``) — 1 for solo runs; the vmapped batch
    changes the measured schedule, so ensemble sizes never share
    winners. ``model``/``n_fields`` (schema v3) identify the registered
    model: measurements of one reaction/field-count never apply to
    another. ``halo_depth`` (schema v4) is the operator's s-step
    exchange pin (0 = auto-searched): a pinned run measures a
    constrained shortlist, so its winner must never leak into an
    auto run or a differently-pinned one. ``member_shards``/``procs``
    (schema v5) complete the ADOPTED placement: with elastic
    resharding (docs/RESHARD.md) the same config can resume on a
    different member split or process count, and measurements never
    transfer across placements. ``compute_precision``/
    ``snapshot_codec`` (schema v6, docs/PRECISION.md) are the
    mixed-precision and lossy-output postures: a bf16-measured winner
    can never be adopted by an f32 run. ``kernel_generator`` (schema
    v7, docs/KERNELGEN.md) is the generator-contract version whose
    generated Pallas kernels were measured (0 = Pallas infeasible for
    this model, XLA-only shortlist)."""
    return {
        "schema": SCHEMA_VERSION,
        "device_kind": str(device_kind or ""),
        "platform": str(platform),
        "dims": [int(d) for d in dims],
        "L": int(L),
        "dtype": str(dtype),
        "noise": float(noise),
        "jax_version": str(jax_version),
        "ensemble": int(ensemble),
        "model": str(model),
        "n_fields": int(n_fields),
        "halo_depth": int(halo_depth),
        "member_shards": int(member_shards),
        "procs": int(procs),
        "compute_precision": str(compute_precision),
        "snapshot_codec": str(snapshot_codec),
        "kernel_generator": int(kernel_generator),
    }


def key_digest(key: dict) -> str:
    blob = json.dumps(key, sort_keys=True, separators=(",", ":"))
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()


def entry_path(key: dict, root: Optional[str] = None) -> str:
    root = cache_dir() if root is None else root
    return os.path.join(
        root, f"v{key.get('schema', SCHEMA_VERSION)}",
        key_digest(key) + ".json",
    )


def _warn(msg: str) -> None:
    print(f"gray-scott: warning: {msg}", file=sys.stderr)


def load(key: dict, root: Optional[str] = None) -> Optional[dict]:
    """The cached record for ``key``, or None on miss.

    A readable-but-invalid file (truncated JSON, wrong shape, digest
    collision with a different key, foreign schema) is a WARNED miss —
    the caller degrades to the analytic pick, exactly like a corrupt
    rollback sidecar degrades to no-sidecar."""
    path = entry_path(key, root)
    try:
        with open(path, encoding="utf-8") as f:
            rec = json.load(f)
    except (FileNotFoundError, NotADirectoryError):
        return None
    except (OSError, json.JSONDecodeError) as e:
        _warn(f"tuning cache entry {path} unreadable ({e}); "
              "falling back to the analytic pick")
        return None
    if not isinstance(rec, dict) or rec.get("schema") != key["schema"] \
            or rec.get("key") != key or "winner" not in rec:
        _warn(f"tuning cache entry {path} is stale or malformed; "
              "falling back to the analytic pick")
        return None
    return rec


def store(key: dict, record: dict, root: Optional[str] = None) -> str:
    """Atomically write ``record`` for ``key``; returns the entry path.

    The record is stamped with the schema and the full key so ``load``
    can verify it independently of the filename. The temp file lives in
    the same directory (``os.replace`` must not cross filesystems); a
    crash between write and replace leaves a ``*.tmp.<pid>`` orphan the
    readers never consult."""
    path = entry_path(key, root)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    rec = dict(record)
    rec["schema"] = key["schema"]
    rec["key"] = dict(key)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(rec, f, indent=1)
            f.write("\n")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return path
