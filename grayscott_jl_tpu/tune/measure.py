"""Micro-bench harness: compile and time candidates on the REAL step
function.

Each candidate is pinned exactly the way an operator would pin it — an
explicit ``kernel_language``, the ``comm_overlap`` Settings key, and
the ``GS_FUSE``/``GS_BX`` env overrides — then run through a fresh
``Simulation`` and the repo's one timing discipline
(``utils/benchmark.time_sim_rounds``: untimed compile-triggering
warmup chunk, completion sync, median-of-rounds). Measuring the real
runner is the whole point: the BENCH_r05 postmortem showed the analytic
model off by large factors away from its calibrated anchors, and no
model refinement beats running the actual program.

Budgeting: ``deadline`` is a wall-clock instant; a candidate is only
*started* while there is time left, and a started candidate finishes
its (short) rounds — compiles are the dominant cost and cannot be
interrupted mid-flight anyway. Skipped candidates are reported, never
silently dropped. A candidate that fails to build or time records its
error and the sweep continues: one infeasible schedule must not void
the whole tuning round.

Tests inject ``timer=`` (a fake with the ``time_sim_rounds`` contract)
so tier-1 exercises the full quick-mode path with zero real
measurement.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, List, Optional, Tuple

from .candidates import Candidate


@dataclasses.dataclass
class Measurement:
    """Timing outcome for one candidate."""

    candidate: Candidate
    median_us_per_step: Optional[float] = None
    best_us_per_step: Optional[float] = None
    rounds_us_per_step: Optional[list] = None
    error: Optional[str] = None

    def ok(self) -> bool:
        return self.error is None and self.median_us_per_step is not None

    def as_dict(self) -> dict:
        d = {"candidate": self.candidate.as_dict()}
        for k in ("median_us_per_step", "best_us_per_step",
                  "rounds_us_per_step", "error"):
            v = getattr(self, k)
            if v is not None:
                d[k] = v
        return d


def pinned_settings(settings, candidate: Candidate):
    """A Settings copy with the candidate's kernel/overlap pinned the
    way an operator would pin them (explicit language strings, so the
    measurement Simulation never re-enters Auto dispatch or the
    tuner). An ensemble candidate's ``member_shards`` is pinned into
    the ensemble table the same way."""
    import dataclasses as dc

    pinned = dc.replace(
        settings,
        kernel_language="Pallas" if candidate.kernel == "pallas"
        else "Plain",
        comm_overlap="on" if candidate.comm_overlap else "off",
        halo_depth=max(1, int(getattr(candidate, "halo_depth", 1))),
        # The candidate's precision posture (docs/PRECISION.md): the
        # probe sim materializes the candidate's storage dtype so a
        # bf16 measurement times bf16 halo/HBM bytes for real.
        compute_precision=getattr(
            candidate, "compute_precision", "f32"
        ) or "f32",
        # Tuning is a construction-time concern; the pinned probe sims
        # must not arm supervision, restart, or checkpoint machinery.
        supervise=False, restart=False, checkpoint=False,
    )
    ens = getattr(pinned, "ensemble", None)
    if ens is not None and candidate.member_shards is not None:
        pinned.ensemble = dc.replace(
            ens, member_shards=int(candidate.member_shards)
        )
    return pinned


class _env_pins:
    """Scoped env overrides (GS_FUSE/GS_BX read at trace time) restored
    on exit even when the candidate build throws."""

    def __init__(self, pins: dict):
        self.pins = {k: v for k, v in pins.items() if v is not None}
        self._saved = {}

    def __enter__(self):
        for k, v in self.pins.items():
            self._saved[k] = os.environ.get(k)
            os.environ[k] = str(v)
        return self

    def __exit__(self, *exc):
        for k, prior in self._saved.items():
            if prior is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = prior


def default_timer(sim, steps: int, rounds: int, deadline: float) -> dict:
    """The production timer: ``utils/benchmark.time_sim_rounds`` with
    the tuner's deadline threaded through so a slow config stops
    spending rounds once the budget is gone."""
    from ..utils.benchmark import time_sim_rounds

    return time_sim_rounds(sim, steps, rounds, deadline=deadline)


def measure_candidates(
    settings,
    cands: List[Candidate],
    *,
    dims,
    n_devices: Optional[int],
    seed: int = 0,
    deadline: float,
    steps: int,
    rounds: int,
    timer: Optional[Callable] = None,
    sim_cls=None,
) -> Tuple[List[Measurement], int]:
    """Time each candidate in shortlist order until the deadline.

    ``dims`` is the mesh of the run being tuned: the probe sims pin it
    via ``GS_TPU_MESH_DIMS`` so a measurement describes the SAME mesh
    the cache key does (an Auto run may have adopted a swept mesh the
    default factorization would not reproduce); a candidate carrying
    its own ``mesh`` (an ensemble member-shard split variant) pins that
    instead. ``sim_cls`` is the Simulation class to probe with — the
    ensemble engine passes ``EnsembleSimulation`` so batched schedules
    are measured as the batched programs they are. Returns
    ``(measurements, skipped)`` — measurements for every candidate that
    was started (successful or errored), and the count of candidates
    never started because the budget ran out.
    """
    if sim_cls is None:
        from ..simulation import Simulation as sim_cls

    timer = default_timer if timer is None else timer
    out: List[Measurement] = []
    skipped = 0
    for i, cand in enumerate(cands):
        if out and time.monotonic() >= deadline:
            skipped = len(cands) - i
            break
        pin_mesh = cand.mesh if cand.mesh is not None else dims
        pins = {"GS_FUSE": cand.fuse, "GS_BX": cand.bx,
                "GS_TPU_MESH_DIMS": ",".join(str(d) for d in pin_mesh),
                # The Settings pins below would lose to stray
                # GS_COMM_OVERLAP/GS_HALO_DEPTH/GS_COMPUTE_PRECISION
                # in the environment.
                "GS_COMM_OVERLAP": "on" if cand.comm_overlap else "off",
                "GS_HALO_DEPTH": max(
                    1, int(getattr(cand, "halo_depth", 1))
                ),
                "GS_COMPUTE_PRECISION": getattr(
                    cand, "compute_precision", "f32"
                ) or "f32",
                # A probe sim must never consult or write the tuning
                # cache itself.
                "GS_AUTOTUNE": "off"}
        try:
            with _env_pins(pins):
                sim = sim_cls(pinned_settings(settings, cand),
                              n_devices=n_devices, seed=seed)
                t = timer(sim, steps, rounds, deadline)
            out.append(Measurement(
                candidate=cand,
                median_us_per_step=round(t["median"] * 1e6, 1),
                best_us_per_step=round(t["best"] * 1e6, 1),
                rounds_us_per_step=[round(s * 1e6, 1)
                                    for s in t["rounds_s_per_step"]],
            ))
        except Exception as e:  # noqa: BLE001 — one bad schedule
            # must not void the sweep
            out.append(Measurement(candidate=cand,
                                   error=f"{type(e).__name__}: {e}"))
    return out, skipped


def best(measurements: List[Measurement]) -> Optional[Measurement]:
    """The fastest successful measurement by median, or None."""
    ok = [m for m in measurements if m.ok()]
    if not ok:
        return None
    return min(ok, key=lambda m: m.median_us_per_step)
