"""Mode knob + decision logic for the measured autotuner.

``GS_AUTOTUNE`` env (wins) / ``autotune`` TOML key:

* ``off``    — the analytic ICI-model pick, untouched; the tuner does
  not even read the cache. Bit-identical to a tuner-less build.
* ``cached`` — (default) cache hit applies the measured winner with
  ZERO measurement; miss falls back to the analytic pick *unchanged*.
  Default behavior on a fresh machine is therefore bit-identical to
  ``off``; machines that ran a sweep get the measured schedule for
  free.
* ``quick``  — on miss, measure the model's top-N shortlist (small
  N, short rounds) within ``GS_AUTOTUNE_BUDGET_S`` and persist the
  winner.
* ``full``   — wider shortlist including Pallas ``bx`` slab variants;
  same budget discipline.

The decision provenance (mode, cache hit/miss, candidates timed,
tuning seconds, model-vs-measured delta) rides in the RunStats
``kernel_selection`` section and the bench JSON, so every artifact says
whether its schedule was projected or measured.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Callable, Optional

from . import cache, candidates, measure
from ..config.env import env_int, env_str

MODES = ("off", "cached", "quick", "full")

#: Shortlist width per mode; env-overridable for sweeps.
_TOP_N = {"quick": 3, "full": 8}


def resolve_mode(settings=None) -> str:
    """``GS_AUTOTUNE`` env > ``autotune`` TOML key > ``"cached"`` —
    one resolution, owned by the config layer."""
    from ..config.settings import resolve_autotune

    return resolve_autotune(settings)


def resolve_budget_s() -> float:
    """Wall budget for one tuning round (``GS_AUTOTUNE_BUDGET_S``,
    default 120 s). The budget bounds when candidates *start*; a
    started compile runs to completion."""
    raw = os.environ.get("GS_AUTOTUNE_BUDGET_S", "120")
    try:
        v = float(raw)
    except ValueError as e:
        raise ValueError(
            f"GS_AUTOTUNE_BUDGET_S must be a number, got {raw!r}"
        ) from e
    if v <= 0:
        raise ValueError(f"GS_AUTOTUNE_BUDGET_S must be > 0, got {v}")
    return v


def _top_n(mode: str) -> int:
    raw = env_str("GS_AUTOTUNE_TOPN", "")
    if raw:
        return max(1, int(raw))
    return _TOP_N[mode]


@dataclasses.dataclass
class TuneDecision:
    """What the run should actually do, plus the story of why."""

    kernel: str
    fuse: Optional[int]  # None: leave the analytic/default depth alone
    comm_overlap: Optional[bool]  # None: leave the resolved value alone
    bx: Optional[int]
    provenance: dict
    #: Ensemble member-axis split the winner measured fastest (None:
    #: leave the configured split alone; docs/ENSEMBLE.md).
    member_shards: Optional[int] = None
    #: s-step exchange depth the winner measured fastest (None: leave
    #: the resolved halo_depth alone; docs/TEMPORAL.md).
    halo_depth: Optional[int] = None
    #: Compute-precision posture the winner measured fastest (None:
    #: leave the run's resolved posture alone; only an authorizing
    #: bf16_f32acc posture ever receives a value — docs/PRECISION.md).
    compute_precision: Optional[str] = None


def _emit_event(prov: dict, kernel: str) -> None:
    """Route the tuning decision into the unified run event stream
    (``obs/events.py``, ``GS_EVENTS``): cache hits/misses and
    measured-vs-analytic outcomes land on the same live timeline as
    faults and restarts — tuning happens inside the ``compile`` phase,
    which is exactly when an operator wonders what the run is doing."""
    from ..obs import events as obs_events

    stream = obs_events.get_events()
    if not stream.enabled:
        return
    winner = prov.get("winner") or {}
    stream.emit(
        "autotune", phase="compile",
        mode=prov.get("mode"), source=prov.get("source"),
        cache=prov.get("cache"), kernel=kernel,
        halo_depth=winner.get("halo_depth"),
        candidates_timed=prov.get("candidates_timed"),
        tuning_s=prov.get("tuning_s"),
    )


def _analytic_decision(mode: str, analytic_kernel: str,
                       extra: Optional[dict] = None) -> TuneDecision:
    prov = {"mode": mode, "source": "analytic", "cache": None,
            "candidates_timed": 0, "tuning_s": 0.0}
    if extra:
        prov.update(extra)
    _emit_event(prov, analytic_kernel)
    return TuneDecision(kernel=analytic_kernel, fuse=None,
                        comm_overlap=None, bx=None, provenance=prov)


def _winner_decision(mode: str, winner: dict, prov: dict) -> TuneDecision:
    ms = winner.get("member_shards")
    sk = winner.get("halo_depth")
    _emit_event(prov, winner["kernel"])
    return TuneDecision(
        kernel=winner["kernel"],
        fuse=int(winner["fuse"]),
        comm_overlap=bool(winner["comm_overlap"]),
        bx=winner.get("bx"),
        provenance=prov,
        member_shards=int(ms) if ms is not None else None,
        # Pre-v4 records carry no halo_depth; None leaves the run's
        # resolved value alone (they are structurally invisible anyway
        # — the schema bump orphaned them).
        halo_depth=int(sk) if sk is not None else None,
        compute_precision=winner.get("compute_precision"),
    )


def autotune(
    settings,
    *,
    dims,
    L: int,
    platform: str,
    device_kind: str,
    dtype: str,
    noise: float,
    itemsize: int,
    n_devices: Optional[int],
    seed: int,
    analytic_kernel: str,
    analytic_fuse: int,
    comm_overlap: bool,
    overlap_toggle: bool,
    link_gbps: float = 90.0,
    links: int = 6,
    timer: Optional[Callable] = None,
    ensemble: int = 1,
    member_shards: int = 1,
    sim_cls=None,
    model: str = "grayscott",
    n_fields: int = 2,
    pallas_allowed: bool = True,
    halo_depth: int = 0,
    procs: int = 1,
    compute_precision: str = "f32",
    snapshot_codec: str = "off",
    kernel_generator: int = 0,
) -> TuneDecision:
    """Resolve the measured schedule for one run config.

    Called from ``Simulation.__init__`` AFTER the analytic Auto
    dispatch (and its mesh adoption) settled, so ``dims`` is the mesh
    the run will actually use and the cache key describes the real
    config. ``timer`` is the test seam — a fake with the
    ``time_sim_rounds`` contract makes the whole quick path
    deterministic and measurement-free.

    Ensemble runs pass their member count (``ensemble``) — it joins
    the cache key (an N-member batched schedule never shares a winner
    with a solo run), widens the candidate space with member-shard
    split variants, and routes measurement through ``sim_cls`` (the
    ensemble engine) so candidates are timed as the batched programs
    they are.
    """
    import jax

    mode = resolve_mode(settings)
    gate = {"model": model, "n_fields": n_fields,
            "pallas_allowed": bool(pallas_allowed),
            "kernel_generator": int(kernel_generator),
            "halo_depth_pin": int(halo_depth),
            # The schema the decision was keyed/measured under (v8:
            # halo_depth semantics per-language, docs/TUNING.md) — in
            # the provenance so an artifact reader can tell which
            # halo_depth era a winner belongs to without the cache.
            "cache_schema": int(cache.SCHEMA_VERSION),
            "compute_precision": compute_precision,
            "snapshot_codec": snapshot_codec}
    if mode == "off":
        return _analytic_decision(mode, analytic_kernel, gate)

    # The key describes the ADOPTED placement (schema v5): with
    # elastic resharding the same config resumes on different meshes /
    # member splits / process counts, and winners never transfer.
    key = cache.cache_key(
        device_kind=device_kind, platform=platform, dims=dims, L=L,
        dtype=dtype, noise=noise, jax_version=jax.__version__,
        ensemble=ensemble, model=model, n_fields=n_fields,
        halo_depth=halo_depth, member_shards=member_shards,
        procs=procs, compute_precision=compute_precision,
        snapshot_codec=snapshot_codec,
        kernel_generator=kernel_generator,
    )
    rec = cache.load(key)
    if rec is not None:
        try:
            winner = dict(rec["winner"])
            prov = {
                "mode": mode, "source": "cache", "cache": "hit",
                "candidates_timed": 0, "tuning_s": 0.0,
                "winner": winner,
                "cache_created": rec.get("created"),
                "cache_path": cache.entry_path(key),
                **gate,
            }
            return _winner_decision(mode, winner, prov)
        except (KeyError, TypeError, ValueError) as e:
            # A verified-schema record with an unusable winner shape —
            # same degradation contract as a corrupt file.
            import sys

            print(f"gray-scott: warning: tuning cache winner unusable "
                  f"({e}); falling back to the analytic pick",
                  file=sys.stderr)

    if mode == "cached":
        # The zero-measurement contract: a miss changes NOTHING about
        # the run — the analytic pick goes through untouched.
        return _analytic_decision(mode, analytic_kernel,
                                  {"cache": "miss", **gate})

    # quick | full: measure the shortlist within the budget.
    budget_s = resolve_budget_s()
    t0 = time.monotonic()
    cands = candidates.generate(
        dims=dims, L=L, platform=platform, itemsize=itemsize,
        fuse_cap=max(analytic_fuse, 1), analytic_kernel=analytic_kernel,
        analytic_fuse=analytic_fuse, comm_overlap=comm_overlap,
        overlap_toggle=overlap_toggle, link_gbps=link_gbps, links=links,
        top_n=_top_n(mode),
        bx_variants=2 if mode == "full" else 0,
        ensemble=ensemble, member_shards=member_shards,
        pallas_allowed=pallas_allowed, halo_depth=halo_depth,
        compute_precision=compute_precision, n_fields=n_fields,
    )
    steps = env_int("GS_AUTOTUNE_STEPS", 20)
    rounds = env_int("GS_AUTOTUNE_ROUNDS",
                     2 if mode == "quick" else 3)
    ms, skipped = measure.measure_candidates(
        settings, cands, dims=dims, n_devices=n_devices, seed=seed,
        deadline=t0 + budget_s, steps=steps, rounds=rounds, timer=timer,
        sim_cls=sim_cls,
    )
    tuning_s = round(time.monotonic() - t0, 3)
    win = measure.best(ms)
    model = next((m for m in ms if m.candidate.analytic), None)
    prov = {
        "mode": mode, "cache": "miss", **gate,
        "candidates_timed": sum(1 for m in ms if m.ok()),
        "candidates_skipped": skipped,
        "candidates_errored": sum(1 for m in ms if not m.ok()),
        "tuning_s": tuning_s,
        "budget_s": budget_s,
    }
    if win is None:
        prov.update({"source": "analytic",
                     "reason": "no candidate measured successfully"})
        return _analytic_decision(mode, analytic_kernel, prov)

    winner = dict(win.candidate.as_dict())
    winner["median_us_per_step"] = win.median_us_per_step
    prov.update({
        "source": "measured",
        "winner": winner,
        "model_pick": (model.candidate.as_dict() if model else None),
        "model_pick_us": (model.median_us_per_step
                          if model and model.ok() else None),
        "measured_pick_us": win.median_us_per_step,
    })
    if model is not None and model.ok() and model.median_us_per_step:
        prov["model_vs_measured_speedup"] = round(
            model.median_us_per_step / win.median_us_per_step, 4
        )
    try:
        import datetime

        path = cache.store(key, {
            "winner": winner,
            "measurements": [m.as_dict() for m in ms],
            "provenance": {k: prov[k] for k in
                           ("mode", "candidates_timed", "tuning_s",
                            "budget_s")},
            "created": datetime.datetime.now(datetime.timezone.utc)
            .isoformat(timespec="seconds"),
        })
        prov["cache_path"] = path
    except OSError as e:
        import sys

        print(f"gray-scott: warning: could not persist tuning cache "
              f"({e}); this round's winner applies to this run only",
              file=sys.stderr)
    return _winner_decision(mode, winner, prov)
