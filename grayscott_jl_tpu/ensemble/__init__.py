"""Batched ensemble subsystem: N Gray-Scott scenarios, one launch.

* :mod:`.spec` — the ``[ensemble]`` TOML table (presets, per-member
  tables, linspace sweeps) -> :class:`~.spec.EnsembleSettings`;
* :mod:`.engine` — :class:`~.engine.EnsembleSimulation`, the vmapped
  member axis over the unchanged per-member step body;
* :mod:`.io` — member-indexed output/checkpoint stores, byte-identical
  to solo stores.

See docs/ENSEMBLE.md. The spec module is import-light (no JAX) so the
config layer can parse ensemble tables without touching the engine.
"""

from .spec import (  # noqa: F401
    EnsembleSettings,
    MemberSpec,
    PRESETS,
    resolve_seeds,
)

__all__ = [
    "EnsembleSettings",
    "EnsembleSimulation",
    "MemberSpec",
    "PRESETS",
    "resolve_seeds",
]


def __getattr__(name):
    # The engine pulls in jax + simulation; keep it lazy so importing
    # the package for spec parsing stays cheap and cycle-free.
    if name == "EnsembleSimulation":
        from .engine import EnsembleSimulation

        return EnsembleSimulation
    raise AttributeError(name)
