"""Member-indexed output/checkpoint stores for ensemble runs.

Each member gets its OWN stores, derived from the configured paths by
an index tag (``gs.bp`` -> ``gs.m00.bp``), each written through the
standard solo machinery (``io/stream.SimStream`` /
``io/checkpoint.CheckpointWriter``) under a per-member Settings copy
carrying that member's parameters. Consequences, all load-bearing:

* member ``k``'s stores are **byte-identical** to the stores of a solo
  run with member ``k``'s params and seed (provenance attributes
  included) — asserted in tier-1;
* restart/resume is per-member: each member resumes from its own
  checkpoint store, and the supervisor's "latest durable checkpoint"
  for an ensemble is the *minimum* durable step across member stores
  (``resilience/supervisor.latest_durable_checkpoint``) — a crash
  mid-boundary (some members checkpointed, some not) rolls every
  member back to the last step all of them have;
* every downstream tool (analysis readers, VTK/ParaView, chaos
  byte-identity asserts) consumes member stores with zero ensemble
  awareness.

The writer-facing classes mirror the solo interfaces exactly
(``write_step(step, blocks)`` / ``save(step, blocks)`` / ``close()``)
taking the ENSEMBLE snapshot's member-stacked 4D blocks; the member
split happens here (``engine.member_blocks``), on the async writer's
worker thread, not in the driver loop.
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional

from ..config.settings import Settings
from ..models import get_model
from .engine import member_blocks
from .spec import EnsembleSettings


def member_tag(i: int, n: int) -> str:
    """Zero-padded member tag, width from the member count (stable for
    a given ensemble size): ``m00`` .. ``m63``."""
    width = max(2, len(str(max(n - 1, 0))))
    return f"m{i:0{width}d}"


def member_path(path: str, i: int, n: int) -> str:
    """Member-indexed store path: the tag goes before the extension
    (``out/gs.bp`` -> ``out/gs.m03.bp``) so derived artifacts (VTK
    series, fault journals, sidecars) inherit the member tag too."""
    root, ext = os.path.splitext(path)
    return f"{root}.{member_tag(i, n)}{ext}" if ext else (
        f"{path}.{member_tag(i, n)}"
    )


def member_settings(settings: Settings, i: int) -> Settings:
    """The Settings a SOLO run of member ``i`` would use: member
    parameters substituted, store paths member-indexed, the ensemble
    table dropped. This is the one definition of "what member i means
    as a solo run" — the stream/checkpoint writers, the restore path,
    and the equality tests all build on it.

    Model-generic: member parameters land in the ``model_params``
    table (the ``[model]`` spelling) AND, where the model declares
    legacy flat keys (Gray-Scott's F/k/Du/Dv), in the flat Settings
    attributes too — both resolve to the same values, so a solo run
    configured either way is byte-identical."""
    ens: EnsembleSettings = settings.ensemble
    n = ens.n
    mem = ens.members[i]
    model = get_model(ens.model)
    params = mem.params()
    dt = params.pop("dt")
    noise = params.pop("noise")
    flat = {
        model.legacy_keys[k]: v for k, v in params.items()
        if k in model.legacy_keys
    }
    return dataclasses.replace(
        settings,
        dt=dt, noise=noise, **flat,
        model=model.name,
        model_params={
            **(getattr(settings, "model_params", None) or {}), **params,
        },
        output=member_path(settings.output, i, n),
        checkpoint_output=member_path(settings.checkpoint_output, i, n),
        restart_input=member_path(settings.restart_input, i, n),
        ensemble=None,
    )


class EnsembleStream:
    """N member output streams behind the solo ``SimStream`` interface."""

    def __init__(
        self,
        settings: Settings,
        domain,
        dtype,
        *,
        writer_id: int = 0,
        nwriters: int = 1,
        resume_step: Optional[int] = None,
    ):
        from ..io.stream import SimStream

        self.n = settings.ensemble.n
        # Idle pack slots (docs/SERVICE.md) get NO stores at all: a
        # padded member must leave zero filesystem footprint — the
        # member==solo byte-identity contract is about real members.
        self.members: List[Optional[SimStream]] = [
            SimStream(
                member_settings(settings, i), domain, dtype,
                writer_id=writer_id, nwriters=nwriters,
                resume_step=resume_step,
            )
            if settings.ensemble.members[i].active else None
            for i in range(self.n)
        ]

    def write_step(self, step: int, blocks, checksums=None) -> None:
        blocks = list(blocks)
        for i, stream in enumerate(self.members):
            if stream is not None:
                stream.write_step(
                    step, member_blocks(blocks, i),
                    checksums=(
                        checksums[i] if checksums is not None else None
                    ),
                )

    def close(self) -> None:
        for stream in self.members:
            if stream is not None:
                stream.close()


class EnsembleCheckpointWriter:
    """N member checkpoint stores behind the solo writer interface."""

    def __init__(
        self,
        settings: Settings,
        dtype,
        *,
        writer_id: int = 0,
        nwriters: int = 1,
        resume_step: Optional[int] = None,
        layout=None,
    ):
        from ..io.checkpoint import CheckpointWriter

        self.n = settings.ensemble.n
        # The SAME (spatial) layout record goes to every member store —
        # it is exactly what an equivalent solo run would write, which
        # preserves the member==solo store byte-identity contract.
        # Idle pack slots checkpoint nothing (their restore action is
        # re-initialization, reshard/plan.member_map).
        self.members: List[Optional[CheckpointWriter]] = [
            CheckpointWriter(
                member_settings(settings, i), dtype,
                writer_id=writer_id, nwriters=nwriters,
                resume_step=resume_step, layout=layout,
            )
            if settings.ensemble.members[i].active else None
            for i in range(self.n)
        ]

    def save(self, step: int, blocks, checksums=None) -> None:
        blocks = list(blocks)
        for i, writer in enumerate(self.members):
            if writer is not None:
                writer.save(
                    step, member_blocks(blocks, i),
                    checksums=(
                        checksums[i] if checksums is not None else None
                    ),
                )

    def close(self) -> None:
        for writer in self.members:
            if writer is not None:
                writer.close()


def restore_ensemble(sim, settings: Settings, *, allow: str = "auto"):
    """Restore the ensemble from its member-indexed checkpoint stores —
    elastically (docs/RESHARD.md).

    ``restart_step = -1`` resolves to the QUORUM step: the latest step
    every *present* member store holds durably (the minimum of the
    per-member latest steps) — after an uneven crash the whole ensemble
    rolls back together, keeping members in lockstep. An explicit
    ``restart_step`` must exist in every present member store.

    Elastic semantics: the configured member count N' may differ from
    the checkpointed N. **Grow** (N' > N): members beyond the present
    store prefix initialize from their spec at the resume step
    (``EnsembleSimulation.member_init_fields`` — the model's t=0 block;
    position-keyed noise means a late joiner equals a solo run whose
    integration begins at the resume step). **Shrink** (N' < N): only
    the first N' stores are consulted; trailing members are dropped,
    their stores left untouched. A GAP in the store prefix is a loud
    :class:`~..reshard.plan.ReshardError` (``reshard/plan.member_map``).
    The spatial mesh may change at the same time — each member restore
    is a full-host-array restore, so the member path is layout-agnostic
    by construction. Returns ``(restored_step, ReshardPlan)``.
    """
    import dataclasses as _dc

    from ..io.checkpoint import open_checkpoint, read_layout
    from ..reshard import plan as plan_mod
    from ..reshard.restore import layout_of
    from ..resilience import integrity

    n = settings.ensemble.n
    active = settings.ensemble.active
    # Idle pack slots never wrote a store and never will: their restore
    # action is re-initialization, not a selection read. Each member's
    # resumable step is the best any of its checkpoint REPLICAS can
    # serve (docs/RESILIENCE.md "Data integrity").
    latest = [
        integrity.latest_durable_step_replicated(
            member_path(settings.restart_input, i, n)
        )
        if active[i] else None
        for i in range(n)
    ]
    mapping = plan_mod.member_map(
        [s is not None for s in latest], n, active=active
    )
    restored = [i for action, i in mapping if action == "restore"]
    grown = [i for action, i in mapping if action == "init"]
    grown_real = [i for i in grown if active[i]]
    if grown_real and allow == "off":
        raise plan_mod.ReshardError(
            f"resuming {len(restored)} checkpointed members as {n} "
            "(ensemble grow) is an elastic resume and reshard='off' "
            "refuses it; set reshard='auto' (or GS_RESHARD=auto)"
        )
    want = settings.restart_step
    if want < 0:
        want = min(latest[i] for i in restored)

    field_names = get_model(settings.ensemble.model).field_names
    blocks = []
    old = None
    for action, i in mapping:
        if action == "init":
            blocks.append(sim.member_init_fields())
            continue
        ms = member_settings(settings, i)

        def read_member(candidate, ms=ms):
            reader, idx, step = open_checkpoint(candidate, ms, want)
            try:
                layout = read_layout(reader)
                return layout, tuple(
                    reader.get(name, step=idx)
                    for name in field_names
                )
            finally:
                reader.close()

        # Replica failover per member store: a corrupt or unreadable
        # primary fails over to its mirrors in health order
        # (replica_failover events per skip); a sole corrupted member
        # store refuses the whole ensemble restore loudly.
        layout, fields = integrity.restore_with_failover(
            ms.restart_input, read_member
        )
        if old is None:
            # Member 0 speaks for the ensemble's old spatial layout
            # (member stores are solo-identical, so they all carry
            # the same record).
            old = layout
        blocks.append(fields)
    plan = plan_mod.plan_restore(
        old, layout_of(sim), L=settings.L, allow=allow
    )
    members = {"restored": len(restored), "grown": len(grown_real),
               "new_n": n}
    idle = n - sum(1 for a in active if a)
    if idle:
        members["idle"] = idle
    plan = _dc.replace(
        plan, members=members,
        changed=plan.changed or bool(grown_real),
    )
    sim.restore_members(blocks, want)
    return want, plan
