"""Batched ensemble engine: N scenarios of one model in ONE executable.

A parameter sweep (e.g. the Gray-Scott phase diagram over F/k/Du/Dv,
or a Brusselator A/B sweep — members parametrize the run's registered
model) used to cost N full launches; here the N parameter sets run as one compiled program:
:class:`EnsembleSimulation` stacks a leading **member** axis onto the
fields, params, and PRNG keys, and ``vmap``-s the *unchanged* per-member
step body (``Simulation._local_run``) over it — stencil, in-jit noise,
temporal-blocking chains, and the ``lax.ppermute`` halo exchange all
batch through JAX's collective batching rules with zero ensemble-aware
code in ``ops/`` or ``parallel/``. That is the point: the member axis
composes with the existing spatial sharding instead of forking it.

Mesh: the member axis is optionally sharded on a ``member`` ('m') mesh
dimension in FRONT of the spatial axes — ``member_shards = m`` builds a
``(m, dx, dy, dz)`` mesh where each device group of ``dx*dy*dz`` chips
holds ``N/m`` members, and halo ppermutes still ride the spatial axes
only (members are independent; no member-axis collectives exist at
all).

Equality contract (asserted in tier-1, ``tests/unit/test_ensemble.py``):
member ``k`` of an N-member run is **bitwise identical** to a solo
:class:`~..simulation.Simulation` with member ``k``'s params and seed
on the same spatial mesh. Everything downstream leans on this — the
per-member output stores (``ensemble/io.py``) are byte-identical to
solo stores, so ensemble restart/resume and the chaos byte-identity
harness reuse the solo machinery unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.6 style
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map  # type: ignore

from ..config.settings import Settings
from ..parallel.domain import CartDomain
from ..simulation import (
    AXIS_NAMES,
    FieldSnapshot,
    Simulation,
    _SHARD_MAP_CHECK_FLAG,
    mesh_for_topology,
)
from . import spec as ensemble_spec

#: Mesh-axis name of the member dimension (in front of the spatial
#: ('x', 'y', 'z') axes).
MEMBER_AXIS = "m"


class EnsembleFieldSnapshot(FieldSnapshot):
    """A member-stacked snapshot: blocks carry a leading member axis
    and the health probe resolves per member."""

    #: Per-slot activity mask stamped by
    #: :meth:`EnsembleSimulation.snapshot_async`; None = every slot is
    #: a real member. Idle pack slots (docs/SERVICE.md) are excluded
    #: from health/numerics aggregation but still resolve per index.
    member_active = None

    def health_report(self):
        """Per-member :class:`~..resilience.health.EnsembleHealthReport`
        (or None) — each member's fused isfinite+range probe, so one
        diverging member is attributed by index instead of anonymously
        aborting the whole sweep. Idle pack slots are masked out of the
        aggregate verdict and the bad-member attribution."""
        if self._health is None:
            return None
        from ..resilience.health import EnsembleHealthReport, HealthReport

        finite, *minmax = (np.asarray(x) for x in self._health)
        return EnsembleHealthReport(tuple(
            HealthReport(
                bool(finite[i]),
                *(float(m[i]) for m in minmax),
                names=self.field_names,
            )
            for i in range(finite.shape[0])
        ), active=self.member_active)

    def numerics_report(self):
        """Per-member numerics statistics aggregated into one
        :class:`~..obs.numerics.NumericsReport` (``members`` carries
        the per-member rows; ``fields`` the cross-member aggregate over
        the ACTIVE slots) — the same attribution shape as the
        per-member health probe."""
        if self._numerics is None:
            return None
        from ..obs import numerics as obs_numerics

        vals = [np.asarray(x) for x in self._numerics]
        members = [
            obs_numerics.resolve_report(
                [v[i] for v in vals], self.field_names
            ).fields
            for i in range(vals[0].shape[0])
        ]
        return obs_numerics.NumericsReport.aggregate_members(
            members, active=self.member_active
        )

    def checksum_report(self):
        """Per-member device checksums ``[{field: int}, ...]`` (the
        vmapped integrity probe resolves one value per member per
        field) — the ensemble writers route member ``k``'s record to
        member ``k``'s store, keeping member stores byte-identical to
        solo stores."""
        if self._checksums is None:
            return None
        vals = [np.asarray(c) for c in self._checksums]
        return [
            {n: int(v[i]) for n, v in zip(self.field_names, vals)}
            for i in range(vals[0].shape[0])
        ]

    def _verify_checksums(self, host_parts) -> None:
        """Member-resolved verification: the device checksum of each
        member slice is recomputed from that member's landed host
        bytes, so silent write-path corruption is attributed by member
        index — the same attribution shape as the health probe."""
        from ..resilience.integrity import (
            CorruptionError,
            host_field_checksum,
        )

        vals = [np.asarray(c) for c in self._checksums]
        n = vals[0].shape[0]
        totals = [[0] * n for _ in self.field_names]
        for part in host_parts:
            m_off = part[0][0]
            for fi, arr in enumerate(part[2:]):
                for j in range(arr.shape[0]):
                    totals[fi][m_off + j] = (
                        totals[fi][m_off + j]
                        + host_field_checksum(arr[j])
                    ) % (1 << 32)
        for fi, name in enumerate(self.field_names):
            for i in range(n):
                want, got = int(vals[fi][i]), totals[fi][i]
                if want != got:
                    raise CorruptionError(
                        "device-side field checksum mismatch: device "
                        f"{want:#010x}, host {got:#010x} — snapshot "
                        "bytes were silently corrupted in flight",
                        step=self.step, var=name, member=i,
                    )


def member_blocks(blocks, member: int, member_offset: int = 0):
    """Extract one member's spatial ``(offsets, sizes, *fields)``
    blocks from member-stacked 4D snapshot blocks.

    Each 4D entry covers a member range ``[off_m, off_m + n_m)``; the
    entry contributes iff it holds ``member``. Returns solo-format 3D
    blocks — exactly what a solo run's ``local_blocks()`` yields, which
    is what keeps per-member stores byte-identical to solo stores.
    """
    out = []
    for offsets, sizes, *fblocks in blocks:
        off_m, n_m = offsets[0], sizes[0]
        if not (off_m <= member < off_m + n_m):
            continue
        i = member - off_m
        out.append(
            (tuple(offsets[1:]), tuple(sizes[1:]))
            + tuple(fb[i] for fb in fblocks)
        )
    return out


class EnsembleSimulation(Simulation):
    """N independent parameter sets advancing in one compiled launch."""

    snapshot_cls = EnsembleFieldSnapshot
    is_ensemble = True

    def __init__(
        self,
        settings: Settings,
        *,
        n_devices: Optional[int] = None,
        seed: int = 0,
        mesh_dims: Optional[Tuple[int, int, int]] = None,
    ):
        ens = getattr(settings, "ensemble", None)
        if ens is None:
            raise ValueError(
                "EnsembleSimulation requires settings.ensemble "
                "(an [ensemble] TOML table; docs/ENSEMBLE.md)"
            )
        self.ens: ensemble_spec.EnsembleSettings = ens
        self.n_members = ens.n
        self.member_shards = int(ens.member_shards)
        self.member_seeds = ensemble_spec.resolve_seeds(ens, seed)
        #: Per-slot activity mask (None = all real): idle pack slots
        #: (docs/SERVICE.md) advance inside the same compiled program
        #: but write no stores and never pollute health attribution or
        #: the aggregate cell-updates/s.
        self.member_active = (
            None if all(ens.active) else tuple(ens.active)
        )
        super().__init__(
            settings, n_devices=n_devices, seed=seed,
            mesh_dims=mesh_dims,
        )

    @property
    def active_member_count(self) -> int:
        """Real (non-idle) members — the count aggregate throughput
        and the driver's completion line are scaled by."""
        return self.ens.active_n

    # ------------------------------------------------- construction hooks

    def _make_domain(self, devices) -> CartDomain:
        m = self.member_shards
        if len(devices) % m:
            raise ValueError(
                f"member_shards = {m} does not divide the "
                f"{len(devices)} selected devices"
            )
        # The member axis consumes its devices in front; the spatial
        # decomposition (and therefore `self.sharded`, the halo
        # exchange, kernel dispatch, autotune mesh sweeps) sees only
        # the remaining count — unchanged solo semantics underneath.
        return CartDomain.create(
            len(devices) // m, self.settings.L,
            dims=self._mesh_dims_override,
        )

    def _make_params(self):
        """Member-stacked Params pytree of the run's model: every leaf
        is ``(N,)``, fed to the vmapped step body with ``in_axes=0``.
        Params live at the COMPUTE dtype, like the solo path
        (docs/PRECISION.md — f32 under the ``bf16_f32acc`` posture)."""
        return self.model.params_cls(*(
            jnp.asarray([mem.value(f) for mem in self.ens.members],
                        self.compute_dtype)
            for f in self.model.params_cls._fields
        ))

    def _resolve_use_noise(self) -> bool:
        # One compiled program for all members: the noise term is
        # traced in if ANY member draws (a member with noise = 0 then
        # adds an exact-zero field — see docs/ENSEMBLE.md for the
        # equality fine print).
        return any(mem.value("noise") != 0.0 for mem in self.ens.members)

    def _make_base_key(self, seed: int):
        """(N, 2) stacked PRNG keys — per-member position-keyed noise
        streams; member k's stream equals a solo run at its seed."""
        return jnp.stack([
            jax.random.PRNGKey(s) for s in self.member_seeds
        ])

    def _tune_extras(self) -> dict:
        return {
            "ensemble": self.n_members,
            "member_shards": self.member_shards,
            "sim_cls": type(self),
        }

    def _apply_tune_extras(self, decision) -> None:
        """Adopt a measured ``member_shards`` split (the batch-size ×
        block-shape trade-off axis) before the mesh is built."""
        m = getattr(decision, "member_shards", None)
        if m is None or int(m) == self.member_shards:
            return
        m = int(m)
        if self.n_members % m or self.domain.n_blocks * self.member_shards % m:
            return  # infeasible for this run's device/member counts
        total = self.domain.n_blocks * self.member_shards
        self.member_shards = m
        self.domain = CartDomain.create(total // m, self.settings.L)
        self.sharded = self.domain.n_blocks > 1
        decision.provenance["adopted_member_shards"] = m

    def _build_mesh(self, devices, backend: str) -> None:
        m = self.member_shards
        if m == 1 and not self.sharded:
            self.mesh = None
            self.field_sharding = None
            self.device = devices[0]
            return
        shape = (m,) + self.domain.dims
        self.mesh = Mesh(
            mesh_for_topology(shape, devices, backend),
            (MEMBER_AXIS,) + AXIS_NAMES,
        )
        self.field_sharding = NamedSharding(
            self.mesh, P(MEMBER_AXIS, *AXIS_NAMES)
        )

    def _probe_fn(self):
        from ..resilience.health import device_probe

        return jax.vmap(device_probe)

    def _numerics_probe_fn(self):
        """Numerics reductions vmapped over the member axis — each
        member's statistics resolve individually
        (``EnsembleFieldSnapshot.numerics_report``), so a drifting
        member of a sweep is attributed by index, mirroring the
        per-member health probe."""
        from ..obs.numerics import device_numerics_probe

        return jax.vmap(device_numerics_probe)

    def _resolve_numerics_host(self, raw):
        from ..obs import numerics as obs_numerics

        vals = [np.asarray(x) for x in raw]
        members = [
            obs_numerics.resolve_report(
                [v[i] for v in vals], self.model.field_names
            ).fields
            for i in range(vals[0].shape[0])
        ]
        return obs_numerics.NumericsReport.aggregate_members(
            members, active=self.member_active
        )

    def _checksum_probe_fn(self):
        """Integrity checksums vmapped over the member axis — one
        wrapped word sum per member per field, so corruption detection
        attributes the bad member by index."""
        from ..resilience.integrity import device_field_checksum

        return jax.vmap(device_field_checksum)

    def _apply_snapshot_bitflip(self, copies, field="u"):
        """Member-addressable ``bitflip``: corrupt ONE member's slice
        of the snapshot copy (member from ``GS_FAULT_MEMBER``, like
        ``poison_nan``) — detection must name this member while the
        other members' boundary bytes verify clean."""
        from ..config.env import env_int
        from ..resilience.integrity import apply_bitflip

        member = env_int("GS_FAULT_MEMBER", 0) % self.n_members
        i = self._field_index(field if field is not True else "u")
        flipped = apply_bitflip(copies[i], (member, 0, 0, 0))
        return copies[:i] + (flipped,) + copies[i + 1:]

    def snapshot_async(self, **kw):
        """Member-stacked snapshot with the activity mask stamped on,
        so the health/numerics resolution downstream (async writer
        thread, health guard) knows which slots are real members."""
        snap = super().snapshot_async(**kw)
        snap.member_active = self.member_active
        return snap

    # ------------------------------------------------------------ fields

    def _init_fields(self):
        """Member-stacked initial fields ``(N, *grid)``.

        The model's seed pattern is parameter-independent (it only
        depends on L), so every member starts from the same block —
        broadcast, not recomputed N times.
        """
        L, dtype, N = self.settings.L, self.dtype, self.n_members
        if self.mesh is None:
            return tuple(
                jax.device_put(
                    jnp.broadcast_to(f, (N,) + f.shape), self.device
                )
                for f in self.model.init(L, dtype)
            )

        dom = self.domain
        gshape = (N,) + dom.storage_shape

        def make(field_idx: int):
            def cb(index):
                m_sl, sp = index[0], index[1:]
                offsets = tuple(s.start or 0 for s in sp)
                sizes = tuple(
                    (s.stop or g) - (s.start or 0)
                    for s, g in zip(sp, dom.storage_shape)
                )
                blk = self.model.init(
                    L, dtype, offsets=offsets, sizes=sizes
                )[field_idx]
                n_m = (m_sl.stop or N) - (m_sl.start or 0)
                return jnp.broadcast_to(blk, (n_m,) + blk.shape)

            return jax.make_array_from_callback(
                gshape, self.field_sharding, cb
            )

        return tuple(make(i) for i in range(self.model.n_fields))

    # ------------------------------------------------------------ runner

    def _make_step_fn(self, nsteps: int, mesh=None):
        """The un-jitted ``nsteps``-step ensemble advance (see the base
        class: shared by the donating live runner and the non-donating
        SDC replay, optionally on a permuted ``mesh``).

        ``vmap`` of the per-member body over the leading axis; under a
        mesh, ``shard_map`` wraps the vmapped body with the member axis
        sharded on 'm' and the spatial axes exactly as solo — halo
        ppermutes batch through vmap's collective batching rules, so
        every per-member value (noise draws included) is computed by
        the same program a solo run compiles.
        """
        local = partial(self._local_run, nsteps=nsteps)
        nf = self.model.n_fields
        member_local = jax.vmap(
            local, in_axes=(0,) * nf + (0, None, 0)
        )
        if self.mesh is not None:
            fspec = P(MEMBER_AXIS, *AXIS_NAMES)
            mspec = P(MEMBER_AXIS)  # keys (N, 2) / params leaves (N,)
            return shard_map(
                member_local,
                mesh=self.mesh if mesh is None else mesh,
                in_specs=(fspec,) * nf + (mspec, P(), mspec),
                out_specs=(fspec,) * nf,
                **{_SHARD_MAP_CHECK_FLAG: False},
            )
        return member_local

    def _replay_arg_shardings(self, mesh):
        """(base_key, params) ride the member axis: both are
        member-stacked inputs sharded on 'm' (see ``_make_step_fn``'s
        in_specs), so a shadow replay must place them on the permuted
        mesh the same way."""
        ms = NamedSharding(mesh, P(MEMBER_AXIS))
        return ms, ms

    # ------------------------------------------------------------ output

    def _shard_parts(self, *arrays):
        """4D per-shard parts: offsets/sizes carry the member range in
        front of the spatial box; only the spatial dims are clipped to
        the true domain."""
        L = self.settings.L
        first = arrays[0]

        def box(index):
            idx = index if isinstance(index, tuple) else (index,)
            offsets = tuple(sl.start or 0 for sl in idx)
            sizes = tuple(
                (sl.stop or g) - (sl.start or 0)
                for sl, g in zip(idx, first.shape)
            )
            return offsets, sizes

        other_shards = [
            {box(s.index): s for s in a.addressable_shards}
            for a in arrays[1:]
        ]
        parts = []
        for sh in first.addressable_shards:
            offsets, sizes = box(sh.index)
            true = (sizes[0],) + tuple(
                min(L - o, s) for o, s in zip(offsets[1:], sizes[1:])
            )
            parts.append(
                (offsets, true, sh.data)
                + tuple(m[(offsets, sizes)].data for m in other_shards)
            )
        return parts

    def metrics_labels(self) -> dict:
        """Solo labels plus the member count: an 8-member batched
        launch and a solo run of the same model/mesh must not share a
        step-latency histogram — the batched step does N members of
        work per sample (``obs/metrics.py``)."""
        return {**super().metrics_labels(),
                "members": str(self.n_members)}

    def get_fields(self):
        """Host ``(N, L, L, L)`` copies of the model's fields, storage
        pad stripped."""
        jax.block_until_ready(self.fields)
        L = self.settings.L
        return tuple(
            np.asarray(f)[:, :L, :L, :L] for f in self.fields
        )

    def member_fields(self, member: int):
        """Host fields of one member — the solo ``get_fields`` shape."""
        return tuple(f[member] for f in self.get_fields())

    def poison_nan(self, field="u", member: Optional[int] = None
                   ) -> None:
        """Chaos hook: poison ONE member's field (default from
        ``GS_FAULT_MEMBER``, else member 0) — the per-member health
        attribution scenario: the guard must name this member, and the
        other members' trajectories must stay untouched."""
        from ..config.env import env_int

        if member is None:
            member = env_int("GS_FAULT_MEMBER", 0)
        member %= self.n_members
        i = self._field_index(field)
        arr = self.fields[i]
        poisoned = arr.at[(member,) + (0,) * (arr.ndim - 1)].set(
            jnp.asarray(float("nan"), arr.dtype)
        )
        self.fields = (
            self.fields[:i] + (poisoned,) + self.fields[i + 1:]
        )

    def _sdc_site(self, arr, device=None):
        """Member-addressable ``sdc`` poison site: the spatial center
        of the target device's shard, with the member coordinate pinned
        from ``GS_FAULT_MEMBER`` when set. Under ``member_shards > 1``
        pinning the member can move the cell into ANOTHER device's
        member-block — the owning device is re-resolved so the
        injection record (and the attribution the test asserts) names
        the device that actually holds the poisoned cell."""
        from ..config.env import env_int

        name, index = super()._sdc_site(arr, device)
        member = env_int("GS_FAULT_MEMBER", -1)
        if member >= 0:
            index = (member % self.n_members,) + index[1:]
            for sh in arr.addressable_shards:
                idx = (sh.index if isinstance(sh.index, tuple)
                       else (sh.index,))
                if all(
                    (sl.start or 0) <= c < (
                        g if sl.stop is None else sl.stop)
                    for sl, c, g in zip(idx, index, arr.shape)
                ):
                    d = sh.device
                    name = f"{d.platform}:{d.id}"
                    break
        return name, index

    # ------------------------------------------------------------ repack

    def repack(self, settings: Settings, *, seed: int = 0) -> None:
        """Rebind this (already-compiled) ensemble to a NEW member set
        — the warm-launch seam the serve scheduler packs requests onto
        (docs/SERVICE.md).

        Member parameters, PRNG keys, and seeds are runtime *inputs* of
        the compiled step program (``_make_params`` stacks them as
        arrays the jitted runner takes as arguments), so a batch with
        the same shape signature — member count, member_shards, model,
        L, precision, halo/overlap schedule, and noise tracing — reuses
        every cached executable in ``self._runners`` with zero
        recompilation. Anything that would change the traced program is
        refused loudly; the caller (``serve/worker.py``) keys its warm
        cache so that never happens in practice.
        """
        ens = getattr(settings, "ensemble", None)
        if ens is None:
            raise ValueError("repack needs settings.ensemble")
        if ens.n != self.n_members or int(ens.member_shards) != (
            self.member_shards
        ):
            raise ValueError(
                f"repack shape mismatch: compiled for "
                f"{self.n_members} members x {self.member_shards} "
                f"shards, got {ens.n} x {ens.member_shards}"
            )
        if ens.model != self.ens.model:
            raise ValueError(
                f"repack model mismatch: compiled for "
                f"{self.ens.model!r}, got {ens.model!r}"
            )
        if settings.L != self.settings.L:
            raise ValueError(
                f"repack L mismatch: compiled for L={self.settings.L}, "
                f"got L={settings.L}"
            )
        old_ens = self.ens
        self.ens = ens
        if self._resolve_use_noise() != self.use_noise:
            self.ens = old_ens
            raise ValueError(
                "repack noise-tracing mismatch: the compiled program "
                f"{'draws' if self.use_noise else 'draws no'} noise; "
                "pack batches keyed by noise as serve/scheduler does"
            )
        self.settings = settings
        self.n_members = ens.n
        self.member_seeds = ensemble_spec.resolve_seeds(ens, seed)
        self.member_active = (
            None if all(ens.active) else tuple(ens.active)
        )
        self.params = self._make_params()
        self.base_key = self._make_base_key(seed)
        self.fields = self._init_fields()
        self.step = 0
        # Per-launch provenance: a previous batch's elastic-restore
        # plan must not leak into the next batch's RunStats.
        self.reshard = None

    # ----------------------------------------------------------- restore

    def member_init_fields(self):
        """Host initial fields of ONE member — what an elastic GROW
        initializes new trailing members from (docs/RESHARD.md).

        The model's init is parameter-independent by declaration
        contract (it depends on L only), so a grown member's state at
        the resume step is exactly the block a fresh member would have
        started from; its trajectory from there equals a solo run of
        its params/seed whose integration *begins* at the resume step
        (the noise stream is keyed on absolute step, so joining late
        does not alias any other member's draws).
        """
        return tuple(
            np.asarray(f)
            for f in self.model.init(self.settings.L, self.dtype)
        )

    def restore_members(self, blocks: List, step: int) -> None:
        """Restore from per-member host field tuples (each field the
        true ``L^3`` domain, declaration order, from the member-indexed
        checkpoint stores).

        Host-side stack + one sharded device_put: ensemble restores are
        N small solo restores, not a selection-read fan-out — fine at
        ensemble scale (members are small by construction; huge-L runs
        use few members).
        """
        if len(blocks) != self.n_members:
            raise ValueError(
                f"restore_members got {len(blocks)} member states for "
                f"{self.n_members} members"
            )
        L = self.settings.L
        expected = (L, L, L)
        nf = self.model.n_fields
        per_field = [[] for _ in range(nf)]
        for i, member_fields in enumerate(blocks):
            member_fields = tuple(member_fields)
            if len(member_fields) != nf:
                raise ValueError(
                    f"member {i} checkpoint has {len(member_fields)} "
                    f"fields; model {self.model.name!r} declares {nf}"
                )
            for j, (name, f) in enumerate(
                zip(self.model.field_names, member_fields)
            ):
                f = jnp.asarray(f, self.dtype)
                if f.shape != expected:
                    raise ValueError(
                        f"member {i} checkpoint shape {name}={f.shape} "
                        f"does not match L={L}"
                    )
                per_field[j].append(f)
        stacked = [jnp.stack(fs) for fs in per_field]
        if self.mesh is not None and self.domain.padded:
            pads = [(0, 0)] + [
                (0, g - L) for g in self.domain.storage_shape
            ]
            stacked = [
                jnp.pad(f, pads, constant_values=bv)
                for f, bv in zip(stacked, self.model.boundaries)
            ]
        target = (
            self.field_sharding if self.mesh is not None else self.device
        )
        self.fields = tuple(
            jax.device_put(f, target) for f in stacked
        )
        self.step = int(step)
