"""Ensemble parameter specification: the ``[ensemble]`` TOML table.

An ensemble runs N independent parameter sets **of one registered
model** (the run's ``[model]`` selection; Gray-Scott by default) as ONE
compiled executable (``ensemble/engine``): the member axis is
``vmap``-ed through the whole step loop and optionally sharded on a
``member`` mesh dimension alongside the spatial axes. This module owns
the *description* of that ensemble — which members exist and what
parameters each carries — with three equivalent TOML spellings
(mixable; members concatenate in order):

``presets``
    Named parameter sets, namespaced per model
    (:data:`MODEL_PRESETS`); for Gray-Scott these are the Pearson
    phase-diagram classes::

        [ensemble]
        presets = ["spots", "stripes", "waves", "mitosis", "chaos"]

``[[ensemble.member]]`` tables
    Explicit per-member parameter tables over the model's declared
    parameter names (plus the framework's ``dt``/``noise``);
    unspecified fields inherit the base config values::

        [[ensemble.member]]
        F = 0.03
        k = 0.062

``[ensemble.sweep]``
    Linspace sweeps over ``members = N`` points; every swept key takes
    ``{ from = a, to = b }`` (inclusive endpoints) or an explicit
    N-long list; unswept parameters inherit the base config::

        [ensemble]
        members = 8
        [ensemble.sweep]
        F = { from = 0.01, to = 0.06 }
        k = { from = 0.045, to = 0.065 }

``member_shards = m`` shards the member axis over ``m`` devices (the
``member`` mesh dimension; must divide both the member count and the
device count). ``seeds = [..]`` pins per-member PRNG seeds; the
default is ``base_seed + index`` (resolved at Simulation
construction, so a solo run with ``seed = base_seed + k`` reproduces
member ``k`` bit-for-bit — the equality contract tier-1 asserts).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from ..models import FRAMEWORK_PARAMS, get_model

#: Gray-Scott member parameter fields — the historical flat tuple, kept
#: as the compat alias; the generic form is :func:`member_param_fields`
#: over the run's model declaration.
PARAM_FIELDS = ("Du", "Dv", "F", "k", "dt", "noise")

#: Named Gray-Scott phase-diagram parameter sets (Pearson 1993
#: classes): the (F, k) pairs that land the classic regimes with the
#: standard diffusion ratio Du = 2*Dv. The compat alias for
#: ``MODEL_PRESETS["grayscott"]``.
PRESETS: Dict[str, Dict[str, float]] = {
    "spots":   {"F": 0.030, "k": 0.062, "Du": 0.2, "Dv": 0.1},
    "stripes": {"F": 0.055, "k": 0.062, "Du": 0.2, "Dv": 0.1},
    "waves":   {"F": 0.018, "k": 0.051, "Du": 0.2, "Dv": 0.1},
    "mitosis": {"F": 0.037, "k": 0.065, "Du": 0.2, "Dv": 0.1},
    "chaos":   {"F": 0.026, "k": 0.051, "Du": 0.2, "Dv": 0.1},
}

#: Presets namespaced per registered model: ``presets = [...]`` in the
#: ``[ensemble]`` table resolves against the RUN's model, so a
#: Brusselator ensemble can never silently inherit Gray-Scott numbers.
MODEL_PRESETS: Dict[str, Dict[str, Dict[str, float]]] = {
    "grayscott": PRESETS,
    "brusselator": {
        # Distance from the Hopf/Turing thresholds at A=1 (B_c = 1+A^2).
        "steady":      {"A": 1.0, "B": 1.7, "Du": 0.2, "Dv": 0.02},
        "turing":      {"A": 1.0, "B": 3.0, "Du": 0.2, "Dv": 0.02},
        "oscillatory": {"A": 1.0, "B": 2.4, "Du": 0.2, "Dv": 0.02},
    },
    "fhn": {
        "excitable":   {"a": 0.7, "b": 0.8, "eps": 0.08, "I": 0.5},
        "oscillatory": {"a": 0.7, "b": 0.8, "eps": 0.08, "I": 1.0},
        "stiff":       {"a": 0.7, "b": 0.8, "eps": 0.02, "I": 0.5},
    },
    "heat": {
        "slow": {"D": 0.1},
        "fast": {"D": 0.4},
    },
}


def member_param_fields(model) -> Tuple[str, ...]:
    """The member parameter universe for one model: its declared params
    plus the framework-level ``dt`` and ``noise``."""
    return tuple(model.param_names) + FRAMEWORK_PARAMS


def _model_for(base):
    return get_model(getattr(base, "model", "grayscott") or "grayscott")


@dataclasses.dataclass(frozen=True)
class MemberSpec:
    """One ensemble member's parameter set, model-generic.

    ``values`` is the ordered ``(param, value)`` tuple over
    :func:`member_param_fields`; parameters read as attributes
    (``member.F``) for the two-field classics. ``seed`` is Optional:
    ``None`` resolves to ``base_seed + index`` at Simulation
    construction (``engine.EnsembleSimulation``), so the spec stays
    independent of the launch seed.
    """

    values: Tuple[Tuple[str, float], ...]
    seed: Optional[int] = None
    name: str = ""
    #: False marks an IDLE pack slot (``serve/scheduler.py`` pads a
    #: partially-filled batch up to a canonical executable shape so the
    #: warm-compile cache stays warm): the member still advances inside
    #: the vmapped launch (one program for all slots), but it writes no
    #: stores, is excluded from health attribution and from the
    #: aggregate cell-updates/s, and restores by re-initialization.
    #: TOML-declared members are always active.
    active: bool = True

    def params(self) -> Dict[str, float]:
        return dict(self.values)

    def value(self, key: str) -> float:
        for k, v in self.values:
            if k == key:
                return v
        raise KeyError(key)

    def __getattr__(self, key: str) -> float:
        # Only consulted for names not found normally — parameter
        # attribute access (member.F, member.noise).
        if key.startswith("_"):
            raise AttributeError(key)
        for k, v in self.__dict__.get("values", ()):
            if k == key:
                return v
        raise AttributeError(key)

    def describe(self) -> dict:
        d = dict(self.values)
        if self.seed is not None:
            d["seed"] = self.seed
        if self.name:
            d["name"] = self.name
        if not self.active:
            d["idle"] = True
        return d


@dataclasses.dataclass(frozen=True)
class EnsembleSettings:
    """Parsed ``[ensemble]`` table: the members plus the mesh split."""

    members: Tuple[MemberSpec, ...]
    member_shards: int = 1
    #: The registered model the members parametrize (every member is
    #: the same physics; ensembles sweep parameters, not equations).
    model: str = "grayscott"

    @property
    def n(self) -> int:
        return len(self.members)

    @property
    def active(self) -> Tuple[bool, ...]:
        """Per-slot activity mask (``MemberSpec.active``); idle pack
        slots (scheduler padding) read False."""
        return tuple(m.active for m in self.members)

    @property
    def active_n(self) -> int:
        """Real members only — what health attribution and aggregate
        throughput are scaled by; idle pack slots never count."""
        return sum(1 for m in self.members if m.active)

    def describe(self) -> dict:
        return {
            "model": self.model,
            "members": self.n,
            "active_members": self.active_n,
            "member_shards": self.member_shards,
            "params": [m.describe() for m in self.members],
        }


def _base_params(base) -> Dict[str, float]:
    """Every member parameter's base-config value, resolved through the
    model declaration (``[model]`` table > legacy flat keys >
    defaults)."""
    model = _model_for(base)
    vals = model.resolve_param_values(base)
    vals["dt"] = float(base.dt)
    vals["noise"] = float(base.noise)
    return vals


def _member(defaults: Dict[str, float], fields, *, seed=None,
            name="") -> MemberSpec:
    return MemberSpec(
        values=tuple((f, float(defaults[f])) for f in fields),
        seed=seed, name=name,
    )


def _linspace(a: float, b: float, n: int) -> List[float]:
    if n == 1:
        return [a]
    return [a + (b - a) * i / (n - 1) for i in range(n)]


def _sweep_members(table: dict, base, n: Optional[int]) -> List[MemberSpec]:
    model = _model_for(base)
    fields = member_param_fields(model)
    sweep = table["sweep"]
    if not isinstance(sweep, dict) or not sweep:
        raise ValueError("[ensemble.sweep] must be a non-empty table")
    # Resolve every swept key to an N-long value list first, inferring
    # N from explicit lists when `members` was not given.
    lists: Dict[str, List[float]] = {}
    for key, spec in sweep.items():
        if key not in fields:
            raise ValueError(
                f"[ensemble.sweep] key {key!r} is not a member parameter "
                f"of model {model.name!r} (one of {', '.join(fields)})"
            )
        if isinstance(spec, dict):
            if not {"from", "to"} <= set(spec):
                raise ValueError(
                    f"[ensemble.sweep] {key} needs 'from' and 'to'"
                )
            if n is None:
                raise ValueError(
                    "[ensemble] sweeps with from/to need an explicit "
                    "'members = N' count"
                )
            lists[key] = _linspace(float(spec["from"]), float(spec["to"]), n)
        elif isinstance(spec, (list, tuple)):
            lists[key] = [float(v) for v in spec]
            if n is None:
                n = len(lists[key])
        else:
            raise ValueError(
                f"[ensemble.sweep] {key} must be {{from=,to=}} or a list"
            )
    assert n is not None
    for key, vals in lists.items():
        if len(vals) != n:
            raise ValueError(
                f"[ensemble.sweep] {key} has {len(vals)} values, "
                f"expected {n}"
            )
    defaults = _base_params(base)
    out = []
    for i in range(n):
        params = dict(defaults)
        for key, vals in lists.items():
            params[key] = vals[i]
        out.append(_member(params, fields, name=f"sweep{i}"))
    return out


def from_toml(table: dict, base) -> EnsembleSettings:
    """Parse the ``[ensemble]`` TOML table against base settings.

    ``base`` supplies the model selection (``base.model``) and the
    default value for every member parameter the table leaves
    unspecified (duck-typed: anything carrying the model's parameter
    attributes works). Member parameter names, sweeps, and presets all
    resolve against the selected model's declaration.
    """
    if not isinstance(table, dict):
        raise ValueError("[ensemble] must be a TOML table")
    known = {"presets", "member", "sweep", "members", "member_shards",
             "seeds"}
    unknown = set(table) - known
    if unknown:
        raise ValueError(
            f"[ensemble] has unknown keys {sorted(unknown)}; "
            f"supported: {sorted(known)}"
        )
    model = _model_for(base)
    fields = member_param_fields(model)
    defaults = _base_params(base)
    model_presets = MODEL_PRESETS.get(model.name, {})
    members: List[MemberSpec] = []

    presets = table.get("presets")
    if presets is not None:
        if isinstance(presets, str):
            presets = (
                list(model_presets) if presets == "all" else [presets]
            )
        for name in presets:
            if name not in model_presets:
                raise ValueError(
                    f"Unknown ensemble preset {name!r} for model "
                    f"{model.name!r}; available: "
                    f"{', '.join(sorted(model_presets)) or '(none)'}"
                )
            members.append(_member(
                {**defaults, **model_presets[name]}, fields, name=name,
            ))

    for i, m in enumerate(table.get("member", []) or []):
        if not isinstance(m, dict):
            raise ValueError("[[ensemble.member]] entries must be tables")
        bad = set(m) - set(fields) - {"seed", "name"}
        if bad:
            raise ValueError(
                f"[[ensemble.member]] has unknown keys {sorted(bad)} "
                f"for model {model.name!r}"
            )
        params = {f: float(m.get(f, defaults[f])) for f in fields}
        members.append(_member(
            params, fields,
            seed=int(m["seed"]) if "seed" in m else None,
            name=str(m.get("name", f"member{i}")),
        ))

    if "sweep" in table:
        n = int(table["members"]) if "members" in table else None
        members.extend(_sweep_members(table, base, n))
    elif "members" in table and int(table["members"]) != len(members):
        raise ValueError(
            f"[ensemble] members = {table['members']} does not match the "
            f"{len(members)} members declared by presets/member tables"
        )

    if not members:
        raise ValueError(
            "[ensemble] declares no members (need presets, "
            "[[ensemble.member]] tables, or an [ensemble.sweep])"
        )

    seeds = table.get("seeds")
    if seeds is not None:
        if len(seeds) != len(members):
            raise ValueError(
                f"[ensemble] seeds has {len(seeds)} entries for "
                f"{len(members)} members"
            )
        members = [dataclasses.replace(m, seed=int(s))
                   for m, s in zip(members, seeds)]

    shards = int(table.get("member_shards", 1))
    if shards < 1:
        raise ValueError(f"member_shards must be >= 1, got {shards}")
    if len(members) % shards:
        raise ValueError(
            f"member_shards = {shards} does not divide the member "
            f"count {len(members)}"
        )
    return EnsembleSettings(
        members=tuple(members), member_shards=shards, model=model.name,
    )


def resolve_seeds(ens: EnsembleSettings, base_seed: int) -> List[int]:
    """Per-member PRNG seeds: the spec's pinned seed, else
    ``base_seed + index`` — the contract that makes member ``k`` of an
    ensemble reproduce a solo run with ``seed = base_seed + k``."""
    return [
        m.seed if m.seed is not None else base_seed + i
        for i, m in enumerate(ens.members)
    ]
