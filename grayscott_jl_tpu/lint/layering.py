"""Pass ``layering`` — the import-graph contracts of the package.

Three checks, all structural replacements for what used to be grep:

* **model isolation** — ``ops/`` and ``parallel/`` are model-generic
  execution machinery: they must not import concrete ``models/*``
  modules, nor the bare ``models`` package whose import registers
  them (``models.base``, the declaration protocol, is allowed). No
  exceptions: since the kernel generator (``ops/kernelgen``) builds
  the fused Pallas kernel from any model's declaration, the former
  ``pallas_stencil`` -> ``models.grayscott`` sanction is gone.
* **JAX-free at import** — the modules the docs promise are importable
  without JAX (``obs/*``, ``models/*``, ``config/*``, ``lint/*``,
  ``reshard/plan``, ``parallel/domain``) must keep every import-time
  import either non-JAX third-party/stdlib or inside the JAX-free set
  itself (so the property holds transitively).  ``TYPE_CHECKING``
  blocks and function-local imports are exempt — that is exactly how
  a lazy JAX dependency is spelled.
* **model-literal scan** — the original grep assertion, kept verbatim
  as a pass check: no model seeding constants or boundary-value
  definitions in shared code.
"""

from __future__ import annotations

import ast
import re
from typing import List, Tuple

from . import Finding
from .context import LintContext, SourceFile
from .astutil import resolve_imports

PASS_ID = "layering"

#: Layered subpackages that must stay model-generic.
SHARED_SUBPACKAGES = ("grayscott_jl_tpu.ops", "grayscott_jl_tpu.parallel")

#: Modules promised importable without JAX (docs/ANALYSIS.md).
JAXFREE_PREFIXES = (
    "grayscott_jl_tpu.obs",
    "grayscott_jl_tpu.lint",
    "grayscott_jl_tpu.models",
    "grayscott_jl_tpu.config",
)
JAXFREE_EXACT = (
    "grayscott_jl_tpu.reshard.plan",
    "grayscott_jl_tpu.parallel.domain",
)

#: The literal-scan regexes (kept from the original grep test body).
_BANNED_TOKENS = re.compile(
    r"\bSEED_HALF_WIDTH\b|\bSEED_U\b|\bSEED_V\b|\bSEED_T\b"
)
_BOUNDARY_DEF = re.compile(r"^\s*[UVTW]_BOUNDARY\s*=")
_UNQUALIFIED_BOUNDARY = re.compile(r"(?<![\w.])[UVT]_BOUNDARY\b")


def _in_jaxfree_set(module: str) -> bool:
    """True for modules in the JAX-free set — and for names *inside*
    one (``reshard.plan.shard_boxes`` is a function import, vouched
    for by its module)."""
    if any(
        module == e or module.startswith(e + ".")
        for e in JAXFREE_EXACT
    ):
        return True
    return any(
        module == p or module.startswith(p + ".")
        for p in JAXFREE_PREFIXES
    )


def _is_type_checking_if(node: ast.AST) -> bool:
    if not isinstance(node, ast.If):
        return False
    t = node.test
    return (isinstance(t, ast.Name) and t.id == "TYPE_CHECKING") or (
        isinstance(t, ast.Attribute) and t.attr == "TYPE_CHECKING"
    )


def _import_time_imports(
    sf: SourceFile,
) -> List[Tuple[ast.AST, List[str]]]:
    """Imports executed when the module is imported: everything except
    function bodies and ``TYPE_CHECKING`` blocks."""
    out: List[Tuple[ast.AST, List[str]]] = []

    def walk(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
            ):
                continue
            if _is_type_checking_if(child):
                continue
            if isinstance(child, (ast.Import, ast.ImportFrom)):
                out.append((child, resolve_imports(sf, child)))
            else:
                walk(child)

    walk(sf.tree)
    return out


def _all_imports(sf: SourceFile) -> List[Tuple[ast.AST, List[str]]]:
    out: List[Tuple[ast.AST, List[str]]] = []
    for node in ast.walk(sf.tree):
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            out.append((node, resolve_imports(sf, node)))
    return out


def run(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for sf in ctx.package_files():
        if any(
            sf.module.startswith(p + ".") for p in SHARED_SUBPACKAGES
        ):
            findings.extend(_check_model_isolation(sf))
            findings.extend(_check_literals(sf))
        if _in_jaxfree_set(sf.module):
            findings.extend(_check_jaxfree(sf))
    return findings


def _check_model_isolation(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    for node, names in _all_imports(sf):
        for name in names:
            # The bare package import is as concrete as a module
            # import: ``import grayscott_jl_tpu.models`` registers
            # every built-in model as a side effect.
            if name != "grayscott_jl_tpu.models" and not name.startswith(
                "grayscott_jl_tpu.models."
            ):
                continue
            if name == "grayscott_jl_tpu.models.base":
                continue
            findings.append(Finding(
                PASS_ID, sf.rel, node.lineno,
                f"shared code imports concrete model module "
                f"{name!r} — ops/ and parallel/ must stay "
                f"model-generic",
                hint="consume the declaration passed in as the "
                     "`model` argument instead of importing one",
            ))
    return findings


def _check_jaxfree(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    for node, names in _import_time_imports(sf):
        for name in names:
            top = name.split(".", 1)[0]
            if top in ("jax", "jaxlib"):
                findings.append(Finding(
                    PASS_ID, sf.rel, node.lineno,
                    f"{sf.module} must be importable without JAX but "
                    f"imports {name!r} at module scope",
                    hint="move the import inside the function that "
                         "needs it",
                ))
            elif top == "grayscott_jl_tpu" and not _in_jaxfree_set(
                name
            ):
                # Importing a sibling that is itself allowed to pull
                # JAX breaks the property transitively.
                findings.append(Finding(
                    PASS_ID, sf.rel, node.lineno,
                    f"JAX-free module {sf.module} imports {name!r}, "
                    f"which is outside the JAX-free set",
                    hint="import it lazily, or add the target to the "
                         "JAX-free set if it genuinely avoids JAX at "
                         "import",
                ))
    return findings


def _check_literals(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []
    in_parallel = sf.module.startswith("grayscott_jl_tpu.parallel.")
    for i, line in enumerate(sf.lines, start=1):
        if _BANNED_TOKENS.search(line):
            findings.append(Finding(
                PASS_ID, sf.rel, i,
                "model seeding constants belong in models/",
                hint="read them from the model declaration",
            ))
        if _BOUNDARY_DEF.search(line):
            findings.append(Finding(
                PASS_ID, sf.rel, i,
                "boundary values are model declarations — shared "
                "code must not define them",
                hint="thread the model's boundary constants through "
                     "the call instead",
            ))
        elif in_parallel and "BOUNDARY" in line:
            findings.append(Finding(
                PASS_ID, sf.rel, i,
                "parallel/ must receive boundaries via the model "
                "declaration, not name them",
            ))
        elif not in_parallel and _UNQUALIFIED_BOUNDARY.search(line):
            findings.append(Finding(
                PASS_ID, sf.rel, i,
                "boundary constants must come from the model "
                "declaration (qualified reads only)",
            ))
    return findings
