"""Small AST helpers shared by the passes.  Stdlib only."""

from __future__ import annotations

import ast
import sys
from typing import Iterator, List, Optional, Tuple

from .context import SourceFile


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    """The dotted name a call targets, else None."""
    return dotted(call.func)


def tail_name(node: ast.AST) -> Optional[str]:
    """The final identifier of a Name/Attribute (``self._run`` ->
    ``_run``)."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def is_stdlib(module: str) -> bool:
    top = module.split(".", 1)[0]
    return top == "__future__" or top in sys.stdlib_module_names


def resolve_imports(sf: SourceFile, node: ast.AST) -> List[str]:
    """Absolute dotted module names an Import/ImportFrom statement
    references (relative imports resolved against the file's module).

    ``from ..models import grayscott`` in ``ops/pallas_stencil`` yields
    both ``grayscott_jl_tpu.models`` and
    ``grayscott_jl_tpu.models.grayscott`` — an imported *name* may be a
    submodule, and layering checks need to see it either way."""
    if isinstance(node, ast.Import):
        return [alias.name for alias in node.names]
    if not isinstance(node, ast.ImportFrom):
        return []
    if node.level == 0:
        base = node.module or ""
    else:
        parts = sf.module.split(".")
        if not sf.is_package:
            parts = parts[:-1]
        if node.level > 1:
            parts = parts[: len(parts) - (node.level - 1)]
        base = ".".join(parts)
        if node.module:
            base = f"{base}.{node.module}" if base else node.module
    out = [base] if base else []
    for alias in node.names:
        if alias.name != "*" and base:
            out.append(f"{base}.{alias.name}")
    return out


FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)


def iter_functions(
    tree: ast.Module,
) -> Iterator[Tuple[str, ast.AST, Tuple[ast.AST, ...]]]:
    """Yield ``(qualname, func_node, parents)`` for every function and
    lambda, with ``qualname`` like ``Simulation._runner.<locals>.chain``
    abbreviated to dotted defs only (``Simulation._runner.chain``)."""

    def walk(node: ast.AST, prefix: str, parents: Tuple[ast.AST, ...]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, FuncDef):
                qual = f"{prefix}{child.name}"
                yield qual, child, parents
                yield from walk(child, qual + ".", parents + (child,))
            elif isinstance(child, ast.ClassDef):
                yield from walk(
                    child, f"{prefix}{child.name}.", parents + (child,)
                )
            else:
                yield from walk(child, prefix, parents)

    yield from walk(tree, "", ())


def enclosing_function_names(
    parents: Tuple[ast.AST, ...]
) -> List[str]:
    return [
        p.name for p in parents if isinstance(p, FuncDef)
    ]
