"""Pass ``donation`` — recompile and donated-buffer hazards.

Best-effort *warnings* (the other passes are contracts; these are the
two jit footguns that cost silent performance or correctness):

* **jit-in-loop** — constructing ``jax.jit`` (or ``shard_map``)
  inside a ``for``/``while`` body builds a fresh traced callable per
  iteration: at best a cache lookup per step, at worst a recompile.
  Runner construction belongs outside the loop (cached, like
  ``Simulation._runner``).
* **use-after-donate** — a call through a callable built with
  ``donate_argnums`` invalidates the donated argument buffers; a
  later read of the same Python name in the same function is a
  use-after-free on device memory (XLA may have aliased the buffer
  into the output).  Reassignment (the canonical
  ``fields = runner(*fields)``) clears the hazard.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Sequence, Set, Tuple

from . import Finding
from .context import LintContext, SourceFile
from .astutil import dotted, iter_functions

PASS_ID = "donation"


def run(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for sf in ctx.package_files():
        findings.extend(_jit_in_loop(sf))
        for qual, fnode, parents in iter_functions(sf.tree):
            findings.extend(_use_after_donate(sf, qual, fnode))
    return findings


def _jit_in_loop(sf: SourceFile) -> List[Finding]:
    findings: List[Finding] = []

    def walk(node: ast.AST, loop_depth: int) -> None:
        for child in ast.iter_child_nodes(node):
            d = loop_depth
            if isinstance(child, (ast.For, ast.While)):
                d += 1
            elif isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda),
            ):
                # A def inside a loop runs at call time, not per
                # iteration of this loop.
                d = 0
            if isinstance(child, ast.Call) and d > 0:
                name = dotted(child.func)
                tail = name.split(".")[-1] if name else None
                if tail in ("jit", "shard_map"):
                    findings.append(Finding(
                        PASS_ID, sf.rel, child.lineno,
                        f"{name} constructed inside a loop — every "
                        f"iteration rebuilds the traced callable",
                        hint="hoist construction out of the loop and "
                             "cache the compiled callable",
                        severity="warning",
                    ))
            walk(child, d)

    walk(sf.tree, 0)
    return findings


def _donating_locals(fnode: ast.AST) -> Dict[str, Sequence[int]]:
    """Local names bound to ``jax.jit(..., donate_argnums=...)``."""
    out: Dict[str, Sequence[int]] = {}
    for node in ast.walk(fnode):
        if not (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        name = dotted(call.func)
        if not name or name.split(".")[-1] != "jit":
            continue
        for kw in call.keywords:
            if kw.arg != "donate_argnums":
                continue
            positions: List[int] = []
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(
                v.value, int
            ):
                positions = [v.value]
            elif isinstance(v, (ast.Tuple, ast.List)):
                for e in v.elts:
                    if isinstance(e, ast.Constant) and isinstance(
                        e.value, int
                    ):
                        positions.append(e.value)
            if positions:
                out[node.targets[0].id] = positions
    return out


def _use_after_donate(
    sf: SourceFile, qual: str, fnode: ast.AST
) -> List[Finding]:
    donors = _donating_locals(fnode)
    if not donors:
        return []
    findings: List[Finding] = []
    # Donation call sites: donated positional args that are bare
    # names.
    donated: List[Tuple[int, str]] = []  # (call line, var name)
    stores: Dict[str, List[int]] = {}
    loads: Dict[str, List[int]] = {}
    for node in ast.walk(fnode):
        if isinstance(node, ast.Name):
            target = (
                stores if isinstance(node.ctx, ast.Store) else loads
            )
            target.setdefault(node.id, []).append(node.lineno)
        if not isinstance(node, ast.Call):
            continue
        cname = dotted(node.func)
        if not cname or cname not in donors:
            continue
        for pos in donors[cname]:
            if pos < len(node.args) and isinstance(
                node.args[pos], ast.Name
            ):
                donated.append(
                    (node.lineno, node.args[pos].id)
                )
    for call_line, var in donated:
        # The donated name is dead until reassigned; any load after
        # the donating call and before the next store is a hazard.
        # The canonical rebind stores on the donating call's own line
        # (``u = runner(u, v)``), so the clearing store scan is >=.
        next_store = min(
            (ln for ln in stores.get(var, ()) if ln >= call_line),
            default=None,
        )
        for ln in loads.get(var, ()):
            if ln <= call_line:
                continue
            if next_store is not None and ln >= next_store:
                continue
            findings.append(Finding(
                PASS_ID, sf.rel, ln,
                f"{var!r} was donated to a jit call at line "
                f"{call_line} in {qual!r} and read again here — its "
                f"device buffer may already be aliased",
                hint="rebind the result (x = runner(x)) or drop "
                     "donate_argnums for this argument",
                severity="warning",
            ))
    return findings
