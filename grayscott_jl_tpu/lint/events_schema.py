"""Pass ``event-schema`` — GS_EVENTS kinds vs ``gs_report --check``.

The unified run event stream (``obs/events.py``) promises one schema
per record *kind*, and ``scripts/gs_report.py --check`` is the CI
validator of that promise.  The two drift independently: a producer
can invent a kind the checker never validates, and the checker can
keep validating a kind nothing emits anymore.  This pass closes the
loop statically:

* every kind emitted in the tree — a string-literal first argument to
  an ``.emit(...)`` call, or a ``journal.record(event="...")`` (the
  journal mirrors every record onto the stream with the ``event`` name
  as the stream kind) — must be a key of gs_report's
  ``EVENT_KIND_SCHEMA`` registry;
* every registry key must be emitted somewhere (no dead validators).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Tuple

from . import Finding
from .context import LintContext, SourceFile
from .astutil import dotted

PASS_ID = "event-schema"

#: The registry the checker side must declare.
REGISTRY_NAME = "EVENT_KIND_SCHEMA"
REGISTRY_FILE = "scripts/gs_report.py"


def emitted_kinds(ctx: LintContext) -> Dict[str, Tuple[str, int]]:
    """``kind -> (rel path, line)`` of the first emit site found."""
    out: Dict[str, Tuple[str, int]] = {}
    for sf in ctx.package_files():
        for node in ast.walk(sf.tree):
            # Journal events built as dict literals and passed via
            # ``record(**event)`` (the watchdog's hang record, the
            # health guard's report) still name their kind statically.
            if isinstance(node, ast.Dict):
                for k, v in zip(node.keys, node.values):
                    if (isinstance(k, ast.Constant)
                            and k.value == "event"
                            and isinstance(v, ast.Constant)
                            and isinstance(v.value, str)):
                        out.setdefault(v.value, (sf.rel, v.lineno))
                continue
            if not isinstance(node, ast.Call):
                continue
            # The receiver may be a call chain
            # (``get_events().emit``): classify by attribute tail,
            # not by a fully-resolvable dotted name.
            if isinstance(node.func, ast.Attribute):
                tail = node.func.attr
            else:
                name = dotted(node.func)
                tail = name.split(".")[-1] if name else None
            kind: Optional[str] = None
            if tail == "emit" and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, str
                ):
                    kind = arg.value
            elif tail == "record":
                for kw in node.keywords:
                    if kw.arg == "event" and isinstance(
                        kw.value, ast.Constant
                    ) and isinstance(kw.value.value, str):
                        kind = kw.value.value
            if kind is not None:
                out.setdefault(kind, (sf.rel, node.lineno))
    return out


def _registry_source(ctx: LintContext) -> Optional[SourceFile]:
    for sf in ctx.files:
        if sf.rel == REGISTRY_FILE:
            return sf
    path = os.path.join(ctx.root, REGISTRY_FILE)
    if os.path.isfile(path):
        return SourceFile(ctx.root, path)
    return None


def registry_kinds(
    sf: SourceFile,
) -> Optional[Dict[str, int]]:
    """``kind -> line`` of the checker's registry dict literal, or
    None when the registry assignment is missing entirely."""
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == REGISTRY_NAME
            for t in node.targets
        ):
            continue
        if not isinstance(node.value, ast.Dict):
            return None
        out: Dict[str, int] = {}
        for k in node.value.keys:
            if isinstance(k, ast.Constant) and isinstance(
                k.value, str
            ):
                out[k.value] = k.lineno
        return out
    return None


def run(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    emitted = emitted_kinds(ctx)
    if not emitted:
        return findings  # fixture trees without producers: nothing to sync
    reg_sf = _registry_source(ctx)
    if reg_sf is None:
        findings.append(Finding(
            PASS_ID, REGISTRY_FILE, 1,
            f"{REGISTRY_FILE} not found — the GS_EVENTS kinds have "
            f"no --check validator registry",
            hint=f"declare {REGISTRY_NAME} = {{kind: (required "
                 f"attrs...)}} in gs_report.py",
        ))
        return findings
    registry = registry_kinds(reg_sf)
    if registry is None:
        findings.append(Finding(
            PASS_ID, reg_sf.rel, 1,
            f"{REGISTRY_NAME} is missing (or not a dict literal) in "
            f"{reg_sf.rel}",
            hint="declare the kind registry as a plain dict literal "
                 "so it is statically enumerable",
        ))
        return findings
    for kind, (rel, line) in sorted(emitted.items()):
        if kind not in registry:
            findings.append(Finding(
                PASS_ID, rel, line,
                f"event kind {kind!r} is emitted here but has no "
                f"validator entry in {reg_sf.rel}:{REGISTRY_NAME}",
                hint="add the kind (and its required attrs) to the "
                     "registry so --check covers it",
            ))
    for kind, line in sorted(registry.items()):
        if kind not in emitted:
            findings.append(Finding(
                PASS_ID, reg_sf.rel, line,
                f"{REGISTRY_NAME} validates kind {kind!r}, which "
                f"nothing in the tree emits (dead validator)",
                hint="drop the registry entry, or restore the "
                     "producer",
            ))
    return findings
