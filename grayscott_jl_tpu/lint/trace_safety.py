"""Pass ``trace-safety`` — no host syncs inside compiled step bodies.

The known jit roots (``simulation.py`` runners / snapshot and
numerics probes, ``ensemble/engine.py``'s vmapped member bodies) are
discovered structurally: every callable handed to ``jax.jit`` /
``shard_map`` / ``jax.vmap`` / ``jax.pmap`` in the package, resolved
through local assignments and ``functools.partial``.  From those
roots the pass walks a call/reference closure over the package's
functions and flags host-sync and host-effect hazards inside it:

* ``.item()`` / ``.tolist()`` / ``jax.device_get`` /
  ``.block_until_ready()`` — device->host syncs that stall or break
  the trace;
* ``np.asarray`` / ``np.array`` — silent host materialization of a
  traced value;
* ``print(...)`` — executes at trace time (misleading) or forces a
  callback;
* host clocks (``time.time`` etc.) — trace-time constants in
  disguise;
* ``float(x)`` / ``int(x)`` applied to a *parameter* of a traced
  function — concretization that raises (or silently syncs) under
  tracing.  ``float()`` on host-side Python scalars never fires: host
  code is simply not reachable from a jit root.

Reachability follows only plausible function links — bare names
resolved in the referencing file (or through its in-package imports),
``self.method`` within the same file, ``module_alias.fn`` for
in-package module aliases, and the model-protocol tails
``reaction``/``init``.  Generic attribute tails (``somedict.get``,
``queue.put``) are not links; following them would drag the host side
of the codebase into the traced set.  A deliberate trace-time
exception takes a one-line ``# gslint: disable=trace-safety``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from . import Finding
from .context import LintContext, SourceFile
from .astutil import dotted, iter_functions

PASS_ID = "trace-safety"

#: Callable-wrapping entry points whose argument becomes device code.
_TRACE_WRAPPERS = {"jit", "vmap", "pmap", "shard_map"}

_SYNC_TAILS = {"item", "tolist", "block_until_ready"}
_HOST_MATERIALIZE = {
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
}
_HOST_CLOCKS = {
    "time.time", "time.perf_counter", "time.monotonic",
    "time.sleep",
}

#: Attribute tails always followed: the model protocol's callables
#: are traced whenever the machinery that receives a ``model`` is.
_PROTOCOL_TAILS = {"reaction", "init"}

#: Host-only subpackages: never traced, and full of legitimate host
#: constructs that would only feed name-collision noise.
_HOST_ONLY_PREFIXES = (
    "grayscott_jl_tpu.lint",
    "grayscott_jl_tpu.analysis",
)

FuncEntry = Tuple[SourceFile, str, ast.AST]


class _Index:
    """Function definitions, resolvable per-file or package-wide."""

    def __init__(self, ctx: LintContext):
        self.by_file: Dict[str, Dict[str, List[FuncEntry]]] = {}
        self.global_: Dict[str, List[FuncEntry]] = {}
        self.aliases: Dict[str, Set[str]] = {}
        for sf in _device_files(ctx):
            per = self.by_file.setdefault(sf.rel, {})
            for qual, fnode, parents in iter_functions(sf.tree):
                e = (sf, qual, fnode)
                per.setdefault(fnode.name, []).append(e)
                self.global_.setdefault(fnode.name, []).append(e)
            self.aliases[sf.rel] = _module_aliases(sf)

    def resolve(
        self, name: str, sf: SourceFile, scope: str
    ) -> List[FuncEntry]:
        """Targets a reference may denote.  ``scope`` is ``"file"``
        (bare names, ``self.X``: same file, or an imported name) or
        ``"global"`` (module-alias attributes, protocol tails)."""
        if scope == "global":
            return self.global_.get(name, [])
        local = self.by_file.get(sf.rel, {}).get(name)
        if local:
            return local
        if name in self.aliases.get(sf.rel, ()):
            return self.global_.get(name, [])
        return []


def _device_files(ctx: LintContext) -> List[SourceFile]:
    return [
        sf for sf in ctx.package_files()
        if not any(
            sf.module == p or sf.module.startswith(p + ".")
            for p in _HOST_ONLY_PREFIXES
        )
    ]


def _module_aliases(sf: SourceFile) -> Set[str]:
    """Names this file binds via in-package imports — module aliases
    (``from .ops import pallas_stencil``) and imported functions
    (``from .noise import plane_seed``) alike."""
    out: Set[str] = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.ImportFrom):
            if node.level > 0 or (
                node.module or ""
            ).startswith("grayscott_jl_tpu"):
                for alias in node.names:
                    out.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.startswith("grayscott_jl_tpu"):
                    out.add(
                        alias.asname or alias.name.split(".")[0]
                    )
    return out


def _references(
    fnode: ast.AST, sf: SourceFile, index: _Index
) -> List[FuncEntry]:
    out: List[FuncEntry] = []
    for node in ast.walk(fnode):
        if isinstance(node, ast.Name):
            out.extend(index.resolve(node.id, sf, "file"))
        elif isinstance(node, ast.Attribute):
            if node.attr in _PROTOCOL_TAILS:
                out.extend(index.resolve(node.attr, sf, "global"))
            elif isinstance(node.value, ast.Name):
                base = node.value.id
                if base == "self":
                    out.extend(
                        index.resolve(node.attr, sf, "file")
                    )
                elif base in index.aliases.get(sf.rel, ()):
                    out.extend(
                        index.resolve(node.attr, sf, "global")
                    )
    return out


def _callable_entries(
    expr: ast.AST,
    sf: SourceFile,
    scope: Optional[ast.AST],
    index: _Index,
    lambdas: List[Tuple[SourceFile, ast.Lambda]],
    depth: int = 0,
) -> List[FuncEntry]:
    """Function definitions an expression may denote (through partial
    and one level of local assignment)."""
    if depth > 4:
        return []
    if isinstance(expr, ast.Lambda):
        lambdas.append((sf, expr))
        return []
    if isinstance(expr, ast.Call):
        name = dotted(expr.func)
        if name and name.split(".")[-1] == "partial" and expr.args:
            return _callable_entries(
                expr.args[0], sf, scope, index, lambdas, depth + 1
            )
        return []
    if isinstance(expr, ast.Attribute):
        return index.resolve(expr.attr, sf, "file")
    if isinstance(expr, ast.Name):
        direct = index.resolve(expr.id, sf, "file")
        if direct:
            return direct
        if scope is not None:
            out: List[FuncEntry] = []
            for stmt in ast.walk(scope):
                if (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)
                        and stmt.targets[0].id == expr.id):
                    out.extend(_callable_entries(
                        stmt.value, sf, scope, index, lambdas,
                        depth + 1,
                    ))
            return out
    return []


def _roots(
    ctx: LintContext, index: _Index
) -> Tuple[List[FuncEntry], List[Tuple[SourceFile, ast.Lambda]]]:
    roots: List[FuncEntry] = []
    lambdas: List[Tuple[SourceFile, ast.Lambda]] = []
    for sf in _device_files(ctx):
        encl: Dict[int, ast.AST] = {}
        for qual, fnode, parents in iter_functions(sf.tree):
            for node in ast.walk(fnode):
                encl.setdefault(id(node), fnode)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted(node.func)
            if not name:
                continue
            if name.split(".")[-1] not in _TRACE_WRAPPERS:
                continue
            if not node.args:
                continue
            roots.extend(_callable_entries(
                node.args[0], sf, encl.get(id(node)), index, lambdas
            ))
    return roots, lambdas


def run(ctx: LintContext) -> List[Finding]:
    index = _Index(ctx)
    roots, lambdas = _roots(ctx, index)
    findings: List[Finding] = []
    seen: Set[int] = set()
    work = list(roots)
    while work:
        sf, qual, fnode = work.pop()
        if id(fnode) in seen:
            continue
        seen.add(id(fnode))
        findings.extend(_scan(sf, qual, fnode))
        work.extend(_references(fnode, sf, index))
    for sf, lam in lambdas:
        if id(lam) not in seen:
            seen.add(id(lam))
            findings.extend(_scan(sf, "<lambda>", lam))
    return findings


def _params(fnode: ast.AST) -> Set[str]:
    if isinstance(
        fnode, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
    ):
        a = fnode.args
        names = {
            p.arg for p in a.posonlyargs + a.args + a.kwonlyargs
        }
        names.discard("self")
        names.discard("cls")
        return names
    return set()


def _scan(
    sf: SourceFile, qual: str, fnode: ast.AST
) -> List[Finding]:
    findings: List[Finding] = []
    params = _params(fnode)
    for node in ast.walk(fnode):
        if not isinstance(node, ast.Call):
            continue
        name = dotted(node.func)
        tail = name.split(".")[-1] if name else (
            node.func.attr if isinstance(node.func, ast.Attribute)
            else None
        )
        msg = hint = None
        if tail in _SYNC_TAILS and not node.args:
            msg = (f".{tail}() inside jit-reachable {qual!r} forces "
                   f"a device->host sync")
            hint = ("return the value and resolve it host-side at "
                    "the call boundary")
        elif name and tail == "device_get" and name.startswith(
            "jax"
        ):
            msg = (f"jax.device_get inside jit-reachable {qual!r} "
                   f"is a host transfer")
            hint = "move the transfer outside the traced body"
        elif name in _HOST_MATERIALIZE:
            msg = (f"{name} inside jit-reachable {qual!r} "
                   f"materializes a traced value on host")
            hint = "use jnp equivalents inside traced code"
        elif name == "print":
            msg = (f"print() inside jit-reachable {qual!r} runs at "
                   f"trace time, not per step")
            hint = ("use jax.debug.print for runtime values, or log "
                    "at the call boundary")
        elif name in _HOST_CLOCKS:
            msg = (f"{name}() inside jit-reachable {qual!r} is a "
                   f"trace-time constant (and a hidden host "
                   f"dependency)")
            hint = "time at the call boundary instead"
        elif name in ("float", "int") and len(node.args) == 1 and (
            isinstance(node.args[0], ast.Name)
            and node.args[0].id in params
        ):
            msg = (f"{name}() on traced argument "
                   f"{node.args[0].id!r} of {qual!r} concretizes "
                   f"under jit")
            hint = ("cast with .astype()/jnp, or hoist the scalar "
                    "out of the traced signature")
        if msg:
            findings.append(Finding(
                PASS_ID, sf.rel, node.lineno, msg, hint=hint or ""
            ))
    return findings
