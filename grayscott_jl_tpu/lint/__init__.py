"""gslint — the framework's contracts as machine-checked passes.

Eleven PRs of conventions (pure model reactions, zero per-model code in
``ops``/``parallel``, env knobs synced with the docs knob tables, event
kinds synced with ``gs_report --check``, jit/donation trace-safety)
live here as AST-based static-analysis passes over the repo's own
source.  Stdlib-only and JAX-free to import, like ``obs/`` — the suite
must run on a laptop holding a checkout and nothing else, and it lints
itself.

Entry points:

* ``scripts/gslint.py`` — the CLI (``--json`` for tooling),
* :func:`run_lint` — the library call the tier-1 self-check test uses,
* per-line suppression: ``# gslint: disable=<pass>[,<pass>|all]``,
* ``gslint-baseline.json`` at the repo root — committed **empty** by
  contract; real findings get fixed, not baselined.

See ``docs/ANALYSIS.md`` for the pass catalog and how to add a pass.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Dict, List, Optional, Sequence

from .context import LintContext

__all__ = [
    "Finding",
    "LintContext",
    "PASSES",
    "findings_to_json",
    "run_lint",
]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One contract violation: where, which pass, what, and the fix."""

    pass_id: str
    path: str  #: repo-relative posix path
    line: int
    message: str
    hint: str = ""
    severity: str = "error"  #: "error" fails the CLI; "warning" reports

    def key(self) -> str:
        """Stable identity used by the (always-empty) baseline file."""
        return f"{self.pass_id}:{self.path}:{self.line}"

    def render(self) -> str:
        text = (f"{self.path}:{self.line}: [{self.pass_id}] "
                f"{self.severity}: {self.message}")
        if self.hint:
            text += f"\n    hint: {self.hint}"
        return text


def _registry() -> Dict[str, Callable[[LintContext], List[Finding]]]:
    # Imported lazily so a syntax error in one pass module does not
    # take down `import grayscott_jl_tpu.lint` for the others' tests.
    from . import (
        donation,
        env_knobs,
        events_schema,
        layering,
        purity,
        trace_safety,
    )

    return {
        trace_safety.PASS_ID: trace_safety.run,
        purity.PASS_ID: purity.run,
        layering.PASS_ID: layering.run,
        env_knobs.PASS_ID: env_knobs.run,
        events_schema.PASS_ID: events_schema.run,
        donation.PASS_ID: donation.run,
    }


#: pass id -> pass callable; import-time stable so ``--list`` and the
#: docs catalog can enumerate without running anything.
PASSES: Dict[str, Callable[[LintContext], List[Finding]]] = _registry()


def run_lint(
    root: str,
    targets: Sequence[str],
    select: Optional[Sequence[str]] = None,
    baseline: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """Run the selected passes (default: all) over ``targets`` and
    return unsuppressed, non-baselined findings, stable-sorted by
    (path, line, pass)."""
    ctx = LintContext(root, targets)
    selected = list(select) if select else sorted(PASSES)
    unknown = [p for p in selected if p not in PASSES]
    if unknown:
        raise ValueError(
            f"unknown pass id(s) {unknown}; available: {sorted(PASSES)}"
        )
    findings: List[Finding] = []
    for pass_id in selected:
        for f in PASSES[pass_id](ctx):
            if ctx.suppressed(f.path, f.line, f.pass_id):
                continue
            findings.append(f)
    baselined = set(baseline or ())
    findings = [f for f in findings if f.key() not in baselined]
    findings.sort(key=lambda f: (f.path, f.line, f.pass_id))
    return findings


def load_baseline(path: str) -> List[str]:
    """The committed baseline: a JSON list of finding keys. Empty by
    contract — the file exists so the *mechanism* stays exercised."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, list) or not all(
        isinstance(k, str) for k in data
    ):
        raise ValueError(
            f"baseline {path} must be a JSON list of finding keys"
        )
    return data


def findings_to_json(
    findings: Sequence[Finding], root: str, targets: Sequence[str]
) -> dict:
    """The stable ``--json`` document (schema documented in
    docs/ANALYSIS.md; consumable by ``benchmarks/artifacts.py``-style
    tooling)."""
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.pass_id] = counts.get(f.pass_id, 0) + 1
    return {
        "schema": "gslint/1",
        "root": root,
        "targets": list(targets),
        "passes": sorted(PASSES),
        "counts": counts,
        "errors": sum(1 for f in findings if f.severity == "error"),
        "warnings": sum(
            1 for f in findings if f.severity == "warning"
        ),
        "findings": [dataclasses.asdict(f) for f in findings],
    }
