"""Shared lint context: file discovery, parsing, suppressions.

Every pass sees the same :class:`LintContext` — one parse of each
target file, one suppression index, one place that knows how a file
path maps to a package module name.  Stdlib only.
"""

from __future__ import annotations

import ast
import glob
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: ``# gslint: disable=trace-safety,env-knobs`` (or ``all``) anywhere
#: on a line suppresses that line's findings for the named passes.
_SUPPRESS_RE = re.compile(r"#\s*gslint:\s*disable=([\w\-, ]+)")


class SourceFile:
    """One parsed target file."""

    def __init__(self, root: str, path: str):
        self.path = path
        self.rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=self.rel)
        #: dotted module name: ``grayscott_jl_tpu/ops/stencil.py`` ->
        #: ``grayscott_jl_tpu.ops.stencil``; ``bench.py`` -> ``bench``.
        mod = self.rel[:-3] if self.rel.endswith(".py") else self.rel
        self.is_package = mod.endswith("/__init__")
        if self.is_package:
            mod = mod[: -len("/__init__")]
        self.module = mod.replace("/", ".")
        self.suppressions = self._parse_suppressions()

    def _parse_suppressions(self) -> Dict[int, Tuple[str, ...]]:
        out: Dict[int, Tuple[str, ...]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if m:
                out[i] = tuple(
                    p.strip() for p in m.group(1).split(",") if p.strip()
                )
        return out


class LintContext:
    """The target file set plus repo-level lookups the passes share."""

    def __init__(self, root: str, targets: Sequence[str]):
        self.root = os.path.abspath(root)
        self.targets = list(targets)
        self.files: List[SourceFile] = []
        seen = set()
        for path in self._expand(targets):
            if path in seen:
                continue
            seen.add(path)
            self.files.append(SourceFile(self.root, path))
        self.files.sort(key=lambda f: f.rel)
        self._by_module = {f.module: f for f in self.files}

    def _expand(self, targets: Sequence[str]) -> Iterable[str]:
        for t in targets:
            path = (
                t if os.path.isabs(t) else os.path.join(self.root, t)
            )
            if os.path.isfile(path):
                yield path
            elif os.path.isdir(path):
                for dirpath, dirnames, filenames in os.walk(path):
                    dirnames[:] = [
                        d for d in dirnames
                        if d != "__pycache__" and not d.startswith(".")
                    ]
                    for name in sorted(filenames):
                        if name.endswith(".py"):
                            yield os.path.join(dirpath, name)
            else:
                raise FileNotFoundError(f"lint target {t!r} not found")

    # ------------------------------------------------------- lookups

    def module(self, name: str) -> Optional[SourceFile]:
        return self._by_module.get(name)

    def package_files(self) -> List[SourceFile]:
        """Target files inside the ``grayscott_jl_tpu`` package."""
        return [
            f for f in self.files
            if f.module.startswith("grayscott_jl_tpu")
        ]

    def suppressed(self, rel: str, line: int, pass_id: str) -> bool:
        for f in self.files:
            if f.rel == rel:
                tags = f.suppressions.get(line, ())
                return pass_id in tags or "all" in tags
        return False

    # -------------------------------------------- repo-level sources

    def doc_files(self) -> List[str]:
        """The knob-table documentation set: ``docs/*.md``, README, and
        BASELINE.md (the bench contract doc)."""
        out = [
            p for p in (
                os.path.join(self.root, "README.md"),
                os.path.join(self.root, "BASELINE.md"),
            )
            if os.path.isfile(p)
        ]
        out.extend(
            sorted(glob.glob(os.path.join(self.root, "docs", "*.md")))
        )
        return out

    def doc_text(self) -> str:
        parts = []
        for p in self.doc_files():
            with open(p, encoding="utf-8") as f:
                parts.append(f.read())
        return "\n".join(parts)

    def auxiliary_reader_text(self) -> str:
        """Source text of non-target knob *readers* (tests, benchmarks,
        shell launchers): a knob only these read is still alive, so the
        dead-knob check scans them — as text, not AST."""
        parts = []
        patterns = (
            os.path.join(self.root, "tests", "**", "*.py"),
            os.path.join(self.root, "benchmarks", "**", "*.py"),
            os.path.join(self.root, "benchmarks", "**", "*.sh"),
            os.path.join(self.root, "scripts", "**", "*.sh"),
            os.path.join(self.root, "examples", "**", "*"),
        )
        for pattern in patterns:
            for p in sorted(glob.glob(pattern, recursive=True)):
                if os.path.isfile(p):
                    try:
                        with open(p, encoding="utf-8") as f:
                            parts.append(f.read())
                    except (OSError, UnicodeDecodeError):
                        continue
        return "\n".join(parts)
