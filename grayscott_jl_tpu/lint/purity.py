"""Pass ``purity`` — registered models stay pure declarations.

A model's ``reaction`` is traced into every compiled step program and
its ``init`` must produce identical blocks for identical ``(offsets,
sizes, seed)`` on every host; both promises die the moment a model
reaches for ambient process state.  This pass checks every function in
a concrete ``models/*`` module that is (or is reachable by name from)
a model's ``reaction``/``init`` for:

* environment access (``os.environ`` / ``os.getenv``),
* host I/O (``open``/``print``/``input``) and host entropy or clocks
  (``random``, ``np.random``, ``time``, ``datetime``, ``uuid``),
* ``global`` statements (mutable module state).

Module-scope *constants* (seeding geometry, boundary values) are the
declaration itself and remain fine — only behavior inside the model
callables is constrained.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from . import Finding
from .context import LintContext, SourceFile
from .astutil import dotted, iter_functions

PASS_ID = "purity"

#: Entry points of the model contract.
MODEL_ENTRY_NAMES = ("reaction", "init")

#: Dotted-prefix accesses banned inside model callables.
_BANNED_PREFIXES = (
    "os.environ", "os.getenv", "np.random", "numpy.random",
    "random.", "time.", "datetime.", "uuid.",
)

#: Bare calls banned inside model callables.
_BANNED_CALLS = {"open", "print", "input", "eval", "exec",
                 "__import__"}


def _model_files(ctx: LintContext) -> List[SourceFile]:
    out = []
    for sf in ctx.package_files():
        if (sf.module.startswith("grayscott_jl_tpu.models.")
                and sf.module != "grayscott_jl_tpu.models.base"):
            out.append(sf)
    return out


def _roots_and_index(
    sf: SourceFile,
) -> Tuple[Set[str], Dict[str, List[ast.AST]]]:
    """Model entry functions plus keyword-registered callables, and a
    name index of every function in the module."""
    index: Dict[str, List[ast.AST]] = {}
    for qual, fnode, parents in iter_functions(sf.tree):
        index.setdefault(fnode.name, []).append(fnode)
    roots = {n for n in MODEL_ENTRY_NAMES if n in index}
    # reaction=foo / init=bar keyword registrations (Model(...) calls).
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg in MODEL_ENTRY_NAMES:
                    name = dotted(kw.value)
                    if name and name.split(".")[-1] in index:
                        roots.add(name.split(".")[-1])
    return roots, index


def _reachable(
    roots: Set[str], index: Dict[str, List[ast.AST]]
) -> Set[str]:
    seen: Set[str] = set()
    work = list(roots)
    while work:
        name = work.pop()
        if name in seen:
            continue
        seen.add(name)
        for fnode in index.get(name, ()):
            for node in ast.walk(fnode):
                ref = None
                if isinstance(node, ast.Name):
                    ref = node.id
                elif isinstance(node, ast.Attribute):
                    ref = node.attr
                if ref and ref in index and ref not in seen:
                    work.append(ref)
    return seen


def run(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for sf in _model_files(ctx):
        roots, index = _roots_and_index(sf)
        if not roots:
            continue
        for name in sorted(_reachable(roots, index)):
            for fnode in index[name]:
                findings.extend(_check_function(sf, name, fnode))
    return findings


def _check_function(
    sf: SourceFile, name: str, fnode: ast.AST
) -> List[Finding]:
    findings: List[Finding] = []
    for node in ast.walk(fnode):
        if isinstance(node, ast.Global):
            findings.append(Finding(
                PASS_ID, sf.rel, node.lineno,
                f"model callable {name!r} mutates module globals",
                hint="models are declarations — thread state through "
                     "params instead",
            ))
            continue
        ref = dotted(node) if isinstance(
            node, (ast.Attribute, ast.Name)
        ) else None
        if ref:
            for prefix in _BANNED_PREFIXES:
                if ref == prefix.rstrip(".") or ref.startswith(prefix):
                    findings.append(Finding(
                        PASS_ID, sf.rel, node.lineno,
                        f"model callable {name!r} touches ambient "
                        f"process state ({ref})",
                        hint="reaction/init must be pure functions "
                             "of their arguments (see "
                             "docs/MODELS.md)",
                    ))
                    break
        if isinstance(node, ast.Call):
            cname = dotted(node.func)
            if cname in _BANNED_CALLS:
                findings.append(Finding(
                    PASS_ID, sf.rel, node.lineno,
                    f"model callable {name!r} performs host I/O "
                    f"({cname}())",
                    hint="models must not read or write the host — "
                         "move I/O to the driver",
                ))
    # Deduplicate Attribute chains reported once per node walk
    # (``os.environ.get`` visits both ``os.environ.get`` and
    # ``os.environ``): keep the first per (line, message).
    seen: Set[Tuple[int, str]] = set()
    unique: List[Finding] = []
    for f in findings:
        k = (f.line, f.message)
        if k not in seen:
            seen.add(k)
            unique.append(f)
    return unique
