"""Pass ``env-knobs`` — the ``GS_*`` knob registry, cross-checked.

The framework is steered by 60+ ``GS_*`` environment knobs whose
contract ("env wins over TOML", documented in the docs knob tables) is
only as good as the sync between code and docs.  This pass collects
every knob *read* in the linted tree (direct ``os.environ`` reads,
``os.getenv``, and calls through knob-accessor helpers such as
``config/env.py``'s typed resolvers) and checks:

* **undocumented** — a knob read in code but absent from every knob
  table (``docs/*.md``, ``README.md``, ``BASELINE.md``) is invisible
  to operators;
* **dead** — a knob documented but never read anywhere (targets,
  tests, benchmarks, shell launchers) is a doc lie;
* **resolver discipline** — a ``GS_*`` read belongs in a dedicated
  resolver helper (a ``resolve*``/``*_from_env`` function, or one of
  the config/obs resolver modules), not inline in execution code, so
  the registry stays enumerable and precedence lives in one place.

Dynamic keys built from a ``GS_``-prefixed f-string register the whole
family (``GS_WATCHDOG_<PHASE>_S`` -> ``GS_WATCHDOG_*``).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from . import Finding
from .context import LintContext, SourceFile
from .astutil import dotted, enclosing_function_names, iter_functions

PASS_ID = "env-knobs"

#: Modules whose whole body counts as resolver context: the config
#: layer and the env-resolved obs singletons.
RESOLVER_MODULES = (
    "grayscott_jl_tpu.config.settings",
    "grayscott_jl_tpu.config.env",
    "grayscott_jl_tpu.obs.",
)

_KNOB_RE = re.compile(r"GS_[A-Z][A-Z0-9_]*")


def _is_resolver_context(
    sf: SourceFile, func_names: List[str]
) -> bool:
    for m in RESOLVER_MODULES:
        if sf.module == m.rstrip(".") or (
            m.endswith(".") and sf.module.startswith(m)
        ):
            return True
    return any(
        n.lstrip("_").startswith("resolve") or n.endswith("from_env")
        for n in func_names
    )


class _Read:
    """One static knob read site."""

    def __init__(self, sf: SourceFile, line: int, knob: str,
                 family: bool, resolver: bool):
        self.sf = sf
        self.line = line
        self.knob = knob  #: exact name, or prefix when ``family``
        self.family = family
        self.resolver = resolver


def _environ_key(node: ast.AST) -> Optional[ast.expr]:
    """The key expression of an ``os.environ`` / ``os.getenv`` read,
    else None.  Stores (writes, ``pop``) are not reads."""
    if isinstance(node, ast.Subscript) and isinstance(
        node.ctx, ast.Load
    ):
        base = dotted(node.value)
        if base and base.split(".")[-1] == "environ":
            return node.slice
    if isinstance(node, ast.Call):
        name = dotted(node.func)
        if name and (
            name.endswith("environ.get") or name.endswith("getenv")
        ) and node.args:
            return node.args[0]
    return None


def _classify_key(
    key: ast.expr, scope: Optional[ast.AST]
) -> Tuple[Optional[str], bool]:
    """``(knob_or_prefix, is_family)`` for a key expression;
    ``(None, False)`` when the key cannot be resolved statically."""
    if isinstance(key, ast.Constant) and isinstance(key.value, str):
        if key.value.startswith("GS_"):
            return key.value, False
        return None, False
    if isinstance(key, ast.JoinedStr) and key.values:
        first = key.values[0]
        if isinstance(first, ast.Constant) and isinstance(
            first.value, str
        ) and first.value.startswith("GS_"):
            return first.value, True
    if isinstance(key, ast.Name) and scope is not None:
        # One-hop resolution: `name = f"GS_..."` / `name = "GS_..."`
        # in the same function.
        for stmt in ast.walk(scope):
            if (isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id == key.id):
                return _classify_key(stmt.value, None)
    return None, False


def _function_params(node: ast.AST) -> Set[str]:
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        a = node.args
        return {
            p.arg for p in (
                a.posonlyargs + a.args + a.kwonlyargs
            )
        }
    return set()


def _collect(ctx: LintContext):
    """One walk: direct reads, env writes, accessor helpers, and every
    ``GS_*`` token mentioned in a string constant (liveness only)."""
    reads: List[_Read] = []
    writes: Set[str] = set()
    mentions: Set[str] = set()
    accessors: Set[str] = set()  # function names reading env by param

    # First sweep: direct reads + accessor discovery.
    for sf in ctx.files:
        for qual, fnode, parents in iter_functions(sf.tree):
            params = _function_params(fnode)
            for node in ast.walk(fnode):
                key = _environ_key(node)
                if key is None:
                    continue
                if isinstance(key, ast.Name) and key.id in params:
                    accessors.add(fnode.name)
        _collect_file_reads(sf, reads, writes, mentions)

    # Second sweep: accessor call sites register knobs too.
    for sf in ctx.files:
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = dotted(node.func)
            if not name or name.split(".")[-1] not in accessors:
                continue
            knob, family = _classify_key(node.args[0], None)
            if knob is not None:
                reads.append(_Read(
                    sf, node.lineno, knob, family, resolver=True
                ))
    return reads, writes, mentions


def _collect_file_reads(
    sf: SourceFile,
    reads: List[_Read],
    writes: Set[str],
    mentions: Set[str],
) -> None:
    # String-constant mentions (f-string fragments, literal key args):
    # liveness signal only.
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Constant) and isinstance(
            node.value, str
        ):
            mentions.update(_KNOB_RE.findall(node.value))
        if isinstance(node, ast.Subscript) and isinstance(
            node.ctx, ast.Store
        ):
            base = dotted(node.value)
            if base and base.split(".")[-1] == "environ":
                if isinstance(node.slice, ast.Constant) and isinstance(
                    node.slice.value, str
                ):
                    writes.add(node.slice.value)

    # Direct reads, attributed to their enclosing function chain —
    # innermost function first, so a read inside a nested resolver
    # helper is credited to the helper, not its host.
    covered: Set[int] = set()
    entries = sorted(
        iter_functions(sf.tree),
        key=lambda e: len(e[2]),
        reverse=True,
    )
    for qual, fnode, parents in entries:
        names = enclosing_function_names(parents) + [fnode.name]
        resolver = _is_resolver_context(sf, names)
        for node in ast.walk(fnode):
            key = _environ_key(node)
            if key is None or id(node) in covered:
                continue
            covered.add(id(node))
            knob, family = _classify_key(key, fnode)
            if knob is None:
                continue  # dynamic non-GS key: not a knob read
            reads.append(_Read(
                sf, node.lineno, knob, family, resolver
            ))
    # Module-scope reads (no enclosing function): never resolver
    # context unless the module itself is.
    resolver = _is_resolver_context(sf, [])
    for node in ast.walk(sf.tree):
        key = _environ_key(node)
        if key is None or id(node) in covered:
            continue
        covered.add(id(node))
        knob, family = _classify_key(key, None)
        if knob is None:
            continue
        reads.append(_Read(sf, node.lineno, knob, family, resolver))


def _doc_tokens(ctx: LintContext) -> Dict[str, Tuple[str, int]]:
    """``token -> (doc rel path, line)`` for every GS_* token in the
    docs set (first occurrence wins)."""
    import os

    out: Dict[str, Tuple[str, int]] = {}
    for path in ctx.doc_files():
        rel = os.path.relpath(path, ctx.root).replace(os.sep, "/")
        with open(path, encoding="utf-8") as f:
            for i, line in enumerate(f, start=1):
                for tok in _KNOB_RE.findall(line):
                    out.setdefault(tok, (rel, i))
    return out


def run(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    reads, writes, mentions = _collect(ctx)
    doc_tokens = _doc_tokens(ctx)
    doc_names = set(doc_tokens)

    # --- undocumented: first read site per knob reports it
    reported: Set[str] = set()
    for r in reads:
        if r.knob in reported:
            continue
        if r.family:
            documented = any(
                t == r.knob or t.startswith(r.knob)
                for t in doc_names
            )
        else:
            documented = r.knob in doc_names or any(
                t.endswith("_") and r.knob.startswith(t)
                for t in doc_names
            )
        if not documented:
            reported.add(r.knob)
            label = f"{r.knob}*" if r.family else r.knob
            findings.append(Finding(
                PASS_ID, r.sf.rel, r.line,
                f"env knob {label} is read here but appears in no "
                f"knob table (docs/, README.md, BASELINE.md)",
                hint="add a row to the relevant knob table, or delete "
                     "the dead read",
            ))

    # --- dead: documented but read nowhere
    exact_reads = {r.knob for r in reads if not r.family}
    family_reads = {r.knob for r in reads if r.family}
    aux_tokens = set(_KNOB_RE.findall(ctx.auxiliary_reader_text()))
    for tok, (rel, line) in sorted(doc_tokens.items()):
        if len(tok) <= len("GS_"):
            continue
        if tok.endswith("_"):  # documented family prefix
            alive = any(f.startswith(tok) or tok.startswith(f)
                        for f in family_reads) or any(
                e.startswith(tok) for e in exact_reads
            )
        else:
            alive = (
                tok in exact_reads
                or tok in writes
                or tok in mentions
                or tok in aux_tokens
                or any(tok.startswith(f) for f in family_reads)
            )
        if not alive:
            findings.append(Finding(
                PASS_ID, rel, line,
                f"documented env knob {tok} is never read anywhere "
                f"in the tree (dead knob)",
                hint="drop the table row, or wire the knob back up",
            ))

    # --- resolver discipline
    for r in reads:
        if not r.resolver:
            label = f"{r.knob}*" if r.family else r.knob
            findings.append(Finding(
                PASS_ID, r.sf.rel, r.line,
                f"raw os.environ read of {label} outside a resolver "
                f"helper",
                hint="route it through config/env.py's typed "
                     "accessors or a resolve_* helper so precedence "
                     "and parsing live in one place",
            ))
    return findings
