"""grayscott_jl_tpu — a TPU-native Gray-Scott reaction-diffusion framework.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of
``Rabab53/GrayScott.jl`` (see SURVEY.md): explicit-Euler 7-point-stencil
integration of the 3D Gray-Scott system, 3D domain decomposition over a
device mesh with ICI collective-permute halo exchange, streaming BP-style
parallel output with Fides/VTK visualization schemas, and
checkpoint/restart (the ``analysis`` subpackage adds the companion
PDF-analysis workflow as it lands).

Public API (mirrors the reference's ``GrayScott`` / ``Simulation`` modules):

    from grayscott_jl_tpu import main, initialization, Simulation, Settings
"""

from .config.settings import (  # noqa: F401
    Settings,
    get_settings,
    load_backend_and_lang,
    parse_settings_toml,
    resolve_precision,
)
from .simulation import (  # noqa: F401
    FieldSnapshot,
    Simulation,
    finalize,
    initialization,
)

__version__ = "0.2.0"


def main(args):
    """CLI driver entry point (reference ``GrayScott.main``)."""
    from .driver import main as _main

    return _main(args)


def julia_main(args=None) -> int:
    """Exit-code wrapper (reference ``GrayScott.julia_main``,
    ``src/GrayScott.jl:40-48``).

    Extension beyond the reference's 0/1: a preemption-aware graceful
    shutdown (SIGTERM/SIGINT -> boundary checkpoint -> drain,
    ``resilience/faults.GracefulShutdown``) exits with the distinct
    ``EXIT_PREEMPTED`` code so a relauncher can tell "resume me" from
    "failed" (docs/RESILIENCE.md).
    """
    import sys
    import traceback

    try:
        main(sys.argv[1:] if args is None else args)
    except Exception as e:  # noqa: BLE001 — mirror reference catch-all
        from .resilience.faults import EXIT_PREEMPTED, GracefulShutdown

        if isinstance(e, GracefulShutdown):
            print(
                f"gray-scott: {e}; exiting {EXIT_PREEMPTED} "
                "(rerun under GS_SUPERVISE=1 to auto-resume)",
                file=sys.stderr,
            )
            return EXIT_PREEMPTED
        traceback.print_exc()
        return 1
    return 0


def cli_main() -> None:
    """``gray-scott`` console-script entry point (installed via
    pyproject; the repo-root ``gray-scott.py`` launcher wraps the same
    path for uninstalled use)."""
    import sys
    import time

    t0 = time.perf_counter()
    rc = julia_main(sys.argv[1:])
    if rc == 0:
        print(f"{time.perf_counter() - t0:.6f} seconds", file=sys.stderr)
    sys.exit(rc)
