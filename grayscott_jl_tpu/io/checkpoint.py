"""Checkpoint / restart — implemented for real.

The reference *declares* checkpoint/restart settings (``Structs.jl:15-19``)
but never uses them: the driver hardcodes ``restart_step = 0``
(``src/GrayScott.jl:77-78``) and no checkpoint is ever written (SURVEY
defect #4). Here they work: every ``checkpoint_freq`` steps the driver
writes (u, v, step) to ``checkpoint_output`` as a BP-lite store, and
``restart = true`` resumes from ``restart_input`` — reproducing the exact
trajectory, because the noise key is folded per absolute step
(``models/grayscott.py``).

Checkpoints append as new steps in one store; restart loads the latest.

Elastic resume (docs/RESHARD.md): the store additionally records the
writing run's LAYOUT as attributes (mesh dims, axis names, process
count, halo/chain config, schema version — ``reshard/plan.py``
:data:`~..reshard.plan.LAYOUT_ATTRS`). The data was always
global-indexed, so the layout record is provenance for the restore
plan, not a restore requirement: a run checkpointed on mesh A can
resume on mesh B by selection-reading B's shards out of the same
store.
"""

from __future__ import annotations

import os
import sys
from typing import Optional, Tuple

import numpy as np

from ..config.settings import Settings, resolve_model
from . import open_writer
from .bplite import BpReader, _md_path


class CheckpointWriter:
    def __init__(
        self,
        settings: Settings,
        dtype,
        *,
        writer_id: int = 0,
        nwriters: int = 1,
        resume_step: Optional[int] = None,
        layout=None,
        codec=None,
    ):
        """``layout`` (a :class:`~..reshard.plan.LayoutMeta`, or None)
        is the writing run's decomposition record; written as store
        attributes on a FRESH store only — an append (resume) keeps the
        creation layout, so a resumed store's metadata stays
        byte-identical to an uninterrupted run's even when the resuming
        attempt adopted a different mesh (the per-step blocks say what
        each attempt actually wrote).

        Replication (docs/RESILIENCE.md "Data integrity"):
        ``GS_CKPT_REPLICAS=N`` mirrors every define/save/close to
        ``<path>.r1`` .. ``<path>.r<N-1>`` — each mirror a full
        independent BP-lite store a restore can fail over to. A mirror
        that went missing between launches self-heals as a fresh store
        holding the post-resume history. ``GS_CKPT_VERIFY=full``
        additionally read-back-verifies every saved step against the
        recorded CRCs before the boundary is declared written.

        ``codec`` (``{field_name: bits}``, docs/PRECISION.md) is the
        EXPLICIT opt-in lossy checkpoint posture
        (``snapshot_bits_ckpt``): coded field variables are defined at
        their uint payload dtype with the per-step range scalars, and
        restores dequantize — resume is then value-close, not bitwise.
        Default None keeps checkpoints exact-precision."""
        from ..resilience import integrity

        L = settings.L
        model = resolve_model(settings)
        #: Checkpoint variables are the model's declared field names
        #: (Gray-Scott keeps ``u``/``v``) — the restore path
        #: (``Simulation.restore_from_reader``) reads the same names.
        self.field_names = model.field_names
        self.codec = dict(codec or {})
        self._verify = integrity.resolve_verify(settings) == "full"
        #: Replica store paths, primary first.
        self.paths = integrity.replica_paths(
            settings.checkpoint_output, integrity.resolve_replicas(settings)
        )
        self.writers = []
        for path in self.paths:
            # On restart, append: truncating would destroy the very
            # store the run just resumed from when checkpoint_output ==
            # restart_input. But entries past the resume point
            # (rollback) are dropped so a later restart never sees two
            # trajectories for the same step. The rollback point is
            # computed per replica — a stale mirror keeps fewer steps.
            keep = None
            if settings.restart and resume_step is not None:
                from . import count_steps_upto

                keep = count_steps_upto(path, resume_step)
            # Layout attributes go on fresh stores only (checkpoints
            # are always BP-lite, so rank-0 metadata presence decides
            # "fresh").
            fresh = not (
                settings.restart and os.path.isfile(_md_path(path))
            )
            # Checkpoints stay on the BP-lite engines even when adios2
            # is importable: rollback-append and selection-restore are
            # BP-lite semantics, and nothing downstream needs ADIOS2
            # byte compatibility for checkpoints (the visualization/
            # analysis output store is where that matters).
            w = open_writer(
                path,
                writer_id=writer_id,
                nwriters=nwriters,
                append=settings.restart,
                keep_steps=keep,
                prefer_adios2=False,
            )
            if writer_id == 0:
                w.define_attribute("L", settings.L)
                w.define_attribute("precision", settings.precision)
                w.define_attribute("model", model.name)
                w.define_attribute("fields", list(self.field_names))
                if self.codec:
                    from .codec import CODEC_ATTR, codec_attr_value

                    w.define_attribute(
                        CODEC_ATTR,
                        codec_attr_value(
                            self.codec, self.field_names, dtype
                        ),
                    )
                if layout is not None and fresh:
                    from ..reshard.plan import layout_attrs

                    for name, value in layout_attrs(
                        mesh_dims=layout.mesh_dims,
                        axis_names=layout.axis_names,
                        process_count=layout.process_count,
                        halo_depth=layout.halo_depth,
                        chain_fuse=layout.chain_fuse,
                        ensemble_size=layout.ensemble_size,
                    ).items():
                        w.define_attribute(name, value)
            w.define_variable("step", np.int32)
            from .codec import payload_dtype, qhi_var, qlo_var

            for name in self.field_names:
                bits = self.codec.get(name.lower())
                if bits is None:
                    w.define_variable(
                        name, np.dtype(dtype).name, (L, L, L)
                    )
                else:
                    w.define_variable(
                        name, np.dtype(payload_dtype(bits)).name,
                        (L, L, L),
                    )
                    w.define_variable(qlo_var(name), np.float32)
                    w.define_variable(qhi_var(name), np.float32)
            self.writers.append(w)

    @property
    def writer(self):
        """The primary store's writer (historical single-replica
        accessor; the mirrors ride behind it)."""
        return self.writers[0]

    def save(self, step: int, blocks, checksums=None) -> None:
        """``blocks``: iterable of ``(offsets, sizes, *field_blocks)``
        in model declaration order — this process's shards
        (``Simulation.local_blocks``). ``checksums`` (optional
        ``{field: device checksum}``) is the boundary's in-graph
        device-side record, stored in the integrity sidecar."""
        from .codec import EncodedField, qhi_var, qlo_var

        enc = getattr(blocks, "encoded", None) if self.codec else None
        blocks = list(enc if enc is not None else blocks)
        for w in self.writers:
            w.begin_step()
            w.put("step", np.int32(step))
            if checksums is not None and hasattr(
                    w, "record_device_checksums"):
                w.record_device_checksums(step, checksums)
            ranges_done = set()
            for offsets, sizes, *fblocks in blocks:
                for name, fb in zip(self.field_names, fblocks):
                    if isinstance(fb, EncodedField):
                        w.put(name, fb.q, start=offsets, count=sizes)
                        if name not in ranges_done:
                            w.put(qlo_var(name), np.float32(fb.lo))
                            w.put(qhi_var(name), np.float32(fb.hi))
                            ranges_done.add(name)
                    else:
                        w.put(name, fb, start=offsets, count=sizes)
            w.end_step()
        if self._verify:
            # Write-side read-back verify (GS_CKPT_VERIFY=full): the
            # boundary is not "written" until the landed bytes re-read
            # clean against the CRCs recorded at put time.
            from ..resilience.integrity import verify_last_step

            for w, path in zip(self.writers, self.paths):
                if hasattr(w, "drain"):
                    w.drain()  # native engine publishes asynchronously
                verify_last_step(path)

    def close(self) -> None:
        for w in self.writers:
            w.close()


def latest_durable_step(path: str,
                        max_step: Optional[int] = None) -> Optional[int]:
    """Simulation step of the latest *complete* checkpoint entry in
    ``path``, or None (missing/empty store).

    ``max_step`` caps the answer: the latest durable entry whose step
    is ``<= max_step`` (the SDC recovery path resumes from the last
    *verified* boundary — a durable-but-unscreened entry written after
    it may carry the corruption; ``resilience/sdc.py``).

    The BP-lite reader validates every step entry against the payload
    file sizes and exposes only complete steps, so whatever this
    returns is safe to resume from — the supervisor's per-host
    "latest durable checkpoint" and the multi-host checkpoint quorum
    (``resilience/rendezvous.py``: cluster ``min`` of these) are both
    built on it.

    Hardened against corrupt or torn stores: a metadata file the
    reader cannot even parse (truncated md.json from a dying
    filesystem, scribbled bytes) degrades to "no durable checkpoint"
    with a warning instead of propagating a parse error out of the
    supervisor's restart loop — an unreadable store must cost the
    trajectory (restart from scratch / drag the quorum down), never
    the supervision itself.
    """
    try:
        r = BpReader(path)
    except FileNotFoundError:
        return None
    except Exception as e:  # noqa: BLE001 — corrupt store, documented
        print(
            f"gray-scott: warning: checkpoint store {path} is "
            f"unreadable ({type(e).__name__}: {e}); treating as no "
            "durable checkpoint",
            file=sys.stderr,
        )
        return None
    try:
        n = r.num_steps()
        if n == 0:
            return None
        # Steps are appended in order; scan descending for the newest
        # entry under the cap instead of assuming which index it is.
        for k in range(n - 1, -1, -1):
            s = int(r.get("step", step=k))
            if max_step is None or s <= max_step:
                return s
        return None
    except Exception as e:  # noqa: BLE001 — torn step entry, documented
        print(
            f"gray-scott: warning: checkpoint store {path} has no "
            f"readable step entries ({type(e).__name__}: {e}); "
            "treating as no durable checkpoint",
            file=sys.stderr,
        )
        return None
    finally:
        r.close()


def read_layout(reader: BpReader):
    """The store's recorded layout
    (:class:`~..reshard.plan.LayoutMeta`), or None for a pre-elastic
    store — the "old" side of a restore plan
    (``reshard/plan.plan_restore``)."""
    from ..reshard.plan import read_layout as _read

    try:
        attrs = reader.attributes()
    except Exception:  # noqa: BLE001 — layout is advisory provenance
        return None
    return _read(attrs)


def open_checkpoint(
    path: str, settings: Settings, restart_step: int = -1
) -> Tuple[BpReader, int, int]:
    """Open a checkpoint store and locate the entry to restart from.

    ``restart_step`` selects the checkpoint whose recorded simulation
    step matches (the ``restart_step`` config knob); ``-1`` means the
    latest entry. Selecting an earlier checkpoint is how an operator
    rolls a run back without hand-editing store metadata.

    Returns ``(reader, step_index, sim_step)``; the caller restores state
    via per-shard selection reads (``Simulation.restore_from_reader``) so
    no process ever materializes the full global arrays.
    """
    r = BpReader(path)
    n = r.num_steps()
    if n == 0:
        raise ValueError(f"Checkpoint store {path} contains no steps")
    attrs = r.attributes()
    if int(attrs.get("L", settings.L)) != settings.L:
        raise ValueError(
            f"Checkpoint L={attrs['L']} does not match config L={settings.L}"
        )
    # Identity validation (loud, naming both sides): a store of one
    # model/precision must never restore into a run of another — the
    # variables would even happen to line up for same-arity models
    # (a Brusselator store into a Gray-Scott run), silently fusing two
    # different physics into one trajectory. Attributes absent from
    # old stores are skipped: the store predates the metadata, and L/
    # shape validation still applies.
    model = resolve_model(settings)
    stored_model = attrs.get("model")
    if stored_model is not None and str(stored_model) != model.name:
        raise ValueError(
            f"Checkpoint store {path} holds model {stored_model!r} but "
            f"this run integrates model {model.name!r}; point "
            "restart_input at a matching store"
        )
    stored_fields = attrs.get("fields")
    if stored_fields is not None and list(stored_fields) != list(
        model.field_names
    ):
        raise ValueError(
            f"Checkpoint store {path} holds fields "
            f"{list(stored_fields)} but model {model.name!r} declares "
            f"{list(model.field_names)}"
        )
    stored_precision = attrs.get("precision")
    if stored_precision is not None and str(stored_precision) != str(
        settings.precision
    ):
        raise ValueError(
            f"Checkpoint store {path} was written at precision "
            f"{stored_precision!r} but this run is configured for "
            f"{settings.precision!r}; a silent dtype cast would fork "
            "the trajectory"
        )
    if restart_step < 0:
        idx = n - 1
        sim_step = int(r.get("step", step=idx))
    else:
        available = [int(r.get("step", step=i)) for i in range(n)]
        if restart_step not in available:
            raise ValueError(
                f"Checkpoint store {path} has no entry for simulation "
                f"step {restart_step}; available steps: {available}"
            )
        # Last match: after a rollback-and-resume the store can hold two
        # entries for the same sim step (pre- and post-rollback
        # trajectories); the latest is the live one.
        idx = n - 1 - available[::-1].index(restart_step)
        sim_step = restart_step
    return r, idx, sim_step


def load_checkpoint(
    path: str, settings: Settings, restart_step: int = -1
) -> Tuple:
    """Full ``(*fields, step)`` of one checkpoint entry (single-host
    convenience wrapper around :func:`open_checkpoint`); fields follow
    the model's declaration order — ``(u, v, step)`` for Gray-Scott."""
    r, idx, step = open_checkpoint(path, settings, restart_step)
    fields = tuple(
        r.get(name, step=idx)
        for name in resolve_model(settings).field_names
    )
    r.close()
    return fields + (step,)
