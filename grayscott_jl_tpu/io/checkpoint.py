"""Checkpoint / restart — implemented for real.

The reference *declares* checkpoint/restart settings (``Structs.jl:15-19``)
but never uses them: the driver hardcodes ``restart_step = 0``
(``src/GrayScott.jl:77-78``) and no checkpoint is ever written (SURVEY
defect #4). Here they work: every ``checkpoint_freq`` steps the driver
writes (u, v, step) to ``checkpoint_output`` as a BP-lite store, and
``restart = true`` resumes from ``restart_input`` — reproducing the exact
trajectory, because the noise key is folded per absolute step
(``models/grayscott.py``).

Checkpoints append as new steps in one store; restart loads the latest.

Elastic resume (docs/RESHARD.md): the store additionally records the
writing run's LAYOUT as attributes (mesh dims, axis names, process
count, halo/chain config, schema version — ``reshard/plan.py``
:data:`~..reshard.plan.LAYOUT_ATTRS`). The data was always
global-indexed, so the layout record is provenance for the restore
plan, not a restore requirement: a run checkpointed on mesh A can
resume on mesh B by selection-reading B's shards out of the same
store.
"""

from __future__ import annotations

import os
import sys
from typing import Optional, Tuple

import numpy as np

from ..config.settings import Settings, resolve_model
from . import open_writer
from .bplite import BpReader, _md_path


class CheckpointWriter:
    def __init__(
        self,
        settings: Settings,
        dtype,
        *,
        writer_id: int = 0,
        nwriters: int = 1,
        resume_step: Optional[int] = None,
        layout=None,
    ):
        """``layout`` (a :class:`~..reshard.plan.LayoutMeta`, or None)
        is the writing run's decomposition record; written as store
        attributes on a FRESH store only — an append (resume) keeps the
        creation layout, so a resumed store's metadata stays
        byte-identical to an uninterrupted run's even when the resuming
        attempt adopted a different mesh (the per-step blocks say what
        each attempt actually wrote)."""
        L = settings.L
        # On restart, append: truncating would destroy the very store the
        # run just resumed from when checkpoint_output == restart_input.
        # But entries past the resume point (rollback) are dropped so a
        # later restart never sees two trajectories for the same step.
        keep = None
        if settings.restart and resume_step is not None:
            from . import count_steps_upto

            keep = count_steps_upto(settings.checkpoint_output, resume_step)
        # Layout attributes go on fresh stores only (checkpoints are
        # always BP-lite, so rank-0 metadata presence decides "fresh").
        fresh = not (
            settings.restart
            and os.path.isfile(_md_path(settings.checkpoint_output))
        )
        # Checkpoints stay on the BP-lite engines even when adios2 is
        # importable: rollback-append and selection-restore are BP-lite
        # semantics, and nothing downstream needs ADIOS2 byte
        # compatibility for checkpoints (the visualization/analysis
        # output store is where that matters).
        self.writer = open_writer(
            settings.checkpoint_output,
            writer_id=writer_id,
            nwriters=nwriters,
            append=settings.restart,
            keep_steps=keep,
            prefer_adios2=False,
        )
        model = resolve_model(settings)
        #: Checkpoint variables are the model's declared field names
        #: (Gray-Scott keeps ``u``/``v``) — the restore path
        #: (``Simulation.restore_from_reader``) reads the same names.
        self.field_names = model.field_names
        if writer_id == 0:
            self.writer.define_attribute("L", settings.L)
            self.writer.define_attribute("precision", settings.precision)
            self.writer.define_attribute("model", model.name)
            self.writer.define_attribute(
                "fields", list(self.field_names)
            )
            if layout is not None and fresh:
                from ..reshard.plan import layout_attrs

                for name, value in layout_attrs(
                    mesh_dims=layout.mesh_dims,
                    axis_names=layout.axis_names,
                    process_count=layout.process_count,
                    halo_depth=layout.halo_depth,
                    chain_fuse=layout.chain_fuse,
                    ensemble_size=layout.ensemble_size,
                ).items():
                    self.writer.define_attribute(name, value)
        self.writer.define_variable("step", np.int32)
        for name in self.field_names:
            self.writer.define_variable(
                name, np.dtype(dtype).name, (L, L, L)
            )

    def save(self, step: int, blocks) -> None:
        """``blocks``: iterable of ``(offsets, sizes, *field_blocks)``
        in model declaration order — this process's shards
        (``Simulation.local_blocks``)."""
        w = self.writer
        w.begin_step()
        w.put("step", np.int32(step))
        for offsets, sizes, *fblocks in blocks:
            for name, fb in zip(self.field_names, fblocks):
                w.put(name, fb, start=offsets, count=sizes)
        w.end_step()

    def close(self) -> None:
        self.writer.close()


def latest_durable_step(path: str) -> Optional[int]:
    """Simulation step of the latest *complete* checkpoint entry in
    ``path``, or None (missing/empty store).

    The BP-lite reader validates every step entry against the payload
    file sizes and exposes only complete steps, so whatever this
    returns is safe to resume from — the supervisor's per-host
    "latest durable checkpoint" and the multi-host checkpoint quorum
    (``resilience/rendezvous.py``: cluster ``min`` of these) are both
    built on it.

    Hardened against corrupt or torn stores: a metadata file the
    reader cannot even parse (truncated md.json from a dying
    filesystem, scribbled bytes) degrades to "no durable checkpoint"
    with a warning instead of propagating a parse error out of the
    supervisor's restart loop — an unreadable store must cost the
    trajectory (restart from scratch / drag the quorum down), never
    the supervision itself.
    """
    try:
        r = BpReader(path)
    except FileNotFoundError:
        return None
    except Exception as e:  # noqa: BLE001 — corrupt store, documented
        print(
            f"gray-scott: warning: checkpoint store {path} is "
            f"unreadable ({type(e).__name__}: {e}); treating as no "
            "durable checkpoint",
            file=sys.stderr,
        )
        return None
    try:
        n = r.num_steps()
        if n == 0:
            return None
        return int(r.get("step", step=n - 1))
    except Exception as e:  # noqa: BLE001 — torn step entry, documented
        print(
            f"gray-scott: warning: checkpoint store {path} has no "
            f"readable step entries ({type(e).__name__}: {e}); "
            "treating as no durable checkpoint",
            file=sys.stderr,
        )
        return None
    finally:
        r.close()


def read_layout(reader: BpReader):
    """The store's recorded layout
    (:class:`~..reshard.plan.LayoutMeta`), or None for a pre-elastic
    store — the "old" side of a restore plan
    (``reshard/plan.plan_restore``)."""
    from ..reshard.plan import read_layout as _read

    try:
        attrs = reader.attributes()
    except Exception:  # noqa: BLE001 — layout is advisory provenance
        return None
    return _read(attrs)


def open_checkpoint(
    path: str, settings: Settings, restart_step: int = -1
) -> Tuple[BpReader, int, int]:
    """Open a checkpoint store and locate the entry to restart from.

    ``restart_step`` selects the checkpoint whose recorded simulation
    step matches (the ``restart_step`` config knob); ``-1`` means the
    latest entry. Selecting an earlier checkpoint is how an operator
    rolls a run back without hand-editing store metadata.

    Returns ``(reader, step_index, sim_step)``; the caller restores state
    via per-shard selection reads (``Simulation.restore_from_reader``) so
    no process ever materializes the full global arrays.
    """
    r = BpReader(path)
    n = r.num_steps()
    if n == 0:
        raise ValueError(f"Checkpoint store {path} contains no steps")
    attrs = r.attributes()
    if int(attrs.get("L", settings.L)) != settings.L:
        raise ValueError(
            f"Checkpoint L={attrs['L']} does not match config L={settings.L}"
        )
    # Identity validation (loud, naming both sides): a store of one
    # model/precision must never restore into a run of another — the
    # variables would even happen to line up for same-arity models
    # (a Brusselator store into a Gray-Scott run), silently fusing two
    # different physics into one trajectory. Attributes absent from
    # old stores are skipped: the store predates the metadata, and L/
    # shape validation still applies.
    model = resolve_model(settings)
    stored_model = attrs.get("model")
    if stored_model is not None and str(stored_model) != model.name:
        raise ValueError(
            f"Checkpoint store {path} holds model {stored_model!r} but "
            f"this run integrates model {model.name!r}; point "
            "restart_input at a matching store"
        )
    stored_fields = attrs.get("fields")
    if stored_fields is not None and list(stored_fields) != list(
        model.field_names
    ):
        raise ValueError(
            f"Checkpoint store {path} holds fields "
            f"{list(stored_fields)} but model {model.name!r} declares "
            f"{list(model.field_names)}"
        )
    stored_precision = attrs.get("precision")
    if stored_precision is not None and str(stored_precision) != str(
        settings.precision
    ):
        raise ValueError(
            f"Checkpoint store {path} was written at precision "
            f"{stored_precision!r} but this run is configured for "
            f"{settings.precision!r}; a silent dtype cast would fork "
            "the trajectory"
        )
    if restart_step < 0:
        idx = n - 1
        sim_step = int(r.get("step", step=idx))
    else:
        available = [int(r.get("step", step=i)) for i in range(n)]
        if restart_step not in available:
            raise ValueError(
                f"Checkpoint store {path} has no entry for simulation "
                f"step {restart_step}; available steps: {available}"
            )
        # Last match: after a rollback-and-resume the store can hold two
        # entries for the same sim step (pre- and post-rollback
        # trajectories); the latest is the live one.
        idx = n - 1 - available[::-1].index(restart_step)
        sim_step = restart_step
    return r, idx, sim_step


def load_checkpoint(
    path: str, settings: Settings, restart_step: int = -1
) -> Tuple:
    """Full ``(*fields, step)`` of one checkpoint entry (single-host
    convenience wrapper around :func:`open_checkpoint`); fields follow
    the model's declaration order — ``(u, v, step)`` for Gray-Scott."""
    r, idx, step = open_checkpoint(path, settings, restart_step)
    fields = tuple(
        r.get(name, step=idx)
        for name in resolve_model(settings).field_names
    )
    r.close()
    return fields + (step,)
