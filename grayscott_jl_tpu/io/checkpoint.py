"""Checkpoint / restart — implemented for real.

The reference *declares* checkpoint/restart settings (``Structs.jl:15-19``)
but never uses them: the driver hardcodes ``restart_step = 0``
(``src/GrayScott.jl:77-78``) and no checkpoint is ever written (SURVEY
defect #4). Here they work: every ``checkpoint_freq`` steps the driver
writes (u, v, step) to ``checkpoint_output`` as a BP-lite store, and
``restart = true`` resumes from ``restart_input`` — reproducing the exact
trajectory, because the noise key is folded per absolute step
(``models/grayscott.py``).

Checkpoints append as new steps in one store; restart loads the latest.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from ..config.settings import Settings
from . import open_writer
from .bplite import BpReader


class CheckpointWriter:
    def __init__(
        self,
        settings: Settings,
        dtype,
        *,
        writer_id: int = 0,
        nwriters: int = 1,
    ):
        L = settings.L
        # On restart, append: truncating would destroy the very store the
        # run just resumed from when checkpoint_output == restart_input.
        self.writer = open_writer(
            settings.checkpoint_output,
            writer_id=writer_id,
            nwriters=nwriters,
            append=settings.restart,
        )
        if writer_id == 0:
            self.writer.define_attribute("L", settings.L)
            self.writer.define_attribute("precision", settings.precision)
        self.writer.define_variable("step", np.int32)
        self.writer.define_variable("u", np.dtype(dtype).name, (L, L, L))
        self.writer.define_variable("v", np.dtype(dtype).name, (L, L, L))

    def save(self, step: int, blocks) -> None:
        """``blocks``: iterable of (offsets, sizes, u_block, v_block) —
        this process's shards (``Simulation.local_blocks``)."""
        w = self.writer
        w.begin_step()
        w.put("step", np.int32(step))
        for offsets, sizes, ub, vb in blocks:
            w.put("u", ub, start=offsets, count=sizes)
            w.put("v", vb, start=offsets, count=sizes)
        w.end_step()

    def close(self) -> None:
        self.writer.close()


def open_checkpoint(path: str, settings: Settings) -> Tuple[BpReader, int, int]:
    """Open a checkpoint store and locate the latest entry.

    Returns ``(reader, step_index, sim_step)``; the caller restores state
    via per-shard selection reads (``Simulation.restore_from_reader``) so
    no process ever materializes the full global arrays.
    """
    r = BpReader(path)
    n = r.num_steps()
    if n == 0:
        raise ValueError(f"Checkpoint store {path} contains no steps")
    attrs = r.attributes()
    if int(attrs.get("L", settings.L)) != settings.L:
        raise ValueError(
            f"Checkpoint L={attrs['L']} does not match config L={settings.L}"
        )
    last = n - 1
    sim_step = int(r.get("step", step=last))
    return r, last, sim_step


def load_checkpoint(
    path: str, settings: Settings
) -> Tuple[np.ndarray, np.ndarray, int]:
    """Latest full (u, v, step) from a checkpoint store (single-host
    convenience wrapper around :func:`open_checkpoint`)."""
    r, last, step = open_checkpoint(path, settings)
    u = r.get("u", step=last)
    v = r.get("v", step=last)
    r.close()
    return u, v, step
