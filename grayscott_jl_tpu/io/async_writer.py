"""Double-buffered asynchronous output pipeline.

The driver's reference-parity flow is fully synchronous: at every
``plotgap``/``checkpoint_freq`` boundary the device idles through D2H ->
serialization -> VTK assembly -> disk (``src/GrayScott.jl:68-103``; the
round-5 driver kept that shape). Here the driver instead *submits* a
:class:`~..simulation.FieldSnapshot` (D2H already in flight) and
immediately dispatches the next compute chunk; a single background
writer thread resolves the snapshot and runs the write targets
(``SimStream.write_step`` / ``CheckpointWriter.save``) off the driver
thread — the standard overlapped-output stage of distributed stencil
frameworks (arxiv 2309.10292, 2404.02218).

Guarantees:

* **strict step ordering** — one worker consuming a FIFO queue: steps
  hit the stores in submission order even when snapshots' D2H transfers
  land out of order;
* **bounded buffering with backpressure** — at most ``GS_ASYNC_IO_DEPTH``
  submitted-but-unwritten steps (default 2 — double buffering); a full
  pipeline blocks ``submit`` until the writer catches up, so device
  memory holds a bounded number of live snapshots;
* **synchronous fallback** — ``GS_ASYNC_IO_DEPTH=0`` runs every target
  inline on the driver thread (bitwise-identical stores either way;
  the writers are single-threaded in both modes, only *which* thread
  calls them changes);
* **first-error capture** — a target exception is recorded with its
  step and re-raised on the driver thread (as :class:`AsyncIOError`) at
  the next ``submit`` or at ``close``; later queued steps are discarded
  (writing past a failed step would corrupt store order);
* **draining close** — ``close()`` returns only after every accepted
  step is durably written (or the first error is surfaced).

Overlap accounting for benchmarks: the worker tracks busy seconds per
phase (``device_to_host`` resolution, ``output``, ``checkpoint``), and
the driver side tracks how long it was *blocked* on the pipeline
(backpressure + final drain). ``overlap_stats()`` splits each phase's
busy time into ``hidden_s`` (ran behind compute) and ``exposed_s``
(driver waited), attributing driver-blocked time across phases
pro-rata by busy time; in synchronous mode everything is exposed by
construction.
"""

from __future__ import annotations

import contextlib
import os
import queue
import threading
import time
from typing import Optional, Sequence, Tuple

__all__ = ["AsyncIOError", "AsyncStepWriter", "resolve_depth"]


class AsyncIOError(RuntimeError):
    """A background write failed; re-raised on the driver thread."""

    def __init__(self, step: int, original: BaseException):
        super().__init__(
            f"async I/O writer failed at step {step}: "
            f"{type(original).__name__}: {original}"
        )
        #: Simulation step whose write raised.
        self.step = step
        #: The exception raised by the write target.
        self.original = original

    @property
    def transient(self) -> bool:
        """Is the wrapped failure an OS-level I/O error — the class of
        failure a supervisor may retry (full/flaky disk, NFS hiccup,
        injected ``io_error`` fault)? Value/Key/Runtime errors out of a
        write target are programming or format errors: retrying those
        would re-fail or, worse, corrupt the store.

        Classified here, where the failing write's exception is still
        first-hand, so the supervisor (``resilience/supervisor.py``)
        never guesses from a formatted message. One ``RuntimeError``
        subclass gets its own taxonomy slot upstream: a
        :class:`~..resilience.integrity.CorruptionError` raised on
        this thread (snapshot checksum verify in ``blocks()``, the
        checkpoint read-back verify) is NOT transient-io — the
        supervisor unwraps ``original`` and classifies it
        ``corruption`` (restartable with replica failover, bounded to
        one retry per corrupt site).
        """
        return isinstance(self.original, OSError)


def resolve_depth(depth: Optional[int] = None) -> int:
    """Pipeline depth: the argument, else ``GS_ASYNC_IO_DEPTH``
    (default 2). ``0`` means synchronous; negatives are invalid."""
    if depth is None:
        raw = os.environ.get("GS_ASYNC_IO_DEPTH", "2")
        try:
            depth = int(raw)
        except ValueError as e:
            raise ValueError(
                f"GS_ASYNC_IO_DEPTH must be a non-negative integer, "
                f"got {raw!r}"
            ) from e
    if depth < 0:
        raise ValueError(
            f"async I/O depth must be non-negative, got {depth}"
        )
    return depth


_SENTINEL = object()

#: Phase name for snapshot-to-host resolution time in the busy ledger.
_D2H = "device_to_host"


class AsyncStepWriter:
    """Bounded-queue background writer for simulation output steps.

    ``submit(step, snapshot, targets)`` hands one output boundary to the
    pipeline; ``targets`` is a sequence of ``(phase_name, fn)`` where
    ``fn(step, blocks)`` performs the write (phase names feed the
    overlap accounting and, in synchronous mode, the driver's
    ``RunStats`` phases so depth=0 reproduces the old flow exactly).

    ``stats`` is an optional :class:`~..utils.profiler.RunStats`; when
    given, driver-side time is recorded under the target phase names
    (inline write time when synchronous, submit/backpressure time when
    async) and the drain under ``io_drain``.

    ``progress`` is an optional ``progress(step)`` callback invoked
    from the worker thread after each fully written step — the hang
    watchdog's drain heartbeat (``resilience/watchdog.Watchdog.touch``):
    a close() draining K queued steps is healthy as long as individual
    writes keep completing, and only a *stuck* write should trip the
    drain deadline. Exceptions from the callback are swallowed — a
    monitoring hook must never poison the store path.

    ``metrics`` is an optional :class:`~..obs.metrics.MetricsRegistry`;
    when given (and armed), the pipeline keeps a live
    ``async_io_queue_depth`` gauge and an ``io_steps_written`` counter —
    the queue-depth time series a stalled disk shows up in long before
    the backpressure reaches the driver. Disabled metrics hand back the
    shared null instrument, so the per-step cost is a no-op call.
    """

    def __init__(self, *, depth: Optional[int] = None, stats=None,
                 progress=None, metrics=None):
        if metrics is None:
            from ..obs.metrics import NULL_METRIC

            self._m_depth = self._m_written = NULL_METRIC
        else:
            self._m_depth = metrics.gauge("async_io_queue_depth")
            self._m_written = metrics.counter("io_steps_written")
        self.depth = resolve_depth(depth)
        self._stats = stats
        self._progress = progress
        self._busy: dict = {}
        self._busy_lock = threading.Lock()
        self._submit_wait = 0.0
        self._drain_wait = 0.0
        self._queue_hwm = 0
        self._accepted = 0
        self._written = 0
        self._error: Optional[Tuple[int, BaseException]] = None
        self._raised = False
        self._thread: Optional[threading.Thread] = None
        self._q: Optional[queue.Queue] = None
        if self.depth > 0:
            self._q = queue.Queue(maxsize=self.depth)
            self._thread = threading.Thread(
                target=self._run, name="gs-async-io", daemon=True
            )
            self._thread.start()

    # ---------------------------------------------------------- properties

    @property
    def synchronous(self) -> bool:
        return self.depth == 0

    @property
    def steps_written(self) -> int:
        """Steps fully written so far (monotone; == accepted after a
        clean ``close``)."""
        return self._written

    # ------------------------------------------------------------- worker

    def _add_busy(self, phase: str, seconds: float) -> None:
        with self._busy_lock:
            self._busy[phase] = self._busy.get(phase, 0.0) + seconds

    def _write_one(self, step, snapshot, targets) -> None:
        t = time.perf_counter()
        blocks = snapshot.blocks()
        self._add_busy(_D2H, time.perf_counter() - t)
        for phase, fn in targets:
            t = time.perf_counter()
            fn(step, blocks)
            self._add_busy(phase, time.perf_counter() - t)
        self._written += 1
        self._m_written.inc()
        self._m_depth.set(self._q.qsize() if self._q is not None else 0)
        if self._progress is not None:
            try:
                self._progress(step)
            except Exception:  # noqa: BLE001 — monitoring must not kill writes
                pass

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is _SENTINEL:
                return
            step, snapshot, targets = item
            # After a failure later steps are consumed but DISCARDED —
            # continuing to write would put steps after a hole — while
            # draining the queue keeps a backpressure-blocked submit
            # from deadlocking against a dead pipeline.
            if self._error is None:
                try:
                    self._write_one(step, snapshot, targets)
                except BaseException as e:  # noqa: BLE001 — must not die
                    self._error = (step, e)

    # ------------------------------------------------------------- driver

    def _raise_pending(self) -> None:
        if self._error is not None and not self._raised:
            self._raised = True
            step, exc = self._error
            raise AsyncIOError(step, exc) from exc

    def _phase_cm(self, name: str):
        if self._stats is None:
            return contextlib.nullcontext()
        return self._stats.phase(name)

    def submit(
        self, step: int, snapshot, targets: Sequence[Tuple[str, object]]
    ) -> None:
        """Hand one output step to the pipeline.

        Synchronous mode writes inline (under each target's stats
        phase). Async mode enqueues, blocking while the pipeline is at
        depth; a previously captured writer error re-raises here before
        anything new is accepted.
        """
        self._raise_pending()
        if self._raised:
            step0 = self._error[0] if self._error else "?"
            raise RuntimeError(
                f"async I/O writer already failed at step {step0}; "
                "no further steps are accepted"
            )
        targets = list(targets)
        if self.synchronous:
            blocks = snapshot.blocks()
            for phase, fn in targets:
                t = time.perf_counter()
                with self._phase_cm(phase):
                    fn(step, blocks)
                self._add_busy(phase, time.perf_counter() - t)
            self._written += 1
            self._m_written.inc()
            self._accepted += 1
            return
        with contextlib.ExitStack() as st:
            # Submit time (≈0 unless backpressured) lands in the same
            # stats phases the writes used to occupy, so phase output
            # keeps meaning "driver wall time spent on output".
            for phase, _ in targets:
                st.enter_context(self._phase_cm(phase))
            t = time.perf_counter()
            self._q.put((step, snapshot, targets))
            self._submit_wait += time.perf_counter() - t
        self._accepted += 1
        self._queue_hwm = max(self._queue_hwm, self._q.qsize())
        self._m_depth.set(self._q.qsize())

    def drain(self) -> None:
        """Block until every accepted step is durably written (or the
        first failure has surfaced), WITHOUT stopping the worker — the
        live-reshape path (docs/RESHARD.md) retires in-flight writes
        against the old stores here before swapping in the new ones."""
        if self._thread is not None:
            with self._phase_cm("io_drain"):
                t = time.perf_counter()
                while (self._error is None
                       and self._written < self._accepted):
                    time.sleep(0.002)
                self._drain_wait += time.perf_counter() - t
        self._raise_pending()

    def close(self) -> None:
        """Drain and stop the worker; re-raise a pending writer error.

        Returns only once every accepted step is durably written (or
        the first failure has been surfaced). Idempotent."""
        if self._thread is not None:
            with self._phase_cm("io_drain"):
                t = time.perf_counter()
                self._q.put(_SENTINEL)
                self._thread.join()
                self._drain_wait += time.perf_counter() - t
            self._thread = None
        self._raise_pending()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        if exc_type is None:
            self.close()
            return
        # Abort path: still drain (the worker must not outlive the
        # driver and a blocked peer must unwedge), but never let a
        # secondary writer error mask the in-flight exception.
        try:
            self.close()
        except AsyncIOError:
            pass

    # -------------------------------------------------------------- stats

    def overlap_stats(self) -> dict:
        """JSON-able overlap accounting for ``RunStats``.

        ``busy_s`` is worker (or inline) write time per phase;
        ``exposed_s`` splits the driver-blocked time (backpressure +
        drain; everything, in synchronous mode) across phases pro-rata
        by busy time, and ``hidden_s`` is the remainder — I/O that ran
        behind compute."""
        with self._busy_lock:
            busy = dict(self._busy)
        total_busy = sum(busy.values())
        if self.synchronous:
            exposed_total = total_busy
        else:
            exposed_total = min(
                self._submit_wait + self._drain_wait, total_busy
            )
        frac = exposed_total / total_busy if total_busy > 0 else 0.0
        exposed = {k: v * frac for k, v in busy.items()}
        hidden = {k: v - exposed[k] for k, v in busy.items()}
        rounded = lambda d: {k: round(v, 6) for k, v in d.items()}  # noqa: E731
        return {
            "depth": self.depth,
            "steps_accepted": self._accepted,
            "steps_written": self._written,
            "queue_depth_hwm": self._queue_hwm,
            "busy_s": rounded(busy),
            "hidden_s": rounded(hidden),
            "exposed_s": rounded(exposed),
            "submit_wait_s": round(self._submit_wait, 6),
            "drain_wait_s": round(self._drain_wait, 6),
        }
