"""Optional real-ADIOS2 engine: genuine ``.bp`` output when the
``adios2`` wheel is importable.

The reference's output is a real ADIOS2 BP store consumed by ParaView's
ADIOS/Fides readers and any adios2 tooling
(``src/simulation/IO.jl:37-70,123-163``). The adios2 Python package is
not installable in this build environment (zero egress), so BP-lite
(``io/bplite.py``) preserves the *contract* — variables, attributes,
step streaming, (shape, start, count) blocks — in its own format. This
adapter closes the byte-compatibility gap for deployments that DO have
the wheel: :func:`grayscott_jl_tpu.io.open_writer` routes to
:class:`Adios2Writer` when ``import adios2`` succeeds, producing a BP
store with the identical variable names, provenance attributes, and
Fides/VTK schemas, so ADIOS2 tools open this framework's output exactly
as they open the reference's. BP-lite remains the always-available
fallback and the on-disk format spec.

Targets the adios2 >= 2.9 Python API (``adios2.Adios`` /
``declare_io`` / snake_case engine methods). Scope: single-writer
stores, including restart-append (BP4 ``Append`` mode — a resumed run
keeps writing its original store). Multi-writer (one process per host,
no MPI communicator to hand adios2) and ROLLBACK-append (step
truncation, which BP4 cannot do) stay on BP-lite, where those
semantics are implemented; ``open_writer`` gates both.

Tests: the full adapter contract runs in the default suite against a
strict API fake (``tests/unit/test_adios2_contract.py``,
``tests/support/adios2_fake`` — r4, closing the dead-code gap of a
wheel-less environment), plus the availability-gated suite against the
genuine wheel where one exists (``requires_adios2``,
``tests/unit/test_adios2_engine.py``).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Sequence

import numpy as np

from .bplite import StepStatus, VarInfo


@functools.cache
def available() -> bool:
    """True when the real adios2 Python bindings are importable (and new
    enough to carry the 2.9+ API this adapter targets)."""
    try:
        import adios2  # noqa: F401
    except ImportError:
        return False
    return hasattr(adios2, "Adios")


def _mode(name: str):
    from adios2 import bindings

    return getattr(bindings.Mode, name)


#: adios2 C-style type names whose numpy spelling differs. NB
#: ``np.dtype("float")`` is float64, but adios2's ``"float"`` is C
#: float — mapping through numpy directly silently doubles the element
#: size of every f32 variable (caught by the strict-dtype contract
#: tests, ``tests/unit/test_adios2_contract.py``).
_ADIOS_TYPE_TO_NP = {
    "float": "float32",
    "double": "float64",
    "long double": "longdouble",
    "char": "int8",
    "unsigned char": "uint8",
}


def _np_dtype(adios_type: str) -> np.dtype:
    return np.dtype(
        _ADIOS_TYPE_TO_NP.get(adios_type, adios_type.replace("_t", ""))
    )


class Adios2Writer:
    """``BpWriter``-interface writer emitting a genuine ADIOS2 BP store.

    Same call contract as ``bplite.BpWriter`` (define_attribute /
    define_variable / begin_step / put / end_step / close), so
    ``SimStream`` and the checkpoint writer run unchanged on top of it.
    """

    def __init__(
        self,
        path: str,
        *,
        writer_id: int = 0,
        nwriters: int = 1,
        append: bool = False,
        io_name: str = "SimulationOutput",
    ):
        if nwriters != 1 or writer_id != 0:
            raise ValueError(
                "Adios2Writer is single-writer; multi-writer stores use "
                "the BP-lite engines (open_writer gates this)"
            )
        import adios2

        self._adios = adios2.Adios()
        self._io = self._adios.declare_io(io_name)
        # The reference never calls set_engine (ADIOS2.jl lacks it —
        # IO.jl has a TODO to that effect) and so gets ADIOS2's default
        # engine, which was BP4 in its era; pin BP4 here explicitly for
        # byte-compatibility with that output.
        self._io.set_engine("BP4")
        # Append: BP4 continues the step sequence of an existing store —
        # the restart-append path (VERDICT r3 weak #5: a restarted run
        # can keep writing its original real-BP output store instead of
        # being told to rerun with GS_TPU_ADIOS2=0). Note BP4 cannot
        # TRUNCATE steps, so rollback-append (dropping an abandoned
        # trajectory's tail) remains BP-lite-only; open_writer routes
        # that case away from this engine.
        mode = _mode("Append") if append else _mode("Write")
        self._engine = self._io.open(path, mode)
        self._vars: Dict[str, Any] = {}
        self._meta: Dict[str, dict] = {}

    def define_attribute(self, name: str, value: Any) -> None:
        if isinstance(value, (list, tuple)) and value and isinstance(
            value[0], str
        ):
            self._io.define_attribute(name, list(value))
        elif isinstance(value, (list, tuple, np.ndarray)):
            self._io.define_attribute(
                name, np.asarray(value, dtype=np.float64)
            )
        elif isinstance(value, str):
            self._io.define_attribute(name, value)
        elif isinstance(value, bool):
            self._io.define_attribute(name, np.int64(value))
        elif isinstance(value, (int, np.integer)):
            self._io.define_attribute(name, np.int64(value))
        else:
            self._io.define_attribute(name, np.float64(value))

    def define_variable(
        self, name: str, dtype, shape: Sequence[int] = ()
    ) -> None:
        shape = [int(s) for s in shape]
        self._meta[name] = {"dtype": np.dtype(dtype), "shape": shape}
        # The adios2 variable is created lazily at first put (the 2.9 API
        # infers the dtype from the numpy array it is given).

    def begin_step(self) -> None:
        self._engine.begin_step()

    def put(
        self,
        name: str,
        value,
        *,
        start: Optional[Sequence[int]] = None,
        count: Optional[Sequence[int]] = None,
    ) -> None:
        meta = self._meta.get(name)
        if meta is None:
            raise KeyError(f"Variable {name!r} not defined")
        shape = meta["shape"]
        arr = np.ascontiguousarray(np.asarray(value, dtype=meta["dtype"]))
        if start is None:
            start = [0] * len(shape)
        if count is None:
            count = list(shape)
        var = self._vars.get(name)
        if var is None:
            var = self._io.define_variable(
                name, arr, shape, [int(s) for s in start],
                [int(c) for c in count],
            )
            self._vars[name] = var
        elif shape:
            var.set_selection(
                ([int(s) for s in start], [int(c) for c in count])
            )
        self._engine.put(var, arr, _mode("Sync"))

    def end_step(self) -> None:
        self._engine.end_step()

    def close(self) -> None:
        self._engine.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class Adios2Reader:
    """``BpReader``-interface reader over a real ADIOS2 BP store.

    Supports both access patterns the framework uses: streaming
    (``begin_step(timeout)`` -> OK | NOT_READY | END_OF_STREAM, the
    pdfcalc live-coupling loop) and random access (``get(name, step=i)``,
    the checkpoint/restart and analysis paths) via a separate
    random-access engine opened on demand.
    """

    def __init__(self, path: str, *, io_name: str = "SimulationInput"):
        import adios2

        self.path = path
        self._adios = adios2.Adios()
        self._io = self._adios.declare_io(io_name)
        self._stream = None
        self._ra_io = None
        self._ra = None  # random-access engine, opened lazily
        self._selections: Dict[str, tuple] = {}

    # -- step streaming ----------------------------------------------------

    def _ensure_stream(self):
        if self._stream is None:
            self._stream = self._io.open(self.path, _mode("Read"))
        return self._stream

    def begin_step(self, timeout: float = 10.0) -> StepStatus:
        from adios2 import bindings

        status = self._ensure_stream().begin_step(
            bindings.StepMode.Read, float(timeout)
        )
        if status == bindings.StepStatus.OK:
            return StepStatus.OK
        if status == bindings.StepStatus.NotReady:
            return StepStatus.NOT_READY
        return StepStatus.END_OF_STREAM

    def current_step(self) -> int:
        return int(self._ensure_stream().current_step())

    def end_step(self) -> None:
        self._ensure_stream().end_step()
        self._selections = {}

    # -- inquiry -----------------------------------------------------------

    def _inquiry_io(self):
        """IO/engine pair that can answer variable inquiries now."""
        if self._stream is not None:
            return self._io
        self._ensure_ra()
        return self._ra_io

    def _ensure_ra(self):
        if self._ra is None:
            import adios2

            self._ra_io = self._adios.declare_io("RandomAccessInput")
            self._ra = self._ra_io.open(
                self.path, _mode("ReadRandomAccess")
            )
        return self._ra

    def attributes(self) -> Dict[str, Any]:
        io = self._inquiry_io()
        out = {}
        for name in io.available_attributes():
            att = io.inquire_attribute(name)
            data = att.data_string() if att.type() == "string" else att.data()
            if isinstance(data, (list, np.ndarray)) and len(data) == 1:
                data = data[0]
            out[name] = data
        return out

    def available_variables(self) -> Dict[str, VarInfo]:
        io = self._inquiry_io()
        out = {}
        for name in io.available_variables():
            var = io.inquire_variable(name)
            out[name] = VarInfo(
                name,
                _np_dtype(var.type()),
                tuple(var.shape()),
            )
        return out

    def inquire_variable(self, name: str) -> Optional[VarInfo]:
        return self.available_variables().get(name)

    def num_steps(self) -> int:
        self._ensure_ra()
        for name in self._ra_io.available_variables():
            return int(self._ra_io.inquire_variable(name).steps())
        return 0

    def set_selection(
        self, name: str, start: Sequence[int], count: Sequence[int]
    ) -> None:
        self._selections[name] = (
            [int(s) for s in start],
            [int(c) for c in count],
        )

    # -- data --------------------------------------------------------------

    def get(
        self,
        name: str,
        *,
        step: Optional[int] = None,
        start: Optional[Sequence[int]] = None,
        count: Optional[Sequence[int]] = None,
    ) -> np.ndarray:
        if step is None:
            io, engine = self._io, self._ensure_stream()
        else:
            io, engine = self._ra_io, self._ensure_ra()
            if io is None:
                io = self._ra_io
        var = io.inquire_variable(name)
        if var is None:
            raise KeyError(f"Variable {name!r} has no data at this step")
        if step is not None:
            var.set_step_selection([int(step), 1])
        shape = tuple(var.shape())
        if start is None:
            sel = self._selections.get(name)
            if sel is not None:
                start, count = sel
        if shape and start is not None:
            var.set_selection(
                ([int(s) for s in start], [int(c) for c in count])
            )
            shape = tuple(int(c) for c in count)
        out = np.empty(shape, dtype=_np_dtype(var.type()))
        engine.get(var, out, _mode("Sync"))
        return out.reshape(shape) if shape else out[()]

    def close(self) -> None:
        if self._stream is not None:
            self._stream.close()
            self._stream = None
        if self._ra is not None:
            self._ra.close()
            self._ra = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
