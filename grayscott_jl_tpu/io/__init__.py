"""I/O subsystem: BP-lite streaming stores, VTK output, checkpointing.

Three interchangeable writer engines behind :func:`open_writer` (the
reference's single engine is the ADIOS2 C++ library, ``IO.jl``):

* real ADIOS2 (``io/adios.py``) — genuine ``.bp`` output, used
  automatically when the ``adios2`` wheel is importable (single-writer
  stores, including restart-append via BP4 Append mode; rollback-append
  — step truncation, which BP4 cannot express — routes post-rollback
  steps to a BP-lite sidecar merged back at read time,
  ``io/sidecar.py``); ADIOS2/Fides/ParaView tooling opens it exactly
  as it opens the reference's output;
* native BP-lite (``csrc/libbplite.so`` via ``io/native.py``) — C++,
  async step pipeline with background write/fsync/publish; default when
  built;
* pure Python BP-lite (``io/bplite.py``) — reference implementation and
  format spec; always available.

``GS_TPU_ADIOS2=0`` / ``GS_TPU_NATIVE_IO=0`` force the fallbacks.
:func:`open_reader` dispatches the matching reader by inspecting the
store (BP-lite directories carry ``md.json``).
"""

from __future__ import annotations

import os

from ..config.env import env_str


def _real_bp_evidence(path: str) -> bool:
    """Is ``path`` a real ADIOS2 BP store (vs BP-lite, possibly
    mid-startup)?

    The test must be POSITIVE evidence of ADIOS2, not absence of BP-lite
    metadata: a BP-lite multi-writer store mid-startup may contain only
    bare ``data.<w>`` payload files — writer 0 commits ``md.json`` last,
    after peers have already created the directory and opened their
    payloads — and that window is exactly when a peer's ``open_writer``
    or a live-coupled reader inspects the store. ADIOS2 BP4/BP5 engines
    create ``md.idx`` and extensionless ``md.<n>`` subfiles at open
    time; a BP3 store is a single regular FILE (BP-lite stores are
    always directories); BP-lite's metadata is always ``md[.<w>].json``.
    """
    if os.path.isfile(path):
        return True
    try:
        names = os.listdir(path)
    except (FileNotFoundError, NotADirectoryError):
        return False
    return any(
        n == "md.idx"
        or n == "mmd.0"
        or (n.startswith("md.") and n[3:].isdigit())
        for n in names
    )


def _foreign_dir(path: str) -> bool:
    """Is ``path`` a non-empty directory with NO BP-lite-shaped entries?

    Guards rollback-append against scribbling into an unrelated
    directory (a typo'd or stale config path): BP-lite entries are
    ``md[.<w>].json[.tmp]`` metadata and ``data.<w>`` payloads; an empty
    directory is presumed ours (a peer just created it, mid-startup).
    """
    try:
        names = os.listdir(path)
    except (FileNotFoundError, NotADirectoryError):
        return False

    def ours(n: str) -> bool:
        # md.json / md.json.tmp / md.<w>.json[.tmp] / data.<w>, plus
        # the integrity/quarantine sidecar files (docs/RESILIENCE.md
        # "Data integrity").
        if n.startswith(("md.", "integrity")) and n.endswith(
            (".json", ".json.tmp")
        ):
            return True
        if n in ("quarantine.json", "quarantine.json.tmp"):
            return True
        return n.startswith("data.") and n[5:].isdigit()

    return bool(names) and not any(ours(n) for n in names)


def count_steps_upto(path: str, sim_step: int):
    """Number of leading step entries in a store whose recorded ``step``
    scalar is <= ``sim_step`` (None when the store does not exist).

    The rollback helper: a run resuming from ``restart_step`` keeps this
    many entries of its output/checkpoint stores and drops the abandoned
    trajectory's tail (pass the result as ``keep_steps``).
    """
    from .bplite import BpReader, _md_path

    def count_leading(r) -> int:
        k = 0
        for i in range(r.num_steps()):
            if int(r.get("step", step=i)) <= sim_step:
                k = i + 1
            else:
                break
        return k

    if _real_bp_evidence(path):
        # Real-ADIOS2 store: countable only through the bindings. The
        # None return for a wheel-less process keeps the old behavior
        # (the loud append gate in open_writer catches it). A rollback
        # sidecar, when present, is part of the step sequence.
        from . import adios, sidecar

        if not adios.available():
            return None
        r = adios.Adios2Reader(path)
        keep_base = sidecar.read_keep_base(path)
        if keep_base is not None:
            r = sidecar.MergedReader(
                r, sidecar.sidecar_reader(path), keep_base
            )
        try:
            return count_leading(r)
        finally:
            r.close()

    # Gate on the rank-0 metadata FILE, not the directory: in a
    # multi-process restart with a fresh store, a peer's open_writer may
    # have just created the directory while md.json can only ever be
    # written by THIS process (writer 0) later — waiting on it here
    # deadlocks. No committed metadata == nothing to roll back.
    if not os.path.isfile(_md_path(path)):
        return None

    r = BpReader(path)
    k = count_leading(r)
    r.close()
    return k


def _bplite_writer(path, *, writer_id, nwriters, append, keep_steps):
    """The BP-lite engine chain (native C++ if built, else Python)."""
    if env_str("GS_TPU_NATIVE_IO", "1") != "0":
        from . import native

        if native.available():
            return native.NativeBpWriter(
                path, writer_id=writer_id, nwriters=nwriters, append=append,
                keep_steps=keep_steps,
            )
    from .bplite import BpWriter

    return BpWriter(
        path, writer_id=writer_id, nwriters=nwriters, append=append,
        keep_steps=keep_steps,
    )


def open_writer(
    path: str,
    *,
    writer_id: int = 0,
    nwriters: int = 1,
    append: bool = False,
    keep_steps=None,
    prefer_adios2: bool = True,
):
    """Open a step-based writer with the best available engine.

    Preference order: real ADIOS2 (genuine ``.bp``; single-writer
    stores when the wheel is importable — including restart-append onto
    an existing real-BP store or a fresh path), then the native C++
    BP-lite engine, then pure-Python BP-lite. The BP-lite engines
    implement the full multi-writer layout (``nwriters > 1``, one writer
    per JAX process, private ``data.<w>`` payload + per-writer metadata,
    reader-side merge) and rollback-append (``keep_steps`` truncation).
    BP4 cannot truncate steps, so a rollback restart onto a real BP
    store routes post-rollback steps to a BP-lite **sidecar** merged
    back at read time (``io/sidecar.py``); pod-scale runs get the async
    native engine.
    """
    from . import sidecar

    if not append:
        # Fresh write: a leftover rollback sidecar from a previous run
        # at this path would otherwise graft the OLD run's tail onto
        # the NEW store at read time.
        sidecar.remove_sidecar(path)
    if (
        prefer_adios2
        and env_str("GS_TPU_ADIOS2", "1") != "0"
        and nwriters == 1
    ):
        from . import adios

        if adios.available():
            if not append:
                # Overwriting a previous BP-lite run at this path: drop
                # its metadata/payload files, or open_reader would later
                # find the stale md.json and silently serve the OLD
                # run's data.
                if os.path.isdir(path):
                    for name in os.listdir(path):
                        if name in (
                            "md.json", "quarantine.json"
                        ) or (
                            name.startswith(
                                ("md.", "data.", "integrity")
                            )
                            and not name.endswith(".bp")
                        ):
                            os.remove(os.path.join(path, name))
                return adios.Adios2Writer(path, writer_id=writer_id,
                                          nwriters=nwriters)
            has_bp = _real_bp_evidence(path)
            if has_bp or not os.path.exists(path):
                keep_base = sidecar.read_keep_base(path)
                if keep_base is not None and not has_bp:
                    # Orphaned sidecar at a path whose base store is
                    # gone (deleted between runs): routing steps there
                    # would write output no reader looks at, and a new
                    # base store would graft the stale tail back on.
                    sidecar.remove_sidecar(path)
                    keep_base = None
                if keep_base is not None:
                    # A rollback sidecar already exists: ALL further
                    # appends go there (base steps written after
                    # sidecar steps would break the merged order). A
                    # deeper rollback lowers keep_base; a shallower one
                    # truncates within the sidecar.
                    if keep_steps is None:
                        inner_keep = None
                    elif keep_steps <= keep_base:
                        sidecar.write_keep_base(path, keep_steps)
                        inner_keep = 0
                    else:
                        inner_keep = keep_steps - keep_base
                    return _bplite_writer(
                        sidecar.sidecar_path(path), writer_id=writer_id,
                        nwriters=nwriters, append=True,
                        keep_steps=inner_keep,
                    )
                if keep_steps is not None and has_bp:
                    r = adios.Adios2Reader(path)
                    try:
                        total = r.num_steps()
                    finally:
                        r.close()
                    if keep_steps < total:
                        # Rollback-append onto a real BP store: BP4
                        # cannot TRUNCATE, so the first keep_steps base
                        # steps stay live (recorded in the sidecar
                        # marker) and every post-rollback step goes to
                        # a fresh BP-lite sidecar; open_reader serves
                        # the merged sequence.
                        sidecar.write_keep_base(path, keep_steps)
                        return _bplite_writer(
                            sidecar.sidecar_path(path),
                            writer_id=writer_id, nwriters=nwriters,
                            append=False, keep_steps=None,
                        )
                return adios.Adios2Writer(path, writer_id=writer_id,
                                          nwriters=nwriters, append=True)
    if append and (_real_bp_evidence(path) or _foreign_dir(path)):
        if _foreign_dir(path):
            why = "an unrelated directory (typo'd or stale config path?)"
        else:
            from . import adios

            if not adios.available():
                why = (
                    "a real ADIOS2 BP store and the adios2 bindings are "
                    "not importable to append to it"
                )
            elif not prefer_adios2:
                why = (
                    "a real ADIOS2 BP store, but this store type "
                    "(checkpoints) stays on the BP-lite engines by "
                    "design (rollback-append and selection-restore are "
                    "BP-lite semantics)"
                )
            elif nwriters != 1:
                why = (
                    "a real ADIOS2 BP store and the adios2 engine is "
                    "single-writer (this is a multi-process run); "
                    "multi-writer append is a BP-lite feature"
                )
            elif env_str("GS_TPU_ADIOS2", "1") == "0":
                why = (
                    "a real ADIOS2 BP store but GS_TPU_ADIOS2=0 disables "
                    "the adios2 engine; unset it to append to this store"
                )
            else:  # pragma: no cover — rollback now goes to the sidecar
                why = "a real ADIOS2 BP store in an unexpected state"
        raise RuntimeError(
            f"cannot append to {path}: it is {why}. Point the restart at "
            "a fresh output path, or keep output stores on BP-lite "
            "(GS_TPU_ADIOS2=0 from the first run) where multi-writer and "
            "rollback-append are implemented"
        )
    return _bplite_writer(path, writer_id=writer_id, nwriters=nwriters,
                          append=append, keep_steps=keep_steps)


def open_reader(path: str, *, live: bool = False):
    """Open a store with the matching reader engine.

    Real ADIOS2 BP stores (positive ``md.idx``/``md.<n>`` evidence,
    :func:`_real_bp_evidence`) need the adios2 bindings (a clear error
    when they are absent); anything else gets ``BpReader``.

    ``live=True`` is the streaming-coupling form (pdfcalc attaching to
    a simulation that may still be in its first-step compile window):
    the store is allowed to not exist yet — construction succeeds with
    zero steps and ``begin_step`` polls (NOT_READY until its timeout)
    until the writer creates the store, at which point the reader
    engine is dispatched on the store's ACTUAL format (the writer may
    turn out to be either engine). The default is strict: for offline
    analysis (gdsplot) a missing store is an operator error that must
    fail fast with the path in the message.
    """
    from .bplite import BpReader

    if _real_bp_evidence(path):
        from . import adios, sidecar

        if adios.available():
            base = adios.Adios2Reader(path)
            keep_base = sidecar.read_keep_base(path)
            if keep_base is not None:
                # Rollback sidecar present: serve base[0:keep_base] +
                # sidecar as one step sequence (io/sidecar.py). Live
                # consumers keep retrying the sidecar attach — its
                # first metadata flush may not have landed yet.
                return sidecar.MergedReader(
                    base, sidecar.sidecar_reader(path, live=live),
                    keep_base,
                    reattach=(
                        (lambda: sidecar.sidecar_reader(path, live=True))
                        if live else None
                    ),
                )
            return base
        raise RuntimeError(
            f"{path} is not a BP-lite store and the adios2 bindings are "
            "not importable to read it as a real BP store"
        )
    if not live:
        return BpReader(path)
    from . import adios

    if not adios.available():
        # Without the wheel every writer engine in this process family
        # produces BP-lite metadata — commit to the polling BpReader.
        return BpReader(path, wait_for_writer=True)
    return _LiveReader(path)


class _LiveReader:
    """Deferred-dispatch reader for live coupling when the store does
    not exist yet AND the adios2 bindings are importable — the writer
    may turn out to be the real-ADIOS2 engine (``md.idx``, no
    ``md.json``) or a BP-lite engine, and committing to either reader
    class up front would hang forever on the other (review finding r4).

    ``begin_step`` polls until the store's format is identifiable, then
    instantiates the matching reader and delegates everything to it.
    """

    def __init__(self, path: str):
        self.path = path
        self._inner = None

    def _try_attach(self):
        from .bplite import BpReader, _md_path

        if _real_bp_evidence(self.path):
            from . import adios, sidecar

            self._inner = adios.Adios2Reader(self.path)
            keep_base = sidecar.read_keep_base(self.path)
            if keep_base is not None:
                path = self.path
                self._inner = sidecar.MergedReader(
                    self._inner,
                    sidecar.sidecar_reader(path, live=True),
                    keep_base,
                    # The sidecar's first metadata flush may land after
                    # this live attach; keep retrying in begin_step.
                    reattach=lambda: sidecar.sidecar_reader(
                        path, live=True
                    ),
                )
        elif os.path.isfile(_md_path(self.path)):
            self._inner = BpReader(self.path, wait_for_writer=True)
        return self._inner

    def begin_step(self, timeout: float = 10.0):
        import time

        from .bplite import StepStatus

        deadline = time.monotonic() + timeout
        while self._inner is None:
            if self._try_attach() is not None:
                break
            if time.monotonic() >= deadline:
                return StepStatus.NOT_READY
            time.sleep(0.05)
        return self._inner.begin_step(
            timeout=max(0.0, deadline - time.monotonic())
        )

    def close(self):
        # Explicit so the give-up path (begin_step never returned OK,
        # e.g. pdfcalc's max_not_ready bound) can close gracefully
        # instead of tripping the __getattr__ not-attached error.
        if self._inner is not None:
            self._inner.close()

    def __getattr__(self, name):
        # Everything except begin_step/close requires an attached
        # store; the streaming protocol guarantees callers begin_step
        # first.
        if self._inner is None:
            raise RuntimeError(
                f"store {self.path} has not appeared yet; call "
                "begin_step until it returns OK before other reads"
            )
        return getattr(self._inner, name)
