"""I/O subsystem: BP-lite streaming stores, VTK output, checkpointing.

Three interchangeable writer engines behind :func:`open_writer` (the
reference's single engine is the ADIOS2 C++ library, ``IO.jl``):

* real ADIOS2 (``io/adios.py``) — genuine ``.bp`` output, used
  automatically when the ``adios2`` wheel is importable (single-writer,
  non-append stores); ADIOS2/Fides/ParaView tooling opens it exactly as
  it opens the reference's output;
* native BP-lite (``csrc/libbplite.so`` via ``io/native.py``) — C++,
  async step pipeline with background write/fsync/publish; default when
  built;
* pure Python BP-lite (``io/bplite.py``) — reference implementation and
  format spec; always available.

``GS_TPU_ADIOS2=0`` / ``GS_TPU_NATIVE_IO=0`` force the fallbacks.
:func:`open_reader` dispatches the matching reader by inspecting the
store (BP-lite directories carry ``md.json``).
"""

from __future__ import annotations

import os


def _md_path_of(path: str) -> str:
    from .bplite import _md_path

    return _md_path(path)


def count_steps_upto(path: str, sim_step: int):
    """Number of leading step entries in a store whose recorded ``step``
    scalar is <= ``sim_step`` (None when the store does not exist).

    The rollback helper: a run resuming from ``restart_step`` keeps this
    many entries of its output/checkpoint stores and drops the abandoned
    trajectory's tail (pass the result as ``keep_steps``).
    """
    from .bplite import BpReader, _md_path

    # Gate on the rank-0 metadata FILE, not the directory: in a
    # multi-process restart with a fresh store, a peer's open_writer may
    # have just created the directory while md.json can only ever be
    # written by THIS process (writer 0) later — waiting on it here
    # deadlocks. No committed metadata == nothing to roll back.
    if not os.path.isfile(_md_path(path)):
        return None

    r = BpReader(path)
    k = 0
    for i in range(r.num_steps()):
        if int(r.get("step", step=i)) <= sim_step:
            k = i + 1
        else:
            break
    r.close()
    return k


def open_writer(
    path: str,
    *,
    writer_id: int = 0,
    nwriters: int = 1,
    append: bool = False,
    keep_steps=None,
    prefer_adios2: bool = True,
):
    """Open a step-based writer with the best available engine.

    Preference order: real ADIOS2 (genuine ``.bp``; single-writer
    non-append stores when the wheel is importable), then the native C++
    BP-lite engine, then pure-Python BP-lite. The BP-lite engines
    implement the full multi-writer layout (``nwriters > 1``, one writer
    per JAX process, private ``data.<w>`` payload + per-writer metadata,
    reader-side merge) and rollback-append — pod-scale runs get the
    async native engine.
    """
    if (
        prefer_adios2
        and os.environ.get("GS_TPU_ADIOS2", "1") != "0"
        and nwriters == 1
        and not append
    ):
        from . import adios

        if adios.available():
            # Overwriting a previous BP-lite run at this path: drop its
            # metadata/payload files, or open_reader would later find the
            # stale md.json and silently serve the OLD run's data.
            if os.path.isdir(path):
                for name in os.listdir(path):
                    if name == "md.json" or (
                        name.startswith(("md.", "data."))
                        and not name.endswith(".bp")
                    ):
                        os.remove(os.path.join(path, name))
            return adios.Adios2Writer(path, writer_id=writer_id,
                                      nwriters=nwriters)
    if append and os.path.isdir(path) and not os.path.isfile(_md_path_of(path)):
        raise RuntimeError(
            f"{path} exists but is not a BP-lite store (a real ADIOS2 BP "
            "store from a previous run?); rollback-append is a BP-lite "
            "feature — rerun the original run with GS_TPU_ADIOS2=0, or "
            "point the restart at a fresh output path"
        )
    if os.environ.get("GS_TPU_NATIVE_IO", "1") != "0":
        from . import native

        if native.available():
            return native.NativeBpWriter(
                path, writer_id=writer_id, nwriters=nwriters, append=append,
                keep_steps=keep_steps,
            )
    from .bplite import BpWriter

    return BpWriter(
        path, writer_id=writer_id, nwriters=nwriters, append=append,
        keep_steps=keep_steps,
    )


def open_reader(path: str):
    """Open a store with the matching reader engine.

    BP-lite stores are directories carrying ``md.json``; anything else is
    a real ADIOS2 BP store and needs the adios2 bindings (a clear error
    when they are absent).
    """
    from .bplite import BpReader, _md_path

    def _bplite_evidence() -> bool:
        # A BP-lite store mid-startup may exist without md.json yet
        # (rank 0 commits it after peers create the directory): any
        # md.<w>.json marks it ours, and an empty directory gets
        # BpReader's retry-until-metadata behavior. Only .json metadata
        # is distinguishing — real ADIOS2 BP4 stores also carry bare
        # data.0 / md.0 subfiles.
        if os.path.isfile(_md_path(path)):
            return True
        try:
            names = os.listdir(path)
        except (FileNotFoundError, NotADirectoryError):
            return False
        return not names or any(
            n.startswith("md.") and n.endswith((".json", ".json.tmp"))
            for n in names
        )

    if not os.path.exists(path) or _bplite_evidence():
        return BpReader(path)
    from . import adios

    if adios.available():
        return adios.Adios2Reader(path)
    raise RuntimeError(
        f"{path} is not a BP-lite store and the adios2 bindings are not "
        "importable to read it as a real BP store"
    )
