"""I/O subsystem: BP-lite streaming stores, VTK output, checkpointing.

Two interchangeable writer engines for the same on-disk format (the
reference's single engine is the ADIOS2 C++ library, ``IO.jl``):

* native (``csrc/libbplite.so`` via ``io/native.py``) — C++, async step
  pipeline with background write/fsync/publish; default when built;
* pure Python (``io/bplite.py``) — reference implementation and format
  spec; always available.

``GS_TPU_NATIVE_IO=0`` forces the Python engine.
"""

from __future__ import annotations

import os


def open_writer(path: str, *, writer_id: int = 0, append: bool = False):
    """Open a BP-lite writer with the best available engine."""
    if os.environ.get("GS_TPU_NATIVE_IO", "1") != "0":
        from . import native

        if native.available():
            return native.NativeBpWriter(
                path, writer_id=writer_id, append=append
            )
    from .bplite import BpWriter

    return BpWriter(path, writer_id=writer_id, append=append)
