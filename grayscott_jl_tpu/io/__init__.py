"""I/O subsystem: BP-lite streaming stores, VTK output, checkpointing.

Two interchangeable writer engines for the same on-disk format (the
reference's single engine is the ADIOS2 C++ library, ``IO.jl``):

* native (``csrc/libbplite.so`` via ``io/native.py``) — C++, async step
  pipeline with background write/fsync/publish; default when built;
* pure Python (``io/bplite.py``) — reference implementation and format
  spec; always available.

``GS_TPU_NATIVE_IO=0`` forces the Python engine.
"""

from __future__ import annotations

import os


def open_writer(
    path: str,
    *,
    writer_id: int = 0,
    nwriters: int = 1,
    append: bool = False,
):
    """Open a BP-lite writer with the best available engine.

    Multi-writer stores (``nwriters > 1``, one writer per JAX process) use
    the Python engine; the native engine currently implements the
    single-writer layout.
    """
    if nwriters == 1 and os.environ.get("GS_TPU_NATIVE_IO", "1") != "0":
        from . import native

        if native.available():
            return native.NativeBpWriter(
                path, writer_id=writer_id, append=append
            )
    from .bplite import BpWriter

    return BpWriter(
        path, writer_id=writer_id, nwriters=nwriters, append=append
    )
