"""I/O subsystem: BP-lite streaming stores, VTK output, checkpointing.

Two interchangeable writer engines for the same on-disk format (the
reference's single engine is the ADIOS2 C++ library, ``IO.jl``):

* native (``csrc/libbplite.so`` via ``io/native.py``) — C++, async step
  pipeline with background write/fsync/publish; default when built;
* pure Python (``io/bplite.py``) — reference implementation and format
  spec; always available.

``GS_TPU_NATIVE_IO=0`` forces the Python engine.
"""

from __future__ import annotations

import os


def count_steps_upto(path: str, sim_step: int):
    """Number of leading step entries in a store whose recorded ``step``
    scalar is <= ``sim_step`` (None when the store does not exist).

    The rollback helper: a run resuming from ``restart_step`` keeps this
    many entries of its output/checkpoint stores and drops the abandoned
    trajectory's tail (pass the result as ``keep_steps``).
    """
    from .bplite import BpReader, _md_path

    # Gate on the rank-0 metadata FILE, not the directory: in a
    # multi-process restart with a fresh store, a peer's open_writer may
    # have just created the directory while md.json can only ever be
    # written by THIS process (writer 0) later — waiting on it here
    # deadlocks. No committed metadata == nothing to roll back.
    if not os.path.isfile(_md_path(path)):
        return None

    r = BpReader(path)
    k = 0
    for i in range(r.num_steps()):
        if int(r.get("step", step=i)) <= sim_step:
            k = i + 1
        else:
            break
    r.close()
    return k


def open_writer(
    path: str,
    *,
    writer_id: int = 0,
    nwriters: int = 1,
    append: bool = False,
    keep_steps=None,
):
    """Open a BP-lite writer with the best available engine.

    Both engines implement the full multi-writer layout (``nwriters > 1``,
    one writer per JAX process, private ``data.<w>`` payload +
    per-writer metadata, reader-side merge) — pod-scale runs get the
    async native engine too.
    """
    if os.environ.get("GS_TPU_NATIVE_IO", "1") != "0":
        from . import native

        if native.available():
            return native.NativeBpWriter(
                path, writer_id=writer_id, nwriters=nwriters, append=append,
                keep_steps=keep_steps,
            )
    from .bplite import BpWriter

    return BpWriter(
        path, writer_id=writer_id, nwriters=nwriters, append=append,
        keep_steps=keep_steps,
    )
