"""Direct VTK ImageData (.vti) output for ParaView.

The reference embeds a VTK ImageData XML schema as an ADIOS2 attribute so
ParaView's ADIOS reader can interpret the BP file (``IO.jl:123-163``).
Without the ADIOS2 C++ library in this environment, BP-lite stores that
same schema for parity — and this module additionally writes real ``.vti``
files (plus a ``.pvd`` time-series index), so the simulation remains
directly ParaView-visualizable end-to-end.

Axis convention: our fields are C-order ``[x, y, z]``; VTK flat ordering is
x-fastest, so blocks are transposed before writing.
"""

from __future__ import annotations

import os
import struct
import xml.sax.saxutils as saxutils

import numpy as np

_VTK_TYPES = {
    "float32": "Float32",
    "float64": "Float64",
    "int32": "Int32",
    "int64": "Int64",
}


def _extent_str(extent) -> str:
    return " ".join(f"{lo} {hi}" for lo, hi in extent)


def write_vti(
    path: str,
    L: int,
    step: int,
    *arrays: np.ndarray,
    names=None,
    extent=None,
) -> None:
    """One .vti file with the model's fields as CellData (appended raw
    encoding); ``names`` defaults to the Gray-Scott ``("U", "V")`` for
    two arrays.

    ``extent`` is the block's cell-space box in *global* coordinates as
    ``((x0, x1), (y0, y1), (z0, z1))`` — a piece of a larger grid, the
    form ``.pvti`` indexes reference; default is the whole ``[0, L]^3``
    grid. Dtypes VTK has no type name for (e.g. bfloat16) are widened to
    float32.
    """
    if names is None:
        names = _default_names(len(arrays))
    if arrays[0].dtype.name not in _VTK_TYPES:
        arrays = tuple(a.astype(np.float32) for a in arrays)
    vtk_type = _VTK_TYPES[arrays[0].dtype.name]
    if extent is None:
        extent = ((0, L),) * 3
    ext = _extent_str(extent)
    payloads = []
    offsets = []
    off = 0
    for arr in arrays:
        raw = np.ascontiguousarray(arr.transpose(2, 1, 0)).tobytes()
        payloads.append(struct.pack("<Q", len(raw)) + raw)
        offsets.append(off)
        off += len(payloads[-1])

    data_arrays = "\n".join(
        f'        <DataArray type="{vtk_type}" Name="{n}" '
        f'format="appended" offset="{o}"/>'
        for n, o in zip(names, offsets)
    )
    header = (
        '<?xml version="1.0"?>\n'
        '<VTKFile type="ImageData" version="1.0" byte_order="LittleEndian" '
        'header_type="UInt64">\n'
        f'  <ImageData WholeExtent="{ext}" Origin="0 0 0" '
        'Spacing="1 1 1">\n'
        f'    <Piece Extent="{ext}">\n'
        f'      <CellData Scalars="{names[0]}">\n'
        f'{data_arrays}\n'
        '      </CellData>\n'
        '    </Piece>\n'
        '  </ImageData>\n'
        '  <AppendedData encoding="raw">_'
    )
    footer = "</AppendedData>\n</VTKFile>\n"
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(header.encode())
        for p in payloads:
            f.write(p)
        f.write(footer.encode())
    os.replace(tmp, path)


_NP_TYPES = {v: k for k, v in _VTK_TYPES.items()}


def _default_names(n: int):
    """Gray-Scott's historical (U, V) for two arrays, F0..Fn otherwise."""
    return ("U", "V") if n == 2 else tuple(f"F{i}" for i in range(n))


def read_vti(path: str):
    """Read back a :func:`write_vti` file -> ``(extent, {"U": a, "V": a})``.

    Parses exactly the subset this module writes (appended raw encoding,
    UInt64 headers) — used by tests and the analysis tools to round-trip
    visualization output without a VTK dependency.
    """
    import re

    with open(path, "rb") as f:
        blob = f.read()
    marker = blob.index(b'<AppendedData encoding="raw">_') + len(
        b'<AppendedData encoding="raw">_'
    )
    header = blob[:marker].decode()
    m = re.search(r'<Piece Extent="([^"]+)"', header)
    nums = [int(x) for x in m.group(1).split()]
    extent = tuple((nums[i], nums[i + 1]) for i in (0, 2, 4))
    shape = tuple(hi - lo for lo, hi in extent)
    out = {}
    for am in re.finditer(
        r'<DataArray type="(\w+)" Name="(\w+)" format="appended" '
        r'offset="(\d+)"/>', header
    ):
        vtk_type, name, off = am.group(1), am.group(2), int(am.group(3))
        dtype = np.dtype(_NP_TYPES[vtk_type])
        (nbytes,) = struct.unpack_from("<Q", blob, marker + off)
        arr = np.frombuffer(
            blob, dtype=dtype, count=nbytes // dtype.itemsize,
            offset=marker + off + 8,
        )
        # stored x-fastest (VTK flat order); back to C-order [x, y, z]
        out[name] = arr.reshape(shape[::-1]).transpose(2, 1, 0)
    return extent, out


class PvtiSeriesWriter:
    """Parallel time series: per-block ``.vti`` pieces + a ``.pvti``
    index per step + a ``.pvd`` collection — ParaView opens the ``.pvd``
    and assembles pieces itself.

    The multi-host output path: every process writes only the pieces it
    owns (no gather, no cross-process coordination); writer 0 also
    writes the ``.pvti``/``.pvd`` indexes, which it can do without
    communication because the block decomposition is global static data
    (``CartDomain``). This restores the reference's "ParaView reads the
    output" property (``IO.jl:123-163``) for pod runs.

    Known window: writer 0 publishes a step's ``.pvti`` after writing
    its *own* pieces; a peer still flushing (or crashed mid-step) leaves
    the index referencing not-yet-present piece files until it catches
    up. Same semantics as the BP store's per-writer metadata — readers
    that need all-writers-committed steps should follow the BP store
    (whose merge enforces exactly that) and use the ``.pvti`` for
    visualization.
    """

    def __init__(
        self,
        output_name: str,
        domain,
        dtype,
        *,
        writer_id: int = 0,
        append: bool = False,
        max_step=None,
        names=("U", "V"),
    ):
        base = output_name[:-3] if output_name.endswith(".bp") else output_name
        self.dir = base + ".vtk"
        self.domain = domain
        self.L = domain.L
        self.names = tuple(names)
        self.writer_id = writer_id
        dtype = np.dtype(dtype)
        if dtype.name not in _VTK_TYPES:
            dtype = np.dtype(np.float32)  # bf16 pieces are widened
        self._vtk_type = _VTK_TYPES[dtype.name]
        os.makedirs(self.dir, exist_ok=True)
        self._entries = _scan_series(
            self.dir, ".pvti", max_step
        ) if append and writer_id == 0 else []
        self._pvd_path = os.path.join(self.dir, "series.pvd")

    @staticmethod
    def _piece_name(step: int, offsets) -> str:
        return f"step_{step:07d}_b{'_'.join(str(o) for o in offsets)}.vti"

    def write(self, step: int, blocks) -> None:
        """Write this process's ``(offsets, sizes, *fields)`` blocks as
        pieces; writer 0 also publishes the step's ``.pvti`` index."""
        for offsets, sizes, *fblocks in blocks:
            extent = tuple(
                (o, o + s) for o, s in zip(offsets, sizes)
            )
            write_vti(
                os.path.join(self.dir, self._piece_name(step, offsets)),
                self.L, step, *fblocks, names=self.names, extent=extent,
            )
        if self.writer_id == 0:
            self._write_pvti(step)

    def _write_pvti(self, step: int) -> None:
        whole = _extent_str(((0, self.L),) * 3)
        lines = [
            '<?xml version="1.0"?>',
            '<VTKFile type="PImageData" version="0.1" '
            'byte_order="LittleEndian">',
            f'  <PImageData WholeExtent="{whole}" GhostLevel="0" '
            'Origin="0 0 0" Spacing="1 1 1">',
            f'    <PCellData Scalars="{self.names[0]}">',
            *(
                f'      <PDataArray type="{self._vtk_type}" Name="{n}"/>'
                for n in self.names
            ),
            "    </PCellData>",
        ]
        # Every block of the global decomposition, regardless of which
        # process writes it — the decomposition is global static data.
        for rank in range(self.domain.n_blocks):
            coords = self.domain.coords(rank)
            offsets = self.domain.proc_offsets(coords)
            sizes = self.domain.proc_sizes(coords)
            ext = _extent_str(
                tuple((o, o + s) for o, s in zip(offsets, sizes))
            )
            name = self._piece_name(step, offsets)
            lines.append(
                f'    <Piece Extent="{ext}" '
                f'Source="{saxutils.escape(name)}"/>'
            )
        lines += ["  </PImageData>", "</VTKFile>", ""]
        name = f"step_{step:07d}.pvti"
        tmp = os.path.join(self.dir, name + ".tmp")
        with open(tmp, "w", encoding="utf-8") as f:
            f.write("\n".join(lines))
        os.replace(tmp, os.path.join(self.dir, name))
        self._entries.append((step, name))
        self._flush_pvd()

    def _flush_pvd(self) -> None:
        _write_pvd(self._pvd_path, self._entries)

    def close(self) -> None:
        if self.writer_id == 0:
            self._flush_pvd()


def _scan_series(directory: str, suffix: str, max_step) -> list:
    """(step, name) entries of existing ``step_<n><suffix>`` files,
    skipping anything that is not a plain series frame (e.g. ``.pvti``
    indexes and ``_b*``-suffixed piece files share the directory), and —
    after a rollback — anything past ``max_step``."""
    entries = []
    for name in sorted(os.listdir(directory)):
        stem = name[5:-len(suffix)]
        if not (name.startswith("step_") and name.endswith(suffix)
                and stem.isdigit()):
            continue
        if max_step is not None and int(stem) > max_step:
            continue
        entries.append((int(stem), name))
    return entries


class VtiSeriesWriter:
    """Time series of .vti files with a .pvd collection index."""

    def __init__(
        self, output_name: str, L: int, *, append: bool = False,
        max_step=None, names=("U", "V"),
    ):
        base = output_name[:-3] if output_name.endswith(".bp") else output_name
        self.dir = base + ".vtk"
        self.L = L
        self.names = tuple(names)
        os.makedirs(self.dir, exist_ok=True)
        # restart: keep pre-restart frames in the series index
        self._entries = _scan_series(self.dir, ".vti", max_step) if append else []
        self._pvd_path = os.path.join(self.dir, "series.pvd")

    def write(self, step: int, *arrays: np.ndarray) -> None:
        name = f"step_{step:07d}.vti"
        write_vti(os.path.join(self.dir, name), self.L, step, *arrays,
                  names=self.names)
        self._entries.append((step, name))
        self._flush_pvd()

    def _flush_pvd(self) -> None:
        _write_pvd(self._pvd_path, self._entries)

    def close(self) -> None:
        self._flush_pvd()


def _write_pvd(pvd_path: str, entries) -> None:
    """Atomic ``.pvd`` collection index over (step, file) entries."""
    lines = [
        '<?xml version="1.0"?>',
        '<VTKFile type="Collection" version="0.1" '
        'byte_order="LittleEndian">',
        "  <Collection>",
    ]
    for step, name in entries:
        lines.append(
            f'    <DataSet timestep="{step}" part="0" '
            f'file="{saxutils.escape(name)}"/>'
        )
    lines += ["  </Collection>", "</VTKFile>", ""]
    tmp = pvd_path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write("\n".join(lines))
    os.replace(tmp, pvd_path)
