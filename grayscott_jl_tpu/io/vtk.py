"""Direct VTK ImageData (.vti) output for ParaView.

The reference embeds a VTK ImageData XML schema as an ADIOS2 attribute so
ParaView's ADIOS reader can interpret the BP file (``IO.jl:123-163``).
Without the ADIOS2 C++ library in this environment, BP-lite stores that
same schema for parity — and this module additionally writes real ``.vti``
files (plus a ``.pvd`` time-series index), so the simulation remains
directly ParaView-visualizable end-to-end.

Axis convention: our fields are C-order ``[x, y, z]``; VTK flat ordering is
x-fastest, so blocks are transposed before writing.
"""

from __future__ import annotations

import os
import struct
import xml.sax.saxutils as saxutils

import numpy as np

_VTK_TYPES = {
    "float32": "Float32",
    "float64": "Float64",
    "int32": "Int32",
    "int64": "Int64",
}


def write_vti(path: str, L: int, step: int, u: np.ndarray, v: np.ndarray) -> None:
    """One .vti file with U and V as CellData (appended raw encoding).

    Dtypes VTK has no type name for (e.g. bfloat16) are widened to float32.
    """
    if u.dtype.name not in _VTK_TYPES:
        u = u.astype(np.float32)
        v = v.astype(np.float32)
    vtk_type = _VTK_TYPES[u.dtype.name]
    extent = f"0 {L} 0 {L} 0 {L}"
    payloads = []
    offsets = []
    off = 0
    for arr in (u, v):
        raw = np.ascontiguousarray(arr.transpose(2, 1, 0)).tobytes()
        payloads.append(struct.pack("<Q", len(raw)) + raw)
        offsets.append(off)
        off += len(payloads[-1])

    header = (
        '<?xml version="1.0"?>\n'
        '<VTKFile type="ImageData" version="1.0" byte_order="LittleEndian" '
        'header_type="UInt64">\n'
        f'  <ImageData WholeExtent="{extent}" Origin="0 0 0" '
        'Spacing="1 1 1">\n'
        f'    <Piece Extent="{extent}">\n'
        '      <CellData Scalars="U">\n'
        f'        <DataArray type="{vtk_type}" Name="U" format="appended" '
        f'offset="{offsets[0]}"/>\n'
        f'        <DataArray type="{vtk_type}" Name="V" format="appended" '
        f'offset="{offsets[1]}"/>\n'
        '      </CellData>\n'
        '    </Piece>\n'
        '  </ImageData>\n'
        '  <AppendedData encoding="raw">_'
    )
    footer = "</AppendedData>\n</VTKFile>\n"
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(header.encode())
        for p in payloads:
            f.write(p)
        f.write(footer.encode())
    os.replace(tmp, path)


class VtiSeriesWriter:
    """Time series of .vti files with a .pvd collection index."""

    def __init__(self, output_name: str, L: int, *, append: bool = False):
        base = output_name[:-3] if output_name.endswith(".bp") else output_name
        self.dir = base + ".vtk"
        self.L = L
        os.makedirs(self.dir, exist_ok=True)
        self._entries = []
        if append:
            # restart: keep pre-restart frames in the series index
            for name in sorted(os.listdir(self.dir)):
                if name.startswith("step_") and name.endswith(".vti"):
                    self._entries.append((int(name[5:-4]), name))
        self._pvd_path = os.path.join(self.dir, "series.pvd")

    def write(self, step: int, u: np.ndarray, v: np.ndarray) -> None:
        name = f"step_{step:07d}.vti"
        write_vti(os.path.join(self.dir, name), self.L, step, u, v)
        self._entries.append((step, name))
        self._flush_pvd()

    def _flush_pvd(self) -> None:
        lines = [
            '<?xml version="1.0"?>',
            '<VTKFile type="Collection" version="0.1" '
            'byte_order="LittleEndian">',
            "  <Collection>",
        ]
        for step, name in self._entries:
            lines.append(
                f'    <DataSet timestep="{step}" part="0" '
                f'file="{saxutils.escape(name)}"/>'
            )
        lines += ["  </Collection>", "</VTKFile>", ""]
        tmp = self._pvd_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write("\n".join(lines))
        os.replace(tmp, self._pvd_path)

    def close(self) -> None:
        self._flush_pvd()
