"""Lossy snapshot codec: device-side uint quantization for output.

High-frequency output (``plotgap`` every few steps) is the
bandwidth-bound regime where a run's wall clock is D2H + serialization
+ disk, not compute (``benchmarks/async_io_bench.py``; the portable-
stencil roofline analysis, arxiv 2309.04671, makes the regime precise).
This module cuts that volume at the *source*: each configured output
field is quantized to ``bits`` uniform levels INSIDE the fused
snapshot-copy jit (``Simulation.snapshot_async``), so the bytes that
cross the device boundary, ride the async writer, and land on disk are
the ``uint8``/``uint16`` payload — a 4x (f32 -> u8) to 2x (bf16 -> u8)
reduction before the store sees a single byte.

Scheme — per-field, per-step uniform uint quantization::

    lo = min(f),  hi = max(f)                    (f32 reductions)
    q  = round((f - lo) * (2^bits - 1) / (hi - lo))   as uintN
    f' = lo + q * (hi - lo) / (2^bits - 1)            (decode)

**Error bound** (documented, test-asserted per dtype): the decode error
of any cell is at most half a quantization level,

    |f' - f| <= (hi - lo) / (2^bits - 1) / 2   (+ one storage-dtype ulp)

where ``hi - lo`` is that field's value range *at that step*. The
bound is exact for float64 payloads up to the f32 arithmetic of the
encoder (the reductions and scale run in f32 — negligible next to any
bits <= 16 level width).

Store schema (docs/PRECISION.md): a coded variable is DEFINED at its
uint payload dtype, two per-step scalar variables ``<NAME>__qlo`` /
``<NAME>__qhi`` (f32) carry the step's range, and one store attribute
``snapshot_codec`` (a JSON object ``{name: {"bits": b, "dtype": d}}``)
names the coded variables and their original dtypes. ``BpReader``
decodes transparently — ``get`` of a coded variable returns the
dequantized float array — and the integrity layer is untouched:
per-block CRCs are computed over the *compressed* payload bytes at
write time and verified before decode, so a torn or flipped compressed
block is refused exactly like an exact one.

Scope: **plotgap output only by default** — checkpoints stay
exact-precision so a resumed run is byte-identical to an uninterrupted
one; ``snapshot_bits_ckpt`` / ``GS_SNAPSHOT_BITS_CKPT`` opts
checkpoints in explicitly (restores then dequantize; resume is no
longer bitwise). The ``compute_precision = "equality"`` escape hatch
refuses any codec loudly.

Host-side pieces are numpy + stdlib; only :func:`device_quantize`
touches ``jax.numpy``, lazily, when traced.
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Sequence

import numpy as np

__all__ = [
    "CODEC_ATTR",
    "CodecConfig",
    "EncodedField",
    "decode_attr",
    "dequantize",
    "device_quantize",
    "error_bound",
    "parse_bits_spec",
    "payload_dtype",
    "qhi_var",
    "qlo_var",
    "resolve_snapshot_codec",
]

#: Store attribute naming the coded variables: JSON object
#: ``{var_name: {"bits": int, "dtype": numpy-dtype-name}}``.
CODEC_ATTR = "snapshot_codec"

#: Valid quantization widths: uint payloads of at most 16 bits (wider
#: would stop compressing f32 at all); below 2 bits a field collapses
#: to its endpoints.
MIN_BITS, MAX_BITS = 2, 16


def qlo_var(name: str) -> str:
    """Per-step range-minimum scalar variable for coded ``name``."""
    return f"{name}__qlo"


def qhi_var(name: str) -> str:
    return f"{name}__qhi"


def payload_dtype(bits: int):
    """The uint payload dtype for a bit width."""
    return np.uint8 if bits <= 8 else np.uint16


def error_bound(lo: float, hi: float, bits: int, dtype=None) -> float:
    """The documented max-abs decode error: half a quantization level,
    plus the encoder/decoder's f32 arithmetic rounding at the range
    magnitude, plus one ulp of the storage dtype (the decode's final
    cast). The half-level term dominates for every bits <= 16."""
    mag = max(abs(lo), abs(hi), 1e-30)
    half_level = (hi - lo) / (2 ** bits - 1) / 2.0
    # The scale/round/dequantize arithmetic runs in f32 regardless of
    # the payload's original dtype (device_quantize/dequantize).
    bound = half_level + float(np.finfo(np.float32).eps) * mag * 4
    if dtype is None:
        return bound
    dt = np.dtype(dtype)
    try:
        eps = float(np.finfo(dt).eps)
    except (TypeError, ValueError):
        # Extension float dtypes (bfloat16 registers as kind 'V') are
        # invisible to numpy's finfo; ml_dtypes' own finfo knows them.
        try:
            import ml_dtypes

            eps = float(ml_dtypes.finfo(dt).eps)
        except (ImportError, TypeError, ValueError):
            eps = 0.0  # pragma: no cover — non-float payloads
    return bound + eps * mag


def parse_bits_spec(raw: str, field_names: Sequence[str]) -> Dict[str, int]:
    """``"8"`` (every field) or ``"u:8,v:12"`` (per field; ``=`` also
    accepted) -> ``{field_name: bits}``. Unknown fields and
    out-of-range widths raise a loud ValueError naming the model's
    fields — a typo must never silently write exact output."""
    raw = (raw or "").strip()
    if not raw:
        return {}
    names = [n.lower() for n in field_names]
    out: Dict[str, int] = {}

    def _bits(tok: str) -> int:
        try:
            b = int(tok)
        except ValueError as e:
            raise ValueError(
                f"snapshot_bits entry {tok!r} is not an integer"
            ) from e
        if not MIN_BITS <= b <= MAX_BITS:
            raise ValueError(
                f"snapshot_bits must be in [{MIN_BITS}, {MAX_BITS}], "
                f"got {b}"
            )
        return b

    if ":" not in raw and "=" not in raw:
        b = _bits(raw)
        return {n: b for n in names}
    for entry in raw.split(","):
        entry = entry.strip()
        if not entry:
            continue
        sep = ":" if ":" in entry else "="
        fname, _, tok = entry.partition(sep)
        fname = fname.strip().lower()
        if fname not in names:
            raise ValueError(
                f"snapshot_bits names unknown field {fname!r} "
                f"(model fields: {', '.join(names)})"
            )
        out[fname] = _bits(tok.strip())
    return out


class CodecConfig:
    """Resolved codec posture for one run: ``output`` / ``ckpt`` map
    field names to bit widths (empty = exact)."""

    def __init__(self, output: Dict[str, int], ckpt: Dict[str, int]):
        self.output = dict(output)
        self.ckpt = dict(ckpt)

    @property
    def enabled(self) -> bool:
        return bool(self.output or self.ckpt)

    def describe(self) -> Optional[dict]:
        """The RunStats / provenance record — None when fully exact."""
        if not self.enabled:
            return None
        return {
            "output": dict(self.output),
            "checkpoint": dict(self.ckpt) if self.ckpt else None,
        }

    def posture(self) -> str:
        """Canonical string for cache keys (schema v6): ``"off"`` or a
        sorted ``u:8,v:8[+ckpt]`` spelling — two runs with different
        codec postures must never share a tuned winner."""
        if not self.output and not self.ckpt:
            return "off"
        spec = ",".join(
            f"{n}:{b}" for n, b in sorted(self.output.items())
        )
        return spec + ("+ckpt" if self.ckpt else "")


def resolve_snapshot_codec(settings, field_names) -> CodecConfig:
    """``GS_SNAPSHOT_BITS`` env > ``snapshot_bits`` TOML key (and
    ``GS_SNAPSHOT_BITS_CKPT`` > ``snapshot_bits_ckpt`` for the
    checkpoint opt-in) -> :class:`CodecConfig`. The
    ``compute_precision = "equality"`` posture refuses any lossy codec
    loudly — equality means byte-identical stores, full stop."""
    raw = os.environ.get("GS_SNAPSHOT_BITS")
    if raw is None:
        raw = getattr(settings, "snapshot_bits", "") or ""
    output = parse_bits_spec(raw, field_names)
    raw_ck = os.environ.get("GS_SNAPSHOT_BITS_CKPT")
    if raw_ck is None:
        ckpt_on = bool(getattr(settings, "snapshot_bits_ckpt", False))
    else:
        ckpt_on = raw_ck.strip().lower() in ("1", "true", "yes", "on")
    ckpt = dict(output) if ckpt_on and output else {}
    if output:
        from ..config.settings import resolve_compute_precision

        if resolve_compute_precision(settings) == "equality":
            from ..models.base import SettingsError

            raise SettingsError(
                "compute_precision = 'equality' refuses the lossy "
                f"snapshot codec (snapshot_bits={raw!r}): equality "
                "asserts byte-identical trajectories AND stores — "
                "drop one of the two settings"
            )
    return CodecConfig(output, ckpt)


def codec_attr_value(codec: Dict[str, int], var_names, dtype) -> str:
    """The ``snapshot_codec`` attribute payload for a store whose
    variables are ``var_names`` (store spelling, e.g. upper-cased) over
    fields stored at ``dtype``. ``codec`` is keyed by lower-cased field
    name."""
    doc = {}
    for vn in var_names:
        bits = codec.get(vn.lower())
        if bits is not None:
            doc[vn] = {"bits": int(bits),
                       "dtype": np.dtype(dtype).name}
    return json.dumps(doc, sort_keys=True)


def decode_attr(attrs: dict) -> Dict[str, dict]:
    """Parse a store's ``snapshot_codec`` attribute into
    ``{var_name: {"bits": int, "dtype": str}}``; missing or torn
    attributes degrade to no-codec (exact reads) — the attribute is
    load-bearing only for stores that actually wrote coded payloads,
    and those always committed it at definition time."""
    raw = attrs.get(CODEC_ATTR)
    if not raw:
        return {}
    try:
        doc = json.loads(raw)
        return {
            str(k): {"bits": int(v["bits"]), "dtype": str(v["dtype"])}
            for k, v in doc.items()
        }
    except (ValueError, TypeError, KeyError):
        return {}


def device_quantize(field, bits: int):
    """The traced encoder: ``(q, lo, hi)`` with ``q`` the uint payload
    (same sharding as ``field`` — an elementwise map plus two global
    reductions) and ``lo``/``hi`` f32 scalars. A constant field
    (``hi == lo``) encodes to all-zeros and decodes to ``lo`` exactly.
    Fused into the snapshot-copy jit so the exact f32/bf16 field never
    crosses the device boundary for coded output."""
    import jax.numpy as jnp

    g = field.astype(jnp.float32)
    lo = g.min()
    hi = g.max()
    levels = jnp.float32(2 ** bits - 1)
    span = hi - lo
    scale = levels / jnp.where(span > 0, span, jnp.float32(1.0))
    q = jnp.clip(jnp.round((g - lo) * scale), 0, levels)
    return q.astype(payload_dtype(bits)), lo, hi


def dequantize(q, lo: float, hi: float, bits: int, dtype) -> np.ndarray:
    """Host-side decode of a uint payload back to ``dtype`` — the
    reader half of :func:`device_quantize`, error-bounded by
    :func:`error_bound`."""
    level = (np.float32(hi) - np.float32(lo)) / np.float32(2 ** bits - 1)
    out = np.float32(lo) + np.asarray(q).astype(np.float32) * level
    return out.astype(np.dtype(dtype))


class EncodedField:
    """One field's quantized block riding the output pipeline: the
    uint payload plus the step's (lo, hi) range and the original
    dtype. Store writers put ``.q`` (so CRCs cover the compressed
    payload) and record the range scalars; :meth:`decode` serves
    consumers that need values (VTK assembly, tests)."""

    __slots__ = ("q", "lo", "hi", "bits", "dtype")

    def __init__(self, q: np.ndarray, lo: float, hi: float, bits: int,
                 dtype):
        self.q = q
        self.lo = float(lo)
        self.hi = float(hi)
        self.bits = int(bits)
        self.dtype = np.dtype(dtype)

    @property
    def shape(self):
        return self.q.shape

    def decode(self) -> np.ndarray:
        return dequantize(self.q, self.lo, self.hi, self.bits,
                          self.dtype)

    def error_bound(self) -> float:
        return error_bound(self.lo, self.hi, self.bits, self.dtype)


class BoundaryBlocks(list):
    """The list the async writer hands to write targets, grown an
    ``encoded`` attribute: the exact blocks ride in the list body
    (empty when the boundary captured no exact copies), and
    ``encoded`` holds the codec form (entries mixing
    :class:`EncodedField` for coded fields and plain arrays for
    uncoded ones), or None when no codec ran. Plain lists keep working
    everywhere — consumers use ``getattr(blocks, "encoded", None)``."""

    encoded = None
