"""Rollback sidecar for real-ADIOS2 BP stores.

BP4 can append steps but never truncate them (no ADIOS2 API removes
committed steps), so a rollback restart — resume from a checkpoint
earlier than the store's last step, dropping the abandoned trajectory's
tail — cannot be expressed against a real BP store at all. Rather than
forcing operators onto ``GS_TPU_ADIOS2=0`` from run one (the r4
behavior: correct-and-loud refusal, VERDICT item 6), post-rollback
steps go to a **BP-lite sidecar** next to the store:

* ``<store>.sidecar/`` is a normal BP-lite store holding every step
  written after the rollback, plus a ``sidecar.json`` marker recording
  ``keep_base`` — how many leading steps of the base store are live;
* ``open_writer`` creates/extends the sidecar transparently when a
  rollback-append targets a real BP store (and routes ALL later
  appends there — base steps after sidecar steps would break order);
* ``open_reader`` returns a :class:`MergedReader` presenting
  ``base[0:keep_base] + sidecar[*]`` as one step sequence, so pdfcalc
  / gdsplot / restart counting see a single consistent store.

The base store stays byte-valid for any external ADIOS2/Fides tool —
such a tool just also shows the rolled-back tail (documented in
docs/PARITY.md); tools going through this package see the truth.

Integrity (docs/RESILIENCE.md "Data integrity"): the rollback sidecar
is a normal BP-lite store, so it carries its OWN per-writer integrity
ledger (``integrity[.<w>].json``) and its reads are CRC-verified like
any other BP-lite read; the real-ADIOS2 base has no ledger and reads
unverified (its own format carries no recorded CRCs to check).

Reference anchor: the store contract being preserved is
``/root/reference/src/simulation/IO.jl:37-70``.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from .bplite import StepStatus, _md_path

_MARKER = "sidecar.json"


def sidecar_path(path: str) -> str:
    return path.rstrip("/") + ".sidecar"


def read_keep_base(path: str) -> Optional[int]:
    """``keep_base`` from the sidecar marker of store ``path``, or None
    when no (valid) sidecar exists.

    TypeError covers corrupt markers whose JSON parses but has the
    wrong shape (top-level list, null ``keep_base``): a damaged sidecar
    must degrade to the documented no-sidecar behavior, not raise out
    of ``open_reader``/``open_writer``/``count_steps_upto``."""
    try:
        with open(os.path.join(sidecar_path(path), _MARKER),
                  encoding="utf-8") as f:
            return int(json.load(f)["keep_base"])
    except (FileNotFoundError, NotADirectoryError, KeyError, ValueError,
            TypeError):
        return None


def write_keep_base(path: str, keep_base: int) -> None:
    """Atomically (re)write the sidecar marker for store ``path``."""
    side = sidecar_path(path)
    os.makedirs(side, exist_ok=True)
    tmp = os.path.join(side, _MARKER + ".tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"keep_base": int(keep_base), "base": os.path.basename(
            path.rstrip("/"))}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(side, _MARKER))


def remove_sidecar(path: str) -> None:
    """Delete a stale sidecar (fresh non-append write at ``path``): a
    leftover marker would otherwise graft the OLD run's rollback tail
    onto the new store at read time. ``ignore_errors``: in a
    multi-writer run every process calls open_writer(append=False) at
    the same path concurrently, and rmtree does not tolerate a peer
    deleting entries under it."""
    import shutil

    side = sidecar_path(path)
    if os.path.isdir(side):
        shutil.rmtree(side, ignore_errors=True)


def sidecar_reader(path: str, *, live: bool = False):
    """BP-lite reader for the sidecar of store ``path``, or None when
    the sidecar holds no committed metadata yet (a marker written
    moments before the writer's first flush)."""
    from .bplite import BpReader

    side = sidecar_path(path)
    if not os.path.isfile(_md_path(side)):
        return None
    return BpReader(side, wait_for_writer=live)


class MergedReader:
    """Read-side merge of ``base[0:keep_base] + side[*]``.

    Presents the same reader API as ``BpReader``/``Adios2Reader``
    (streaming ``begin_step``/``end_step`` plus random-access
    ``get(step=...)``), routing each step index to the store that owns
    it. ``side`` may be None (marker exists, no committed sidecar
    metadata yet): the merged store is then just the capped base —
    the cap itself is load-bearing, it hides the rolled-back tail.
    ``reattach`` (live coupling) is retried on each ``begin_step``
    while ``side`` is None, so a reader that attached in the window
    between the marker write and the sidecar writer's first metadata
    flush still picks up the resumed run's steps (returning NOT_READY,
    not END_OF_STREAM, in the meantime).
    """

    def __init__(self, base, side, keep_base: int, *, reattach=None):
        self.base = base
        self.side = side
        self.keep_base = int(keep_base)
        self._reattach = reattach
        self._consumed = 0
        self._in_step = False

    # -- streaming ---------------------------------------------------------

    def begin_step(self, timeout: float = 10.0) -> StepStatus:
        if self._in_step:
            raise RuntimeError("begin_step with a step already open")
        if self._consumed < self.keep_base:
            self._in_step = True
            return StepStatus.OK
        if self.side is None and self._reattach is not None:
            self.side = self._reattach()
        if self.side is None:
            return (StepStatus.NOT_READY if self._reattach is not None
                    else StepStatus.END_OF_STREAM)
        st = self.side.begin_step(timeout=timeout)
        if st == StepStatus.OK:
            self._in_step = True
        return st

    def current_step(self) -> int:
        return self._consumed

    def end_step(self) -> None:
        if not self._in_step:
            raise RuntimeError("end_step without an open step")
        if self._consumed >= self.keep_base:
            self.side.end_step()
        self._in_step = False
        self._consumed += 1

    # -- inquiry -----------------------------------------------------------

    def attributes(self):
        out = dict(self.base.attributes())
        if self.side is not None:
            out.update(self.side.attributes())
        return out

    def available_variables(self):
        out = dict(self.base.available_variables())
        if self.side is not None:
            out.update(self.side.available_variables())
        return out

    def inquire_variable(self, name: str):
        return self.available_variables().get(name)

    def num_steps(self) -> int:
        n = self.keep_base
        if self.side is not None:
            n += self.side.num_steps()
        return n

    def set_selection(self, name, start, count) -> None:
        self.base.set_selection(name, start, count)
        if self.side is not None:
            self.side.set_selection(name, start, count)

    # -- data --------------------------------------------------------------

    def get(self, name: str, *, step: Optional[int] = None,
            start=None, count=None):
        if step is None:
            if not self._in_step:
                raise RuntimeError("get outside begin_step/end_step "
                                   "(or pass step=...)")
            if self._consumed < self.keep_base:
                return self.base.get(name, step=self._consumed,
                                     start=start, count=count)
            # the side reader has its own open step
            return self.side.get(name, start=start, count=count)
        if not 0 <= step < self.num_steps():
            raise IndexError(f"step {step} out of range")
        if step < self.keep_base:
            return self.base.get(name, step=step, start=start, count=count)
        return self.side.get(name, step=step - self.keep_base,
                             start=start, count=count)

    def close(self) -> None:
        self.base.close()
        if self.side is not None:
            self.side.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
