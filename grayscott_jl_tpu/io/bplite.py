"""BP-lite: a streaming, step-based, self-describing array store.

This plays the role ADIOS2 (the reference's only C++ native dependency,
``Project.toml:7-8``, bound in ``src/simulation/IO.jl``) plays for the
reference: step-based engines with ``begin_step / put / end_step`` writer
semantics, global arrays decomposed into per-writer blocks with
``(shape, start, count)`` boxes, named typed attributes for provenance, and
a streaming reader with ``begin_step(timeout) -> OK | NOT_READY |
END_OF_STREAM`` polling semantics (used by the PDF-analysis coupling,
``src/analysis/pdfcalc.jl:112-123``).

The ADIOS2 library itself is not available in this environment (zero
egress, no wheels); BP-lite keeps the *contract* — variable names, typed
attributes, step streaming, block decomposition — in a documented on-disk
format. This module is the pure-Python engine and the format's
specification; a native C++ engine for the same on-disk format is the
``csrc/`` component (used automatically when its shared library is built
— see ``io/native.py`` if present).

On-disk layout of ``name.bp`` (a directory, like BP4/BP5)::

    name.bp/
      md.json     -- metadata: attributes, variables, per-step block index;
                     rewritten atomically (tmp + rename) at every end_step
                     so concurrent readers always see a consistent snapshot
      data.<w>    -- append-only binary payload of writer w (C-order raw
                     array bytes, little-endian)

``md.json`` schema::

    {
      "format": "bplite-1",
      "complete": false,            # true once the writer closed
      "attributes": {name: {"dtype": str, "value": scalar|list}},
      "variables":  {name: {"dtype": str, "shape": [..] | []}},
      "steps": [                    # one entry per completed step
        {name: [ {"file": "data.0", "offset": int,
                  "start": [..], "count": [..]} , ...] }
      ]
    }

Scalars are zero-dim variables with ``start=count=[]``.

Durability: the reader validates every step entry against the payload
file sizes and exposes only *complete* steps (a crash between
``begin_step`` and a durable ``end_step`` — or a filesystem losing the
tail — never yields a readable torn step); the writer's append path
truncates the payload to the metadata-durable end, so rollback-resumed
stores are byte-identical to uninterrupted ones. Both are load-bearing
for the resilience subsystem's "latest durable checkpoint"
(``resilience/supervisor.py``).

Integrity (docs/RESILIENCE.md "Data integrity"): every payload block's
CRC32 is recorded in a per-writer **integrity sidecar file**
(``integrity[.<w>].json``) inside the store directory — sidecar
metadata only, so the ``md.json`` schema and the payload bytes above
stay exactly as documented and every byte-identity contract on stores
is preserved. The reader recomputes the CRC on every block read
(``GS_CKPT_VERIFY``, default ``read``) and raises
:class:`~..resilience.integrity.CorruptionError` naming the file,
offset, variable, and both CRCs instead of serving silently corrupt
bytes; a store whose integrity sidecar is missing or torn degrades to
the historical unverified read. Step entries quarantined by the
scrubber (``quarantine.json``, ``resilience/integrity.py``) are hidden
from readers like torn steps are.
"""

from __future__ import annotations

import enum
import json
import os
import time
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

FORMAT_NAME = "bplite-1"


class StepStatus(enum.Enum):
    """Reader step states (ADIOS2 ``step_status_*`` analog)."""

    OK = "ok"
    NOT_READY = "not_ready"
    END_OF_STREAM = "end_of_stream"


def _md_path(path: str) -> str:
    return os.path.join(path, "md.json")


def _integrity_path(path: str, writer_id: int = 0) -> str:
    name = (
        "integrity.json" if writer_id == 0
        else f"integrity.{writer_id}.json"
    )
    return os.path.join(path, name)


def read_integrity_crcs(path: str, writer_id: int = 0) -> dict:
    """One writer's recorded block CRCs: ``(file, offset) -> crc32``.
    A missing or torn sidecar degrades to an empty map (unverified
    reads) — the sidecar is advisory metadata, never a read gate."""
    try:
        with open(_integrity_path(path, writer_id),
                  encoding="utf-8") as f:
            doc = json.load(f)
        out = {}
        for key, val in (doc.get("crc") or {}).items():
            fname, _, off = key.rpartition(":")
            out[(fname, int(off))] = int(val[1])
        return out
    except (FileNotFoundError, NotADirectoryError, ValueError,
            TypeError, AttributeError, json.JSONDecodeError):
        return {}


class IntegrityMeta:
    """Writer-side ledger behind the integrity sidecar file.

    ``crc`` maps ``"file:offset"`` to ``[nbytes, crc32]`` for every
    payload block this writer committed; ``device`` is a list aligned
    with this writer's step entries holding the in-graph device-side
    field checksums recorded for that step (None when the boundary ran
    without the device probe). Rewritten atomically at every
    ``end_step`` — same discipline as ``md.json`` — and pruned on
    rollback-append so a resumed store's sidecar is byte-identical to
    an uninterrupted run's."""

    def __init__(self, store: str, writer_id: int = 0):
        self.path = _integrity_path(store, writer_id)
        self.crc: Dict[str, list] = {}
        self.device: List[Optional[dict]] = []
        self._pending_device: Optional[dict] = None

    def load(self) -> "IntegrityMeta":
        try:
            with open(self.path, encoding="utf-8") as f:
                doc = json.load(f)
            self.crc = dict(doc.get("crc") or {})
            self.device = list(doc.get("device") or [])
        except (FileNotFoundError, NotADirectoryError, ValueError,
                TypeError, json.JSONDecodeError):
            self.crc, self.device = {}, []
        return self

    def prune(self, data_file: str, cut: Optional[int],
              keep_steps: int) -> None:
        """Rollback: drop CRC entries at-or-past the payload cut of
        ``data_file`` and device records past the kept step count."""
        if cut is not None:
            self.crc = {
                k: v for k, v in self.crc.items()
                if not (k.rpartition(":")[0] == data_file
                        and int(k.rpartition(":")[2]) >= cut)
            }
        self.device = self.device[:keep_steps]

    def record_block(self, data_file: str, offset: int,
                     data: bytes) -> None:
        self.crc[f"{data_file}:{offset}"] = [
            len(data), zlib.crc32(data) & 0xFFFFFFFF,
        ]

    def record_device(self, checksums: Optional[dict]) -> None:
        """Device-side field checksums for the step currently being
        written (flushed with that step's ``end_step``)."""
        self._pending_device = (
            {str(k): int(v) for k, v in checksums.items()}
            if checksums else None
        )

    def note_step(self, n_steps: int) -> None:
        """Align the device list with the writer's committed step
        count (called at ``end_step``; pads boundaries that ran
        without the device probe)."""
        while len(self.device) < n_steps - 1:
            self.device.append(None)
        if len(self.device) < n_steps:
            self.device.append(self._pending_device)
        self._pending_device = None

    def flush(self) -> None:
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump({"crc": self.crc, "device": self.device}, f)
        os.replace(tmp, self.path)

    def remove(self) -> None:
        try:
            os.remove(self.path)
        except (FileNotFoundError, NotADirectoryError):
            pass


def _block_nbytes(variables: dict, name: str, block: dict) -> Optional[int]:
    """Byte length of one block's payload, or None when the metadata is
    too damaged to tell (unknown variable/dtype)."""
    var = variables.get(name)
    if var is None:
        return None
    try:
        itemsize = np.dtype(var["dtype"]).itemsize
    except (KeyError, TypeError):
        return None
    n = 1
    for c in block.get("count", []):
        n *= int(c)
    return n * itemsize


def durable_step_count(md: dict, dirpath: str) -> int:
    """Number of leading step entries whose every payload block lies
    fully inside its data file.

    A crash (or an injected fault) between ``begin_step`` and a durable
    ``end_step`` can leave a final step entry whose bytes never landed
    — e.g. metadata replicated before the payload reached disk, or a
    payload file truncated by the filesystem. Reads of such a step
    would raise mid-restore or return garbage; capping the visible step
    count here is what makes "latest durable checkpoint" well-defined
    for the supervisor (``resilience/supervisor.py``). Unverifiable
    metadata (unknown variable/dtype) is treated as non-durable.
    """
    variables = md.get("variables", {})
    sizes: Dict[str, int] = {}
    steps = md.get("steps", [])
    for i, step_blocks in enumerate(steps):
        for name, blocks in step_blocks.items():
            for b in blocks:
                nbytes = _block_nbytes(variables, name, b)
                if nbytes is None:
                    return i
                fname = b.get("file")
                if fname not in sizes:
                    try:
                        sizes[fname] = os.path.getsize(
                            os.path.join(dirpath, fname)
                        )
                    except (OSError, TypeError):
                        sizes[fname] = -1
                if sizes[fname] < int(b.get("offset", 0)) + nbytes:
                    return i
    return len(steps)


def data_end_offset(md: dict, data_file: str) -> Optional[int]:
    """End offset of the last payload byte ``data_file`` owns across
    every step entry of ``md``, or None when the metadata cannot be
    verified. ``0`` for a store whose steps never touched the file.

    The writer's rollback path truncates its append-only payload here:
    entries past ``keep_steps`` (and any torn tail from a crashed
    step) vanish from the *bytes*, not just the metadata, so a resumed
    run's store is byte-identical to an uninterrupted one.
    """
    variables = md.get("variables", {})
    end = 0
    for step_blocks in md.get("steps", []):
        for name, blocks in step_blocks.items():
            for b in blocks:
                if b.get("file") != data_file:
                    continue
                nbytes = _block_nbytes(variables, name, b)
                if nbytes is None:
                    return None
                end = max(end, int(b.get("offset", 0)) + nbytes)
    return end


class BpWriter:
    """Step-based writer engine (``ADIOS2.open(io, name, mode_write)``).

    Multi-writer stores (the ADIOS2 MPI-aggregated-I/O analog for JAX
    multi-host runs): each process opens the same store with its own
    ``writer_id`` and ``nwriters`` set; every writer owns its private
    ``data.<w>`` payload and metadata file (``md.json`` for writer 0 —
    which also carries the attribute/variable definitions and the writer
    count — ``md.<w>.json`` for the rest), so NO cross-process
    coordination is needed. The reader merges per-step blocks and
    publishes a step only once every writer has committed it.
    """

    def __init__(
        self,
        path: str,
        *,
        writer_id: int = 0,
        nwriters: int = 1,
        append: bool = False,
        keep_steps: Optional[int] = None,
    ):
        """``keep_steps`` (append mode): keep only the first N existing
        step entries — the rollback path, dropping the abandoned
        trajectory's steps past a ``restart_step`` so the resumed run
        does not append duplicates after them. The payload is truncated
        to the kept entries' end (``data_end_offset``), so the resumed
        store is byte-identical to one that never rolled back."""
        self.path = path
        self.writer_id = writer_id
        self.nwriters = nwriters
        if not 0 <= writer_id < nwriters:
            raise ValueError(f"writer_id {writer_id} not in [0, {nwriters})")
        os.makedirs(path, exist_ok=True)
        self._md_path = (
            _md_path(path)
            if writer_id == 0
            else os.path.join(path, f"md.{writer_id}.json")
        )
        self._data_path = os.path.join(path, f"data.{writer_id}")
        self._integrity = IntegrityMeta(path, writer_id)
        if append and os.path.exists(self._md_path):
            with open(self._md_path, "r", encoding="utf-8") as f:
                self._md = json.load(f)
            self._md["complete"] = False
            if keep_steps is not None:
                self._md["steps"] = self._md["steps"][:keep_steps]
            self._offset = (
                os.path.getsize(self._data_path)
                if os.path.exists(self._data_path)
                else 0
            )
            # Trim the payload to the metadata-durable end: rolled-back
            # entries and any torn tail from a crashed step are removed
            # from the bytes too, so the resumed store stays
            # byte-identical to an uninterrupted run's. Unverifiable
            # metadata falls back to plain append (absolute offsets
            # keep orphan bytes harmless, as before).
            cut = data_end_offset(
                self._md, os.path.basename(self._data_path)
            )
            if cut is not None and cut < self._offset:
                os.truncate(self._data_path, cut)
                self._offset = cut
            # Rollback the integrity sidecar in lockstep: CRC entries
            # past the payload cut and device records past the kept
            # steps vanish too, keeping the sidecar byte-identical to
            # an uninterrupted run's.
            self._integrity.load()
            self._integrity.prune(
                os.path.basename(self._data_path), cut,
                len(self._md["steps"]),
            )
        else:
            self._md = {
                "format": FORMAT_NAME,
                "complete": False,
                "nwriters": nwriters,
                "attributes": {},
                "variables": {},
                "steps": [],
            }
            with open(self._data_path, "wb"):
                pass
            self._offset = 0
            # Fresh store: stale integrity/quarantine markers from a
            # previous run at this path would mis-verify the new bytes.
            self._integrity.remove()
            if writer_id == 0:
                try:
                    os.remove(os.path.join(path, "quarantine.json"))
                except OSError:
                    pass
        self._data = open(self._data_path, "ab")
        self._in_step = False
        self._step_blocks: Dict[str, List[dict]] = {}
        self._flush_md()

    # -- definition phase (ADIOS2 define_attribute / define_variable) ------

    def define_attribute(self, name: str, value: Any) -> None:
        if isinstance(value, (list, tuple, np.ndarray)):
            arr = np.asarray(value)
            self._md["attributes"][name] = {
                "dtype": arr.dtype.name if arr.dtype.kind != "U" else "string",
                "value": arr.tolist(),
            }
        elif isinstance(value, str):
            self._md["attributes"][name] = {"dtype": "string", "value": value}
        elif isinstance(value, bool):
            self._md["attributes"][name] = {"dtype": "bool", "value": value}
        elif isinstance(value, (int, np.integer)):
            self._md["attributes"][name] = {"dtype": "int64", "value": int(value)}
        elif isinstance(value, (float, np.floating)):
            self._md["attributes"][name] = {
                "dtype": "float64",
                "value": float(value),
            }
        else:
            raise TypeError(f"Unsupported attribute type for {name!r}: {type(value)}")

    def define_variable(
        self, name: str, dtype, shape: Sequence[int] = ()
    ) -> None:
        self._md["variables"][name] = {
            "dtype": np.dtype(dtype).name,
            "shape": [int(s) for s in shape],
        }

    # -- step phase --------------------------------------------------------

    def begin_step(self) -> None:
        if self._in_step:
            raise RuntimeError("begin_step called inside an open step")
        self._in_step = True
        self._step_blocks = {}

    def put(
        self,
        name: str,
        value,
        *,
        start: Optional[Sequence[int]] = None,
        count: Optional[Sequence[int]] = None,
    ) -> None:
        """Write one block of variable ``name`` for the current step.

        ``start``/``count`` give the block's box in the global array
        (``IO.jl:60-67`` semantics); both default to the full variable.
        """
        if not self._in_step:
            raise RuntimeError("put called outside begin_step/end_step")
        var = self._md["variables"].get(name)
        if var is None:
            raise KeyError(f"Variable {name!r} not defined")
        shape = var["shape"]
        arr = np.asarray(value, dtype=var["dtype"])
        if not shape:
            # scalar variable: ascontiguousarray would promote 0-d to 1-d
            arr = arr.reshape(())
        else:
            arr = np.ascontiguousarray(arr)
        if start is None:
            start = [0] * len(shape)
        if count is None:
            count = list(shape)
        if list(arr.shape) != [int(c) for c in count]:
            raise ValueError(
                f"{name!r}: data shape {arr.shape} != count {tuple(count)}"
            )
        block = {
            "file": os.path.basename(self._data_path),
            "offset": self._offset,
            "start": [int(s) for s in start],
            "count": [int(c) for c in count],
        }
        data = arr.tobytes()
        self._integrity.record_block(
            os.path.basename(self._data_path), self._offset, data
        )
        self._data.write(data)
        self._offset += len(data)
        self._step_blocks.setdefault(name, []).append(block)

    def record_device_checksums(self, step: int, checksums) -> None:
        """Attach the boundary's in-graph device-side field checksums
        (``resilience/integrity.device_field_checksum``) to the step
        being written; they land in the integrity sidecar next to the
        block CRCs as per-step provenance."""
        self._integrity.record_device(checksums)

    def end_step(self) -> None:
        """Complete the step: payload is flushed, then the metadata index is
        atomically replaced — a streaming reader sees the step only after
        its data is durable (ADIOS2 deferred-put flush, ``IO.jl:91-95``)."""
        if not self._in_step:
            raise RuntimeError("end_step called outside a step")
        self._data.flush()
        os.fsync(self._data.fileno())
        self._md["steps"].append(self._step_blocks)
        # Sidecar before metadata: a crash between the two leaves CRC
        # entries for a step the metadata never committed (harmless —
        # keyed by payload offset, overwritten on the re-append) rather
        # than a committed step with no CRCs (silently unverifiable).
        self._integrity.note_step(len(self._md["steps"]))
        self._integrity.flush()
        self._flush_md()
        self._in_step = False
        self._step_blocks = {}

    def close(self) -> None:
        if self._in_step:
            raise RuntimeError("close called inside an open step")
        self._md["complete"] = True
        self._flush_md()
        self._data.close()

    def _flush_md(self) -> None:
        tmp = self._md_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(self._md, f)
        os.replace(tmp, self._md_path)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class VarInfo:
    def __init__(self, name: str, dtype: str, shape: Tuple[int, ...]):
        self.name = name
        self.dtype = np.dtype(dtype)
        self.shape = shape

    def __repr__(self):
        return f"VarInfo({self.name!r}, {self.dtype}, {self.shape})"


class BpReader:
    """Streaming step reader (``ADIOS2.open(io, name, mode_read)``).

    Supports live coupling: ``begin_step`` polls ``md.json`` until a step
    beyond the last-consumed one appears (NOT_READY while the writer is
    alive, END_OF_STREAM once it closed with no new steps) — the semantics
    the reference's pdfcalc loop relies on (``pdfcalc.jl:112-123``).
    """

    def __init__(self, path: str, *, wait_for_writer: bool = False,
                 verify: Optional[str] = None):
        """``wait_for_writer=True`` tolerates a store that does not exist
        yet (no directory, or no committed ``md.json``): construction
        succeeds with zero visible steps and ``begin_step`` polls until
        the writer commits — the live-coupling form ``open_reader``
        uses, where the reader may attach during the writer's first-step
        compile window (20-60 s). The default is strict (immediate
        ``FileNotFoundError``), the right behavior for checkpoint
        restores where a missing store is an operator error.

        ``verify`` overrides the resolved ``GS_CKPT_VERIFY`` mode for
        this reader (any non-``off`` mode recomputes the CRC of every
        block read against the store's integrity sidecar)."""
        self.path = path
        self._wait_for_writer = wait_for_writer
        if verify is None:
            from ..resilience.integrity import resolve_verify

            verify = resolve_verify()
        self._verify = verify != "off"
        if not wait_for_writer and not os.path.isdir(path):
            raise FileNotFoundError(f"No such BP-lite store: {path}")
        self._consumed = 0
        self._current: Optional[dict] = None
        self._selections: Dict[str, Tuple[List[int], List[int]]] = {}
        self._md: dict = {}
        self._crcs: Dict[Tuple[str, int], int] = {}
        self._load_md()

    def _load_md(self) -> None:
        # Writers replace their metadata files atomically; retry briefly on
        # the window where a JSON read could race a slow filesystem.
        md0 = self._load_one(
            _md_path(self.path), required=not self._wait_for_writer
        )
        if md0 is None:
            # Writer not started yet (wait_for_writer mode): nothing
            # visible; begin_step keeps polling until md.json appears.
            self._md = {
                "format": FORMAT_NAME, "complete": False, "steps": [],
                "attributes": {}, "variables": {},
            }
            return
        nwriters = int(md0.get("nwriters", 1))
        if self._verify:
            self._crcs = {}
            for w in range(nwriters):
                self._crcs.update(read_integrity_crcs(self.path, w))
        if nwriters == 1:
            # Publish only durable steps: a torn final entry (crash
            # between begin_step and a durable end_step) must not be
            # readable — it would raise mid-restore or return garbage.
            md0["steps"] = md0["steps"][:durable_step_count(md0, self.path)]
            self._drop_quarantined(md0)
            self._md = md0
            return
        # Multi-writer store: merge. A step is visible only once EVERY
        # writer has committed it durably; the stream is complete when all
        # writers closed and no unmerged steps remain.
        mds = [md0]
        for w in range(1, nwriters):
            md_w = self._load_one(
                os.path.join(self.path, f"md.{w}.json"), required=False
            )
            if md_w is None:  # writer not started yet: nothing visible
                md_w = {"complete": False, "steps": []}
            mds.append(md_w)
        for w, m in enumerate(mds):
            # Peer metadata normally carries its own variables table; a
            # (corrupt) one without falls back to writer 0's — LOUDLY:
            # a writer whose variable registry vanished is a damaged
            # store, and a silent fallback would hide the first symptom
            # of the corruption the integrity layer exists to surface.
            if w > 0 and m.get("steps") and not m.get("variables"):
                self._warn_corrupt_writer_md(w)
            checked = (
                m if m.get("variables")
                else dict(m, variables=md0.get("variables", {}))
            )
            m["steps"] = m.get("steps", [])[
                :durable_step_count(checked, self.path)
            ]
        n_steps = min(len(m["steps"]) for m in mds)
        steps = []
        for i in range(n_steps):
            merged: dict = {}
            for m in mds:
                for var, blocks in m["steps"][i].items():
                    merged.setdefault(var, []).extend(blocks)
            steps.append(merged)
        merged = {
            "format": md0.get("format", FORMAT_NAME),
            "complete": all(m.get("complete") for m in mds),
            "nwriters": nwriters,
            "attributes": md0.get("attributes", {}),
            "variables": md0.get("variables", {}),
            "steps": steps,
        }
        self._drop_quarantined(merged)
        self._md = merged

    def _drop_quarantined(self, md: dict) -> None:
        """Hide step entries the scrubber quarantined
        (``resilience/integrity.py``): a corrupt durable entry must
        not be served, and hiding it here is what lets "latest durable
        checkpoint" roll past it to the newest *healthy* entry."""
        from ..resilience.integrity import read_quarantine

        bad = read_quarantine(self.path)
        if bad:
            md["steps"] = [
                s for i, s in enumerate(md["steps"]) if i not in bad
            ]

    def _warn_corrupt_writer_md(self, writer_id: int) -> None:
        """One ``corruption`` event + warn per reader for a writer
        whose metadata lost its variable registry (satellite fix for
        the old silent writer-0 fallback)."""
        if getattr(self, "_warned_writers", None) is None:
            self._warned_writers: set = set()
        if writer_id in self._warned_writers:
            return
        self._warned_writers.add(writer_id)
        fname = f"md.{writer_id}.json"
        detail = (
            f"writer {writer_id} metadata {fname} has steps but no "
            "variable registry; validating its payloads against "
            "writer 0's registry"
        )
        from ..obs import events as obs_events
        from ..utils.log import Logger

        obs_events.get_events().emit(
            "corruption", path=self.path, file=fname, detail=detail
        )
        Logger().warn(f"BP-lite store {self.path}: {detail}")

    def _load_one(self, path: str, *, required: bool):
        for _ in range(50):
            try:
                with open(path, "r", encoding="utf-8") as f:
                    return json.load(f)
            except FileNotFoundError:
                if not required:
                    return None
                time.sleep(0.01)
            except json.JSONDecodeError:
                time.sleep(0.01)
        raise RuntimeError(f"Unreadable BP-lite metadata at {path}")

    # -- step streaming ----------------------------------------------------

    def begin_step(self, timeout: float = 10.0) -> StepStatus:
        deadline = time.monotonic() + timeout
        while True:
            self._load_md()
            if self._consumed < len(self._md["steps"]):
                self._current = self._md["steps"][self._consumed]
                self._selections = {}
                return StepStatus.OK
            if self._md.get("complete"):
                return StepStatus.END_OF_STREAM
            if time.monotonic() >= deadline:
                return StepStatus.NOT_READY
            time.sleep(0.05)

    def current_step(self) -> int:
        return self._consumed

    def end_step(self) -> None:
        if self._current is None:
            raise RuntimeError("end_step without an open step")
        self._current = None
        self._consumed += 1

    # -- inquiry -----------------------------------------------------------

    def attributes(self) -> Dict[str, Any]:
        return {
            k: v["value"] for k, v in self._md.get("attributes", {}).items()
        }

    def available_variables(self) -> Dict[str, VarInfo]:
        return {
            name: VarInfo(name, v["dtype"], tuple(v["shape"]))
            for name, v in self._md.get("variables", {}).items()
        }

    def inquire_variable(self, name: str) -> Optional[VarInfo]:
        return self.available_variables().get(name)

    def num_steps(self) -> int:
        return len(self._md["steps"])

    def set_selection(
        self, name: str, start: Sequence[int], count: Sequence[int]
    ) -> None:
        """Select a box of the global array for the next ``get`` (ADIOS2
        ``set_selection``, used by pdfcalc's z-split, ``pdfcalc.jl:144``)."""
        self._selections[name] = (
            [int(s) for s in start],
            [int(c) for c in count],
        )

    # -- data --------------------------------------------------------------

    def _codec_info(self) -> Dict[str, dict]:
        """The store's snapshot-codec registry (docs/PRECISION.md):
        ``{var_name: {"bits": int, "dtype": str}}``, empty for exact
        stores. Parsed from the ``snapshot_codec`` attribute on every
        call — the attribute is tiny, and a live-coupled reader may see
        it appear after construction."""
        from .codec import decode_attr

        return decode_attr(self.attributes())

    def get(
        self,
        name: str,
        *,
        step: Optional[int] = None,
        start: Optional[Sequence[int]] = None,
        count: Optional[Sequence[int]] = None,
    ) -> np.ndarray:
        """Read variable ``name`` at the current (or given) step, honoring
        any selection (``start``/``count`` here override a stored
        ``set_selection``). Assembles the box from the step's blocks.
        A CRC-mismatching block surfaces as a
        :class:`~..resilience.integrity.CorruptionError` naming the
        variable and step entry alongside the file/offset/CRC pair.

        Variables written through the lossy snapshot codec
        (docs/PRECISION.md — the ``snapshot_codec`` attribute names
        them) decode transparently: the uint payload is CRC-verified
        exactly like an exact block, then dequantized against the
        step's ``<NAME>__qlo``/``__qhi`` range scalars, and the
        original-dtype float array is returned."""
        try:
            out = self._get(name, step=step, start=start, count=count)
        except Exception as e:
            from ..resilience.integrity import CorruptionError

            if isinstance(e, CorruptionError) and e.var is None:
                raise CorruptionError(
                    e.detail, path=e.path or self.path, file=e.file,
                    offset=e.offset, var=name,
                    step=step if step is not None else self._consumed,
                ) from e
            raise
        info = self._codec_info().get(name)
        if info is not None:
            from .codec import dequantize, qhi_var, qlo_var

            idx = step if step is not None else self._consumed
            lo = float(self._get(qlo_var(name), step=idx))
            hi = float(self._get(qhi_var(name), step=idx))
            return dequantize(out, lo, hi, info["bits"], info["dtype"])
        return out

    def _get(
        self,
        name: str,
        *,
        step: Optional[int] = None,
        start: Optional[Sequence[int]] = None,
        count: Optional[Sequence[int]] = None,
    ) -> np.ndarray:
        if step is None:
            if self._current is None:
                raise RuntimeError("get outside begin_step/end_step "
                                   "(or pass step=...)")
            blocks = self._current.get(name)
        else:
            if not 0 <= step < len(self._md["steps"]):
                raise IndexError(f"step {step} out of range")
            blocks = self._md["steps"][step].get(name)
        if blocks is None:
            raise KeyError(f"Variable {name!r} has no data at this step")
        info = self.inquire_variable(name)

        if not info.shape:  # scalar
            return self._read_block(blocks[0], info.dtype, ())

        if start is None:
            sel = self._selections.get(name)
            if sel is None:
                start = [0] * len(info.shape)
                count = list(info.shape)
            else:
                start, count = sel
        else:
            start = [int(s) for s in start]
            count = [int(c) for c in count]
        out = np.empty(count, dtype=info.dtype)
        filled = np.zeros(count, dtype=bool)
        sel_lo = np.array(start)
        sel_hi = sel_lo + np.array(count)
        for b in blocks:
            b_lo = np.array(b["start"])
            b_hi = b_lo + np.array(b["count"])
            lo = np.maximum(sel_lo, b_lo)
            hi = np.minimum(sel_hi, b_hi)
            if np.any(lo >= hi):
                continue
            data = self._read_block(b, info.dtype, tuple(b["count"]))
            src = tuple(
                slice(int(l - bl), int(h - bl))
                for l, h, bl in zip(lo, hi, b_lo)
            )
            dst = tuple(
                slice(int(l - sl), int(h - sl))
                for l, h, sl in zip(lo, hi, sel_lo)
            )
            out[dst] = data[src]
            filled[dst] = True
        if not filled.all():
            raise ValueError(
                f"Selection {start}+{count} of {name!r} not fully covered "
                "by written blocks"
            )
        return out

    def _read_block(self, block: dict, dtype, shape) -> np.ndarray:
        nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize if shape else dtype.itemsize
        with open(os.path.join(self.path, block["file"]), "rb") as f:
            f.seek(block["offset"])
            buf = f.read(nbytes)
        if len(buf) != nbytes:
            raise IOError(
                f"Short read in {block['file']} at {block['offset']}"
            )
        if self._verify:
            # Verify-on-read: a payload whose recorded CRC mismatches
            # is never served (blocks written before the integrity
            # sidecar existed have no recorded CRC and read as before).
            want = self._crcs.get(
                (block["file"], int(block["offset"]))
            )
            if want is not None:
                got = zlib.crc32(buf) & 0xFFFFFFFF
                if got != want:
                    from ..resilience.integrity import CorruptionError

                    raise CorruptionError(
                        f"payload CRC mismatch: recorded {want:#010x}, "
                        f"read {got:#010x}",
                        path=self.path, file=block["file"],
                        offset=int(block["offset"]),
                    )
        arr = np.frombuffer(buf, dtype=dtype)
        return arr.reshape(shape) if shape else arr[0]

    def close(self) -> None:
        self._current = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
