"""Simulation output stream: the reference's ``ADIOSStream`` re-imagined.

Mirrors ``src/simulation/IO.jl`` variable-for-variable and
attribute-for-attribute: provenance attributes (F, k, dt, Du, Dv, noise —
``IO.jl:48-53``), Fides and VTK ImageData visualization schemas
(``IO.jl:123-163``), and per-step ``step``/``U``/``V`` variables with the
domain-decomposed (shape, start, count) boxes (``IO.jl:60-67``).

Output goes to a BP-lite store (``io/bplite.py``); optionally also to VTK
``.vti`` files (``io/vtk.py``) so ParaView can open results directly even
without an ADIOS2/Fides reader.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..config.settings import Settings
from ..parallel.domain import CartDomain
from . import open_writer


def fides_vtk_schemas(L: int) -> dict:
    """The Fides + VTK schema attributes, matching ``IO.jl:123-163``."""
    # Example: L=64 -> "0 64 0 64 0 64"
    extent = (("0 " + str(L) + " ") * 3).rstrip()
    vtk_schema = (
        "\n        <?xml version=\"1.0\"?>\n"
        "        <VTKFile type=\"ImageData\" version=\"0.1\" "
        "byte_order=\"LittleEndian\">\n"
        f"          <ImageData WholeExtent=\"{extent}\" Origin=\"0 0 0\" "
        "Spacing=\"1 1 1\">\n"
        f"            <Piece Extent=\"{extent}\">\n"
        "              <CellData Scalars=\"U\">\n"
        "                <DataArray Name=\"U\" />\n"
        "                <DataArray Name=\"V\" />\n"
        "                <DataArray Name=\"TIME\">\n"
        "                  step\n"
        "                </DataArray>\n"
        "              </CellData>\n"
        "            </Piece>\n"
        "          </ImageData>\n"
        "        </VTKFile>"
    )
    return {
        "Fides_Data_Model": "uniform",
        "Fides_Origin": [0.0, 0.0, 0.0],
        "Fides_Spacing": [0.1, 0.1, 0.1],
        "Fides_Dimension_Variable": "U",
        "Fides_Variable_List": ["U", "V"],
        "Fides_Variable_Associations": ["points", "points"],
        "vtk.xml": vtk_schema,
    }


class SimStream:
    """Step-output stream for a simulation (``IO.init`` analog)."""

    def __init__(
        self,
        settings: Settings,
        domain: CartDomain,
        dtype,
        *,
        io_name: str = "SimulationOutput",
        writer_id: int = 0,
        nwriters: int = 1,
        resume_step: Optional[int] = None,
    ):
        self.settings = settings
        self.domain = domain
        self.io_name = io_name
        L = settings.L

        # On restart, append — a resumed run must not truncate the output
        # steps written before the checkpoint it resumed from — but DO
        # drop entries past the resume point: after a rollback
        # (restart_step earlier than the last run's end) the abandoned
        # trajectory's steps would otherwise precede duplicates.
        keep = None
        if settings.restart and resume_step is not None:
            from . import count_steps_upto

            keep = count_steps_upto(settings.output, resume_step)
        self.writer = open_writer(
            settings.output,
            writer_id=writer_id,
            nwriters=nwriters,
            append=settings.restart,
            keep_steps=keep,
        )
        if writer_id == 0:
            # Provenance attributes (IO.jl:48-53)
            self.writer.define_attribute("F", settings.F)
            self.writer.define_attribute("k", settings.k)
            self.writer.define_attribute("dt", settings.dt)
            self.writer.define_attribute("Du", settings.Du)
            self.writer.define_attribute("Dv", settings.Dv)
            self.writer.define_attribute("noise", settings.noise)
            # Visualization schemas (IO.jl:123-163)
            for name, value in fides_vtk_schemas(L).items():
                self.writer.define_attribute(name, value)

        self.writer.define_variable("step", np.int32)
        self.writer.define_variable("U", np.dtype(dtype).name, (L, L, L))
        self.writer.define_variable("V", np.dtype(dtype).name, (L, L, L))

        self._vtk = None
        self._pvti = None
        if settings.mesh_type.lower() == "image":
            if nwriters == 1:
                from .vtk import VtiSeriesWriter

                self._vtk = VtiSeriesWriter(
                    settings.output, L, append=settings.restart,
                    max_step=resume_step,
                )
            else:
                # Multi-host: per-block .vti pieces + .pvti index — the
                # run stays ParaView-openable without any gather.
                from .vtk import PvtiSeriesWriter

                self._pvti = PvtiSeriesWriter(
                    settings.output, domain, dtype,
                    writer_id=writer_id, append=settings.restart,
                    max_step=resume_step,
                )

    def write_step(self, step: int, blocks) -> None:
        """Write one output step (``IO.write_step!``, ``IO.jl:82-96``).

        ``blocks`` is an iterable of ``(offsets, sizes, u_block, v_block)``
        — this process's shards of the global fields
        (``Simulation.local_blocks``).
        """
        w = self.writer
        w.begin_step()
        w.put("step", np.int32(step))
        blocks = list(blocks)
        for offsets, sizes, ub, vb in blocks:
            w.put("U", ub, start=offsets, count=sizes)
            w.put("V", vb, start=offsets, count=sizes)
        w.end_step()
        if self._pvti is not None:
            self._pvti.write(step, blocks)
        if self._vtk is not None:
            L = self.settings.L
            if len(blocks) == 1 and blocks[0][1] == (L, L, L):
                u, v = blocks[0][2], blocks[0][3]
            else:
                u = np.empty((L, L, L), blocks[0][2].dtype)
                v = np.empty_like(u)
                for offsets, sizes, ub, vb in blocks:
                    sl = tuple(
                        slice(o, o + s) for o, s in zip(offsets, sizes)
                    )
                    u[sl] = ub
                    v[sl] = vb
            self._vtk.write(step, u, v)

    def close(self) -> None:
        self.writer.close()
        if self._vtk is not None:
            self._vtk.close()
        if self._pvti is not None:
            self._pvti.close()
