"""Simulation output stream: the reference's ``ADIOSStream`` re-imagined.

Mirrors ``src/simulation/IO.jl`` variable-for-variable and
attribute-for-attribute for the Gray-Scott default: provenance
attributes (F, k, dt, Du, Dv, noise — ``IO.jl:48-53``), Fides and VTK
ImageData visualization schemas (``IO.jl:123-163``), and per-step
``step``/``U``/``V`` variables with the domain-decomposed (shape,
start, count) boxes (``IO.jl:60-67``).

Model-generic: the per-step variables and visualization schemas are
built from the run's model declaration — field names come from the
model (uppercased store spelling, so Gray-Scott keeps ``U``/``V``), and
the provenance attributes are the model's resolved parameters plus the
framework's ``dt``/``noise``, alongside ``model`` and ``fields``
metadata attributes naming what the store holds.

Output goes to a BP-lite store (``io/bplite.py``); optionally also to VTK
``.vti`` files (``io/vtk.py``) so ParaView can open results directly even
without an ADIOS2/Fides reader.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..config.settings import Settings, resolve_model
from ..parallel.domain import CartDomain
from . import open_writer


def fides_vtk_schemas(L: int, var_names: Sequence[str] = ("U", "V")) -> dict:
    """The Fides + VTK schema attributes (``IO.jl:123-163``), over the
    model's store variable names (Gray-Scott: ``U``/``V``)."""
    var_names = list(var_names)
    # Example: L=64 -> "0 64 0 64 0 64"
    extent = (("0 " + str(L) + " ") * 3).rstrip()
    arrays = "\n".join(
        f"                <DataArray Name=\"{n}\" />" for n in var_names
    )
    vtk_schema = (
        "\n        <?xml version=\"1.0\"?>\n"
        "        <VTKFile type=\"ImageData\" version=\"0.1\" "
        "byte_order=\"LittleEndian\">\n"
        f"          <ImageData WholeExtent=\"{extent}\" Origin=\"0 0 0\" "
        "Spacing=\"1 1 1\">\n"
        f"            <Piece Extent=\"{extent}\">\n"
        f"              <CellData Scalars=\"{var_names[0]}\">\n"
        f"{arrays}\n"
        "                <DataArray Name=\"TIME\">\n"
        "                  step\n"
        "                </DataArray>\n"
        "              </CellData>\n"
        "            </Piece>\n"
        "          </ImageData>\n"
        "        </VTKFile>"
    )
    return {
        "Fides_Data_Model": "uniform",
        "Fides_Origin": [0.0, 0.0, 0.0],
        "Fides_Spacing": [0.1, 0.1, 0.1],
        "Fides_Dimension_Variable": var_names[0],
        "Fides_Variable_List": var_names,
        "Fides_Variable_Associations": ["points"] * len(var_names),
        "vtk.xml": vtk_schema,
    }


class SimStream:
    """Step-output stream for a simulation (``IO.init`` analog)."""

    def __init__(
        self,
        settings: Settings,
        domain: CartDomain,
        dtype,
        *,
        io_name: str = "SimulationOutput",
        writer_id: int = 0,
        nwriters: int = 1,
        resume_step: Optional[int] = None,
        codec=None,
    ):
        """``codec`` (docs/PRECISION.md, ``{field_name: bits}`` lower-
        cased, or None) arms the lossy snapshot codec for this store:
        coded variables are DEFINED at their uint payload dtype, the
        per-step ``<NAME>__qlo``/``__qhi`` range scalars are declared
        beside them, and the ``snapshot_codec`` attribute names the
        coded variables so readers decode transparently
        (``io/bplite.BpReader``)."""
        self.settings = settings
        self.domain = domain
        self.io_name = io_name
        L = settings.L
        model = resolve_model(settings)
        self.model = model
        #: Store variable names: the model's field names uppercased
        #: (Gray-Scott keeps the reference's ``U``/``V`` spelling).
        self.var_names = tuple(n.upper() for n in model.field_names)
        self.codec = dict(codec or {})

        # On restart, append — a resumed run must not truncate the output
        # steps written before the checkpoint it resumed from — but DO
        # drop entries past the resume point: after a rollback
        # (restart_step earlier than the last run's end) the abandoned
        # trajectory's steps would otherwise precede duplicates.
        keep = None
        if settings.restart and resume_step is not None:
            from . import count_steps_upto

            keep = count_steps_upto(settings.output, resume_step)
        self.writer = open_writer(
            settings.output,
            writer_id=writer_id,
            nwriters=nwriters,
            append=settings.restart,
            keep_steps=keep,
        )
        if writer_id == 0:
            # Provenance attributes (IO.jl:48-53), routed through the
            # model declaration: every model parameter by name, then
            # the framework dt/noise, then what-is-this-store metadata.
            for name, value in model.resolve_param_values(
                settings
            ).items():
                self.writer.define_attribute(name, value)
            self.writer.define_attribute("dt", settings.dt)
            self.writer.define_attribute("noise", settings.noise)
            self.writer.define_attribute("model", model.name)
            self.writer.define_attribute("fields", list(self.var_names))
            if self.codec:
                from .codec import CODEC_ATTR, codec_attr_value

                self.writer.define_attribute(
                    CODEC_ATTR,
                    codec_attr_value(self.codec, self.var_names, dtype),
                )
            # Visualization schemas (IO.jl:123-163)
            for name, value in fides_vtk_schemas(
                L, self.var_names
            ).items():
                self.writer.define_attribute(name, value)

        from .codec import payload_dtype, qhi_var, qlo_var

        self.writer.define_variable("step", np.int32)
        for name in self.var_names:
            bits = self.codec.get(name.lower())
            if bits is None:
                self.writer.define_variable(
                    name, np.dtype(dtype).name, (L, L, L)
                )
            else:
                # Coded variable: the uint payload IS the store format
                # — CRCs, durability, and rollback all operate on the
                # compressed bytes; the range scalars complete the
                # decode (docs/PRECISION.md).
                self.writer.define_variable(
                    name, np.dtype(payload_dtype(bits)).name, (L, L, L)
                )
                self.writer.define_variable(qlo_var(name), np.float32)
                self.writer.define_variable(qhi_var(name), np.float32)

        self._vtk = None
        self._pvti = None
        if settings.mesh_type.lower() == "image":
            if nwriters == 1:
                from .vtk import VtiSeriesWriter

                self._vtk = VtiSeriesWriter(
                    settings.output, L, append=settings.restart,
                    max_step=resume_step, names=self.var_names,
                )
            else:
                # Multi-host: per-block .vti pieces + .pvti index — the
                # run stays ParaView-openable without any gather.
                from .vtk import PvtiSeriesWriter

                self._pvti = PvtiSeriesWriter(
                    settings.output, domain, dtype,
                    writer_id=writer_id, append=settings.restart,
                    max_step=resume_step, names=self.var_names,
                )

    def write_step(self, step: int, blocks, checksums=None) -> None:
        """Write one output step (``IO.write_step!``, ``IO.jl:82-96``).

        ``blocks`` is an iterable of ``(offsets, sizes, *field_blocks)``
        — this process's shards of the global fields in model
        declaration order (``Simulation.local_blocks``). ``checksums``
        (optional ``{field: device checksum}``,
        ``GS_CKPT_VERIFY=full``) records the boundary's in-graph
        device-side field checksums in the store's integrity sidecar
        (real-ADIOS2 stores have no sidecar and skip the record).
        """
        from .codec import EncodedField, qhi_var, qlo_var

        w = self.writer
        # Codec routing (docs/PRECISION.md): a coded store consumes the
        # snapshot's encoded form (``BoundaryBlocks.encoded``); exact
        # stores take the list body, exactly as before. Plain lists
        # (tests, analysis tools) have no ``encoded`` and write exact.
        enc = getattr(blocks, "encoded", None) if self.codec else None
        blocks = list(enc if enc is not None else blocks)
        w.begin_step()
        w.put("step", np.int32(step))
        if checksums is not None and hasattr(
                w, "record_device_checksums"):
            w.record_device_checksums(step, checksums)
        ranges_done = set()
        for offsets, sizes, *fblocks in blocks:
            for name, fb in zip(self.var_names, fblocks):
                if isinstance(fb, EncodedField):
                    w.put(name, fb.q, start=offsets, count=sizes)
                    if name not in ranges_done:
                        # The (lo, hi) range is a global reduction —
                        # one pair per step per field, identical
                        # across shards and writers.
                        w.put(qlo_var(name), np.float32(fb.lo))
                        w.put(qhi_var(name), np.float32(fb.hi))
                        ranges_done.add(name)
                else:
                    w.put(name, fb, start=offsets, count=sizes)
        w.end_step()
        if self._pvti is not None or self._vtk is not None:
            # Visualization consumes VALUES: coded blocks decode here
            # (the documented max-abs-error bound applies — the .vti
            # shows what the store serves).
            vis_blocks = [
                (offsets, sizes) + tuple(
                    fb.decode() if isinstance(fb, EncodedField) else fb
                    for fb in fblocks
                )
                for offsets, sizes, *fblocks in blocks
            ]
        if self._pvti is not None:
            self._pvti.write(step, vis_blocks)
        if self._vtk is not None:
            L = self.settings.L
            if len(vis_blocks) == 1 and vis_blocks[0][1] == (L, L, L):
                arrays = vis_blocks[0][2:]
            else:
                arrays = tuple(
                    np.empty((L, L, L), vis_blocks[0][2].dtype)
                    for _ in self.var_names
                )
                for offsets, sizes, *fblocks in vis_blocks:
                    sl = tuple(
                        slice(o, o + s) for o, s in zip(offsets, sizes)
                    )
                    for full, fb in zip(arrays, fblocks):
                        full[sl] = fb
            self._vtk.write(step, *arrays)

    def close(self) -> None:
        self.writer.close()
        if self._vtk is not None:
            self._vtk.close()
        if self._pvti is not None:
            self._pvti.close()
