"""ctypes binding for the native C++ BP-lite writer engine (csrc/bplite.cpp).

Drop-in replacement for the pure-Python ``BpWriter`` with the same on-disk
format, plus an asynchronous step pipeline: ``end_step`` returns as soon as
the step's payload is staged, and a background C++ I/O thread performs
write + fsync + atomic metadata publication while the simulation computes
— the ADIOS2 deferred-put analog. ``drain()``/``close()`` block until
everything queued is durable.

Engine selection lives in :func:`grayscott_jl_tpu.io.open_writer`: native
when ``csrc/libbplite.so`` is built (``make -C csrc``), pure Python
otherwise, overridable with ``GS_TPU_NATIVE_IO=0``.
"""

from __future__ import annotations

import ctypes
import json
import os
from typing import Any, Optional, Sequence

import numpy as np

from . import bplite as _py

_LIB_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "csrc",
    "libbplite.so",
)

_lib = None


def load_library(path: str = _LIB_PATH):
    """The loaded libbplite, or None if not built/loadable."""
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    # A stale build would silently misread the current argument lists
    # (e.g. nwriters landing in the old append slot -> every store opens
    # in append mode). Refuse anything but the expected ABI and fall
    # back to the Python engine.
    try:
        lib.bpw_abi_version.restype = ctypes.c_int
        if lib.bpw_abi_version() != 2:
            return None
    except AttributeError:
        return None
    lib.bpw_open.restype = ctypes.c_void_p
    lib.bpw_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
    ]
    lib.bpw_define_attribute_json.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
    ]
    lib.bpw_define_variable.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
    ]
    lib.bpw_set_prior_steps_json.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.bpw_publish.argtypes = [ctypes.c_void_p]
    lib.bpw_begin_step.argtypes = [ctypes.c_void_p]
    lib.bpw_begin_step.restype = ctypes.c_int
    lib.bpw_put.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64),
        ctypes.c_int,
    ]
    lib.bpw_put.restype = ctypes.c_int64
    lib.bpw_end_step.argtypes = [ctypes.c_void_p]
    lib.bpw_end_step.restype = ctypes.c_int
    lib.bpw_drain.argtypes = [ctypes.c_void_p]
    lib.bpw_drain.restype = ctypes.c_int
    lib.bpw_close.argtypes = [ctypes.c_void_p]
    lib.bpw_close.restype = ctypes.c_int
    _lib = lib
    return lib


def available() -> bool:
    return load_library() is not None


def _i64(seq: Sequence[int]):
    return (ctypes.c_int64 * len(seq))(*[int(s) for s in seq])


class NativeBpWriter:
    """Same interface as :class:`grayscott_jl_tpu.io.bplite.BpWriter`."""

    def __init__(
        self,
        path: str,
        *,
        writer_id: int = 0,
        nwriters: int = 1,
        append: bool = False,
        keep_steps: Optional[int] = None,
    ):
        lib = load_library()
        if lib is None:
            raise RuntimeError(
                "libbplite.so not built — run `make -C csrc` or use the "
                "Python engine"
            )
        self._lib = lib
        self.path = path
        self.writer_id = writer_id
        self.nwriters = nwriters
        if not 0 <= writer_id < nwriters:
            raise ValueError(f"writer_id {writer_id} not in [0, {nwriters})")
        md_name = "md.json" if writer_id == 0 else f"md.{writer_id}.json"
        # variable registry mirrored host-side for dtype coercion/validation
        self._vars = {}
        # Integrity sidecar (io/bplite.py IntegrityMeta): the native
        # engine stages payloads in C++, but the CRC ledger is managed
        # host-side from the staged offsets bpw_put returns — same
        # sidecar file, same schema, byte-compatible across engines.
        self._integrity = _py.IntegrityMeta(path, writer_id)
        self._n_steps = 0
        prior = None
        if append and os.path.exists(os.path.join(path, md_name)):
            with open(os.path.join(path, md_name), "r", encoding="utf-8") as f:
                prior = json.load(f)
            for name, v in prior.get("variables", {}).items():
                self._vars[name] = (v["dtype"], tuple(v["shape"]))
            # Trim the payload to the end of the steps being kept BEFORE
            # the native open (which fstat's the file size as its append
            # offset): rolled-back entries and torn crash tails vanish
            # from the bytes, keeping resumed stores byte-identical to
            # uninterrupted ones — same semantics as the Python engine.
            data_name = f"data.{writer_id}"
            kept = prior.get("steps", [])
            if keep_steps is not None:
                kept = kept[:keep_steps]
            cut = _py.data_end_offset(
                {"variables": prior.get("variables", {}), "steps": kept},
                data_name,
            )
            data_path = os.path.join(path, data_name)
            if (
                cut is not None
                and os.path.exists(data_path)
                and cut < os.path.getsize(data_path)
            ):
                os.truncate(data_path, cut)
            self._integrity.load()
            self._integrity.prune(data_name, cut, len(kept))
            self._n_steps = len(kept)
        self._h = lib.bpw_open(
            path.encode(), writer_id, nwriters, 1 if append else 0
        )
        if not self._h:
            raise IOError(f"Cannot open BP-lite store at {path}")
        if prior is None:
            # Fresh store: drop stale integrity/quarantine markers from
            # a previous run at this path (mirrors the Python engine).
            self._integrity.remove()
            if writer_id == 0:
                try:
                    os.remove(os.path.join(path, "quarantine.json"))
                except OSError:
                    pass
        if prior is not None:
            # Forward ALL prior state (steps, variables, attributes) before
            # the single publish — a streaming reader must never observe
            # steps without their variables/attributes. keep_steps drops
            # rolled-back trajectory steps (see BpWriter docstring).
            prior_steps = prior.get("steps", [])
            if keep_steps is not None:
                prior_steps = prior_steps[:keep_steps]
            steps_json = ", ".join(json.dumps(s) for s in prior_steps)
            lib.bpw_set_prior_steps_json(self._h, steps_json.encode())
            for name, (dtype, shape) in self._vars.items():
                lib.bpw_define_variable(
                    self._h, name.encode(), dtype.encode(),
                    _i64(shape), len(shape),
                )
            for name, val in prior.get("attributes", {}).items():
                lib.bpw_define_attribute_json(
                    self._h, name.encode(), json.dumps(val).encode()
                )
            lib.bpw_publish(self._h)
        self._in_step = False

    def _handle(self):
        if not self._h:
            raise RuntimeError("writer is closed")
        return self._h

    def define_attribute(self, name: str, value: Any) -> None:
        self._handle()
        # reuse the Python engine's attribute typing rules
        probe = _py.BpWriter.__new__(_py.BpWriter)
        probe._md = {"attributes": {}}
        _py.BpWriter.define_attribute(probe, name, value)
        encoded = json.dumps(probe._md["attributes"][name])
        self._lib.bpw_define_attribute_json(
            self._h, name.encode(), encoded.encode()
        )

    def define_variable(self, name: str, dtype, shape: Sequence[int] = ()) -> None:
        self._handle()
        dtype_name = np.dtype(dtype).name
        self._vars[name] = (dtype_name, tuple(int(s) for s in shape))
        self._lib.bpw_define_variable(
            self._h, name.encode(), dtype_name.encode(), _i64(shape), len(shape)
        )

    def begin_step(self) -> None:
        if self._lib.bpw_begin_step(self._handle()) != 0:
            raise RuntimeError("begin_step called inside an open step")
        self._in_step = True

    def put(
        self,
        name: str,
        value,
        *,
        start: Optional[Sequence[int]] = None,
        count: Optional[Sequence[int]] = None,
    ) -> None:
        if not self._in_step:
            raise RuntimeError("put called outside begin_step/end_step")
        if name not in self._vars:
            raise KeyError(f"Variable {name!r} not defined")
        dtype_name, shape = self._vars[name]
        arr = np.asarray(value, dtype=dtype_name)
        arr = arr.reshape(()) if not shape else np.ascontiguousarray(arr)
        if start is None:
            start = [0] * len(shape)
        if count is None:
            count = list(shape)
        if list(arr.shape) != [int(c) for c in count]:
            raise ValueError(
                f"{name!r}: data shape {arr.shape} != count {tuple(count)}"
            )
        rc = self._lib.bpw_put(
            self._handle(),
            name.encode(),
            arr.ctypes.data_as(ctypes.c_void_p),
            arr.nbytes,
            _i64(start),
            _i64(count),
            len(count),
        )
        if rc < 0:
            raise RuntimeError(f"native put failed for {name!r}")
        # rc is the staged payload offset this block will land at.
        self._integrity.record_block(
            f"data.{self.writer_id}", int(rc), arr.tobytes()
        )

    def record_device_checksums(self, step: int, checksums) -> None:
        """Same contract as ``BpWriter.record_device_checksums``."""
        self._integrity.record_device(checksums)

    def end_step(self) -> None:
        if self._lib.bpw_end_step(self._handle()) != 0:
            raise RuntimeError("end_step called outside a step")
        self._in_step = False
        self._n_steps += 1
        self._integrity.note_step(self._n_steps)
        self._integrity.flush()

    def drain(self) -> None:
        """Block until all queued steps are durable on disk."""
        if self._lib.bpw_drain(self._handle()) != 0:
            raise IOError(
                f"native BP-lite writer failed writing {self.path} "
                "(disk full or I/O error); failed steps were not published"
            )

    def close(self) -> None:
        if self._in_step:
            raise RuntimeError("close called inside an open step")
        if self._h:
            h, self._h = self._h, None
            if self._lib.bpw_close(h) != 0:
                raise IOError(
                    f"native BP-lite writer failed writing {self.path} "
                    "(disk full or I/O error); failed steps were not published"
                )

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
