"""Cross-shard temporal blocking for the sharded Pallas path.

The fused Pallas kernel (``ops/pallas_stencil.py``) chains ``k``
timesteps per HBM pass by walking shrinking windows along its leading
(x) axis. Crossing shard boundaries with that chain needs k-deep halo
data on every sharded axis — and what that costs depends on which
*Mosaic tiling dimension* the axis lands on:

* **x** (untiled leading dim): free — the x-chain mode consumes k-wide
  exchanged x slabs directly (round 3);
* **y** (sublane dim, 8/16-granularity): cheap — :func:`xy_chain`
  extends the operand by a k-deep exchanged y halo (rounded up to the
  sublane tile with boundary-constant filler rows) and the kernel's
  mid-stage global-coordinate pinning makes in-domain pad rows
  ring-recompute the y neighbor's values, so the in-kernel chain
  crosses y shard boundaries at a few percent of plane-area overhead;
* **z** (128-lane dim): expensive — a ±k z pad would round the lane
  extent up to the next 128 multiple (up to ~50% wasted vector work),
  so z shard boundaries are instead handled OUTSIDE the kernel:
  the kernel runs with frozen z edges, contaminating the outermost k
  z-cells per sharded z side (one cell per stage), and
  :func:`window_chain` recomputes those k-wide bands in XLA from a
  corner-propagated k-deep frame (``halo.halo_pad_wide``) — O(k * n^2)
  cells per side per round against the kernel's O(n^3).

Per ``k`` steps: ONE exchange round (4 ppermutes for an (n, m, 1)
mesh, 6 with z sharded — the per-step cost the reference pays in
``communication.jl:138-199``), one fused k-deep kernel pass, and — only
when z is sharded — two thin XLA band chains. Everything reproduces the
step-at-a-time trajectory exactly (position-keyed noise,
``ops/noise.py``), which the CPU-mesh bitwise tests assert.

This supersedes the round-3 design (single-step kernel launches with an
XLA-advanced ghost shell), which paid a measured 1.46x per-stage
penalty because in-kernel fusion stopped at every shard boundary.
"""

from __future__ import annotations

from typing import Callable, Sequence, Tuple

import jax.numpy as jnp
from jax import lax

from . import halo


def pin_out_of_domain(arr, bv, origin, row):
    """Pin every cell whose GLOBAL coordinate falls outside ``[0, row)``
    on any axis to the frozen boundary value (the reference's
    ``MPI.PROC_NULL`` ghost semantics); ``origin`` (int32[3]) is the
    global coordinate of ``arr[0, 0, 0]``.

    Works on any offset sub-box of a shard, and — unlike a mesh-edge
    ring mask — also pins **pad cells inside the block** (non-divisible
    L stores a padded grid, ``parallel/domain.py``)."""
    origin = jnp.asarray(origin, jnp.int32)
    valid = None
    for dim in range(3):
        g = origin[dim] + jnp.arange(arr.shape[dim])
        vd = ((g >= 0) & (g < row)).reshape(
            tuple(arr.shape[dim] if d == dim else 1 for d in range(3))
        )
        valid = vd if valid is None else valid & vd
    return jnp.where(valid, arr, jnp.asarray(bv, arr.dtype))


def window_chain(
    u_w, v_w, params, *, depth, step, origin, row, use_noise, unit_noise,
    boundaries: Sequence[float],
):
    """``depth`` XLA steps on a ghost-inclusive window, shrinking one
    cell per side per stage; returns the (shape - 2*depth) core.

    ``origin`` (int32[3]) is the global coordinate of ``u_w[0, 0, 0]``;
    after each stage, cells outside the global domain are pinned to the
    frozen ``boundaries`` values by :func:`pin_out_of_domain`'s
    global-coordinate masks. Same op order and position-keyed noise
    as every other path — bitwise-exact against the stepwise
    trajectory, so a band it computes can be stitched next to
    kernel-computed cells seamlessly."""
    from ..ops import stencil

    u_bv, v_bv = boundaries
    origin = jnp.asarray(origin, jnp.int32)
    for s in range(depth):
        shape = tuple(d - 2 for d in u_w.shape)
        o = origin + (s + 1)
        if use_noise:
            nzf = params.noise * unit_noise(step + s, o, shape)
        else:
            nzf = jnp.asarray(0.0, u_w.dtype)
        u_w, v_w = stencil.reaction_update(u_w, v_w, nzf, params)
        u_w = pin_out_of_domain(u_w, u_bv, o, row)
        v_w = pin_out_of_domain(v_w, v_bv, o, row)
    return u_w, v_w


def xy_chain(
    u, v, params, *, depth, step, offs, chain_kernel: Callable,
    use_noise, unit_noise, row, axis_names, axis_sizes,
    boundaries: Sequence[float], sublane: int = 8,
):
    """``depth`` fused steps on an (n, m, p) sharded block: in-kernel
    chain across x and y shard boundaries, XLA band correction on
    sharded z sides. See the module docstring for the design.

    ``chain_kernel(u_p, v_p, faces4, step, offs_p)`` runs the fused
    kernel (or its bitwise XLA fallback) at ``fuse=depth`` on the
    y-extended operand; ``unit_noise(step_idx, origin, shape)`` draws
    from the shared position-keyed stream. Must be called inside
    ``shard_map``."""
    nx, ny, nz = u.shape
    dims = axis_sizes
    k = depth
    u_bv, v_bv = boundaries
    z_sharded = dims[2] > 1

    if z_sharded:
        # One corner-propagated k-deep frame serves the kernel operand,
        # its x faces, AND the z-band windows (6 ppermutes total).
        u_w, v_w = halo.halo_pad_wide(
            (u, v), boundaries, axis_names, dims, k
        )
        u_p = u_w[k:k + nx, :, k:k + nz]
        v_p = v_w[k:k + nx, :, k:k + nz]
        faces = (
            u_w[0:k, :, k:k + nz], u_w[k + nx:, :, k:k + nz],
            v_w[0:k, :, k:k + nz], v_w[k + nx:, :, k:k + nz],
        )
    else:
        # Lean 4-ppermute build: k-wide y slabs first, then x slabs of
        # the y-padded fields so the x faces carry y corner data.
        (u_ylo, u_yhi), (v_ylo, v_yhi) = halo.exchange_slabs(
            [u, v], boundaries, 1, axis_names[1], dims[1], k
        )
        u_p = jnp.concatenate([u_ylo, u, u_yhi], axis=1)
        v_p = jnp.concatenate([v_ylo, v, v_yhi], axis=1)
        pairs = halo.exchange_slabs(
            [u_p, v_p], boundaries, 0, axis_names[0], dims[0], k
        )
        faces = (pairs[0][0], pairs[0][1], pairs[1][0], pairs[1][1])

    # Round the y extent up to the sublane tile with boundary-constant
    # filler rows at the high end — Mosaic needs sublane-aligned planes,
    # and extra rows only push the contamination front farther from the
    # interior (they are sliced away with the rest of the pad).
    extra = (-(ny + 2 * k)) % sublane
    if extra:
        def pad_y(a, bv):
            return jnp.pad(
                a, ((0, 0), (0, extra), (0, 0)), constant_values=bv
            )

        u_p, v_p = pad_y(u_p, u_bv), pad_y(v_p, v_bv)
        faces = (pad_y(faces[0], u_bv), pad_y(faces[1], u_bv),
                 pad_y(faces[2], v_bv), pad_y(faces[3], v_bv))

    offs_p = jnp.stack([offs[0], offs[1] - k, offs[2]])
    u_o, v_o = chain_kernel(u_p, v_p, faces, step, offs_p)
    u_o = u_o[:, k:k + ny, :]
    v_o = v_o[:, k:k + ny, :]

    if z_sharded:
        # The kernel ran with frozen z edges: its outermost k z-cells
        # are stale wherever a z neighbor exists (and exactly correct
        # on global z edges). Recompute both k-wide bands from the
        # frame — bitwise the same values, so overwriting
        # unconditionally is correct on edge shards too.
        base = jnp.stack([offs[0] - k, offs[1] - k, offs[2]])
        for z0, dz in ((0, -k), (nz - k, nz - 2 * k)):
            bu, bv_ = window_chain(
                u_w[:, :, z0:z0 + 3 * k], v_w[:, :, z0:z0 + 3 * k],
                params, depth=k, step=step,
                origin=base.at[2].add(dz), row=row,
                use_noise=use_noise, unit_noise=unit_noise,
                boundaries=boundaries,
            )
            u_o = lax.dynamic_update_slice(u_o, bu, (0, 0, z0))
            v_o = lax.dynamic_update_slice(v_o, bv_, (0, 0, z0))
    return u_o, v_o
