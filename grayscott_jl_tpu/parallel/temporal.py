"""Halo-amortized k-deep temporal blocking for the sharded Pallas path.

The fused Pallas kernel (``ops/pallas_stencil.py``) reads interior-shaped
blocks plus 1-thick resolved halo faces — its Mosaic layout needs the
lane dimension to stay 128-aligned, so unlike the XLA language it cannot
consume the shrinking ghost-padded windows the XLA chain uses
(``simulation.py``). A step-at-a-time sharded run therefore pays one
6-``ppermute`` exchange per step. This module cuts that by ``k``: ONE
k-deep ghost exchange feeds ``k`` kernel steps —

1. ``halo.halo_pad_wide`` materializes a depth-k padded frame per field
   (edge/corner ghosts included, via the sequential corner-propagation
   ordering the reference's xy/xz/yz exchange also has,
   ``communication.jl:138-199``);
2. each stage s advances the interior n^3 block with the Pallas kernel,
   its 6 faces sliced from the frame (:func:`_frame_faces`);
3. between stages, the frame's ghost SHELL — O(k * n^2) cells — advances
   one step in XLA (:func:`_advance_frame`): six overlapping stencil
   windows around the shell, reassembled with the kernel's interior into
   a depth-(m-1) frame, out-of-domain ghosts re-frozen
   (:func:`freeze_out_of_domain`). Position-keyed noise (``ops/noise.py``)
   makes the shell's recomputed cells identical to what the owning
   neighbor computed, so the chain reproduces the step-at-a-time
   trajectory exactly.

Per ``k`` steps: one exchange + k kernel HBM passes + O(k^2 n^2) XLA
shell math — vs k exchanges for step-at-a-time. The XLA kernel language
amortizes the same way but without the kernel/shell split (its whole
window shrinks, ``simulation.py``); both reproduce the stepwise
trajectory, noise included.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
from jax import lax

from . import halo


def freeze_out_of_domain(arr, bv, m, axis_names, axis_sizes):
    """Pin the outermost ``m`` ring positions to the frozen boundary
    value where they fall outside the global domain (the reference's
    ``MPI.PROC_NULL`` ghost semantics). Must run inside ``shard_map``."""
    if m == 0:
        return arr
    out = arr
    for dim, (ax, n) in enumerate(zip(axis_names, axis_sizes)):
        idx = lax.axis_index(ax)
        pos = lax.broadcasted_iota(jnp.int32, out.shape, dim)
        lo = (pos < m) & (idx == 0)
        hi = (pos >= out.shape[dim] - m) & (idx == n - 1)
        out = jnp.where(lo | hi, jnp.asarray(bv, out.dtype), out)
    return out


def _frame_faces(u_w, v_w, m, shape):
    """1-thick kernel faces adjacent to the interior block, sliced from
    depth-``m`` padded frames, in ``fused_step``'s face order
    (u_xlo, u_xhi, v_xlo, v_xhi, u_ylo, ..., v_zhi)."""

    def face(w, dim, lo):
        sl = [slice(m, m + s) for s in shape]
        sl[dim] = (
            slice(m - 1, m) if lo else slice(m + shape[dim], m + shape[dim] + 1)
        )
        return w[tuple(sl)]

    return tuple(
        face(w, dim, lo)
        for dim in range(3)
        for w in (u_w, v_w)
        for lo in (True, False)
    )


def _advance_frame(
    u_w, v_w, u_new, v_new, params, *, m, step_idx, offs, use_noise,
    unit_noise, axis_names, axis_sizes, boundaries,
):
    """Advance a depth-``m`` frame one step: the six ghost-shell regions
    in XLA (six overlapping stencil windows), the interior from the
    already-kernel-advanced ``u_new``/``v_new``; returns depth-(m-1)
    frames with out-of-domain ghosts re-frozen."""
    from ..ops import stencil

    nx, ny, nz = u_new.shape
    X, Y, Z = nx + 2 * m, ny + 2 * m, nz + 2 * m
    d = m - 1

    def upd(usl, vsl, origin):
        """One XLA stencil step on a window (returns its interior)."""
        if use_noise:
            shape = tuple(s - 2 for s in usl.shape)
            nzf = params.noise * unit_noise(step_idx, origin, shape)
        else:
            nzf = jnp.asarray(0.0, u_new.dtype)
        return stencil.reaction_update(usl, vsl, nzf, params)

    o = offs

    def go(dx, dy, dz):
        return (o[0] + dx, o[1] + dy, o[2] + dz)

    # x shells span the full frame y/z extent (their outputs carry the
    # new frame's corners); y shells span full z; z shells are core-only.
    xl_u, xl_v = upd(u_w[0:m + 1], v_w[0:m + 1], go(-d, -d, -d))
    xh_u, xh_v = upd(u_w[X - m - 1:], v_w[X - m - 1:], go(nx, -d, -d))
    xsl = slice(m - 1, m + nx + 1)
    yl_u, yl_v = upd(u_w[xsl, 0:m + 1], v_w[xsl, 0:m + 1], go(0, -d, -d))
    yh_u, yh_v = upd(u_w[xsl, Y - m - 1:], v_w[xsl, Y - m - 1:], go(0, ny, -d))
    ysl = slice(m - 1, m + ny + 1)
    zl_u, zl_v = upd(
        u_w[xsl, ysl, 0:m + 1], v_w[xsl, ysl, 0:m + 1], go(0, 0, -d)
    )
    zh_u, zh_v = upd(
        u_w[xsl, ysl, Z - m - 1:], v_w[xsl, ysl, Z - m - 1:], go(0, 0, nz)
    )

    def assemble(zl, core, zh, yl, yh, xl, xh):
        inner = jnp.concatenate([zl, core, zh], axis=2)
        mid = jnp.concatenate([yl, inner, yh], axis=1)
        return jnp.concatenate([xl, mid, xh], axis=0)

    u_bv, v_bv = boundaries
    u_out = assemble(zl_u, u_new, zh_u, yl_u, yh_u, xl_u, xh_u)
    v_out = assemble(zl_v, v_new, zh_v, yl_v, yh_v, xl_v, xh_v)
    u_out = freeze_out_of_domain(u_out, u_bv, d, axis_names, axis_sizes)
    v_out = freeze_out_of_domain(v_out, v_bv, d, axis_names, axis_sizes)
    return u_out, v_out


def pallas_chain(
    u, v, params, *, depth, step, offs, use_noise, unit_noise,
    kernel_step, axis_names, axis_sizes,
    boundaries: Sequence[float],
):
    """``depth`` sharded Pallas kernel steps from ONE depth-wide halo
    exchange; see module docstring. ``kernel_step(u, v, step_idx, faces)``
    runs the fused kernel on an interior block; ``unit_noise(step_idx,
    origin, shape)`` draws from the shared position-keyed stream. Must be
    called inside ``shard_map``."""
    if depth == 1:
        faces = halo.exchange_faces(
            (u, v), boundaries, axis_names, axis_sizes
        )
        return kernel_step(u, v, step, faces)

    u_w, v_w = halo.halo_pad_wide(
        (u, v), boundaries, axis_names, axis_sizes, depth
    )
    shape = u.shape
    for s in range(depth):
        m = depth - s
        faces = _frame_faces(u_w, v_w, m, shape)
        u, v = kernel_step(u, v, step + s, faces)
        if m > 1:
            u_w, v_w = _advance_frame(
                u_w, v_w, u, v, params, m=m, step_idx=step + s, offs=offs,
                use_noise=use_noise, unit_noise=unit_noise,
                axis_names=axis_names, axis_sizes=axis_sizes,
                boundaries=boundaries,
            )
    return u, v
