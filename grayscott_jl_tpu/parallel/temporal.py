"""Cross-shard temporal blocking for the sharded Pallas path.

The fused Pallas kernel (``ops/pallas_stencil.py``) chains ``k``
timesteps per HBM pass by walking shrinking windows along its leading
(x) axis. Crossing shard boundaries with that chain needs k-deep halo
data on every sharded axis — and what that costs depends on which
*Mosaic tiling dimension* the axis lands on:

* **x** (untiled leading dim): free — the x-chain mode consumes k-wide
  exchanged x slabs directly (round 3);
* **y** (sublane dim, 8/16-granularity): cheap — :func:`xy_chain`
  extends the operand by a k-deep exchanged y halo (rounded up to the
  sublane tile with boundary-constant filler rows) and the kernel's
  mid-stage global-coordinate pinning makes in-domain pad rows
  ring-recompute the y neighbor's values, so the in-kernel chain
  crosses y shard boundaries at a few percent of plane-area overhead;
* **z** (128-lane dim): expensive — a ±k z pad would round the lane
  extent up to the next 128 multiple (up to ~50% wasted vector work),
  so z shard boundaries are instead handled OUTSIDE the kernel:
  the kernel runs with frozen z edges, contaminating the outermost k
  z-cells per sharded z side (one cell per stage), and
  :func:`window_chain` recomputes those k-wide bands in XLA from a
  corner-propagated k-deep frame (``halo.halo_pad_wide``) — O(k * n^2)
  cells per side per round against the kernel's O(n^3).

Per ``k`` steps: ONE exchange round (4 ppermutes for an (n, m, 1)
mesh, 6 with z sharded — the per-step cost the reference pays in
``communication.jl:138-199``), one fused k-deep kernel pass, and — only
when z is sharded — two thin XLA band chains. Everything reproduces the
step-at-a-time trajectory exactly (position-keyed noise,
``ops/noise.py``), which the CPU-mesh bitwise tests assert.

This supersedes the round-3 design (single-step kernel launches with an
XLA-advanced ghost shell), which paid a measured 1.46x per-stage
penalty because in-kernel fusion stopped at every shard boundary.

Communication-avoiding s-step exchange (``halo_depth``, round 9,
docs/TEMPORAL.md): the XLA chain path generalizes the same machinery
into exchanging once per ``halo_depth`` chain rounds — a
(chain_depth x halo_depth)-deep corner-propagated frame
(``halo.halo_pad_wide``) feeds one :func:`window_chain` whose valid
region shrinks one cell per side per step until the next exchange
restores full width. Because :func:`window_chain` shrinks uniformly,
composing ``k`` depth-``d`` segments on the shared frame is the SAME
program as one depth-``k*d`` chain — the realization ``simulation.py``
uses — so ``halo_depth=k`` at chain depth ``d`` is bitwise identical
to ``halo_depth=1`` at chain depth ``k*d``, and the split-phase form
(:func:`stitch_bands_from_frame` after an interior pass on a frozen
frame) composes with it unchanged: the deeper transfer hides behind
proportionally more interior steps.
"""

from __future__ import annotations

from typing import Callable, Sequence, Tuple

import jax.numpy as jnp
from jax import lax

from . import halo


def pin_out_of_domain(arr, bv, origin, row):
    """Pin every cell whose GLOBAL coordinate falls outside ``[0, row)``
    on any axis to the frozen boundary value (the reference's
    ``MPI.PROC_NULL`` ghost semantics); ``origin`` (int32[3]) is the
    global coordinate of ``arr[0, 0, 0]``.

    Works on any offset sub-box of a shard, and — unlike a mesh-edge
    ring mask — also pins **pad cells inside the block** (non-divisible
    L stores a padded grid, ``parallel/domain.py``)."""
    origin = jnp.asarray(origin, jnp.int32)
    valid = None
    for dim in range(3):
        g = origin[dim] + jnp.arange(arr.shape[dim])
        vd = ((g >= 0) & (g < row)).reshape(
            tuple(arr.shape[dim] if d == dim else 1 for d in range(3))
        )
        valid = vd if valid is None else valid & vd
    return jnp.where(valid, arr, jnp.asarray(bv, arr.dtype))


def window_chain(
    fields_w, params, model, *, depth, step, origin, row, use_noise,
    unit_noise, boundaries: Sequence[float], final_pin: bool = True,
    compute_dtype=None,
):
    """``depth`` XLA steps on ghost-inclusive field windows, shrinking
    one cell per side per stage; returns the (shape - 2*depth) cores.

    Model-generic: ``fields_w`` is the model's field tuple in
    declaration order and the update is the shared n-field
    ``stencil.reaction_update`` with ``model``'s reaction.

    ``origin`` (int32[3]) is the global coordinate of each window's
    ``[0, 0, 0]``; after each stage, cells outside the global domain are
    pinned to the frozen per-field ``boundaries`` values by
    :func:`pin_out_of_domain`'s global-coordinate masks. Same op order
    and position-keyed noise as every other path — bitwise-exact
    against the stepwise trajectory, so a band it computes can be
    stitched next to kernel-computed cells seamlessly.

    ``final_pin=False`` skips the last stage's pin masks — legal only
    when the caller knows every output cell is in-domain (a divisible-L
    block-shaped result), where the pin is a provably-all-true mask.
    Mid-stage pins always run: the shrinking ring reads them back.

    ``compute_dtype`` widens each stage's accumulation (the
    ``bf16_f32acc`` posture, docs/PRECISION.md): fields stay in the
    storage dtype between stages — so the exchanged frame and the
    per-stage rounding match the stepwise path exactly — and each
    stage upcasts, accumulates, and rounds back inside
    ``stencil.reaction_update``."""
    from ..ops import stencil

    fields_w = tuple(fields_w)
    origin = jnp.asarray(origin, jnp.int32)
    for s in range(depth):
        shape = tuple(d - 2 for d in fields_w[0].shape)
        o = origin + (s + 1)
        if use_noise:
            nzf = params.noise * unit_noise(step + s, o, shape)
        else:
            nzf = jnp.asarray(0.0, fields_w[0].dtype)
        fields_w = stencil.reaction_update(
            fields_w, nzf, params, model, compute_dtype=compute_dtype
        )
        if s + 1 < depth or final_pin:
            fields_w = tuple(
                pin_out_of_domain(f, bv, o, row)
                for f, bv in zip(fields_w, boundaries)
            )
    return fields_w


def stitch_bands_from_frame(
    fields_i, fields_w, params, model, *, depth, step, offs, row,
    axis_sizes, use_noise, unit_noise, boundaries: Sequence[float],
    dims_to_stitch: Sequence[int] = (0, 1, 2), compute_dtype=None,
):
    """Overwrite the ``depth``-thick boundary bands of block-shaped
    results with :func:`window_chain` recomputes from the exchanged
    corner-propagated frames ``fields_w`` (``halo.halo_pad_wide``
    width ``depth``). Model-generic over the field tuple.

    The split-phase stitch: ``fields_i`` came from an interior pass
    that saw frozen-constant ghosts, so every cell within ``depth``
    cells of a sharded face is contaminated (one cell per stage). Each
    such band is recomputed from a 3k-deep frame window spanning the
    FULL frame extent on the other axes — so corner cells land in two
    (or three) bands, each recomputing bitwise-identical values from
    the same frame, and sequential overwrites are safe. Axes with a
    single shard (or excluded via ``dims_to_stitch``) are skipped:
    their frozen ghosts were already the truth.

    ``offs`` (int32[3]) is the block's global origin. Must be called
    inside ``shard_map``.
    """
    k = depth
    fields_i = tuple(fields_i)
    fields_w = tuple(fields_w)
    offs = jnp.asarray(offs, jnp.int32)
    base = offs - k  # global origin of the frame
    for dim in range(3):
        if axis_sizes[dim] == 1 or dim not in dims_to_stitch:
            continue
        n_d = fields_i[0].shape[dim]
        m = fields_w[0].shape[dim]  # n_d + 2k
        for d0, w0 in ((0, 0), (n_d - k, m - 3 * k)):
            sl = [slice(None)] * 3
            sl[dim] = slice(w0, w0 + 3 * k)
            sl = tuple(sl)
            bands = window_chain(
                tuple(f[sl] for f in fields_w), params, model,
                depth=k, step=step,
                origin=base.at[dim].add(w0), row=row,
                use_noise=use_noise, unit_noise=unit_noise,
                boundaries=boundaries, compute_dtype=compute_dtype,
            )
            pos = [0, 0, 0]
            pos[dim] = d0
            fields_i = tuple(
                lax.dynamic_update_slice(fi, b, tuple(pos))
                for fi, b in zip(fields_i, bands)
            )
    return fields_i


def xy_overlap_feasible(local, dims, depth) -> bool:
    """Whether the split-phase form of :func:`xy_chain` applies at this
    geometry. The z-sharded (frame) form always does — its bands come
    from one corner-propagated frame and may overlap-write identical
    values. The slab form (p == 1) builds band windows from 2k-deep
    owned slices, so every sharded slab axis must be >= 2k deep (a
    shallower block has no comm-independent interior anyway)."""
    if dims[2] > 1:
        return True
    k = depth
    return not ((dims[0] > 1 and local[0] < 2 * k) or local[1] < 2 * k)


def xy_chain(
    fields, params, model, *, depth, step, offs, chain_kernel: Callable,
    use_noise, unit_noise, row, axis_names, axis_sizes,
    boundaries: Sequence[float], sublane: int = 8,
    overlap: bool = False, band_kernel: Callable = None,
):
    """``depth`` fused steps on an (n, m, p) sharded block: in-kernel
    chain across x and y shard boundaries, XLA band correction on
    sharded z sides. See the module docstring for the design.
    Model-generic: ``fields`` is the model's field tuple in declaration
    order, and every faces tuple is field-major (lo, hi) pairs — the
    generated kernel's x-chain operand order
    (``ops/pallas_stencil.fused_step``). The s-step exchange schedule
    (``halo_depth=k``, docs/TEMPORAL.md) reuses this round unchanged at
    ``depth = fuse*k`` — one k-times-deeper ``halo_pad_wide`` frame per
    round, the same 6 (z-sharded) or 4 collectives, amortized over k
    times the steps.

    ``chain_kernel(fields_p, faces, step, offs_p)`` runs the fused
    kernel (or its bitwise XLA fallback) at ``fuse=depth`` on the
    y-extended operand tuple; ``unit_noise(step_idx, origin, shape)``
    draws from the shared position-keyed stream. Must be called inside
    ``shard_map``.

    ``overlap=True`` is the split-phase form (docs/OVERLAP.md): the
    SAME exchange is issued first, but the kernel consumes frozen
    boundary constants instead — so it has no data dependency on the
    ppermutes and XLA can hide the ICI transfer under it — and the
    exchanged slabs/frame feed only the k-thick x/y (and z) boundary
    bands recomputed afterwards and stitched in. x/y bands run
    ``band_kernel`` — the x-chain XLA reference program
    (``pallas_stencil._xla_xchain_fallback``) on a thin body — NOT a
    different chain formulation: structural identity with the fused
    kernel's own fallback is what keeps the recomputed band bitwise
    equal under XLA's shape-sensitive codegen (FMA contraction). z
    bands keep the fused path's :func:`window_chain` recompute, which
    is identical in both modes. Slab-mode (p == 1) overlap needs every
    sharded slab axis to be at least 2k deep (otherwise there is no
    interior to hide behind); shallower blocks silently take the fused
    round, which is bitwise identical anyway.
    """
    fields = tuple(fields)
    bvs = tuple(boundaries)
    nx, ny, nz = fields[0].shape
    dtype = fields[0].dtype
    dims = axis_sizes
    k = depth
    z_sharded = dims[2] > 1
    if overlap and not xy_overlap_feasible(fields[0].shape, dims, k):
        overlap = False  # no comm-independent interior: fused round
    if overlap and band_kernel is None:
        raise ValueError("xy_chain overlap=True requires band_kernel")

    # (body_fields, faces, offsets, out_row_slice, position) jobs for
    # the split-phase x/y band recompute, built beside the exchange.
    band_jobs = []

    def const_faces(shape_nyz):
        return tuple(
            jnp.full((k,) + shape_nyz, bv, dtype)
            for bv in bvs for _ in (0, 1)
        )

    def interleave(los, his):
        """Field-major (lo, hi) faces tuple from per-field slabs."""
        return tuple(x for pair in zip(los, his) for x in pair)

    if z_sharded:
        # One corner-propagated k-deep frame serves the kernel operand,
        # its x faces, AND the band windows (6 ppermutes total).
        fields_w = halo.halo_pad_wide(
            fields, bvs, axis_names, dims, k
        )
        if overlap:
            # Split phase: the kernel sees frozen constants everywhere,
            # so the frame has NO consumer on the kernel's dataflow
            # path; bands for every sharded axis are stitched after.
            fields_p = tuple(
                jnp.pad(f, ((0, 0), (k, k), (0, 0)), constant_values=bv)
                for f, bv in zip(fields, bvs)
            )
            faces = const_faces((ny + 2 * k, nz))
            m_y = ny + 2 * k

            def fr(x0, x1, ys):
                """Frame windows of the fields at frame x range
                [x0, x1) and y range ``ys``, z clipped to the owned
                planes."""
                return tuple(w[x0:x1, ys, k:k + nz] for w in fields_w)

            if dims[1] > 1:
                # y bands: body rows are the frame's [arrived y slab |
                # 2k owned rows]; x faces are the frame's x ghosts
                # clipped to the same rows (corner-propagated, so the
                # band's x corners carry real neighbor data exactly as
                # the fused kernel's do).
                for ys, o_y, d_y in (
                    (slice(0, 3 * k), -k, 0),
                    (slice(m_y - 3 * k, m_y), ny - 2 * k, ny - k),
                ):
                    band_jobs.append((
                        fr(k, k + nx, ys),
                        interleave(fr(0, k, ys),
                                   fr(k + nx, nx + 2 * k, ys)),
                        jnp.stack([offs[0], offs[1] + o_y, offs[2]]),
                        slice(k, 2 * k), (0, d_y, 0),
                    ))
            if dims[0] > 1:
                # x bands: a k-plane body whose x faces come from the
                # frame — the arrived x ghost on the outside, adjacent
                # owned planes on the inside; full frame y extent.
                ally = slice(None)
                for xs, fl, fh, o_x, d_x in (
                    (slice(k, 2 * k), slice(0, k), slice(2 * k, 3 * k),
                     0, 0),
                    (slice(nx, k + nx), slice(nx - k, nx),
                     slice(k + nx, nx + 2 * k), nx - k, nx - k),
                ):
                    band_jobs.append((
                        fr(xs.start, xs.stop, ally),
                        interleave(fr(fl.start, fl.stop, ally),
                                   fr(fh.start, fh.stop, ally)),
                        jnp.stack([offs[0] + o_x, offs[1] - k,
                                   offs[2]]),
                        slice(k, k + ny), (d_x, 0, 0),
                    ))
        else:
            fields_p = tuple(w[k:k + nx, :, k:k + nz] for w in fields_w)
            faces = interleave(
                tuple(w[0:k, :, k:k + nz] for w in fields_w),
                tuple(w[k + nx:, :, k:k + nz] for w in fields_w),
            )
    else:
        # Lean 4-ppermute build: k-wide y slabs first, then x slabs of
        # the y-padded fields so the x faces carry y corner data.
        y_pairs = halo.exchange_slabs(
            list(fields), bvs, 1, axis_names[1], dims[1], k
        )
        fields_pr = tuple(
            jnp.concatenate([lo, f, hi], axis=1)
            for f, (lo, hi) in zip(fields, y_pairs)
        )
        x_pairs = halo.exchange_slabs(
            list(fields_pr), bvs, 0, axis_names[0], dims[0], k
        )
        if overlap:
            fields_p = tuple(
                jnp.pad(f, ((0, 0), (k, k), (0, 0)), constant_values=bv)
                for f, bv in zip(fields, bvs)
            )
            faces = const_faces((ny + 2 * k, nz))
            m_y = ny + 2 * k
            if dims[1] > 1:
                # y bands: body rows are [arrived y slab | 2k owned
                # rows] of the y-padded fields; the x faces are the
                # arrived x slabs clipped to the same rows, so the
                # band's x corners carry real neighbor data exactly as
                # the fused kernel's do.
                for ys, o_y, d_y in (
                    (slice(0, 3 * k), -k, 0),
                    (slice(m_y - 3 * k, m_y), ny - 2 * k, ny - k),
                ):
                    band_jobs.append((
                        tuple(f[:, ys, :] for f in fields_pr),
                        interleave(
                            tuple(lo[:, ys, :] for lo, _ in x_pairs),
                            tuple(hi[:, ys, :] for _, hi in x_pairs),
                        ),
                        jnp.stack([offs[0], offs[1] + o_y, offs[2]]),
                        slice(k, 2 * k), (0, d_y, 0),
                    ))
            if dims[0] > 1:
                # x bands: a k-plane body whose x faces are the arrived
                # slab and the adjacent owned planes (both y-padded).
                for body, faces_b, o_x, d_x in (
                    (tuple(f[:k] for f in fields_pr),
                     interleave(
                         tuple(lo for lo, _ in x_pairs),
                         tuple(f[k:2 * k] for f in fields_pr),
                     ),
                     0, 0),
                    (tuple(f[nx - k:] for f in fields_pr),
                     interleave(
                         tuple(f[nx - 2 * k:nx - k] for f in fields_pr),
                         tuple(hi for _, hi in x_pairs),
                     ),
                     nx - k, nx - k),
                ):
                    band_jobs.append((
                        body, faces_b,
                        jnp.stack([offs[0] + o_x, offs[1] - k,
                                   offs[2]]),
                        slice(k, k + ny), (d_x, 0, 0),
                    ))
        else:
            fields_p = fields_pr
            faces = interleave(
                tuple(lo for lo, _ in x_pairs),
                tuple(hi for _, hi in x_pairs),
            )

    # Round the y extent up to the sublane tile with boundary-constant
    # filler rows at the high end — Mosaic needs sublane-aligned planes,
    # and extra rows only push the contamination front farther from the
    # interior (they are sliced away with the rest of the pad).
    extra = (-(ny + 2 * k)) % sublane
    if extra:
        def pad_y(a, bv):
            return jnp.pad(
                a, ((0, 0), (0, extra), (0, 0)), constant_values=bv
            )

        fields_p = tuple(
            pad_y(f, bv) for f, bv in zip(fields_p, bvs)
        )
        faces = tuple(
            pad_y(fc, bvs[i // 2]) for i, fc in enumerate(faces)
        )

    offs_p = jnp.stack([offs[0], offs[1] - k, offs[2]])
    out = chain_kernel(fields_p, faces, step, offs_p)
    out = tuple(f[:, k:k + ny, :] for f in out)

    # Split-phase x/y bands first (they reproduce the fused kernel's
    # values, including each other's corners), then the z bands, which
    # overwrite the z shell in BOTH modes with identical values.
    for body, faces_b, offs_b, out_rows, pos in band_jobs:
        band = band_kernel(body, faces_b, step, offs_b)
        out = tuple(
            lax.dynamic_update_slice(o, b[:, out_rows, :], pos)
            for o, b in zip(out, band)
        )

    if z_sharded:
        # The kernel ran with frozen z edges: its outermost k z-cells
        # are stale wherever a z neighbor exists (and exactly correct
        # on global z edges). Recompute both k-wide bands from the
        # frame — bitwise the same values, so overwriting
        # unconditionally is correct on edge shards too.
        out = stitch_bands_from_frame(
            out, fields_w, params, model, depth=k,
            step=step, offs=offs, row=row, axis_sizes=dims,
            use_noise=use_noise, unit_noise=unit_noise,
            boundaries=bvs, dims_to_stitch=(2,),
        )
    return out
