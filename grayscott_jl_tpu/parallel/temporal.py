"""Halo-amortized temporal pairing for the sharded Pallas path.

The fused Pallas kernel (``ops/pallas_stencil.py``) reads interior-shaped
blocks plus 1-thick resolved halo faces; a sharded run therefore pays one
6-``ppermute`` exchange per step. This module halves that: ONE 2-deep
ghost exchange feeds TWO kernel steps —

1. :func:`exchange_wide_faces` delivers 2-deep ghost slabs (with the
   edge/corner data deep stencils need, via the sequential
   axis-by-axis corner-propagation ordering) **without materializing a
   padded block** — slab-level concats only, so the kernel keeps its
   no-ghost-pad HBM layout;
2. step n+1 runs the kernel with the inner ghost planes as faces;
3. :func:`ring_faces` recomputes, *locally and in XLA*, the 1-plane ring
   of step-(n+1) values owned by each neighbor — O(n^2) work on slab
   windows assembled from the wide ghosts. Position-keyed noise
   (``ops/noise.py``) makes the recomputed values identical to what the
   neighbor computed;
4. step n+2 runs the kernel with that ring as its faces.

Per two steps: one exchange + two kernel HBM passes + O(n^2) ring math,
vs two exchanges + two passes for step-at-a-time — the amortization the
reference pays for with ``exchange!`` every step
(``communication.jl:138-199``). The XLA kernel language amortizes
differently (extended-window recompute on a width-2 padded block,
``simulation.py``); both reproduce the step-at-a-time trajectory.

Ghost slab shapes for an (nx, ny, nz) block (2-deep, corner-propagated):
x: (2, ny, nz); y: (nx+4, 2, nz) — x-extended; z: (nx+4, ny+4, 2) —
x- and y-extended. Global-edge slabs hold the frozen boundary value.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
from jax import lax


def exchange_wide_faces(
    arrays: Sequence[jnp.ndarray],
    boundary_values: Sequence[float],
    axis_names: Tuple[str, str, str],
    axis_sizes: Tuple[int, int, int],
):
    """2-deep ghost slabs for each array; see module docstring.

    Returns, per array, ``((x_lo, x_hi), (y_lo, y_hi), (z_lo, z_hi))``.
    Must be called inside ``shard_map``.
    """
    arrays = list(arrays)
    n_arr = len(arrays)
    ghosts = [[] for _ in arrays]

    def ext_slab(i, dim, lo_take):
        """Width-2 boundary slab of array ``i`` along ``dim``, extended
        with the already-received ghosts of axes < dim (that inclusion
        is what propagates edge/corner data)."""

        def slab(x):
            sl = [slice(None)] * 3
            sl[dim] = slice(0, 2) if lo_take else slice(-2, None)
            return x[tuple(sl)]

        core = slab(arrays[i])
        for d2 in range(dim):
            lo2, hi2 = ghosts[i][d2]
            core = jnp.concatenate([slab(lo2), core, slab(hi2)], axis=d2)
        return core

    for dim, (ax, n) in enumerate(zip(axis_names, axis_sizes)):
        sends_up = [ext_slab(i, dim, lo_take=False) for i in range(n_arr)]
        sends_dn = [ext_slab(i, dim, lo_take=True) for i in range(n_arr)]
        if n == 1:
            for i, bv in enumerate(boundary_values):
                bvt = jnp.asarray(bv, arrays[i].dtype)
                shape = sends_up[i].shape
                f = jnp.full(shape, bvt)
                ghosts[i].append((f, f))
            continue
        idx = lax.axis_index(ax)
        up_perm = [(r, r + 1) for r in range(n - 1)]
        dn_perm = [(r + 1, r) for r in range(n - 1)]
        recv_lo = lax.ppermute(
            jnp.concatenate(sends_up, axis=dim), ax, up_perm
        )
        recv_hi = lax.ppermute(
            jnp.concatenate(sends_dn, axis=dim), ax, dn_perm
        )
        lo_slabs = jnp.split(recv_lo, n_arr, axis=dim)
        hi_slabs = jnp.split(recv_hi, n_arr, axis=dim)
        for i, bv in enumerate(boundary_values):
            bvt = jnp.asarray(bv, arrays[i].dtype)
            lo = jnp.where(idx > 0, lo_slabs[i], bvt)
            hi = jnp.where(idx < n - 1, hi_slabs[i], bvt)
            ghosts[i].append((lo, hi))

    return ghosts


def inner_faces(gu, gv):
    """The 1-thick resolved faces for the FIRST kernel step, sliced from
    the wide ghosts — the plane adjacent to the block (x=-1 is index 1 of
    the 2-deep lo slab; x=nx is index 0 of the hi slab). Order matches
    ``ops/pallas_stencil.fused_step``."""
    (uxl, uxh), (uyl, uyh), (uzl, uzh) = gu
    (vxl, vxh), (vyl, vyh), (vzl, vzh) = gv
    return (
        uxl[1:2], uxh[0:1], vxl[1:2], vxh[0:1],
        uyl[2:-2, 1:2, :], uyh[2:-2, 0:1, :],
        vyl[2:-2, 1:2, :], vyh[2:-2, 0:1, :],
        uzl[2:-2, 2:-2, 1:2], uzh[2:-2, 2:-2, 0:1],
        vzl[2:-2, 2:-2, 1:2], vzh[2:-2, 2:-2, 0:1],
    )


def _windows(a, g, ny, nz, nx):
    """Per-direction stencil windows around the block's six ghost ring
    planes, assembled from block ``a`` and its wide ghosts ``g``.

    Index maps (x-lo as the worked example; the rest are mirrors):
    the ring plane x=-1 needs inputs x∈{-2,-1,0}, y∈[-1,ny+1),
    z∈[-1,nz+1). x∈{-2,-1} comes from the x-lo slab, x=0 from the block;
    the y borders at those x come from the y slabs (x-extended: global
    x=-2 is index 0), the z borders from the z slabs (x- and
    y-extended: global x=-2 index 0, global y=-1 index 1).
    """
    (x_lo, x_hi), (y_lo, y_hi), (z_lo, z_hi) = g
    cat = jnp.concatenate

    def xdir(core, xsl):
        w = cat([y_lo[xsl, 1:2, :], core, y_hi[xsl, 0:1, :]], axis=1)
        return cat(
            [z_lo[xsl, 1:ny + 3, 1:2], w, z_hi[xsl, 1:ny + 3, 0:1]],
            axis=2,
        )

    def ydir(core, ysl_lo, ysl_hi, xb_lo, xb_hi):
        w = cat([xb_lo, core, xb_hi], axis=0)
        return cat(
            [z_lo[1:nx + 3, ysl_lo, 1:2], w, z_hi[1:nx + 3, ysl_hi, 0:1]],
            axis=2,
        )

    return {
        "x_lo": xdir(cat([x_lo, a[0:1]], axis=0), slice(0, 3)),
        "x_hi": xdir(cat([a[-1:], x_hi], axis=0), slice(-3, None)),
        "y_lo": ydir(
            cat([y_lo[2:-2], a[:, 0:1]], axis=1),
            slice(0, 3), slice(0, 3),
            cat([y_lo[1:2], x_lo[1:2, 0:1, :]], axis=1),
            cat([y_lo[-2:-1], x_hi[0:1, 0:1, :]], axis=1),
        ),
        "y_hi": ydir(
            cat([a[:, -1:], y_hi[2:-2]], axis=1),
            slice(-3, None), slice(-3, None),
            cat([x_lo[1:2, -1:, :], y_hi[1:2]], axis=1),
            cat([x_hi[0:1, -1:, :], y_hi[-2:-1]], axis=1),
        ),
        "z_lo": cat(
            [
                cat(
                    [z_lo[1:nx + 3, 1:2, :],
                     y_lo[1:nx + 3, 1:2, 0:1]], axis=2
                ),
                cat(
                    [
                        cat([z_lo[1:2, 2:-2, :],
                             x_lo[1:2, :, 0:1]], axis=2),
                        cat([z_lo[2:-2, 2:-2, :], a[:, :, 0:1]], axis=2),
                        cat([z_lo[-2:-1, 2:-2, :],
                             x_hi[0:1, :, 0:1]], axis=2),
                    ],
                    axis=0,
                ),
                cat(
                    [z_lo[1:nx + 3, -2:-1, :],
                     y_hi[1:nx + 3, 0:1, 0:1]], axis=2
                ),
            ],
            axis=1,
        ),
        "z_hi": cat(
            [
                cat(
                    [y_lo[1:nx + 3, 1:2, -1:],
                     z_hi[1:nx + 3, 1:2, :]], axis=2
                ),
                cat(
                    [
                        cat([x_lo[1:2, :, -1:],
                             z_hi[1:2, 2:-2, :]], axis=2),
                        cat([a[:, :, -1:], z_hi[2:-2, 2:-2, :]], axis=2),
                        cat([x_hi[0:1, :, -1:],
                             z_hi[-2:-1, 2:-2, :]], axis=2),
                    ],
                    axis=0,
                ),
                cat(
                    [y_hi[1:nx + 3, 0:1, -1:],
                     z_hi[1:nx + 3, -2:-1, :]], axis=2
                ),
            ],
            axis=1,
        ),
    }


def ring_faces(
    u, v, gu, gv, params, *, step, offs, L, use_noise, unit_noise,
    axis_names, axis_sizes, boundaries,
):
    """Step-(n+1) values on the six neighbor-adjacent ring planes,
    recomputed locally from the wide ghosts — the faces for the SECOND
    kernel step. On a global edge the ring is the frozen boundary value.

    ``unit_noise(step, offsets, shape)`` must draw from the same
    position-keyed stream as the kernel; that is what makes the local
    recomputation reproduce the neighbor's computation exactly.
    """
    from ..ops import stencil

    nx, ny, nz = u.shape
    wu = _windows(u, gu, ny, nz, nx)
    wv = _windows(v, gv, ny, nz, nx)
    u_bv, v_bv = boundaries

    ring_offsets = {
        "x_lo": (offs[0] - 1, offs[1], offs[2]),
        "x_hi": (offs[0] + nx, offs[1], offs[2]),
        "y_lo": (offs[0], offs[1] - 1, offs[2]),
        "y_hi": (offs[0], offs[1] + ny, offs[2]),
        "z_lo": (offs[0], offs[1], offs[2] - 1),
        "z_hi": (offs[0], offs[1], offs[2] + nz),
    }
    has_nbr = {
        "x_lo": lax.axis_index(axis_names[0]) > 0,
        "x_hi": lax.axis_index(axis_names[0]) < axis_sizes[0] - 1,
        "y_lo": lax.axis_index(axis_names[1]) > 0,
        "y_hi": lax.axis_index(axis_names[1]) < axis_sizes[1] - 1,
        "z_lo": lax.axis_index(axis_names[2]) > 0,
        "z_hi": lax.axis_index(axis_names[2]) < axis_sizes[2] - 1,
    }

    rings = {}
    for d in ("x_lo", "x_hi", "y_lo", "y_hi", "z_lo", "z_hi"):
        shape = tuple(s - 2 for s in wu[d].shape)
        if use_noise:
            nz_ring = params.noise * unit_noise(step, ring_offsets[d], shape)
        else:
            nz_ring = jnp.asarray(0.0, u.dtype)
        ru, rv = stencil.reaction_update(wu[d], wv[d], nz_ring, params)
        rings[d] = (
            jnp.where(has_nbr[d], ru, jnp.asarray(u_bv, u.dtype)),
            jnp.where(has_nbr[d], rv, jnp.asarray(v_bv, v.dtype)),
        )

    return (
        rings["x_lo"][0], rings["x_hi"][0],
        rings["x_lo"][1], rings["x_hi"][1],
        rings["y_lo"][0], rings["y_hi"][0],
        rings["y_lo"][1], rings["y_hi"][1],
        rings["z_lo"][0], rings["z_hi"][0],
        rings["z_lo"][1], rings["z_hi"][1],
    )
