"""Analytic ICI weak-scaling model + Auto kernel-language selection.

The cost model that used to live in ``benchmarks/ici_model.py`` (which
now imports from here — the CLI front-end keeps its interface), promoted
into the package so the framework can consult it at construction time:
``kernel_language = "Auto"`` resolves to the kernel the model projects
to be the right one for the actual (mesh, L, dtype, fabric) of the run
(VERDICT r4 item 3 — previously the XLA-vs-Pallas choice at pod scale
was operator knowledge buried in pod scripts).

Model (per step, per device): compute time from measured single-chip
µs/step anchors (``MEASURED_US``, BASELINE.md v5e table), halo bytes
from the face geometry of the chain mode, communication serialized at
the max-loaded ICI link plus hop latency, efficiency = compute /
(compute + exposed comm). Every assumption is stated and overridable;
the fabric parameters are public per-generation figures (v5p ~90 GB/s
per link per direction, ~1 µs hop; v5e ~45 GB/s, 2D torus).

The reference has no equivalent: its kernel choice (communication.jl /
CUDAExt.jl) is fixed per build, and its MPI halo exchange pays full
per-step cost at every scale.
"""

from __future__ import annotations

from typing import Optional

#: Single-chip fused-kernel cost at fuse=k relative to the k=5 optimum,
#: measured round-robin in one process at L=256 f32 noisy (k=1:
#: ab_r3_fuse1v5; k=4,5,6: ab_r3_deepfuse medians). k=2,3 are a+b/k
#: interpolations through the k=1 and k=4 anchors — marked so in the
#: emitted rows. ``benchmarks/update_fuse_ratio.py --apply`` rewrites
#: this literal from a measured artifact.
FUSE_COST_RATIO = {1: 1493.1 / 1023.9, 2: 1.174, 3: 1.079,
                   4: 1077.0 / 1044.0, 5: 1.0, 6: 1069.3 / 1044.0}

#: Measured single-chip f32 noisy µs/step by (kernel language, local
#: side) — BASELINE.md v5e table, fast-window best-of; the throttled
#: state scales compute and comm denominators together, so efficiency
#: is roughly state-invariant. The Pallas numbers are the FUSED
#: (in-kernel k=4/5) single-chip path — the honest baseline a 1-chip
#: user gets; its sharded stages pay STAGE_RATIO on top (see project).
MEASURED_US = {
    ("Pallas", 128): 396.0,
    ("Pallas", 256): 727.6,
    ("Pallas", 512): 3618.2,
    ("XLA", 128): 738.7,
    ("XLA", 256): 1828.3,
    ("XLA", 512): 16073.1,
}

#: Sharded per-stage cost over the fused single-chip step for the
#: Pallas language: fuse=1 vs fuse=5 measured round-robin in ONE
#: process (benchmarks/results/ab_r3_fuse1v5_2026-07-30.jsonl:
#: 1493.1 vs 1023.9 us/step best, medians agree). The XLA language is
#: stepwise on a single chip too, so its ratio is 1.0 by construction.
STAGE_RATIO = {"Pallas": FUSE_COST_RATIO[1], "XLA": 1.0}

#: Fraction of the *ideally hideable* communication the split-phase
#: exchange (GS_COMM_OVERLAP, docs/OVERLAP.md) actually hides behind
#: interior compute: realized_overlap = OVERLAP_EFFICIENCY *
#: min(1, interior_compute / comm). The ideal bound comes from dataflow
#: (comm can hide only under compute that does not consume it); the
#: efficiency discounts scheduler imperfection — async collective-
#: permute issue latency, band-stitch cost, LHS scheduling slack — and
#: is the default until ``benchmarks/update_overlap.py --apply``
#: rewrites this literal from a measured ``halo_bench.py --ab``
#: artifact (the same calibration loop as FUSE_COST_RATIO).
OVERLAP_EFFICIENCY = 0.85

#: Fraction of the *ideal* 1/k s-step latency amortization
#: (``halo_depth``, docs/TEMPORAL.md) the schedule actually realizes,
#: PER KERNEL LANGUAGE: exchanging a (d x k)-deep frame once per k
#: chain rounds removes (1 - 1/k) of the per-round hop latency in the
#: ideal model, but the wider frame costs serialization, cache
#: pressure, and ring-recompute growth the latency term does not see —
#: and the two languages pay it differently (the XLA chain re-windows
#: in HBM; the Pallas chains deepen the in-kernel VMEM-resident walk,
#: whose >6-deep cost has no measured FUSE_COST_RATIO entry, so this
#: literal absorbs it). The defaults are the analytic guesses until
#: ``benchmarks/update_halo_depth.py --apply`` rewrites each entry
#: from a measured language-tagged ``halo_bench.py --ab --halo-depths``
#: artifact (the same calibration loop as OVERLAP_EFFICIENCY). The
#: "xla" value is the PR 9 literal, unchanged.
HALO_DEPTH_EFFICIENCY = {
    "xla": 0.9,
    "pallas": 0.9,
}


#: Single-chip compute-cost ratio of the ``bf16_f32acc`` posture
#: (docs/PRECISION.md) vs the f32 baseline the ``MEASURED_US`` anchors
#: were captured at: the stencil is memory-bandwidth-bound (envelope
#: probe, BASELINE.md), and bf16 fields halve the HBM bytes per step
#: while the f32 accumulation keeps the VPU/MXU work roughly flat — so
#: the analytic guess sits between the 0.5 bandwidth bound and 1.0
#: flops-flat, leaning conservative. An ANALYTIC literal until the
#: precision A/B (``benchmarks/precision_bench.py``) measures it on
#: real hardware — the same calibration discipline as
#: OVERLAP_EFFICIENCY / HALO_DEPTH_EFFICIENCY.
BF16_COMPUTE_RATIO = 0.75


def precision_compute_ratio(compute_precision: str) -> float:
    """Anchor-cost multiplier for a compute-precision posture: 1.0 for
    f32/equality (the anchors' own posture), :data:`BF16_COMPUTE_RATIO`
    for ``bf16_f32acc``. The HALO side of the posture needs no factor
    here — callers price it through ``itemsize`` (2 for bf16 fields),
    which is what halves every ``halo_bytes_*`` figure."""
    return (BF16_COMPUTE_RATIO
            if compute_precision == "bf16_f32acc" else 1.0)


def sstep_amortization(halo_depth: int, efficiency: float = None,
                       lang: str = "xla") -> float:
    """Fraction of the per-chain-round exchange hop latency that
    REMAINS under s-step exchange at depth ``halo_depth`` — 1.0 at
    k=1 (every round exchanges), approaching ``1 - efficiency`` as k
    grows (the calibrated share of the ideal 1/k win). ``lang``
    selects the per-language calibrated efficiency
    (:data:`HALO_DEPTH_EFFICIENCY`) when ``efficiency`` is None."""
    k = max(1, int(halo_depth))
    if k == 1:
        return 1.0
    eff = HALO_DEPTH_EFFICIENCY[lang] if efficiency is None else efficiency
    return 1.0 - eff * (1.0 - 1.0 / k)


def overlap_fraction(compute_us: float, comm_us: float,
                     efficiency: float = None) -> float:
    """Calibrated overlap fraction for a config: the share of raw comm
    hidden behind ``compute_us`` of comm-independent interior work."""
    if comm_us <= 0 or compute_us <= 0:
        return 0.0
    eff = OVERLAP_EFFICIENCY if efficiency is None else efficiency
    return min(1.0, eff * compute_us / comm_us)


def _resolve_overlap(overlap, compute_us: float, raw_comm_us: float):
    """Projection-row overlap: an explicit fraction, or ``"auto"`` for
    the calibrated ``overlap_fraction`` of this config."""
    if overlap == "auto":
        return overlap_fraction(compute_us, raw_comm_us)
    return float(overlap)


def anchor_us(lang: str, L: int) -> float:
    """Single-chip µs/step for a full L^3 grid: the measured anchor with
    the closest side, rescaled throughput-flat (conservative — larger
    locals measure closer to roofline, BASELINE.md)."""
    sides = sorted(s for k, s in MEASURED_US if k == lang)
    side = min(sides, key=lambda s: abs(s - L))
    return MEASURED_US[(lang, side)] * (L / side) ** 3


def project(
    local: int,
    fuse: int,
    us_per_step: float,
    *,
    stage_ratio: float = 1.0,
    itemsize: int = 4,
    links: int = 6,
    link_gbps: float = 90.0,
    hop_us: float = 1.0,
    overlap: float = 0.0,
    halo_depth: int = 1,
    n_fields: int = 2,
) -> dict:
    """Weak-scaling efficiency projection for one cubic-local config.

    Efficiency is sharded-per-step time over the single-chip baseline
    ``us_per_step``, accounting for ALL three sharding overheads:

    * per-stage cost ratio — ``stage_ratio`` x the fused single-chip
      step (1.0 for the XLA language, which is stepwise on one chip
      too);
    * ring recompute — stage s computes a (local+2(k-1-s))-wide
      window (``parallel/temporal.py``), extra volume the single-chip
      measurement does not contain;
    * exposed communication (serialization at the max-loaded link +
      hop latency), amortized over the k steps per exchange round.

    ``halo_depth`` (s-step exchange, docs/TEMPORAL.md) multiplies the
    steps per exchange round: the frame deepens to
    ``fuse * halo_depth`` (pricing the wider slabs and the extra ring
    recompute exactly) while the hop-latency amortization beyond one
    chain round is discounted by the calibrated
    :data:`HALO_DEPTH_EFFICIENCY`.
    """
    sk = max(1, int(halo_depth))
    s_steps = fuse * sk  # steps per exchange round
    wide = local + 2 * s_steps  # corner-propagated exchange slab
    # Every exchanged face carries all of the model's fields.
    face_bytes = wide * wide * s_steps * itemsize * n_fields
    total_bytes = 6 * face_bytes
    # The exchange completes at the MAX-loaded link, not at aggregate
    # bandwidth: with 6 links each face rides its own (1 face/link);
    # with 4 (v5e 2D torus) the y/z-shared links carry 2 faces each.
    faces_per_link = -(-6 // links)  # ceil
    ser_us = faces_per_link * face_bytes / (link_gbps * 1e3) / s_steps
    # One exchange round per s_steps; the amortization beyond the
    # chain-round baseline is what s-step adds, discounted by the
    # calibrated efficiency.
    lat_us = 6 * hop_us / fuse * sstep_amortization(sk)
    raw_us = ser_us + lat_us
    recompute = sum(
        (local + 2 * (s_steps - 1 - s)) ** 3 for s in range(s_steps)
    ) / (s_steps * local**3)
    ov = _resolve_overlap(
        overlap, us_per_step * stage_ratio * recompute, raw_us
    )
    comm_us = raw_us * (1.0 - ov)
    eff = us_per_step / (us_per_step * stage_ratio * recompute + comm_us)
    return {
        "local": local,
        "fuse": fuse,
        "halo_depth": sk,
        "stage_ratio": stage_ratio,
        "compute_us_per_step": round(us_per_step, 1),
        "ring_recompute_ratio": round(recompute, 4),
        "halo_bytes_per_round": total_bytes,
        "halo_bytes_per_step": round(total_bytes / s_steps),
        "exchanges_per_step": round(1.0 / s_steps, 4),
        "comm_us_per_step_exposed": round(comm_us, 2),
        "comm_us_per_step_hidden": round(raw_us - comm_us, 2),
        "links": links,
        "link_gbps": link_gbps,
        "overlap": round(ov, 4),
        "projected_weak_scaling_eff": round(eff, 4),
    }


def best_fuse(local, us_per_step, *, kmax=8, **kw):
    """The fuse depth minimizing total sharding overhead for a config —
    recompute grows and comm shrinks with k, and ``GS_FUSE`` is a free
    knob at launch time, so the projection reports the swept optimum."""
    return max(
        (project(local, k, us_per_step, **kw) for k in range(1, kmax + 1)),
        key=lambda r: r["projected_weak_scaling_eff"],
    )


def pin_big_vmem() -> None:
    """Pin the v4/v5/v6 VMEM budget so feasibility checks never dial a
    device — for CLI/model use where no backend should be touched."""
    from ..ops import pallas_stencil as ps

    ps._VMEM_BUDGET = ps._VMEM_BUDGETS[True]


def _feasible_chain_depth(local, itemsize, kmax, sublane=8, ypad=True,
                          n_fields=2):
    """Deepest chain depth the real Mosaic VMEM feasibility check
    admits for this local shape (``pallas_stencil.max_feasible_fuse*``);
    ``ypad`` selects the xy-chain form (y-extended operand) vs the 1D
    x-chain; ``n_fields`` scales the per-slab VMEM bytes (every field
    rides the same slab pipeline)."""
    from ..ops import pallas_stencil as ps

    if ypad:
        return ps.max_feasible_fuse_ypad(*local, itemsize, kmax, sublane,
                                         n_fields=n_fields)
    return ps.max_feasible_fuse(*local, itemsize, kmax,
                                n_fields=n_fields)


def band_cells_per_round(local, k):
    """Output cells of the two z-side XLA band chains per k-step round
    (``parallel/temporal.window_chain``): stage s shrinks the
    (nx+2k, ny+2k, 3k) window by one cell per side."""
    nx, ny, nz = local
    cells = 0
    for s in range(k):
        cells += ((nx + 2 * (k - s) - 2) * (ny + 2 * (k - s) - 2)
                  * (3 * k - 2 * s - 2))
    return 2 * cells


def project_chain(
    dims,
    L: int,
    fuse: int,
    base_us_full: float,
    *,
    local=None,
    itemsize: int = 4,
    sublane: int = 8,
    links: int = 6,
    link_gbps: float = 90.0,
    hop_us: float = 1.0,
    overlap: float = 0.0,
    xla_us_per_cell: float = None,
    halo_depth: int = 1,
    n_fields: int = 2,
) -> dict:
    """Weak-scaling projection for the round-4 cross-shard fused chain
    (``parallel/temporal.xy_chain``) on an (n, m, p) mesh.

    ``halo_depth`` (s-step exchange, docs/TEMPORAL.md) multiplies the
    in-kernel steps per exchange round: the frame deepens to
    ``fuse * halo_depth`` (pricing the wider y planes, x ring, and z
    bands exactly) while the per-stage cost stays keyed on the BASE
    fuse's measured ratio and the hop-latency amortization beyond one
    chain round is discounted by the calibrated Pallas
    :data:`HALO_DEPTH_EFFICIENCY` — the same scheme as
    :func:`project_1d`, because the generated kernel realizes
    halo_depth=k at fuse=d as the fuse=k*d chain program.

    Every sharded stage runs IN-KERNEL at the fused schedule (the 1.46x
    single-step penalty of the retired round-3 design is gone); the
    overheads are:

    * ``FUSE_COST_RATIO[k]`` — in-kernel depth vs the k=5 optimum;
    * y-plane growth — the operand carries a k-deep y halo rounded up
      to the sublane tile, so every plane computes
      (ny + 2k + align)/ny more rows;
    * x ring recompute — mid-stage windows extend (k-1-s) planes per
      side, 1 + (k-1)/nx extra volume (same as the 1D x-chain);
    * z bands (p > 1 only) — two k-wide bands per round recomputed in
      XLA at the measured big-grid XLA per-cell rate (conservative: the
      band working set can be VMEM-resident, which XLA fuses faster);
    * exposed comm — 4 slab ppermutes per round for (n, m, 1), 6 for
      z-sharded, each face on its own torus link, serialization at the
      largest face.

    ``base_us_full`` is the fused single-chip µs/step for the WHOLE L^3
    grid; per-shard compute is 1/(n*m*p) of it (throughput-flat,
    conservative for big locals). ``local`` overrides the per-shard
    block shape — callers with pad-and-mask storage (non-divisible L)
    pass their ceil blocks so the projection describes the block shape
    actually run, the one the feasibility gates were applied to;
    the default is exact floor division. ``links`` is the number of
    torus links the exchange can ride (``fabric_for``): with fewer
    links than faces the serialization completes at the max-loaded
    link carrying ceil(n_faces/links) faces, mirroring ``project()``'s
    ``faces_per_link`` — a v5e/v6e 2D torus (4 links) pays 2 faces on
    the shared links for a z-sharded chain.
    """
    n, m, p = dims
    if local is None:
        local = (L // n, L // m, L // p)
    nx, ny, nz = local
    us_base = base_us_full / (n * m * p)
    r = FUSE_COST_RATIO.get(fuse)
    if r is None:
        raise ValueError(f"no measured fuse-cost ratio for k={fuse}")
    k = fuse
    sk = max(1, int(halo_depth))
    s_steps = k * sk  # in-kernel steps per exchange round
    ny_ext = ny + 2 * s_steps
    ny_ext += (-ny_ext) % sublane
    y_over = ny_ext / ny if (m > 1 or p > 1) else 1.0
    x_ring = 1.0 + (s_steps - 1) / nx
    compute_us = us_base * r * y_over * x_ring

    if p > 1:
        if xla_us_per_cell is None:
            xla_us_per_cell = MEASURED_US[("XLA", 256)] / 256**3
        band_us = (band_cells_per_round(local, s_steps) * xla_us_per_cell
                   / s_steps)
        # Frame faces span the padded extents (corner propagation).
        zx, zy = nz + 2 * s_steps, ny + 2 * s_steps
        face_bytes = max(
            zy * zx, (nx + 2 * s_steps) * zx, (nx + 2 * s_steps) * zy
        ) * itemsize * n_fields
        n_faces = 6
    else:
        band_us = 0.0
        face_bytes = max(ny_ext * nz, nx * nz) * itemsize * n_fields
        n_faces = (2 if n > 1 else 0) + (2 if m > 1 else 0)
    # Depth-wide slabs every s_steps steps -> per-step bytes are
    # depth-independent; completion at the MAX-loaded link: with fewer
    # links than faces (v5e/v6e 2D torus) some links carry
    # ceil(n_faces/links) faces.
    faces_per_link = -(-n_faces // links) if n_faces else 0
    ser_us = faces_per_link * face_bytes / (link_gbps * 1e3)
    lat_us = n_faces * hop_us / k * sstep_amortization(sk, lang="pallas")
    raw_us = ser_us + lat_us
    # Only the kernel pass is comm-independent dataflow in the split-
    # phase round; the band recomputes consume the exchange, so they
    # are not part of the hiding budget.
    ov = _resolve_overlap(overlap, compute_us, raw_us)
    comm_us = raw_us * (1.0 - ov)

    eff = us_base / (compute_us + band_us + comm_us)
    return {
        "mesh": f"{n},{m},{p}",
        "local": list(local),
        "fuse": k,
        # s-step exchange depth: the generated kernel realizes it as a
        # (fuse x halo_depth)-deep in-kernel chain per exchange round
        # (simulation.py Pallas chain paths, docs/TEMPORAL.md).
        "halo_depth": sk,
        "fuse_cost_ratio": r,
        "fuse_cost_ratio_interpolated": k in (2, 3),
        "compute_us_per_step": round(us_base, 1),
        "halo_bytes_per_step": round(n_faces * face_bytes / s_steps),
        "exchanges_per_step": (round(1.0 / s_steps, 4)
                               if n_faces else 0.0),
        "y_plane_overhead": round(y_over, 4),
        "x_ring_recompute": round(x_ring, 4),
        "z_band_us_per_step": round(band_us, 2),
        "comm_us_per_step_exposed": round(comm_us, 2),
        "comm_us_per_step_hidden": round(raw_us - comm_us, 2),
        "links": links,
        "link_gbps": link_gbps,
        "overlap": round(ov, 4),
        "projected_weak_scaling_eff": round(eff, 4),
    }


def _mesh_candidates(n_devices: int, L: int):
    """All (n, m, p) ordered factorizations of ``n_devices`` whose dims
    divide L — the mixed-mesh sweep space."""
    out = []
    for n in range(1, n_devices + 1):
        if n_devices % n or L % n:
            continue
        rest = n_devices // n
        for m in range(1, rest + 1):
            if rest % m or L % m:
                continue
            p = rest // m
            if L % p:
                continue
            out.append((n, m, p))
    return out


def best_chain_depth(dims, L, base_us_full, *, local=None, itemsize=4,
                     kmin=2, kmax=8, n_fields=2, **kw):
    """Best feasible chain row for ONE mesh: routes (n,1,1) to the 1D
    x-chain model and everything else to the xy-chain model, applying
    the SAME feasibility gates the kernel dispatch applies (Mosaic's
    128-lane tiling on the local z extent, VMEM slab fit, measured
    fuse-ratio availability) so the model never promises a schedule
    the kernel would silently decline. ``None`` when no depth in
    [kmin, kmax] survives. ``local`` defaults to exact division;
    callers with pad-and-mask storage pass their ceil blocks."""
    from ..ops import pallas_stencil as ps

    n, m, p = dims
    if local is None:
        local = tuple(L // d for d in dims)
    if min(local) < 2 or ps.mosaic_gate_reason(local, itemsize):
        # Dispatch-level Mosaic gates (f64 fallback, 128-lane tiling)
        # shared with the kernel — no chain schedule exists to project.
        return None
    sublane = 16 if itemsize == 2 else 8
    if m == 1 and p == 1:
        cap = _feasible_chain_depth(
            local, itemsize, max(kmin, local[0]), ypad=False,
            n_fields=n_fields,
        )
        ks = [k for k in FUSE_COST_RATIO if kmin <= k <= min(cap, kmax)]
        # The projection must describe the SAME block shape the gates
        # above were applied to — pass ``local`` through instead of
        # letting the model recompute it with floor division.
        rows = [project_1d(n, L, k, base_us_full, local=local,
                           itemsize=itemsize, n_fields=n_fields, **kw)
                for k in ks]
    else:
        cap = min(kmax, local[0], local[1])
        if p > 1:
            cap = min(cap, local[2] // 2)
        cap = _feasible_chain_depth(local, itemsize, cap, sublane,
                                    n_fields=n_fields)
        ks = [k for k in FUSE_COST_RATIO if kmin <= k <= cap]
        rows = [project_chain(dims, L, k, base_us_full, local=local,
                              itemsize=itemsize, sublane=sublane,
                              n_fields=n_fields, **kw)
                for k in ks]
    if not rows:
        return None
    return max(rows, key=lambda r: r["projected_weak_scaling_eff"])


def best_chain(n_devices, L, base_us_full, *, itemsize=4, kmax=8, **kw):
    """Sweep mesh factorization x feasible chain depth for the round-4
    chain; returns the best row (the VERDICT-8 mixed-mesh sweep), or
    ``None`` when no factorization admits a feasible depth >= 2."""
    best = None
    for dims in _mesh_candidates(n_devices, L):
        r = best_chain_depth(dims, L, base_us_full, itemsize=itemsize,
                             kmax=kmax, **kw)
        if r is not None and (
            best is None
            or r["projected_weak_scaling_eff"]
            > best["projected_weak_scaling_eff"]
        ):
            best = r
    return best


def project_1d(
    n: int,
    L: int,
    fuse: int,
    base_us_per_step: float,
    *,
    local=None,
    itemsize: int = 4,
    links: int = 6,
    link_gbps: float = 90.0,
    hop_us: float = 1.0,
    overlap: float = 0.0,
    halo_depth: int = 1,
    n_fields: int = 2,
) -> dict:
    """Weak-scaling projection for the 1D x-sharded in-kernel fused
    chain (``GS_TPU_MESH_DIMS=n,1,1``): each shard owns an
    (L/n, L, L) slab, the only halo is a fuse-wide x-slab pair riding
    2 torus links, and the kernel runs its in-kernel chain ACROSS the
    shard boundary — so the per-stage cost is the fused single-chip
    schedule scaled by the measured fuse-depth ratio, not a per-stage
    single-step penalty.

    ``base_us_per_step`` is the fused single-chip time for the WHOLE
    L^3 grid (the 1-chip baseline); per-shard compute is 1/n of it
    (throughput-flat assumption, conservative: bigger blocks measure
    closer to roofline). ``local`` overrides the (nx, ny, nz) block
    shape (pad-and-mask ceil blocks for non-divisible L; default is
    floor division with full L x L slab faces); ``links`` caps how
    many torus links the 2-face exchange can ride.
    """
    if local is None:
        local = (L // n, L, L)
    nx, ny, nz = local
    us_base = base_us_per_step / n
    sk = max(1, int(halo_depth))
    s_steps = fuse * sk  # steps per exchange round (s-step exchange)
    recompute = 1.0 + (s_steps - 1) / nx  # ring grows only along x
    r = FUSE_COST_RATIO.get(fuse)
    if r is None:
        raise ValueError(f"no measured fuse-cost ratio for k={fuse}")
    # k-wide slab each direction every k steps => per-step bytes are
    # k-independent; with >= 2 usable links each face rides its own x
    # link, else they serialize on the shared one.
    faces_per_link = -(-2 // links)
    ser_us = (faces_per_link * ny * nz * itemsize * n_fields
              / (link_gbps * 1e3))
    lat_us = 2 * hop_us / fuse * sstep_amortization(sk, lang="pallas")
    raw_us = ser_us + lat_us
    ov = _resolve_overlap(overlap, us_base * r * recompute, raw_us)
    comm_us = raw_us * (1.0 - ov)
    eff = us_base / (us_base * r * recompute + comm_us)
    return {
        "mesh": f"{n},1,1",
        "local": nx,
        "fuse": fuse,
        "halo_depth": sk,
        "fuse_cost_ratio": r,
        "fuse_cost_ratio_interpolated": fuse in (2, 3),
        "compute_us_per_step": round(us_base, 1),
        "ring_recompute_ratio": round(recompute, 4),
        "halo_bytes_per_step": round(2 * ny * nz * itemsize * n_fields),
        "exchanges_per_step": round(1.0 / s_steps, 4),
        "comm_us_per_step_exposed": round(comm_us, 2),
        "comm_us_per_step_hidden": round(raw_us - comm_us, 2),
        "links": links,
        "link_gbps": link_gbps,
        "overlap": round(ov, 4),
        "projected_weak_scaling_eff": round(eff, 4),
    }


def best_fuse_1d(n, L, base_us, *, itemsize=4, **kw):
    """1D x-chain depth sweep including the depth-1 (unfused-exchange)
    row — the CLI's explicit 1D comparison rows; feasibility gates
    shared with the kernel dispatch via :func:`best_chain_depth`."""
    return best_chain_depth((n, 1, 1), L, base_us, itemsize=itemsize,
                            kmin=1, kmax=max(FUSE_COST_RATIO), **kw)


# --------------------------------------------------------- Auto dispatch

#: Fabric defaults by device generation substring (per-link GB/s per
#: direction, links usable by the 6-face exchange). v5e is a 2D torus
#: (z faces share links with y); v4/v5p/v6 are 3D tori.
_FABRICS = {
    "v5 lite": (45.0, 4),
    "v5e": (45.0, 4),
    "v6 lite": (90.0, 4),
    "v6e": (90.0, 4),
}
_FABRIC_DEFAULT = (90.0, 6)


def fabric_for(device_kind: str):
    """(link_gbps, links) for a device-kind string, env-overridable via
    ``GS_AUTO_LINK_GBPS`` / ``GS_AUTO_LINKS``."""
    from ..config.env import env_float, env_int

    kind = (device_kind or "").lower()
    gbps, links = _FABRIC_DEFAULT
    for sub, fab in _FABRICS.items():
        if sub in kind:
            gbps, links = fab
            break
    gbps = env_float("GS_AUTO_LINK_GBPS", float(gbps))
    links = env_int("GS_AUTO_LINKS", int(links))
    return gbps, links


def select_kernel(
    dims,
    L: int,
    *,
    platform: str = "tpu",
    device_kind: str = "",
    itemsize: int = 4,
    fuse: int = 5,
    eff_target: float = 0.90,
    objective: str = None,
    overlap="auto",
    hop_us: float = 1.0,
    sweep_mesh: bool = False,
    n_fields: int = 2,
):
    """Resolve ``kernel_language = "Auto"`` for a concrete run config.

    Returns ``(lang, info)`` with ``lang`` in {"pallas", "xla"} and
    ``info`` a JSON-able record of the decision (projected rows, the
    objective, and who holds the >=90% weak-scaling bar). With
    ``sweep_mesh`` (the mesh was NOT operator-forced) the Pallas chain
    is projected at its best mesh factorization x feasible depth
    (``best_chain``) instead of at ``dims`` — the chosen mesh/depth
    come back in the winning row for the caller to adopt.

    Policy (documented in BASELINE.md "Auto dispatch"):

    * off-TPU -> XLA always: the Pallas path off-TPU is the interpret-
      mode correctness tool (~1000x, BASELINE.md) or the per-shard XLA
      fallback — never a performance win;
    * single device -> Pallas when the fused kernel is VMEM-feasible
      for this shape (measured 2.5x the XLA kernel single-chip), else
      XLA;
    * sharded -> project both languages with the ICI model for the
      ACTUAL mesh and pick by ``objective``:
      - "efficiency" (default): the BASELINE.json north-star target is
        weak-scaling >=90% at pod scale, so prefer the faster kernel
        AMONG those projected to meet ``eff_target``; when none meets
        it, fall back to fastest outright (and say so in ``info``);
      - "throughput" (``GS_AUTO_OBJECTIVE=throughput``): fastest
        projected absolute step time, efficiency be damned — the
        Pallas chain's single-chip base is 2.3-4.4x the XLA kernel's,
        so it can lose the efficiency race while winning wall-clock.

    ``overlap``: the comm-hiding assumption threaded into every
    projection row. The default ``"auto"`` applies the calibrated
    split-phase overlap (``overlap_fraction`` — the runtime default is
    split-phase ON for sharded runs); pass ``0.0`` when the run has
    ``GS_COMM_OVERLAP=off`` so the pick reflects fully-exposed comm,
    or any explicit fraction for sensitivity studies.
    """
    from ..config.env import env_str

    objective = objective or env_str(
        "GS_AUTO_OBJECTIVE", "efficiency"
    )
    if objective not in ("efficiency", "throughput"):
        raise ValueError(
            f"GS_AUTO_OBJECTIVE must be 'efficiency' or 'throughput', "
            f"got {objective!r}"
        )
    n, m, p = dims
    n_devices = n * m * p
    info = {
        "dims": list(dims), "L": L, "platform": platform,
        "objective": objective, "eff_target": eff_target,
    }

    if platform != "tpu":
        info["reason"] = (
            "off-TPU the Pallas path is the interpret-mode correctness "
            "tool or the per-shard XLA fallback; XLA is the compiled path"
        )
        return "xla", info

    if n_devices == 1:
        from ..ops import pallas_stencil as ps

        gate = ps.mosaic_gate_reason((L, L, L), itemsize)
        if gate is not None:
            # The kernel would silently run its XLA fallback at this
            # shape/dtype — pick XLA openly so the recorded language
            # matches what executes.
            info["reason"] = f"single chip: {gate}"
            return "xla", info
        feasible = _feasible_chain_depth(
            (L, L, L), itemsize, max(fuse, 1), ypad=False,
            n_fields=n_fields,
        )
        if feasible >= 1:
            info["reason"] = (
                f"single chip: fused Pallas kernel feasible (depth "
                f"{feasible}), measured ~2.5x the XLA kernel"
            )
            return "pallas", info
        info["reason"] = (
            "single chip: no VMEM-feasible slab layout for this shape"
        )
        return "xla", info

    link_gbps, links = fabric_for(device_kind)
    info["link_gbps"], info["links"] = link_gbps, links
    # ``links`` rides along to BOTH languages' projections: the chain
    # models share the serialization-at-the-max-loaded-link treatment
    # with project(), so Auto's cross-language pick no longer
    # underestimates z-sharded Pallas chain comm on 2D-torus fabrics
    # (v5e/v6e: 6 faces on 4 links).
    kw = dict(links=links, link_gbps=link_gbps, hop_us=hop_us,
              overlap=overlap, n_fields=n_fields)

    # XLA language on the actual mesh: locals may be non-cubic; use the
    # cubic-equivalent side (the model's project() is cubic) — face
    # geometry differences are second-order next to the language choice.
    local = tuple(-(-L // d) for d in dims)  # ceil: pad-and-mask storage
    side = round((local[0] * local[1] * local[2]) ** (1 / 3))
    xla_us = anchor_us("XLA", L) / n_devices
    xla_row = best_fuse(side, xla_us, itemsize=itemsize, **kw)
    xla_row["kernel"] = "xla"

    # Pallas chain: at the best swept mesh when the caller lets us pick
    # (sweep_mesh), else at the actual mesh — 1D x-sharded runs the
    # x-chain, anything else the xy-chain (+ z bands when p > 1), at
    # the deepest VMEM-feasible depth <= the configured fuse.
    base_full = anchor_us("Pallas", L)
    if fuse < 2:
        # GS_FUSE=1 pins the unfused exchange: no chain schedule is
        # available to the run, so projecting one would justify the
        # pick with a schedule that cannot execute.
        chain_row = None
    elif sweep_mesh:
        chain_row = best_chain(n_devices, L, base_full,
                               itemsize=itemsize, kmax=fuse, **kw)
    else:
        chain_row = best_chain_depth(dims, L, base_full, local=local,
                                     itemsize=itemsize, kmax=fuse, **kw)
    if chain_row is not None:
        chain_row["kernel"] = "pallas"

    # Absolute per-step time: efficiency is relative to each language's
    # OWN single-chip base, so cross-language comparison must go
    # through it (the Pallas base is 2.3-4.4x faster).
    def step_us(row, base):
        return base / row["projected_weak_scaling_eff"]

    rows = [(xla_row, xla_us)]
    if chain_row is not None:
        rows.append((chain_row, base_full / n_devices))
    for row, base in rows:
        row["projected_step_us"] = round(step_us(row, base), 1)
    info["rows"] = [r for r, _ in rows]
    meets = [(r, b) for r, b in rows
             if r["projected_weak_scaling_eff"] >= eff_target]
    info["eff_target_holders"] = [r["kernel"] for r, _ in meets]

    if objective == "efficiency" and meets:
        pick, base = min(meets, key=lambda rb: step_us(*rb))
        info["reason"] = (
            f"fastest among kernels projected >= {eff_target:.0%} "
            "weak-scaling"
        )
    else:
        pick, base = min(rows, key=lambda rb: step_us(*rb))
        if objective == "efficiency":
            info["reason"] = (
                f"no kernel projected >= {eff_target:.0%} at this "
                "config; fastest outright"
            )
        else:
            info["reason"] = "fastest projected absolute step time"
    return pick["kernel"], info


def projected_step_us(
    lang: str,
    dims,
    L: int,
    fuse: int,
    *,
    itemsize: int = 4,
    links: int = 6,
    link_gbps: float = 90.0,
    hop_us: float = 1.0,
    overlap="auto",
    local=None,
    halo_depth: int = 1,
    compute_precision: str = "f32",
    n_fields: int = 2,
) -> Optional[float]:
    """Model-projected µs/step for ONE concrete (language, mesh, depth)
    config — the scalar the measured autotuner (``tune/candidates``)
    ranks its shortlist by. ``compute_precision`` (docs/PRECISION.md)
    prices the ``bf16_f32acc`` posture: the single-chip anchor scales
    by :data:`BF16_COMPUTE_RATIO` and the caller passes the bf16
    ``itemsize`` (2), which halves the projected halo bytes — the two
    halves of why the posture wins on a bandwidth-bound mesh. Routes to the same projection the Auto
    dispatch uses for that shape (cubic :func:`project` for the XLA
    language, :func:`project_1d`/:func:`project_chain` for the Pallas
    chains, the single-chip anchors for one device) and converts
    efficiency back to absolute time against the language's own base.
    ``halo_depth`` prices the s-step exchange for BOTH languages —
    :func:`project` for XLA, :func:`project_1d`/:func:`project_chain`
    for the Pallas chains (whose generated kernel realizes k at fuse=d
    as the fuse=k*d chain program). ``None`` when the model has
    nothing to say (no measured fuse ratio, no chain at this depth) —
    unscored candidates rank last, they are not excluded."""
    n, m, p = dims
    ndev = n * m * p
    ratio = precision_compute_ratio(compute_precision)
    if local is None:
        local = tuple(-(-L // d) for d in dims)
    if lang == "xla":
        base = anchor_us("XLA", L) / ndev * ratio
        if ndev == 1:
            return base
        side = max(2, round((local[0] * local[1] * local[2]) ** (1 / 3)))
        row = project(side, max(1, fuse), base, itemsize=itemsize,
                      links=links, link_gbps=link_gbps, hop_us=hop_us,
                      overlap=overlap, halo_depth=halo_depth,
                      n_fields=n_fields)
        return base / row["projected_weak_scaling_eff"]
    base_full = anchor_us("Pallas", L) * ratio
    r = FUSE_COST_RATIO.get(fuse)
    if ndev == 1:
        # halo_depth is a no-op unsharded (no exchange to amortize).
        return None if r is None else base_full * r
    if fuse < 2 or r is None:
        return None
    kw = dict(local=local, itemsize=itemsize, links=links,
              link_gbps=link_gbps, hop_us=hop_us, overlap=overlap,
              halo_depth=halo_depth, n_fields=n_fields)
    try:
        if m == 1 and p == 1:
            row = project_1d(n, L, fuse, base_full, **kw)
        else:
            row = project_chain(dims, L, fuse, base_full,
                                sublane=16 if itemsize == 2 else 8, **kw)
    except ValueError:
        return None
    return (base_full / ndev) / row["projected_weak_scaling_eff"]


def comm_report(sim) -> dict:
    """Per-step communication budget of a constructed ``Simulation`` —
    the ``comm`` section of RunStats (``utils/profiler.py``), mirroring
    the ``io`` overlap section: how many µs/step of halo exchange the
    ICI model projects for this exact config, and how much of it the
    split-phase schedule hides vs exposes.

    This is a MODEL projection (single-chip anchors + fabric figures,
    same machinery as Auto dispatch), not a measurement — host wall
    clock cannot attribute device-side comm, and a CPU-mesh run has no
    ICI at all. The section says so (``"model"``) and records the
    knobs, so a stats reader can recompute or recalibrate
    (``benchmarks/update_overlap.py``).
    """
    import numpy as np

    if not sim.sharded:
        return {
            "model": "ici-projection",
            "mode": "single-device",
            "comm_us_per_step": 0.0,
            "hidden_us": 0.0,
            "exposed_us": 0.0,
            "overlap": 0.0,
            "halo_depth": 1,
            "exchanges_per_step": 0.0,
            "halo_bytes_per_step": 0,
        }
    dims = sim.domain.dims
    L = sim.settings.L
    itemsize = int(np.dtype(sim.dtype).itemsize)
    try:
        kind = sim.mesh.devices.flat[0].device_kind
    except Exception:  # noqa: BLE001 — virtual meshes have no kind
        kind = ""
    link_gbps, links = fabric_for(kind)
    overlap_on = bool(getattr(sim, "comm_overlap", False))
    ov_arg = "auto" if overlap_on else 0.0
    fuse = max(1, int(sim._fuse_base()))
    local = tuple(-(-L // d) for d in dims)
    lang = "Pallas" if sim.kernel_language == "pallas" else "XLA"
    kw = dict(itemsize=itemsize, links=links, link_gbps=link_gbps,
              overlap=ov_arg,
              n_fields=int(getattr(sim.model, "n_fields", 2)))
    row = None
    if lang == "Pallas" and fuse >= 2:
        k = min(fuse, max(FUSE_COST_RATIO))
        k = k if k in FUSE_COST_RATIO else max(
            f for f in FUSE_COST_RATIO if f <= k
        )
        base_full = anchor_us("Pallas", L)
        sk = max(1, int(getattr(sim, "halo_depth", 1)))
        try:
            if dims[1] == 1 and dims[2] == 1:
                row = project_1d(dims[0], L, k, base_full, local=local,
                                 halo_depth=sk, **kw)
            else:
                row = project_chain(dims, L, k, base_full, local=local,
                                    halo_depth=sk, **kw)
        except ValueError:
            row = None
    if row is None:
        side = max(2, round(
            (local[0] * local[1] * local[2]) ** (1 / 3)
        ))
        n_dev = dims[0] * dims[1] * dims[2]
        row = project(side, fuse, anchor_us("XLA", L) / n_dev,
                      halo_depth=getattr(sim, "halo_depth", 1), **kw)
    exposed = row["comm_us_per_step_exposed"]
    hidden = row.get("comm_us_per_step_hidden", 0.0)
    return {
        "model": "ici-projection",
        "mode": "overlap" if overlap_on else "fused",
        "device_kind": kind or None,
        "kernel": lang,
        "fuse": row.get("fuse", fuse),
        # s-step exchange visibility (docs/TEMPORAL.md): how often this
        # schedule actually exchanges, and how many ghost bytes each
        # step amortizes — the numbers that make a halo_depth win
        # legible in gs_report.py.
        "halo_depth": row.get("halo_depth",
                              getattr(sim, "halo_depth", 1)),
        "exchanges_per_step": row.get("exchanges_per_step", 0.0),
        "halo_bytes_per_step": row.get("halo_bytes_per_step", 0),
        "links": links,
        "link_gbps": link_gbps,
        "comm_us_per_step": round(exposed + hidden, 2),
        "hidden_us": hidden,
        "exposed_us": exposed,
        "overlap": row["overlap"],
    }


def projected_step_us_for(sim) -> Optional[float]:
    """Model-projected µs/step for a CONSTRUCTED ``Simulation`` — the
    reference side of the live model-vs-measured residual gauge
    (``model_vs_measured_residual_us``, docs/OBSERVABILITY.md): the
    driver subtracts this projection from the observed step-latency p50
    so icimodel calibration drift is visible on the same scrape as the
    latency itself. Same machinery as the autotuner's candidate scorer
    (:func:`projected_step_us`), with every knob read off the live
    simulation; None when the model has nothing to say (e.g. a Pallas
    depth with no measured fuse ratio). A projection, anchored to the
    single-chip TPU measurements — on a CPU host the residual mostly
    measures the host, which is exactly what a reader should see."""
    import numpy as np

    try:
        kind = sim.mesh.devices.flat[0].device_kind
    except Exception:  # noqa: BLE001 — virtual/single-device meshes
        kind = ""
    link_gbps, links = fabric_for(kind)
    lang = "pallas" if sim.kernel_language == "pallas" else "xla"
    try:
        return projected_step_us(
            lang, sim.domain.dims, sim.settings.L,
            max(1, int(sim._fuse_base())),
            itemsize=int(np.dtype(sim.dtype).itemsize),
            links=links, link_gbps=link_gbps,
            overlap="auto" if getattr(sim, "comm_overlap", False)
            else 0.0,
            halo_depth=getattr(sim, "halo_depth", 1),
            n_fields=int(getattr(sim.model, "n_fields", 2)),
        )
    except Exception:  # noqa: BLE001 — a gauge must never kill a run
        return None
