"""Six-face halo exchange over a 3D device mesh via ``lax.ppermute``.

TPU-native replacement for the reference's ghost-cell machinery
(``src/simulation/communication.jl:109-199``): where the reference commits
three MPI derived vector datatypes per field and issues 12 ``MPI.Sendrecv!``
per step, here each (axis, direction) is one ``lax.ppermute`` of a
boundary slab riding ICI — and u/v slabs are stacked so the whole exchange
is 6 collectives per step, fused by XLA into the surrounding computation.

Non-periodic boundaries: the reference's edge ranks have ``MPI.PROC_NULL``
neighbors, so their ghost layers stay frozen at the initial values (u=1,
v=0). ``ppermute`` with a partial permutation delivers zeros to edge shards;
we select the frozen boundary value there instead (``jnp.where`` on
``lax.axis_index``).

Corner/edge ghost cells are left at boundary values — the 7-point stencil
never reads them (the reference's sequential xy/xz/yz exchange also leaves
them unsynchronized in a different but equally-unread state).

Split-phase exchange (round 6, docs/OVERLAP.md): the fused helpers above
produce data the *whole* kernel pass depends on, which serializes
ppermute latency in front of the compute. :func:`start_exchange` /
:class:`PendingExchange` issue the same ppermutes with NO consumer on the
interior compute's dataflow path, and :func:`frozen_frame` /
:func:`frozen_slabs` build the constant stand-ins the interior pass reads
instead — so XLA's async collective-permute + latency-hiding scheduler
can run the ICI transfer under the interior work, and the arrived halos
feed only the thin boundary-band recompute that is stitched afterwards
(``parallel/temporal.stitch_bands_from_frame``). Under JAX there is no
imperative wait: "start" means *issued with no dependency on the interior
pass*, and ``finish()`` means *first consumed by the band stitch*.

All functions here must be called *inside* ``shard_map``.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _slab(x: jnp.ndarray, dim: int, index: int, width: int) -> jnp.ndarray:
    """Extract a ``width``-thick boundary slab along ``dim`` (kept 3-D);
    ``index`` 0 = first slab, -1 = last slab."""
    idx = [slice(None)] * x.ndim
    idx[dim] = slice(0, width) if index == 0 else slice(-width, None)
    return x[tuple(idx)]


def _exchange_dim(
    arrays: List[jnp.ndarray],
    boundary_values: Sequence[float],
    dim: int,
    ax: str,
    n: int,
    width: int = 1,
) -> List[Tuple[jnp.ndarray, jnp.ndarray]]:
    """Resolved (lo, hi) ``width``-thick ghost slabs along one mesh axis
    for each array.

    One ``ppermute`` per direction carries all arrays (stacked along the
    transfer axis); global-edge shards get the frozen boundary value.
    ``n == 1`` (single shard on the axis) short-circuits to constants.
    """
    if n == 1:
        out = []
        for a, bv in zip(arrays, boundary_values):
            shape = list(a.shape)
            shape[dim] = width
            f = jnp.full(shape, bv, a.dtype)
            out.append((f, f))
        return out

    n_arr = len(arrays)
    idx = lax.axis_index(ax)

    # Stack the last slabs of all arrays -> send "up" (coord+1);
    # stack the first slabs -> send "down" (coord-1).
    send_up = jnp.concatenate([_slab(a, dim, -1, width) for a in arrays],
                              dim)
    send_dn = jnp.concatenate([_slab(a, dim, 0, width) for a in arrays],
                              dim)

    up_perm = [(i, i + 1) for i in range(n - 1)]
    dn_perm = [(i + 1, i) for i in range(n - 1)]
    recv_from_lo = lax.ppermute(send_up, ax, up_perm)  # lower nbr's top
    recv_from_hi = lax.ppermute(send_dn, ax, dn_perm)  # upper nbr's bottom

    lo_faces = jnp.split(recv_from_lo, n_arr, axis=dim)
    hi_faces = jnp.split(recv_from_hi, n_arr, axis=dim)

    out = []
    for i, (a, bv) in enumerate(zip(arrays, boundary_values)):
        bvt = jnp.asarray(bv, a.dtype)
        lo = jnp.where(idx > 0, lo_faces[i], bvt)
        hi = jnp.where(idx < n - 1, hi_faces[i], bvt)
        out.append((lo, hi))
    return out


def halo_pad(
    arrays: Sequence[jnp.ndarray],
    boundary_values: Sequence[float],
    axis_names: Tuple[str, str, str],
    axis_sizes: Tuple[int, int, int],
) -> Tuple[jnp.ndarray, ...]:
    """Ghost-pad each local block, filling ghosts from mesh neighbors.

    ``arrays`` are interior-shaped local blocks (same shape); ghosts come
    from the adjacent shard along each mesh axis, or stay at the frozen
    ``boundary_values`` on the global edge. This is the XLA-kernel form;
    the Pallas kernel consumes :func:`exchange_faces` instead.
    """
    arrays = list(arrays)
    padded = [
        jnp.pad(a, 1, mode="constant", constant_values=bv)
        for a, bv in zip(arrays, boundary_values)
    ]

    for dim, (ax, n) in enumerate(zip(axis_names, axis_sizes)):
        if n == 1:
            continue  # single shard on this axis: ghosts stay frozen
        faces = _exchange_dim(arrays, boundary_values, dim, ax, n)
        for i, (lo, hi) in enumerate(faces):
            # Write interior-sized faces into the padded array; corners and
            # edges keep the boundary constant (never read by the stencil).
            start_lo = [1] * 3
            start_lo[dim] = 0
            start_hi = [1] * 3
            start_hi[dim] = padded[i].shape[dim] - 1
            padded[i] = lax.dynamic_update_slice(padded[i], lo, start_lo)
            padded[i] = lax.dynamic_update_slice(padded[i], hi, start_hi)

    return tuple(padded)


def halo_pad_wide(
    arrays: Sequence[jnp.ndarray],
    boundary_values: Sequence[float],
    axis_names: Tuple[str, str, str],
    axis_sizes: Tuple[int, int, int],
    width: int,
) -> Tuple[jnp.ndarray, ...]:
    """Ghost-pad each local block with a ``width``-deep halo, **including
    edge/corner ghosts**.

    Deep halos feed temporal blocking: a ``width=2`` halo lets a shard
    recompute step n+1 on a +1-cell-extended window locally, so two
    steps need one exchange (the reference's per-step ``exchange!``,
    ``communication.jl:138-199``, amortized). Unlike the 7-point
    single-step stencil, the extended-window computation reads edge and
    corner ghosts, so exchanges are *sequential by axis* and each slab
    spans the full padded extent of the axes exchanged before it — the
    classic corner-propagation ordering (the reference's xy/xz/yz
    sequence has the same structure).
    """
    arrays = list(arrays)
    w = width
    padded = [
        jnp.pad(a, w, mode="constant", constant_values=bv)
        for a, bv in zip(arrays, boundary_values)
    ]

    for dim, (ax, n) in enumerate(zip(axis_names, axis_sizes)):
        if n == 1:
            continue  # single shard on this axis: ghosts stay frozen
        m = padded[0].shape[dim]
        # One slab-exchange implementation (``_exchange_dim``) serves
        # both the 1-deep face paths and this corner-propagated frame:
        # trimming the exchange axis's own ghosts makes the outermost
        # OWNED slabs the "boundary slabs" _exchange_dim sends, while
        # the other axes keep their full padded extent — so ghosts
        # filled by earlier axes ride along and corners propagate (the
        # reference's sequential xy/xz/yz ordering).
        trim = [slice(None)] * 3
        trim[dim] = slice(w, m - w)
        trim = tuple(trim)
        pairs = _exchange_dim(
            [p[trim] for p in padded], boundary_values, dim, ax, n, w
        )
        for i, (lo, hi) in enumerate(pairs):
            start_lo = [0] * 3
            start_hi = [0] * 3
            start_hi[dim] = m - w
            p = lax.dynamic_update_slice(padded[i], lo, start_lo)
            padded[i] = lax.dynamic_update_slice(p, hi, start_hi)

    return tuple(padded)


def exchange_x_slabs(
    arrays: Sequence[jnp.ndarray],
    boundary_values: Sequence[float],
    ax: str,
    n: int,
    width: int,
) -> List[Tuple[jnp.ndarray, jnp.ndarray]]:
    """``width``-wide (lo, hi) x-slab halos for each array.

    The 1D-x-sharded in-kernel temporal chain's exchange: ONE ppermute
    per direction carries a ``width``-plane slab of all arrays (stacked),
    feeding ``width`` fused kernel steps from a single exchange round —
    2 collectives per k steps where the reference exchanges 6 faces
    every step (``communication.jl:138-199``). Global-edge shards get
    the frozen boundary constant. Must be called inside ``shard_map``.
    (The width-generalized form of the per-axis exchange every other
    path uses — one implementation, ``_exchange_dim``.)
    """
    return _exchange_dim(list(arrays), boundary_values, 0, ax, n, width)


def exchange_slabs(
    arrays: Sequence[jnp.ndarray],
    boundary_values: Sequence[float],
    dim: int,
    ax: str,
    n: int,
    width: int,
) -> List[Tuple[jnp.ndarray, jnp.ndarray]]:
    """``width``-wide (lo, hi) boundary slabs along any one mesh axis —
    the axis-generic form of :func:`exchange_x_slabs` (one ppermute per
    direction carries all arrays; global-edge shards get the frozen
    boundary constant). The xy-chain exchanges its y halos with this
    before exchanging x slabs of the y-padded fields, so the x slabs
    carry the y corner data the in-kernel ring recompute needs. Must be
    called inside ``shard_map``."""
    return _exchange_dim(list(arrays), boundary_values, dim, ax, n, width)


def frozen_slabs(
    arrays: Sequence[jnp.ndarray],
    boundary_values: Sequence[float],
    dim: int,
    width: int,
) -> List[Tuple[jnp.ndarray, jnp.ndarray]]:
    """Constant (lo, hi) ``width``-thick slabs at the frozen boundary
    value for each array — the shape-compatible stand-in the split-phase
    interior pass consumes instead of exchanged slabs (identical to what
    an edge shard, or a single-shard axis, resolves to)."""
    out = []
    for a, bv in zip(arrays, boundary_values):
        shape = list(a.shape)
        shape[dim] = width
        f = jnp.full(shape, bv, a.dtype)
        out.append((f, f))
    return out


def frozen_frame(
    arrays: Sequence[jnp.ndarray],
    boundary_values: Sequence[float],
    width: int,
) -> Tuple[jnp.ndarray, ...]:
    """Each array ghost-padded ``width`` deep with the frozen boundary
    constant on every side — the :func:`halo_pad_wide` stand-in for the
    split-phase interior pass (as if every shard were a global-edge
    shard on every axis)."""
    return tuple(
        jnp.pad(a, width, mode="constant", constant_values=bv)
        for a, bv in zip(arrays, boundary_values)
    )


class PendingExchange:
    """An in-flight corner-propagated wide halo exchange.

    Holds the exchanged frames (``halo_pad_wide`` results). In JAX's
    dataflow model the ppermutes are already issued — *pending* means no
    op on the interior-compute path consumes them, so the scheduler is
    free to run the transfer underneath; :meth:`finish` hands the frames
    to the boundary-band stitch, the only consumer.
    """

    def __init__(self, frames: Tuple[jnp.ndarray, ...], width: int):
        self.frames = frames
        self.width = width

    def finish(self) -> Tuple[jnp.ndarray, ...]:
        """The exchanged frames (first consumption point)."""
        return self.frames


def start_exchange(
    arrays: Sequence[jnp.ndarray],
    boundary_values: Sequence[float],
    axis_names: Tuple[str, str, str],
    axis_sizes: Tuple[int, int, int],
    width: int,
) -> PendingExchange:
    """Issue the corner-propagated ``width``-deep exchange of
    :func:`halo_pad_wide` without tying it into the caller's compute:
    the same ppermutes, in the same per-axis order (so the fused and
    split-phase lowerings carry the SAME collective count), returned as
    a :class:`PendingExchange` consumed only by the band stitch."""
    return PendingExchange(
        halo_pad_wide(arrays, boundary_values, axis_names, axis_sizes,
                      width),
        width,
    )


def exchange_faces(
    arrays: Sequence[jnp.ndarray],
    boundary_values: Sequence[float],
    axis_names: Tuple[str, str, str],
    axis_sizes: Tuple[int, int, int],
) -> Tuple[jnp.ndarray, ...]:
    """Resolved halo faces for each array, without building padded blocks.

    Same communication pattern as :func:`halo_pad`, but the result is the
    1-thick face slabs themselves — the form the fused Pallas kernel
    consumes (``ops/pallas_stencil.fused_step``), which repairs its
    boundary rows/columns in-register instead of reading ghost cells from
    memory.

    Returns, for axes x, y, z in order and per array, ``(lo, hi)`` faces:
    for 2 arrays (u, v) that is
    ``(u_xlo, u_xhi, v_xlo, v_xhi, u_ylo, ..., v_zhi)``. On a global
    edge (or an axis with a single shard) the face is the frozen
    boundary constant.
    """
    arrays = list(arrays)
    flat = []
    for dim, (ax, n) in enumerate(zip(axis_names, axis_sizes)):
        for lo_hi in _exchange_dim(arrays, boundary_values, dim, ax, n):
            flat.extend(lo_hi)
    return tuple(flat)


