"""3D Cartesian domain decomposition over a JAX device mesh.

TPU-native replacement for the reference's MPI Cartesian machinery
(``src/simulation/communication.jl:59-96``): ``MPI.Dims_create`` becomes
:func:`dims_create` (same balanced factorization), ``MPI.Cart_create`` /
``Cart_coords`` / ``Cart_shift`` become a :class:`CartDomain` of pure data
plus a ``jax.sharding.Mesh`` — neighbor discovery is implicit in the mesh
axes, and the halo exchange (``parallel/halo.py``) uses ``lax.ppermute``
over ICI instead of ``MPI.Sendrecv!`` with derived datatypes.

Non-divisible L runs via **pad-and-mask** (r4): storage is padded to
equal ``ceil(L/d)`` blocks per axis (SPMD needs equal shards), pad
cells are pinned to the frozen boundary value by every step path, and
outputs are clipped back to the true ``L^3`` domain — fixing the
reference's ``InexactError`` on non-divisible L
(``communication.jl:73-87``, SURVEY defect #7) with integer math.
"""

from __future__ import annotations

import dataclasses
import os

from ..config.env import env_str
from typing import List, Tuple


def dims_create(nnodes: int, ndims: int = 3) -> Tuple[int, ...]:
    """Balanced factorization of ``nnodes`` into ``ndims`` dims.

    Semantics of ``MPI_Dims_create`` (reference ``communication.jl:63``):
    dims are as close to each other as possible and non-increasing.
    Prime factors are assigned largest-first to the currently smallest dim.
    """
    if nnodes < 1:
        raise ValueError(f"nnodes must be >= 1, got {nnodes}")
    factors: List[int] = []
    n = nnodes
    d = 2
    while d * d <= n:
        while n % d == 0:
            factors.append(d)
            n //= d
        d += 1
    if n > 1:
        factors.append(n)

    dims = [1] * ndims
    for f in sorted(factors, reverse=True):
        dims[dims.index(min(dims))] *= f
    return tuple(sorted(dims, reverse=True))


def block_size_offset(L: int, ndiv: int, coord: int) -> Tuple[int, int]:
    """TRUE-domain size and 0-based global offset of block ``coord`` of
    ``L`` over ``ndiv``.

    Pad-and-mask scheme (r4): SPMD compute needs EQUAL per-shard blocks,
    so storage is padded to ``ceil(L/ndiv) * ndiv`` and each block owns
    the clip of its equal slice to ``[0, L)`` — the high-coordinate
    block absorbs the shortfall. This actually runs non-divisible L on
    the sharded path, where the reference's remainder-spread attempt
    dies with InexactError (``communication.jl:73-87``, defect #7).
    """
    b = -(-L // ndiv)  # ceil: the equal storage block
    offset = min(b * coord, L)
    size = max(0, min(L - offset, b))
    return size, offset


@dataclasses.dataclass(frozen=True)
class CartDomain:
    """Static description of the 3D block decomposition of the L^3 grid.

    Replaces the reference's ``MPICartDomain`` (``Structs.jl:57-73``). This
    is global, pure data — every process/shard sees the same description;
    per-shard coordinates come from ``lax.axis_index`` inside ``shard_map``.
    """

    L: int
    dims: Tuple[int, int, int]

    @classmethod
    def create(
        cls, n_devices: int, L: int,
        dims: "Tuple[int, int, int] | None" = None,
    ) -> "CartDomain":
        """Balanced MPI ``Dims_create`` factorization, overridable with
        ``GS_TPU_MESH_DIMS=nx,ny,nz`` (e.g. ``8,1,1`` selects the 1D
        x-sharded decomposition whose halos feed the Pallas kernel's
        in-kernel fused chain — the fastest pod-slice layout for the
        Pallas language at <=16 chips, see BASELINE.md).

        An explicit ``dims`` wins over the env override: it is the
        programmatic channel the live-reshape path uses to target a
        specific factorization without mutating process-global env
        state (thread-unsafe under the serve worker fleet)."""
        if dims is not None:
            dims = tuple(int(d) for d in dims)
            if len(dims) != 3 or any(d < 1 for d in dims):
                raise ValueError(
                    f"mesh dims {dims!r} must be three positive "
                    "integers"
                )
            if dims[0] * dims[1] * dims[2] != n_devices:
                raise ValueError(
                    f"mesh dims {dims!r} do not factor "
                    f"{n_devices} devices"
                )
            return cls._validated(L, dims, n_devices)
        override = env_str("GS_TPU_MESH_DIMS", "")
        if n_devices == 1:
            # A single device has exactly one decomposition; ignoring
            # the override here lets a pod config export
            # GS_TPU_MESH_DIMS for its multi-chip jobs without breaking
            # single-device runs (bench.py, smoke tests) in the same
            # shell.
            override = ""
        if override:
            try:
                dims = tuple(int(x) for x in override.split(","))
            except ValueError:
                raise ValueError(
                    f"GS_TPU_MESH_DIMS={override!r} is not 'nx,ny,nz'"
                ) from None
            if len(dims) != 3 or any(d < 1 for d in dims):
                raise ValueError(
                    f"GS_TPU_MESH_DIMS={override!r} must be three "
                    "positive integers"
                )
            if dims[0] * dims[1] * dims[2] != n_devices:
                raise ValueError(
                    f"GS_TPU_MESH_DIMS={override!r} does not factor "
                    f"{n_devices} devices"
                )
        else:
            dims = dims_create(n_devices, 3)
        return cls._validated(L, dims, n_devices)

    @classmethod
    def _validated(cls, L, dims, n_devices) -> "CartDomain":
        if n_devices > 1:
            for d in dims:
                # Non-divisible L runs via pad-and-mask (storage padded
                # to equal blocks, pad cells pinned to the boundary
                # value); the only hard requirement is that every block
                # owns at least one true-domain cell.
                if -(-L // d) * (d - 1) >= L:
                    raise ValueError(
                        f"L={L} is too small for mesh dims {dims}: block "
                        f"{d - 1} of axis size {d} would own no "
                        "true-domain cells"
                    )
        return cls(L=L, dims=dims)

    @property
    def n_blocks(self) -> int:
        dx, dy, dz = self.dims
        return dx * dy * dz

    def coords(self, rank: int) -> Tuple[int, int, int]:
        """Row-major rank -> (cx, cy, cz), like ``MPI.Cart_coords``."""
        dx, dy, dz = self.dims
        cz = rank % dz
        cy = (rank // dz) % dy
        cx = rank // (dz * dy)
        return cx, cy, cz

    def proc_sizes(self, coords: Tuple[int, int, int]) -> Tuple[int, int, int]:
        return tuple(
            block_size_offset(self.L, d, c)[0]
            for d, c in zip(self.dims, coords)
        )

    def proc_offsets(self, coords: Tuple[int, int, int]) -> Tuple[int, int, int]:
        return tuple(
            block_size_offset(self.L, d, c)[1]
            for d, c in zip(self.dims, coords)
        )

    @property
    def local_shape(self) -> Tuple[int, int, int]:
        """Per-shard STORAGE block shape (equal blocks; sharded path
        only). For non-divisible L this is ``ceil(L/d)`` — the block
        includes pad cells past the true domain on the high edge."""
        return tuple(-(-self.L // d) for d in self.dims)

    @property
    def storage_shape(self) -> Tuple[int, int, int]:
        """Global padded array shape actually allocated when sharded:
        ``local_shape * dims`` per axis (== (L, L, L) for divisible L).
        Cells at global coordinate >= L are pad, pinned to the frozen
        boundary value by the step paths and stripped from every
        output."""
        return tuple(-(-self.L // d) * d for d in self.dims)

    @property
    def padded(self) -> bool:
        return self.storage_shape != (self.L,) * 3
