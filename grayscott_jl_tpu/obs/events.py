"""The unified run event stream: one schema, one tailable file.

Every discrete thing that happens to a run — injected faults, health
trips, watchdog expiries (stack dumps included), supervisor restart
decisions, autotune cache hits/misses, graceful-shutdown markers,
output/checkpoint boundaries, and the data-integrity records
(``corruption`` / ``replica_failover`` / ``scrub``,
``resilience/integrity.py``) — lands in ``GS_EVENTS=path`` as one JSONL
record per event with a single schema::

    {"ts": <unix seconds>, "proc": <rank>, "kind": <event kind>,
     "phase": <driver phase or null>, "step": <sim step or null>,
     "attrs": {...}}

Producers route through here automatically: ``FaultJournal.record``
(``resilience/supervisor.py``) mirrors every journal event, so the
fault/recovery story that already merges into ``RunStats`` is *also*
live-tailable (``tail -f``) while the run is still going — the journal
stays the fsynced recovery breadcrumb; this stream is the operator's
console. The driver adds run_start / output / checkpoint /
run_complete lifecycle markers and the autotuner its decision
(``tune/autotuner.py``).

Contract: emitting is best-effort — a full disk under the event stream
marks the stream broken and keeps the run alive (the journal, which IS
allowed to fail loudly, still records). stdlib only; importable
without JAX.

In-process consumers (the serve front door's SSE fan-out and job
tracker, ``serve/``, docs/SERVICE.md) read the SAME records live via
:meth:`EventStream.subscribe` — no second telemetry path — and
:func:`bound` stamps thread-local attrs (e.g. the serve batch id) onto
every record the calling thread emits, so a multi-tenant process can
attribute interleaved runs' events without touching the emitters.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
from typing import List, Optional

from .trace import _proc_index, rank_path

__all__ = [
    "EventStream",
    "NULL_EVENTS",
    "arm_events",
    "bound",
    "get_events",
    "parse_events",
    "parse_events_multi",
    "rank_files",
    "reset_events",
]

#: Thread-local attrs merged into every record the thread emits while
#: inside a :func:`bound` block (explicit emit attrs win on collision).
_BOUND = threading.local()


@contextlib.contextmanager
def bound(**attrs):
    """Bind default attrs to every event THIS thread emits inside the
    block — the serve worker runs a whole batch launch under
    ``bound(batch=...)`` so the driver's lifecycle records
    (run_start/output/run_complete) carry the batch id without the
    driver knowing the service exists. Nests; inner bindings win."""
    prev = getattr(_BOUND, "attrs", None)
    _BOUND.attrs = {**(prev or {}), **attrs}
    try:
        yield
    finally:
        _BOUND.attrs = prev

#: The flat record fields; everything else an emitter passes rides in
#: ``attrs`` so readers can rely on the top-level shape.
EVENT_FIELDS = ("ts", "proc", "kind", "phase", "step", "attrs")


class _NullEventStream:
    """Shared no-op stream for when ``GS_EVENTS`` is unset."""

    enabled = False
    emitted = 0

    def emit(self, kind, phase=None, step=None, **attrs):
        return None

    def subscribe(self, fn):
        """No events will ever flow; the unsubscribe is a no-op."""
        return lambda: None

    def describe(self) -> dict:
        return {"enabled": False}


NULL_EVENTS = _NullEventStream()


class EventStream:
    """Append-only JSONL event sink (one line per event, flushed so a
    tail sees it immediately; durability is the FaultJournal's job)."""

    enabled = True

    def __init__(self, path: str, proc: Optional[int] = None):
        self.path = path
        self.proc = _proc_index() if proc is None else proc
        self.emitted = 0
        self.broken: Optional[str] = None
        self._lock = threading.Lock()
        self._subscribers: List = []

    def subscribe(self, fn):
        """Register an in-process consumer: ``fn(record)`` is called
        (on the emitting thread — keep it cheap, e.g. a queue put) for
        every event AFTER it is written. Returns the unsubscribe
        callable. Subscriber exceptions are swallowed: a slow or dead
        SSE client must never take the run down, same contract as the
        file sink."""
        self._subscribers.append(fn)

        def _unsubscribe():
            try:
                self._subscribers.remove(fn)
            except ValueError:
                pass

        return _unsubscribe

    def emit(self, kind, phase=None, step=None, **attrs):
        """Record one event; returns the record dict (or None once the
        stream is broken). Thread-safe — called from the driver thread,
        the async writer's worker, the watchdog monitor (via the
        journal), and signal handlers. Thread-bound attrs
        (:func:`bound`) merge in under the explicit ones."""
        if self.broken is not None:
            return None
        tl = getattr(_BOUND, "attrs", None)
        if tl:
            attrs = {**tl, **attrs}
        event = {
            "ts": round(time.time(), 6),
            "proc": self.proc,
            "kind": str(kind),
            "phase": phase,
            "step": step,
            "attrs": attrs,
        }
        try:
            line = json.dumps(event)
        except (TypeError, ValueError):
            # A non-JSON attr must not kill the producer: stringify.
            event["attrs"] = {k: repr(v) for k, v in attrs.items()}
            line = json.dumps(event)
        try:
            with self._lock:
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(line + "\n")
                    f.flush()
                self.emitted += 1
        except OSError as e:
            # Monitoring must never take the run down: mark broken,
            # warn once, keep going.
            self.broken = f"{type(e).__name__}: {e}"
            print(f"gray-scott: warning: event stream {self.path} "
                  f"failed ({self.broken}); further events are dropped",
                  file=sys.stderr)
            return None
        for fn in list(self._subscribers):
            try:
                fn(event)
            except Exception:  # noqa: BLE001 — consumer must not kill the run
                pass
        return event

    def describe(self) -> dict:
        return {"enabled": True, "path": self.path,
                "emitted": self.emitted, "broken": self.broken,
                "subscribers": len(self._subscribers)}


def parse_events(path: str) -> List[dict]:
    """All events of a stream file, oldest first. Corrupt lines (a torn
    tail from a killed process) are skipped, mirroring
    ``supervisor.resume_marker`` — a live-tailed file must be readable
    mid-write."""
    out: List[dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(ev, dict):
                out.append(ev)
    return out


def rank_files(path: str) -> List[str]:
    """The stream files one ``GS_EVENTS=path`` setting produced,
    rank-merged: the bare path (single-process runs) plus every
    ``path.rank<N>`` sibling a multi-process run wrote (``rank_path``
    suffixing), N-sorted. Works for any of the ``.rank``-suffixed
    artifact families (events, metrics, stats) — the reader-side
    inverse of the writer-side suffixing."""
    import glob
    import re

    out = [path] if os.path.isfile(path) else []
    ranked = []
    for p in glob.glob(f"{glob.escape(path)}.rank*"):
        m = re.fullmatch(r"\.rank(\d+)", p[len(path):])
        if m:
            ranked.append((int(m.group(1)), p))
    return out + [p for _, p in sorted(ranked)]


def parse_events_multi(path: str) -> List[dict]:
    """One merged, time-ordered event list from every rank's stream
    file (:func:`rank_files`): the reader-side join of a multi-process
    run's per-rank ``GS_EVENTS`` files — each record keeps its
    ``proc``, so a report can attribute per process while telling one
    chronological story. Sort is stable on the wall-clock ``ts`` every
    record carries (ranks share the coordinator's clock domain on a
    pod; sub-ms skew reorders nothing a human reads)."""
    events: List[dict] = []
    for p in rank_files(path):
        events.extend(parse_events(p))
    events.sort(key=lambda e: e.get("ts") or 0)
    return events


_stream = None


def get_events():
    """The process-wide stream: an :class:`EventStream` when
    ``GS_EVENTS`` names a path (``.rank<N>``-suffixed in multi-process
    runs), else the shared no-op. Like the tracer, resolved once so
    every attempt of a supervised run appends to the same file — the
    single merged timeline is the point."""
    global _stream
    if _stream is None:
        path = os.environ.get("GS_EVENTS", "").strip()
        _stream = EventStream(rank_path(path)) if path else NULL_EVENTS
    return _stream


def arm_events(path: str, proc: Optional[int] = None) -> EventStream:
    """Point the process-wide stream at ``path`` explicitly, with an
    explicit ``proc`` id. Serve-fleet members (``serve/cluster.py``)
    are a multi-process run WITHOUT a JAX distributed launch — every
    process would resolve ``_proc_index() == 0`` and clobber one file —
    so each member arms its own ``.rank<N>`` file here and the readers'
    existing ``rank_files`` merge tells one fleet-wide story."""
    global _stream
    os.environ["GS_EVENTS"] = path
    _stream = EventStream(path, proc=proc)
    return _stream


def reset_events() -> None:
    """Drop the singleton (tests; re-resolves from env on next use)."""
    global _stream
    _stream = None
