"""Host-side span tracer with Chrome trace-event export.

``GS_TRACE=path`` arms the process-wide tracer; the driver's phase
boundaries become spans with no new hot-path cost — every
``Watchdog.heartbeat`` already marks a phase transition, so the
heartbeat doubles as the span edge (``resilience/watchdog.py``), and
``RunStats.phase`` context managers (``utils/profiler.py``) emit the
nested timing spans they were already measuring. The export is the
Chrome trace-event JSON format (the ``traceEvents`` array of ``"X"``
complete events), directly loadable in Perfetto / ``chrome://tracing``;
``scripts/gs_report.py --check`` validates a file against
:func:`validate_trace`.

Design constraints:

* **stdlib only** — the watchdog must stay importable without JAX
  (``bench.py``'s parent process hooks in by design), so this module
  never imports jax at module level.
* **crash-consistent** — :meth:`SpanTracer.flush` rewrites the whole
  file atomically (tmp + rename), so the trace on disk is valid JSON
  after every attempt of a supervised multi-restart run, including one
  that dies between attempts.
* **bounded** — at most ``GS_TRACE_MAX_EVENTS`` (default 200000) events
  are retained; later spans are counted as dropped rather than growing
  host memory without bound on a long campaign.
* **balanced** — span nesting follows context-manager LIFO per thread
  and edge spans are closed before the next opens, so the exported
  intervals properly nest (asserted by :func:`validate_trace`).

Device-side timelines are a separate tool: ``GS_PROFILE=start:stop``
(:class:`ProfileWindow`) brackets a simulation-step range with
``jax.profiler.start_trace``/``stop_trace`` so the XLA timeline of
exactly the interesting rounds lands in ``GS_PROFILE_DIR`` without
paying profiler overhead for the whole run.
"""

from __future__ import annotations

import contextlib
import json
import os
import sys
import threading
import time
from typing import List, Optional

__all__ = [
    "NULL_TRACER",
    "ProfileWindow",
    "SpanTracer",
    "get_tracer",
    "reset_tracer",
    "validate_trace",
]

#: tid of the driver-phase edge track (heartbeat-fed spans); real
#: threads are numbered from 1 in registration order.
EDGE_TID = 0


def _proc_index() -> int:
    """The JAX process index, without ever forcing a backend init
    (mirrors ``FaultJournal.from_env``): 0 before/without jax."""
    if "jax" in sys.modules:
        try:
            import jax

            return jax.process_index()
        except Exception:  # noqa: BLE001 — pre-init / no backend
            return 0
    return 0


def rank_path(path: str) -> str:
    """``.rank<N>``-suffix a path in multi-process runs (mirrors
    ``GS_TPU_STATS`` / ``GS_FAULT_JOURNAL``) so ranks don't clobber
    each other's file."""
    if "jax" in sys.modules:
        try:
            import jax

            if jax.process_count() > 1:
                return f"{path}.rank{jax.process_index()}"
        except Exception:  # noqa: BLE001
            pass
    return path


class _NullTracer:
    """Shared no-op tracer: ``GS_TRACE`` unset costs one attribute
    check and a no-op call per boundary, nothing more."""

    enabled = False
    _cm = contextlib.nullcontext()

    def span(self, name, phase=None, step=None, **attrs):
        return self._cm

    def edge(self, phase, step=None) -> None:
        pass

    def instant(self, name, step=None, **attrs) -> None:
        pass

    def flush(self) -> Optional[str]:
        return None

    def describe(self) -> dict:
        return {"enabled": False}


NULL_TRACER = _NullTracer()


class SpanTracer:
    """Nestable host-side spans -> Chrome trace-event JSON.

    Span identity is ``(name, phase, step, attrs)``; timestamps are
    microseconds of ``time.perf_counter`` relative to tracer creation
    (``args.epoch`` in the file anchors them to wall clock for
    cross-file correlation with the event stream). Thread-safe: spans
    come from the driver thread, the async writer's worker, and the
    watchdog monitor.
    """

    enabled = True

    def __init__(self, path: str, proc: Optional[int] = None,
                 max_events: Optional[int] = None):
        self.path = path
        self.proc = _proc_index() if proc is None else proc
        if max_events is None:
            max_events = int(os.environ.get("GS_TRACE_MAX_EVENTS",
                                            "200000"))
        if max_events <= 0:
            raise ValueError(
                f"GS_TRACE_MAX_EVENTS must be > 0, got {max_events}"
            )
        self.max_events = max_events
        self.dropped = 0
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._t0 = time.perf_counter()
        self._epoch = time.time()
        #: Currently open heartbeat-fed phase span: (phase, step, t_us).
        self._edge = None
        self._tids = {}  # thread ident -> small tid
        self._meta = [{
            "ph": "M", "name": "process_name", "pid": self.proc,
            "tid": EDGE_TID,
            "args": {"name": f"gray-scott proc {self.proc}"},
        }, {
            "ph": "M", "name": "thread_name", "pid": self.proc,
            "tid": EDGE_TID, "args": {"name": "driver phases"},
        }]

    # ---------------------------------------------------------- plumbing

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _tid(self) -> int:
        ident = threading.get_ident()
        with self._lock:
            tid = self._tids.get(ident)
            if tid is None:
                tid = self._tids[ident] = len(self._tids) + 1
                self._meta.append({
                    "ph": "M", "name": "thread_name", "pid": self.proc,
                    "tid": tid,
                    "args": {"name": threading.current_thread().name},
                })
        return tid

    def _add(self, event: dict) -> None:
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped += 1
                return
            self._events.append(event)

    def _complete(self, name, t0_us, dur_us, *, tid, phase=None,
                  step=None, attrs=None) -> None:
        args = {}
        if step is not None:
            args["step"] = step
        if attrs:
            args.update(attrs)
        self._add({
            "name": str(name),
            "cat": str(phase) if phase else "span",
            "ph": "X",
            "ts": round(t0_us, 3),
            "dur": round(max(dur_us, 0.0), 3),
            "pid": self.proc,
            "tid": tid,
            "args": args,
        })

    # -------------------------------------------------------------- spans

    @contextlib.contextmanager
    def span(self, name, phase=None, step=None, **attrs):
        """A nested timing span around a host-side block (LIFO per
        thread, so the exported intervals nest by construction)."""
        t0 = self._now_us()
        try:
            yield
        finally:
            self._complete(name, t0, self._now_us() - t0,
                           tid=self._tid(), phase=phase, step=step,
                           attrs=attrs)

    def edge(self, phase, step=None) -> None:
        """One driver phase boundary: close the open phase span, open
        the next. Fed by ``Watchdog.heartbeat`` — tracing the top-level
        phase timeline costs nothing the watchdog wasn't already
        paying."""
        now = self._now_us()
        with self._lock:
            prev, self._edge = self._edge, (str(phase), step, now)
        if prev is not None:
            self._complete(prev[0], prev[2], now - prev[2],
                           tid=EDGE_TID, phase=prev[0], step=prev[1])

    def instant(self, name, step=None, **attrs) -> None:
        """A zero-duration marker (fault injected, restart decided)."""
        args = dict(attrs)
        if step is not None:
            args["step"] = step
        self._add({
            "name": str(name), "cat": "event", "ph": "i", "s": "p",
            "ts": round(self._now_us(), 3), "pid": self.proc,
            "tid": self._tid(), "args": args,
        })

    # -------------------------------------------------------------- export

    def describe(self) -> dict:
        with self._lock:
            n = len(self._events)
        return {"enabled": True, "path": self.path, "events": n,
                "dropped": self.dropped}

    def flush(self) -> Optional[str]:
        """Atomically (re)write the whole trace file. The open edge
        span is exported as running-until-now without being closed, so
        flushing mid-run (every supervised attempt does) keeps the
        on-disk nesting balanced AND the in-memory edge alive."""
        now = self._now_us()
        with self._lock:
            events = list(self._meta) + list(self._events)
            edge = self._edge
        if edge is not None:
            args = {} if edge[1] is None else {"step": edge[1]}
            events.append({
                "name": edge[0], "cat": edge[0], "ph": "X",
                "ts": round(edge[2], 3),
                "dur": round(max(now - edge[2], 0.0), 3),
                "pid": self.proc, "tid": EDGE_TID, "args": args,
            })
        doc = {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {
                "epoch_unix_s": round(self._epoch, 6),
                "proc": self.proc,
                "dropped_events": self.dropped,
            },
        }
        tmp = f"{self.path}.tmp{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f)
            f.write("\n")
        os.replace(tmp, self.path)
        return self.path


_tracer = None


def get_tracer():
    """The process-wide tracer: a :class:`SpanTracer` when ``GS_TRACE``
    names a path (``.rank<N>``-suffixed in multi-process runs), else
    the shared no-op. Resolved once — a supervised run's restart
    attempts all append to the same trace, which is the point: one
    timeline for the whole multi-attempt story."""
    global _tracer
    if _tracer is None:
        path = os.environ.get("GS_TRACE", "").strip()
        _tracer = SpanTracer(rank_path(path)) if path else NULL_TRACER
    return _tracer


def reset_tracer() -> None:
    """Drop the singleton (tests; re-resolves from env on next use)."""
    global _tracer
    _tracer = None


# --------------------------------------------------------------- validation


def validate_trace(doc) -> List[str]:
    """Problems with a Chrome trace-event document (empty list = valid).

    Checks the contract ``gs_report.py --check`` and the tier-1 tests
    enforce: a ``traceEvents`` array whose ``"X"`` events each carry
    numeric ``pid``/``tid``/``ts``/``dur`` and whose spans nest without
    partial overlap per ``(pid, tid)`` track.
    """
    problems: List[str] = []
    if isinstance(doc, dict):
        events = doc.get("traceEvents")
        if not isinstance(events, list):
            return ["no traceEvents array"]
    elif isinstance(doc, list):
        events = doc
    else:
        return ["document is neither an object nor an array"]

    spans = {}
    for i, e in enumerate(events):
        if not isinstance(e, dict) or "ph" not in e:
            problems.append(f"event {i}: not an object with a ph field")
            continue
        if e["ph"] != "X":
            continue
        bad = [k for k in ("pid", "tid", "ts", "dur")
               if not isinstance(e.get(k), (int, float))
               or isinstance(e.get(k), bool)]
        if not isinstance(e.get("name"), str) or not e.get("name"):
            bad.append("name")
        if bad:
            problems.append(
                f"event {i} ({e.get('name')!r}): missing/invalid "
                f"{', '.join(sorted(bad))}"
            )
            continue
        if e["dur"] < 0:
            problems.append(f"event {i} ({e['name']!r}): negative dur")
            continue
        spans.setdefault((e["pid"], e["tid"]), []).append(e)

    eps = 1e-3  # exported timestamps are rounded to 1e-3 us
    for track, evs in spans.items():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack: List[dict] = []
        for e in evs:
            while stack and stack[-1]["ts"] + stack[-1]["dur"] \
                    <= e["ts"] + eps:
                stack.pop()
            if stack:
                parent_end = stack[-1]["ts"] + stack[-1]["dur"]
                if e["ts"] + e["dur"] > parent_end + eps:
                    problems.append(
                        f"track {track}: span {e['name']!r} "
                        f"[{e['ts']}, {e['ts'] + e['dur']}] partially "
                        f"overlaps {stack[-1]['name']!r} ending at "
                        f"{parent_end} (nesting unbalanced)"
                    )
                    continue
            stack.append(e)
    return problems


# ------------------------------------------------------- profiler windows


class ProfileWindow:
    """``jax.profiler`` capture bracketing a simulation-step range.

    ``GS_PROFILE=start:stop`` (simulation steps) opens the capture at
    the first driver boundary with ``step >= start`` and closes it at
    the first with ``step >= stop``; the XLA device timeline lands in
    ``GS_PROFILE_DIR`` (default ``gs_profile``) for TensorBoard/XProf.
    Complements the host-side span trace: spans say which *round* was
    slow, the capture says which *op*. Profiler failures are reported
    and disable the window — a profiling misconfig must never kill a
    production run.
    """

    def __init__(self, start: int, stop: int, out_dir: str):
        if start < 0 or stop <= start:
            raise ValueError(
                f"profile window needs 0 <= start < stop, got "
                f"{start}:{stop}"
            )
        self.start = start
        self.stop = stop
        self.out_dir = out_dir
        self.active = False
        self._done = False

    @classmethod
    def from_env(cls) -> Optional["ProfileWindow"]:
        spec = os.environ.get("GS_PROFILE", "").strip()
        if not spec:
            return None
        parts = spec.split(":")
        if len(parts) != 2:
            raise ValueError(
                f"GS_PROFILE must be start:stop (steps), got {spec!r}"
            )
        try:
            start, stop = int(parts[0]), int(parts[1])
        except ValueError as e:
            raise ValueError(
                f"GS_PROFILE must be start:stop integers, got {spec!r}"
            ) from e
        return cls(start, stop,
                   os.environ.get("GS_PROFILE_DIR", "gs_profile"))

    def _fail(self, what: str, exc: Exception) -> None:
        print(f"gray-scott: warning: jax.profiler {what} failed "
              f"({exc}); profile window disabled", file=sys.stderr)
        self.active = False
        self._done = True

    def on_boundary(self, step: int) -> None:
        """Called at every driver boundary with the current step."""
        if self._done:
            return
        if self.active and step >= self.stop:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001
                self._fail("stop_trace", e)
                return
            self.active = False
            self._done = True
        elif not self.active and step >= self.start and step < self.stop:
            try:
                import jax

                jax.profiler.start_trace(self.out_dir)
            except Exception as e:  # noqa: BLE001
                self._fail("start_trace", e)
                return
            self.active = True

    def finish(self) -> None:
        """Close a still-open capture (run ended inside the window)."""
        if self.active:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001
                self._fail("stop_trace", e)
            self.active = False
            self._done = True
