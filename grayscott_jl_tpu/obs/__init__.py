"""Unified observability: span tracing, metrics, and the run event stream.

The five production subsystems (overlap, autotune, supervision,
ensembles, multi-model) each grew their own evidence trail — ``RunStats``
phase dicts, ``FaultJournal`` JSONL, watchdog stack dumps, bench rows —
with no common timeline. This package is the one place they meet
(docs/OBSERVABILITY.md):

* :mod:`.trace` — nestable host-side spans exported as Chrome
  trace-event JSON (``GS_TRACE=path``; opens in Perfetto), fed by the
  driver's existing phase boundaries and the watchdog heartbeat (one
  heartbeat = one span edge — tracing adds nothing new to the hot
  path), plus ``GS_PROFILE=start:stop`` device-side ``jax.profiler``
  capture windows.
* :mod:`.metrics` — counters / gauges / ring-buffer histograms
  (p50/p95/p99) flushed as interval JSONL (``GS_METRICS=path``,
  ``metrics_interval_s`` TOML) with a one-shot Prometheus
  text-exposition dump (``GS_METRICS_PROM=path``). Off means a shared
  no-op object: zero allocations on the hot path.
* :mod:`.events` — ONE schema ``(ts, proc, kind, phase, step, attrs)``
  that fault-journal events, health reports, watchdog expiries,
  supervisor restart decisions, autotune cache hits/misses, and
  graceful-shutdown markers all route through (``GS_EVENTS=path``) —
  tailable live from a single file, rank-merged on read
  (:func:`~.events.parse_events_multi`).
* :mod:`.numerics` — the device-side half: per-field
  min/max/mean/L2/non-finite reductions fused into the snapshot-copy
  jit (``GS_NUMERICS=boundary|every_round``), resolved into gauges,
  ``numerics`` events, and a windowed drift signal gated by the
  precision-policy seam (``resilience.health.DriftGate``).
* :mod:`.xstats` — executable analytics per compile (``GS_XSTATS``):
  cost/memory analysis, HLO collective counts, compile wall time,
  persistent-compile-cache hit/miss, and the model-vs-measured
  step-time residual.

Hard contract (asserted in tier-1): obs on/off leaves trajectories
bitwise identical — every hook here observes host-side control flow and
never touches the jitted programs. All three modules are importable
without JAX (the watchdog and ``bench.py``'s jax-free parent both hook
in), resolve their output path from the environment exactly once
(process-wide singletons, ``.rank<N>``-suffixed in multi-process runs),
and degrade to no-ops when their knob is unset.
"""

from .events import (  # noqa: F401
    EventStream,
    get_events,
    parse_events,
    parse_events_multi,
)
from .metrics import Histogram, MetricsRegistry, get_metrics  # noqa: F401
from .numerics import NumericsRecorder, NumericsReport  # noqa: F401
from .trace import ProfileWindow, SpanTracer, get_tracer  # noqa: F401

__all__ = [
    "EventStream",
    "Histogram",
    "MetricsRegistry",
    "NumericsRecorder",
    "NumericsReport",
    "ProfileWindow",
    "SpanTracer",
    "get_events",
    "get_metrics",
    "get_tracer",
    "parse_events",
    "parse_events_multi",
]
