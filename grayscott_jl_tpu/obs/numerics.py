"""In-graph numerics probes: per-field statistics from inside the step.

The health guard (``resilience/health.py``) answers one question at
write boundaries — "is the field still finite?" — with a fused
isfinite+range reduction riding the snapshot-copy jit. This module
generalizes that seam into a continuous numerics telemetry baseline:
per-field **min / max / mean / L2 / non-finite-count** reductions fused
into the same device program, resolved host-side into gauges, a
``numerics`` record per probe on the unified event stream
(``GS_EVENTS``), and a windowed **drift** signal (relative change of
each statistic against a trailing reference window) whose trips land as
``drift`` records and route through the precision-policy gate
(``resilience.health.DriftGate`` — the hook ROADMAP item 1's
mixed-precision work gates on).

Knob (``GS_NUMERICS`` env / ``numerics`` TOML key):

``off`` (default)
    No probe is traced, no recorder is built — the driver's hot path
    pays one ``is not None`` check (zero allocations, asserted in
    tier-1, matching the PR-8 metrics contract).
``boundary``
    The probe is fused into the snapshot-copy jit at every
    output/checkpoint boundary — the fields are read from HBM once for
    copy, health, and numerics together; the scalars ride the
    boundary's existing D2H.
``every_round``
    A probe-only jitted reduction additionally runs after every fused
    step round (boundaries included), so rounds between write
    boundaries are covered too.

Hard contract (asserted in tier-1 for all four registered models):
arming the probe changes NOTHING about the trajectory or the stores —
the reductions only read the fields; bitwise identity on vs off.

Host-side pieces (resolver, reports, recorder, drift math) are stdlib
only and importable without JAX, like the rest of ``obs/``; only
:func:`device_numerics_probe` imports ``jax.numpy``, lazily, when
traced.
"""

from __future__ import annotations

import os
from collections import deque
from typing import Dict, List, Optional

__all__ = [
    "DRIFT_STATS",
    "MODES",
    "NULL_NUMERICS",
    "NumericsRecorder",
    "NumericsReport",
    "STATS",
    "device_numerics_probe",
    "resolve_numerics",
    "resolve_report",
    "resolve_window",
]

MODES = ("off", "boundary", "every_round")

#: Per-field statistics, in the order :func:`device_numerics_probe`
#: returns them (one group of scalars per field, declaration order).
STATS = ("min", "max", "mean", "l2", "nonfinite")

#: The statistics the drift signal tracks — ``nonfinite`` is excluded
#: (the health guard owns finiteness; a relative change of a count
#: that is almost always zero is not a meaningful ratio).
DRIFT_STATS = ("min", "max", "mean", "l2")


def resolve_numerics(settings=None) -> str:
    """``GS_NUMERICS`` env wins over the ``numerics`` TOML key; default
    ``off``. Unknown values raise at startup, mirroring
    ``health.resolve_policy``."""
    mode = os.environ.get("GS_NUMERICS")
    if mode is None and settings is not None:
        mode = getattr(settings, "numerics", "")
    mode = (mode or "off").lower()
    if mode not in MODES:
        raise ValueError(
            f"Unsupported numerics mode: {mode!r}. "
            f"Supported: {', '.join(MODES)}"
        )
    return mode


def resolve_window(default: int = 8) -> int:
    """Reference-window length for the drift signal
    (``GS_NUMERICS_WINDOW``, default 8 probes)."""
    raw = os.environ.get("GS_NUMERICS_WINDOW", "").strip()
    if not raw:
        return default
    try:
        w = int(raw)
    except ValueError as e:
        raise ValueError(
            f"GS_NUMERICS_WINDOW must be an integer, got {raw!r}"
        ) from e
    if w < 1:
        raise ValueError(f"GS_NUMERICS_WINDOW must be >= 1, got {w}")
    return w


def device_numerics_probe(*fields):
    """The fused device-side reduction: for each field, ``(min, max,
    mean, l2, nonfinite_count)`` as 0-d device arrays, flattened in
    declaration order. Traced inside the snapshot-copy jit
    (``Simulation.snapshot_async(numerics=True)``) or a probe-only jit
    (``Simulation.numerics_stats``) so XLA fuses the reductions with
    whatever else touches the fields — statistics are computed in
    float32 regardless of the field dtype, the accumulation width the
    future bf16 path needs. Statistics cover the stored (padded) grid,
    like the health probe."""
    import jax.numpy as jnp

    out = ()
    for f in fields:
        g = f.astype(jnp.float32)
        out += (
            g.min(),
            g.max(),
            g.mean(),
            jnp.sqrt((g * g).sum()),
            (~jnp.isfinite(g)).sum().astype(jnp.int32),
        )
    return out


def resolve_report(raw, names) -> "NumericsReport":
    """Host-resolve one probe's flat scalar tuple into a
    :class:`NumericsReport` (blocks only on the probe's few scalars)."""
    n = len(STATS)
    fields: Dict[str, dict] = {}
    for i, name in enumerate(names):
        vals = raw[i * n:(i + 1) * n]
        fields[name] = {
            "min": float(vals[0]),
            "max": float(vals[1]),
            "mean": float(vals[2]),
            "l2": float(vals[3]),
            "nonfinite": int(vals[4]),
        }
    return NumericsReport(fields)


class NumericsReport:
    """One probe's resolved per-field statistics.

    ``fields`` maps each model field name to its stats dict
    (:data:`STATS` keys). ``members``, when set (ensembles), holds one
    such mapping per member; ``fields`` then carries the cross-member
    aggregate (min of mins, max of maxs, mean of means, root of the
    summed squares, summed non-finite count) so single-run consumers —
    gauges, the drift window — read an ensemble report transparently,
    exactly like ``EnsembleHealthReport``.
    """

    def __init__(self, fields: Dict[str, dict],
                 members: Optional[List[Dict[str, dict]]] = None):
        self.fields = fields
        self.members = members

    @classmethod
    def aggregate_members(cls, members: List[Dict[str, dict]],
                          active=None) -> "NumericsReport":
        """Cross-member aggregate; ``members`` keeps every slot's rows
        for per-index attribution, while ``active`` (an optional bool
        mask) excludes IDLE pack slots (docs/SERVICE.md) from the
        aggregate statistics — padding must not perturb the drift
        signal real members are gated by."""
        live = (
            members if active is None or all(active)
            else [m for i, m in enumerate(members) if active[i]]
        )
        names = list(members[0])
        agg = {}
        for name in names:
            rows = [m[name] for m in live]
            agg[name] = {
                "min": min(r["min"] for r in rows),
                "max": max(r["max"] for r in rows),
                "mean": sum(r["mean"] for r in rows) / len(rows),
                "l2": sum(r["l2"] ** 2 for r in rows) ** 0.5,
                "nonfinite": sum(r["nonfinite"] for r in rows),
            }
        return cls(agg, members=members)

    @property
    def finite(self) -> bool:
        return all(r["nonfinite"] == 0 for r in self.fields.values())

    def describe(self) -> dict:
        out = {"fields": self.fields}
        if self.members is not None:
            out["members"] = self.members
        return out


class _NullNumericsRecorder:
    """Shared no-op recorder for ``GS_NUMERICS=off`` — the same
    zero-allocation off-switch shape as ``metrics.NULL_METRIC``."""

    __slots__ = ()
    enabled = False

    def observe(self, step, report, boundary=False) -> None:
        pass

    def describe(self) -> Optional[dict]:
        return None


NULL_NUMERICS = _NullNumericsRecorder()


class NumericsRecorder:
    """Boundary-time consumer of resolved probes: gauges, events, drift.

    Per probe it mirrors every field statistic into the metrics
    registry (``numerics_<stat>{field=...}`` gauges), appends one
    ``numerics`` record to the unified event stream, updates the
    trailing reference window, and exposes each statistic's **drift** —
    the bounded relative change vs the window mean (see
    :meth:`_drift`) over the last ``window`` probes — as
    ``numerics_drift{field,stat}`` gauges. Trips
    (any |drift| above the gate's limit) route through the
    :class:`~..resilience.health.DriftGate` and land as ``drift``
    events; the gate is the seam the future precision policy plugs
    into (ROADMAP item 1).
    """

    enabled = True

    def __init__(self, names, *, metrics=None, events=None, gate=None,
                 log=None, labels=None, window: Optional[int] = None,
                 journal=None):
        self.names = tuple(names)
        self.metrics = metrics
        self.events = events
        self.gate = gate
        self.log = log
        #: FaultJournal (``resilience/supervisor.py``): abort/rollback
        #: trips are journaled (the journal mirrors to the stream, so
        #: the record lands in both) before the DriftError unwinds —
        #: the same pre-raise journaling discipline the health guard
        #: follows in the driver.
        self.journal = journal
        self.labels = dict(labels or {})
        self.window = resolve_window() if window is None else int(window)
        self.probes = 0
        self.drift_trips = 0
        self.last: Optional[NumericsReport] = None
        self.max_drift: Dict[str, float] = {}
        self._hist: Dict[tuple, deque] = {}

    # ------------------------------------------------------------ drift

    def _drift(self, field: str, stat: str, value: float
               ) -> Optional[float]:
        """Bounded relative change of ``value`` vs the trailing
        window's mean: ``(value - ref) / max(|ref|, |value|)`` — 0.5
        means the statistic doubled, -0.5 that it halved, ±1 that it
        appeared from (or collapsed to) zero, beyond ±1 that it
        crossed sign (the bound is ±2) — instead of exploding when a
        near-zero statistic (a field minimum during pattern formation)
        moves by an epsilon. None until a reference exists; the
        current value joins the window AFTER the comparison, so the
        reference never includes the probe being judged."""
        key = (field, stat)
        hist = self._hist.get(key)
        if hist is None:
            hist = self._hist[key] = deque(maxlen=self.window)
        drift = None
        if hist:
            ref = sum(hist) / len(hist)
            drift = (value - ref) / max(abs(ref), abs(value), 1e-30)
        hist.append(value)
        return drift

    # ---------------------------------------------------------- observe

    def observe(self, step, report, boundary: bool = False) -> None:
        """Consume one resolved probe (a :class:`NumericsReport`)."""
        if report is None:
            return
        self.probes += 1
        self.last = report
        m = self.metrics
        drifts: Dict[str, float] = {}
        for field, stats in report.fields.items():
            if m is not None:
                for stat in STATS:
                    m.gauge(f"numerics_{stat}", field=field,
                            **self.labels).set(stats[stat])
            for stat in DRIFT_STATS:
                d = self._drift(field, stat, stats[stat])
                if d is None:
                    continue
                key = f"{field}.{stat}"
                drifts[key] = round(d, 9)
                prev = self.max_drift.get(key)
                if prev is None or abs(d) > abs(prev):
                    self.max_drift[key] = round(d, 9)
                if m is not None:
                    m.gauge("numerics_drift", field=field, stat=stat,
                            **self.labels).set(round(d, 9))
        if self.events is not None:
            self.events.emit(
                "numerics", phase="io" if boundary else "step_round",
                step=step, **report.describe(),
            )
        if self.gate is not None and drifts:
            event = self.gate.check(step, drifts)
            if event is not None:
                self.drift_trips += 1
                if self.log is not None:
                    tripped = event.get("tripped", {})
                    self.log.warn(
                        f"numerics drift at step {step}: "
                        + ", ".join(
                            f"{k}={v:+.3f}" for k, v in tripped.items()
                        )
                        + f" (|drift| > {event.get('limit')}, "
                        f"policy={event.get('policy')})"
                    )
                raising = getattr(self.gate, "raising", False)
                if raising and self.journal is not None:
                    # The journal mirrors onto the stream — exactly one
                    # drift record either way.
                    self.journal.record(event="drift", step=step,
                                        **event)
                elif self.events is not None:
                    self.events.emit("drift", step=step, **event)
                # abort/rollback unwind AFTER the trip is recorded:
                # the DriftError reuses the HealthGuard recovery
                # machinery via the supervisor's health classification
                # (docs/PRECISION.md).
                self.gate.enforce(step, event)

    # ----------------------------------------------------------- export

    def describe(self) -> dict:
        """The RunStats ``numerics`` section: probe count, the last
        per-field statistics, and each statistic's worst observed
        drift."""
        return {
            "probes": self.probes,
            "window": self.window,
            "drift_trips": self.drift_trips,
            "last": self.last.describe() if self.last else None,
            "max_drift": dict(self.max_drift),
        }
