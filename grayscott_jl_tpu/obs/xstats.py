"""Executable analytics: what XLA actually built, per compile.

The runtime telemetry (spans, metrics, events — PR 8) sees the run
from the host; this module captures the *compiler's* side of the story
at the moment each step runner is compiled: ``cost_analysis()`` flops
and bytes, ``memory_analysis()`` buffer sizes, the HLO collective
census (how many collective-permutes/all-reduces the schedule really
carries — the number the HLO regression tests and the icimodel
calibration loop reason about), compile wall time, and the persistent
compilation cache outcome (hit/miss) per executable. Records land in
three places at capture time: ``sim.executables`` (merged into the
RunStats ``executables`` section by the driver), one ``executable``
record on the unified event stream, and the
``compiles``/``compile_cache_hits``/``compile_cache_misses`` counters
plus a ``compile_s_total`` gauge in the metrics registry.

Knob: ``GS_XSTATS`` env / ``xstats`` TOML key (on/off, default off).
Capture is also armed implicitly whenever the persistent compilation
cache is (``GS_COMPILE_CACHE``) — the cache's hit/miss story should
never be invisible just because nobody asked for full analytics
(previously ``simulation._enable_compile_cache`` had no success-path
observability at all).

Contract: armed capture routes the runner through the same
``lower().compile()`` AOT path ``Simulation.compile_chunk`` already
uses — the identical program, so trajectories and stores stay bitwise
identical (asserted in tier-1 for all four models). Off costs one
``if`` per runner construction, nothing on the step path. Every
analytics query is best-effort: a jax whose AOT surface drifted
degrades to a partial record, never a failed run.

Module is importable without JAX (it only touches the compiled objects
handed to it), like the rest of ``obs/``.
"""

from __future__ import annotations

import os
import re
import time
from typing import Optional

__all__ = [
    "capture",
    "collective_counts",
    "cache_listing",
    "instrument_compile",
    "publish",
    "resolve_xstats",
    "summarize",
]

_TRUTHY = ("1", "on", "true", "yes")
_FALSY = ("0", "off", "false", "no", "")


def resolve_xstats(settings=None) -> bool:
    """``GS_XSTATS`` env wins over the ``xstats`` TOML key; default
    off. Unknown values raise at startup."""
    raw = os.environ.get("GS_XSTATS")
    if raw is None and settings is not None:
        raw = getattr(settings, "xstats", "")
    raw = (raw or "").strip().lower()
    if raw in _TRUTHY:
        return True
    if raw in _FALSY:
        return False
    raise ValueError(
        f"GS_XSTATS / xstats must be on or off, got {raw!r}"
    )


#: HLO instruction names that move data between devices — the census
#: the collective-count regression tests (test_overlap) key on.
_COLLECTIVE_RE = re.compile(
    r"\b(collective-permute|all-reduce|all-gather|all-to-all|"
    r"reduce-scatter)\b"
)


def collective_counts(hlo_text: str) -> dict:
    """Occurrences of each collective op family in an HLO dump.
    ``-start``/``-done`` async pairs count under their family (the
    family name is a prefix of both halves)."""
    counts: dict = {}
    for m in _COLLECTIVE_RE.finditer(hlo_text):
        counts[m.group(1)] = counts.get(m.group(1), 0) + 1
    return counts


def cache_listing(path: Optional[str]) -> Optional[frozenset]:
    """Entries of the persistent compile cache directory, or None when
    no cache is armed / the directory is unreadable."""
    if not path:
        return None
    try:
        return frozenset(os.listdir(path))
    except OSError:
        return None


#: cost_analysis keys worth keeping — the raw dict carries hundreds of
#: per-operand ``bytes accessedN{}`` entries that would bloat every
#: stats file.
_COST_KEYS = ("flops", "bytes accessed", "transcendentals",
              "optimal_seconds", "utilization")

#: memory_analysis attributes present across the jax versions we care
#: about (each read defensively — absence is recorded as absence).
_MEMORY_ATTRS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)


def capture(compiled, *, name: str, compile_s: float,
            cache_dir: Optional[str] = None,
            cache_before: Optional[frozenset] = None,
            extra: Optional[dict] = None) -> dict:
    """One executable's analytics record, from a ``jax`` AOT-compiled
    object. Every query is individually best-effort."""
    rec = {"name": name, "compile_s": round(compile_s, 6)}
    if extra:
        rec.update(extra)

    try:
        cost = compiled.cost_analysis()
        # Older jax returns a one-dict list (per partition), newer the
        # dict itself.
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if isinstance(cost, dict):
            rec["cost"] = {
                k.replace(" ", "_"): round(float(cost[k]), 3)
                for k in _COST_KEYS if k in cost
            }
    except Exception:  # noqa: BLE001 — optional AOT surface
        pass

    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            out = {}
            for attr in _MEMORY_ATTRS:
                v = getattr(mem, attr, None)
                if v is not None:
                    out[attr] = int(v)
            if out:
                # The operator-facing single number: everything the
                # executable holds live at once (args + outputs +
                # temps), the HBM envelope a capacity planner needs.
                peak = sum(
                    out.get(k, 0)
                    for k in ("argument_size_in_bytes",
                              "output_size_in_bytes",
                              "temp_size_in_bytes")
                )
                out["peak_bytes_estimate"] = peak
                rec["memory"] = out
    except Exception:  # noqa: BLE001
        pass

    try:
        rec["collectives"] = collective_counts(compiled.as_text())
    except Exception:  # noqa: BLE001
        pass

    if cache_dir is not None:
        after = cache_listing(cache_dir)
        if cache_before is None or after is None:
            rec["cache"] = "unknown"
        else:
            # A compile that wrote a new cache entry was a miss; one
            # that left the directory untouched was served from it.
            rec["cache"] = "miss" if after - cache_before else "hit"
    return rec


def publish(rec: dict, *, metrics=None, events=None) -> None:
    """Mirror one capture into the metrics registry and the unified
    event stream (both no-ops when their sinks are off)."""
    if events is not None:
        events.emit("executable", phase="compile", **rec)
    if metrics is None:
        return
    metrics.counter("compiles").inc()
    g = metrics.gauge("compile_s_last")
    g.set(rec.get("compile_s"))
    cache = rec.get("cache")
    if cache == "hit":
        metrics.counter("compile_cache_hits").inc()
    elif cache == "miss":
        metrics.counter("compile_cache_misses").inc()


def summarize(records) -> dict:
    """Aggregate view of a run's capture list — the header of the
    RunStats ``executables`` section."""
    records = list(records)
    cache = [r.get("cache") for r in records]
    return {
        "compiles": len(records),
        "compile_s_total": round(
            sum(r.get("compile_s", 0.0) for r in records), 6
        ),
        "compile_cache_hits": cache.count("hit"),
        "compile_cache_misses": cache.count("miss"),
    }


def instrument_compile(sim, fn, nsteps: int):
    """AOT-compile a runner with analytics capture.

    Returns the compiled executable (stored by the caller in place of
    the jit wrapper, exactly like ``Simulation.compile_chunk``), or the
    wrapper unchanged if anything about the instrumented path fails —
    capture must never take a run down.
    """
    import jax.numpy as jnp

    cache_dir = sim.compile_cache_dir
    before = cache_listing(cache_dir)
    try:
        t0 = time.perf_counter()
        lowered = fn.lower(
            *sim.fields, sim.base_key, jnp.int32(sim.step), sim.params
        )
        compiled = lowered.compile()
        compile_s = time.perf_counter() - t0
    except Exception as e:  # noqa: BLE001 — never break the run
        import sys

        print(
            f"gray-scott: warning: executable analytics capture failed "
            f"for the {nsteps}-step runner ({e}); running uninstrumented",
            file=sys.stderr,
        )
        return fn
    rec = capture(
        compiled, name=f"runner[{nsteps}]", compile_s=compile_s,
        cache_dir=cache_dir, cache_before=before,
        extra={"nsteps": nsteps,
               "kernel": sim.kernel_language,
               "model": sim.model.name},
    )
    sim.executables.append(rec)
    from .events import get_events
    from .metrics import get_metrics

    publish(rec, metrics=get_metrics(), events=get_events())
    return compiled
