"""Metrics registry: counters, gauges, ring-buffer histograms.

``GS_METRICS=path`` arms the process-wide registry; the driver and the
subsystems it owns (async writer, health guard, supervisor) register
instruments once at run start and touch them with plain ``inc`` /
``set`` / ``observe`` calls on the boundary path. Snapshots flush as
interval JSONL records (``metrics_interval_s`` TOML key /
``GS_METRICS_INTERVAL_S`` env; 0 = only at run end) and, for scrapers,
as a one-shot Prometheus text-exposition dump (``GS_METRICS_PROM``).

Off means *really* off: every constructor returns the shared
:data:`NULL_METRIC` singleton whose methods are no-ops — zero
allocations on the hot path (asserted in tier-1 with tracemalloc).

The histogram is a fixed-capacity ring buffer: percentiles (p50 / p95 /
p99, numpy-'linear' interpolation — asserted against numpy in tier-1)
are computed over the retained window while ``count`` / ``sum`` /
``min`` / ``max`` cover the full stream, so a week-long campaign's
step-latency tail stays O(capacity) in memory. stdlib only, importable
without JAX (``bench.py``'s jax-free parent and the benchmarks use
:func:`quantile` for their p50/p95/p99 rows).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from .trace import _proc_index, rank_path

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "NULL_METRIC",
    "get_metrics",
    "quantile",
    "reset_metrics",
    "resolve_interval_s",
]


def quantile(values: Sequence[float], q: float) -> float:
    """The ``q``-th percentile (0..100) of ``values`` with numpy's
    default 'linear' interpolation — the shared percentile math for the
    histogram and the bench p50/p95/p99 rows (kept numpy-free so the
    jax-free entry points can use it)."""
    if not values:
        raise ValueError("quantile of an empty sequence")
    if not 0.0 <= q <= 100.0:
        raise ValueError(f"quantile q must be in [0, 100], got {q}")
    vs = sorted(values)
    rank = (q / 100.0) * (len(vs) - 1)
    lo = int(rank)
    hi = min(lo + 1, len(vs) - 1)
    frac = rank - lo
    return vs[lo] * (1.0 - frac) + vs[hi] * frac


class _NullMetric:
    """The shared off-switch: one instance stands in for every counter,
    gauge, and histogram when metrics are disabled. All mutators are
    no-ops with no allocation."""

    __slots__ = ()

    def inc(self, n=1) -> None:
        pass

    def set(self, value) -> None:
        pass

    def observe(self, value) -> None:
        pass


NULL_METRIC = _NullMetric()


class Counter:
    """Monotone event count (restarts, steps, faults)."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def as_dict(self) -> dict:
        return {"name": self.name, "labels": self.labels,
                "value": self.value}


class Gauge:
    """Last-written value (queue depth, memory in use, field ranges)."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = labels
        self.value = None

    def set(self, value) -> None:
        self.value = value

    def as_dict(self) -> dict:
        return {"name": self.name, "labels": self.labels,
            "value": self.value}


class Histogram:
    """Ring-buffer distribution with streaming count/sum/min/max.

    ``observe`` is O(1): the newest sample overwrites the oldest once
    ``capacity`` is reached, so percentiles describe the trailing
    window (recent behavior — what a live operator wants) while the
    scalar aggregates describe the whole stream.
    """

    __slots__ = ("name", "labels", "capacity", "count", "total",
                 "vmin", "vmax", "_buf", "_idx")

    def __init__(self, name: str = "", labels: Optional[dict] = None,
                 capacity: int = 1024):
        if capacity <= 0:
            raise ValueError(f"histogram capacity must be > 0, got "
                             f"{capacity}")
        self.name = name
        self.labels = dict(labels or {})
        self.capacity = capacity
        self.count = 0
        self.total = 0.0
        self.vmin = None
        self.vmax = None
        self._buf: List[float] = []
        self._idx = 0

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.total += value
        if self.vmin is None or value < self.vmin:
            self.vmin = value
        if self.vmax is None or value > self.vmax:
            self.vmax = value
        if len(self._buf) < self.capacity:
            self._buf.append(value)
        else:
            self._buf[self._idx] = value
            self._idx = (self._idx + 1) % self.capacity

    @property
    def window(self) -> List[float]:
        """The retained samples (unordered; percentile input)."""
        return list(self._buf)

    def percentile(self, q: float) -> Optional[float]:
        if not self._buf:
            return None
        return quantile(self._buf, q)

    def summary(self) -> dict:
        out = {
            "count": self.count,
            "sum": round(self.total, 9),
            "min": self.vmin,
            "max": self.vmax,
            "mean": (round(self.total / self.count, 9)
                     if self.count else None),
            "window": len(self._buf),
        }
        for q in (50, 95, 99):
            p = self.percentile(q)
            out[f"p{q}"] = round(p, 9) if p is not None else None
        return out

    def as_dict(self) -> dict:
        return {"name": self.name, "labels": self.labels,
                **self.summary()}


def resolve_interval_s(settings=None) -> float:
    """Flush cadence: ``GS_METRICS_INTERVAL_S`` env wins over the
    ``metrics_interval_s`` TOML key; 0 (the default) flushes only at
    run end."""
    raw = os.environ.get("GS_METRICS_INTERVAL_S")
    if raw is None or raw.strip() == "":
        v = float(getattr(settings, "metrics_interval_s", 0.0) or 0.0)
    else:
        try:
            v = float(raw)
        except ValueError as e:
            raise ValueError(
                f"GS_METRICS_INTERVAL_S must be a number, got {raw!r}"
            ) from e
    if v < 0:
        raise ValueError(f"metrics interval must be >= 0, got {v}")
    return v


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    n = _PROM_BAD.sub("_", name)
    return n if not n[:1].isdigit() else f"_{n}"


def _prom_labels(labels: Dict[str, str], extra: str = "") -> str:
    parts = [f'{_PROM_BAD.sub("_", k)}="{v}"'
             for k, v in sorted(labels.items())]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class MetricsRegistry:
    """Get-or-create instrument registry with JSONL / Prometheus export.

    Instruments are keyed by ``(kind, name, labels)``; asking twice
    returns the same object, so subsystems can register independently
    without coordination. A disabled registry hands out
    :data:`NULL_METRIC` instead and never builds a table.
    """

    def __init__(self, path: Optional[str] = None,
                 interval_s: float = 0.0, proc: Optional[int] = None,
                 enabled: Optional[bool] = None):
        self.path = path
        self.interval_s = float(interval_s)
        self.proc = _proc_index() if proc is None else proc
        self.enabled = bool(path) if enabled is None else bool(enabled)
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple, object] = {}
        self._t0 = time.monotonic()
        self._last_flush = time.monotonic()
        self.flushes = 0

    # ------------------------------------------------------- instruments

    def _get(self, kind: str, cls, name: str, labels: dict,
             **kw):
        key = (kind, name, tuple(sorted(labels.items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = self._metrics[key] = cls(name, dict(labels), **kw)
        return m

    def counter(self, name: str, **labels):
        if not self.enabled:
            return NULL_METRIC
        return self._get("counter", Counter, name, labels)

    def gauge(self, name: str, **labels):
        if not self.enabled:
            return NULL_METRIC
        return self._get("gauge", Gauge, name, labels)

    def histogram(self, name: str, capacity: int = 1024, **labels):
        if not self.enabled:
            return NULL_METRIC
        return self._get("histogram", Histogram, name, labels,
                         capacity=capacity)

    # ------------------------------------------------------------ export

    def snapshot(self) -> dict:
        """JSON-able state of every registered instrument."""
        with self._lock:
            items = list(self._metrics.items())
        out = {"counters": [], "gauges": [], "histograms": []}
        for (kind, _, _), m in items:
            out[kind + "s"].append(m.as_dict())
        return out

    def due(self) -> bool:
        """Would :meth:`maybe_flush` write now?"""
        return (self.enabled and bool(self.path)
                and self.interval_s > 0
                and time.monotonic() - self._last_flush
                >= self.interval_s)

    def maybe_flush(self, force: bool = False,
                    on_flush=None) -> Optional[str]:
        """Append one interval snapshot record when due (or forced).

        ``on_flush`` runs just before the write — the driver's hook for
        refreshing expensive gauges (device memory stats) only when a
        record is actually about to land.
        """
        if not (self.enabled and self.path):
            return None
        if not force and not self.due():
            return None
        if on_flush is not None:
            on_flush()
        rec = {
            "ts": round(time.time(), 6),
            "uptime_s": round(time.monotonic() - self._t0, 6),
            "proc": self.proc,
            **self.snapshot(),
        }
        self._last_flush = time.monotonic()
        self.flushes += 1
        with open(self.path, "a", encoding="utf-8") as f:
            f.write(json.dumps(rec) + "\n")
            f.flush()
        return self.path

    def prometheus_text(self) -> str:
        """One-shot Prometheus text exposition of the current state:
        counters as ``counter``, gauges as ``gauge``, histograms as
        ``summary`` (quantile series + ``_count``/``_sum``)."""
        lines: List[str] = []
        snap = self.snapshot()
        for kind, prom_type in (("counters", "counter"),
                                ("gauges", "gauge")):
            seen = set()
            for m in snap[kind]:
                name = _prom_name(m["name"])
                if name not in seen:
                    lines.append(f"# TYPE {name} {prom_type}")
                    seen.add(name)
                v = m["value"]
                if v is None or isinstance(v, bool):
                    v = int(bool(v)) if isinstance(v, bool) else "NaN"
                lines.append(f"{name}{_prom_labels(m['labels'])} {v}")
        seen = set()
        for m in snap["histograms"]:
            name = _prom_name(m["name"])
            if name not in seen:
                lines.append(f"# TYPE {name} summary")
                seen.add(name)
            for q in (50, 95, 99):
                v = m.get(f"p{q}")
                if v is None:
                    continue
                qlabel = 'quantile="0.%d"' % q
                lines.append(
                    f"{name}{_prom_labels(m['labels'], qlabel)} {v}"
                )
            lines.append(
                f"{name}_count{_prom_labels(m['labels'])} {m['count']}"
            )
            lines.append(
                f"{name}_sum{_prom_labels(m['labels'])} {m['sum']}"
            )
        return "\n".join(lines) + "\n"

    def write_prometheus(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as f:
            f.write(self.prometheus_text())
        return path

    def describe(self) -> dict:
        with self._lock:
            n = len(self._metrics)
        return {"enabled": self.enabled, "path": self.path,
                "interval_s": self.interval_s, "instruments": n,
                "flushes": self.flushes}


_registry = None


def get_metrics(settings=None) -> MetricsRegistry:
    """The process-wide registry: armed when ``GS_METRICS`` names a
    path (``.rank<N>``-suffixed in multi-process runs), else a disabled
    registry whose instruments are the shared no-op. ``settings`` only
    matters on the first call (it resolves ``metrics_interval_s``)."""
    global _registry
    if _registry is None:
        path = os.environ.get("GS_METRICS", "").strip()
        _registry = MetricsRegistry(
            path=rank_path(path) if path else None,
            interval_s=resolve_interval_s(settings),
        )
    return _registry


def reset_metrics() -> None:
    """Drop the singleton (tests; re-resolves from env on next use)."""
    global _registry
    _registry = None
