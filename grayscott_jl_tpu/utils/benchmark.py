"""Shared single-chip measurement harness for bench.py and benchmarks/.

One implementation of the warmup + best-of-N timing loop and of the
axon-tunnel completion workaround, so the repo's reported numbers cannot
drift apart between entry points.
"""

from __future__ import annotations

import time
from typing import Dict


def setup_platform(cpu: bool, devices: int = 1) -> str:
    """Benchmark-script platform bring-up, shared by ``benchmarks/``.

    With ``cpu``: inject the virtual-device XLA flag (before any backend
    init) and pin the CPU platform via jax.config (the axon sitecustomize
    hook re-pins platforms after import, so the env var alone is not
    enough). Returns the Settings ``backend`` string for the platform.
    """
    import os

    if cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={devices}"
            ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax

    platform = jax.devices()[0].platform
    return {"tpu": "TPU", "cpu": "CPU", "gpu": "CUDA"}[platform]


def time_sim(sim, steps: int, rounds: int) -> float:
    """Best-of-``rounds`` seconds-per-step of ``steps`` fused simulation
    steps (after a compile-triggering warmup chunk).

    The ONLY timing loop in the repo — bench.py, benchmarks/sweep.py,
    halo_bench.py and weak_scaling.py all go through here so the
    completion workaround below cannot drift between entry points.
    """
    import jax.numpy as jnp

    def sync() -> float:
        # block_until_ready does not reliably block under the axon TPU
        # tunnel; a dependent scalar readback forces real completion.
        return float(jnp.sum(sim.u[:1, :1, :4]))

    sim.iterate(steps)  # warmup: trigger compile
    sync()
    best = float("inf")
    for _ in range(rounds):
        t0 = time.perf_counter()
        sim.iterate(steps)
        sync()
        best = min(best, time.perf_counter() - t0)
    return best / steps


def bench_one(
    L: int,
    precision: str,
    lang: str,
    *,
    noise: float = 0.1,
    steps: int = 100,
    rounds: int = 3,
) -> Dict[str, object]:
    """Best-of-``rounds`` throughput of ``steps`` fused simulation steps
    at grid side ``L`` on the default JAX backend (single device)."""
    import jax

    from ..config.settings import Settings
    from ..simulation import Simulation

    platform = jax.devices()[0].platform
    backend = {"tpu": "TPU", "cpu": "CPU", "gpu": "CUDA"}[platform]
    settings = Settings(
        L=L, Du=0.2, Dv=0.1, F=0.02, k=0.048, dt=1.0, noise=noise,
        precision=precision, backend=backend, kernel_language=lang,
    )
    sim = Simulation(settings, n_devices=1)
    per_step = time_sim(sim, steps, rounds)
    return {
        "L": L,
        "precision": precision,
        "kernel": lang,
        "noise": noise,
        "platform": platform,
        "us_per_step": round(per_step * 1e6, 1),
        "cell_updates_per_s": round(L**3 / per_step, 1),
    }
