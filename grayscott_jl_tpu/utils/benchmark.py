"""Shared single-chip measurement harness for bench.py and benchmarks/.

One implementation of the warmup + best-of-N timing loop and of the
axon-tunnel completion workaround, so the repo's reported numbers cannot
drift apart between entry points.
"""

from __future__ import annotations

import time
from typing import Dict


def _utc_stamp() -> str:
    """UTC ISO capture timestamp (mirrors ``benchmarks/artifacts.py``;
    duplicated so this module stays importable from the jax-free
    ``bench.py`` parent without a benchmarks/ path hack)."""
    import datetime

    return datetime.datetime.now(datetime.timezone.utc).isoformat(
        timespec="seconds"
    )


#: XLA flags that let the split-phase halo exchange actually overlap on
#: hardware (docs/OVERLAP.md): async collective-permute turns each
#: ppermute into a start/done pair, and the latency-hiding scheduler
#: moves the done past the comm-independent interior compute. TPU-only
#: flags — injecting them for a CPU backend just produces unknown-flag
#: warnings, so callers gate on the target platform.
OVERLAP_XLA_FLAGS = (
    "--xla_tpu_enable_latency_hiding_scheduler=true",
    "--xla_enable_async_collective_permute=true",
)


def inject_overlap_xla_flags() -> None:
    """Append :data:`OVERLAP_XLA_FLAGS` to ``XLA_FLAGS`` (idempotent:
    a flag whose name is already present — either spelling — is left
    alone so operator overrides win). Must run before the first backend
    initialization; later calls are harmless no-ops at the XLA level."""
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    add = [f for f in OVERLAP_XLA_FLAGS if f.split("=")[0] not in flags]
    if add:
        os.environ["XLA_FLAGS"] = " ".join([flags] + add).strip()


def setup_platform(cpu: bool, devices: int = 1) -> str:
    """Benchmark-script platform bring-up, shared by ``benchmarks/``.

    With ``cpu``: inject the virtual-device XLA flag (before any backend
    init) and pin the CPU platform via jax.config (the axon sitecustomize
    hook re-pins platforms after import, so the env var alone is not
    enough). Without ``cpu`` (an accelerator run) the split-phase
    overlap flags are injected too, unless ``GS_COMM_OVERLAP=off``.
    Returns the Settings ``backend`` string for the platform.
    """
    import os

    if cpu:
        flags = os.environ.get("XLA_FLAGS", "")
        if "host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count={devices}"
            ).strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        from ..config.env import env_str

        if env_str("GS_COMM_OVERLAP", "").strip().lower() not in (
            "off", "0", "false", "no"
        ):
            inject_overlap_xla_flags()
    import jax

    platform = jax.devices()[0].platform
    return {"tpu": "TPU", "cpu": "CPU", "gpu": "CUDA"}[platform]


def time_sim_rounds(
    sim, steps: int, rounds: int, sustain_seconds: float = 0.0,
    round_sleep: float = 0.0, deadline: float = None,
) -> Dict[str, object]:
    """Per-round seconds-per-step of ``steps`` fused simulation steps
    (after a compile-triggering warmup chunk), plus an optional
    fixed-duration "sustained" measurement.

    The ONLY timing loop in the repo — bench.py, benchmarks/sweep.py,
    halo_bench.py and weak_scaling.py all go through here so the
    completion workaround below cannot drift between entry points.

    The tunnel chip's clock wanders between throttled and fast states on
    a minutes timescale independently of load (BASELINE.md caveats;
    ~1.7x spread, and the r3 envelope probe measured HBM streaming
    itself varying ~3x), so a single best-of-N hides the spread AND
    samples only one clock state: callers should record ALL of
    ``rounds_s_per_step`` (chronological), ``best``, ``median``, and —
    when ``sustain_seconds`` > 0 — ``sustained`` (continuous
    back-to-back chunks for at least that long, the throttled
    steady-state number). ``round_sleep`` spaces the rounds out in
    wall-clock so they sample more clock states (fast windows appear
    opportunistically; idle time costs nothing on a shared chip).

    ``deadline`` (a ``time.monotonic()`` instant) is the autotuner's
    wall budget (``tune/measure.py``): rounds after the first stop
    being added once it passes, so one slow candidate cannot eat the
    whole tuning budget — the first round always completes, because a
    measurement with zero rounds is no measurement at all.
    """
    import statistics

    import jax.numpy as jnp

    from ..obs.metrics import Histogram

    def sync() -> float:
        # block_until_ready does not reliably block under the axon TPU
        # tunnel; a dependent scalar readback forces real completion.
        return float(jnp.sum(sim.u[:1, :1, :4]))

    # Execute-to-compile warmup: one untimed chunk triggers compile AND
    # pays the first-execution program-load cost. (An AOT-only warmup
    # via sim.compile_chunk was tried in r3: it shifts ~30 ms of
    # program-load into round 1, and the hoped-for post-idle fast burst
    # turned out to be an external clock lottery, not schedulable —
    # see BASELINE.md throttle notes.)
    sim.iterate(steps)
    sync()
    per_round = []
    for i in range(rounds):
        if i and deadline is not None and time.monotonic() >= deadline:
            break
        if i and round_sleep > 0:
            time.sleep(round_sleep)
        t0 = time.perf_counter()
        sim.iterate(steps)
        sync()
        per_round.append((time.perf_counter() - t0) / steps)
    # Step-latency distribution through the obs histogram (the same
    # percentile math the driver's step_latency_us metric reports), so
    # artifact rows carry the tail — the clock-throttle spread above —
    # not just best/median/mean.
    h = Histogram("round_s_per_step", capacity=max(len(per_round), 1))
    for s in per_round:
        h.observe(s)
    out: Dict[str, object] = {
        "rounds_s_per_step": per_round,
        "best": min(per_round),
        "median": statistics.median(per_round),
        "p50": h.percentile(50),
        "p95": h.percentile(95),
        "p99": h.percentile(99),
    }
    if sustain_seconds > 0:
        t0 = time.perf_counter()
        done = 0
        while time.perf_counter() - t0 < sustain_seconds:
            sim.iterate(steps)
            sync()
            done += steps
        out["sustained"] = (time.perf_counter() - t0) / done
    return out


def time_sim(sim, steps: int, rounds: int) -> float:
    """Best-of-``rounds`` seconds-per-step (compatibility wrapper around
    :func:`time_sim_rounds`)."""
    return time_sim_rounds(sim, steps, rounds)["best"]


def bench_one(
    L: int,
    precision: str,
    lang: str,
    *,
    noise: float = 0.1,
    steps: int = 100,
    rounds: int = 3,
    sustain_seconds: float = 0.0,
    round_sleep: float = 0.0,
    model: str = "grayscott",
) -> Dict[str, object]:
    """Throughput of ``steps``-step chunks at grid side ``L`` on the
    default JAX backend (single device): best / median over ``rounds``
    chronological rounds, plus a fixed-duration sustained row when
    ``sustain_seconds`` > 0 — all carried in the result so artifacts
    show the clock-throttle spread, not just the best window."""
    import jax

    from ..config.settings import Settings
    from ..simulation import Simulation

    platform = jax.devices()[0].platform
    backend = {"tpu": "TPU", "cpu": "CPU", "gpu": "CUDA"}[platform]
    settings = Settings(
        L=L, Du=0.2, Dv=0.1, F=0.02, k=0.048,
        dt=1.0 if model == "grayscott" else 0.05, noise=noise,
        precision=precision, backend=backend, kernel_language=lang,
    )
    settings.model = model
    sim = Simulation(settings, n_devices=1)
    t = time_sim_rounds(sim, steps, rounds, sustain_seconds=sustain_seconds,
                        round_sleep=round_sleep)
    from ..parallel import icimodel

    out = {
        # Capture timestamp (UTC ISO): the staleness anchor for the
        # last-good-TPU provenance scan (bench._last_tpu_provenance).
        # File mtimes are checkout times on a fresh clone — only a
        # stamp INSIDE the record survives the trip through git.
        "t": _utc_stamp(),
        "L": L,
        "precision": precision,
        "kernel": lang,
        "model": sim.model.name,
        "noise": noise,
        "platform": platform,
        "us_per_step": round(t["best"] * 1e6, 1),
        "cell_updates_per_s": round(L**3 / t["best"], 1),
        "rounds_us_per_step": [
            round(s * 1e6, 1) for s in t["rounds_s_per_step"]
        ],
        "median_us_per_step": round(t["median"] * 1e6, 1),
        "median_cell_updates_per_s": round(L**3 / t["median"], 1),
        # Step-latency percentiles over the chronological rounds (obs
        # histogram; see time_sim_rounds) — the tail a mean hides.
        "p50_us_per_step": round(t["p50"] * 1e6, 1),
        "p95_us_per_step": round(t["p95"] * 1e6, 1),
        "p99_us_per_step": round(t["p99"] * 1e6, 1),
        # Comm-exposure accounting (RunStats `comm` mirror): zero for
        # this single-device measurement, but carried so BENCH_r*
        # artifacts keep a uniform schema with sharded runs.
        "comm": icimodel.comm_report(sim),
    }
    if sim.kernel_language == "pallas":
        # Generated-kernel provenance (docs/KERNELGEN.md): every Pallas
        # measurement row names the generator contract that built its
        # kernel, so A/B artifacts can tell generator eras apart.
        from ..ops import kernelgen

        out["generated"] = True
        out["generator_version"] = kernelgen.GENERATOR_VERSION
    if sim.kernel_selection is not None:
        # Auto-dispatch runs (GS_BENCH_KERNEL=Auto) carry the tuner
        # provenance (RunStats `kernel_selection.autotune` mirror):
        # the artifact says whether its schedule was projected or
        # measured, and what the tuning cost.
        out["kernel_resolved"] = sim.kernel_language
        if sim.kernel_selection.get("autotune") is not None:
            out["autotune"] = sim.kernel_selection["autotune"]
    if "sustained" in t:
        out["sustained_us_per_step"] = round(t["sustained"] * 1e6, 1)
        out["sustained_cell_updates_per_s"] = round(
            L**3 / t["sustained"], 1
        )
    return out
