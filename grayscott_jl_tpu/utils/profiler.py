"""Tracing, timing, and run metrics.

The reference's entire observability story is one ``@time`` around the run
and commented-out PProf hooks (``gray-scott.jl:3-14``, SURVEY §5). Here:

* :class:`RunStats` — per-phase wall-clock accumulation (compute, output,
  checkpoint) with a structured JSON summary: cell-updates/s, per-phase
  totals, step counts. Written to ``GS_TPU_STATS`` (file path) and logged
  at verbose runs.
* :class:`trace` — ``jax.profiler`` device tracing, enabled with
  ``GS_TPU_PROFILE=<output-dir>``; view with TensorBoard/XProf or
  ``jax.profiler`` tooling.
"""

from __future__ import annotations

import contextlib
import json
import os

from ..config.env import env_raw
import time
from typing import Dict, Optional


class RunStats:
    """Accumulates per-phase timings and counters for one simulation run.

    Phases are host-side wall clock: JAX dispatch is asynchronous, so
    device compute launched in a "compute" phase may overlap and complete
    inside the next blocking phase (device_to_host / end-of-run sync).
    Total wall time and cell-updates/s are exact; use ``GS_TPU_PROFILE``
    device traces for per-op attribution.
    """

    def __init__(self, L: int, config: Optional[dict] = None,
                 tracer=None):
        self.L = L
        #: Span tracer (``obs/trace.py``): every :meth:`phase` context
        #: doubles as a trace span, so the timings RunStats was already
        #: measuring appear on the Chrome-trace timeline for free. None
        #: (or the null tracer) keeps the historical zero-cost path.
        self.tracer = tracer
        #: Static run configuration echoed into the summary (mesh dims,
        #: kernel language, chain depth, ...) so a pod operator can
        #: correlate a stats file with the layout that produced it
        #: without reconstructing the launch environment.
        self.config = dict(config or {})
        self.phases: Dict[str, float] = {}
        self.counters: Dict[str, int] = {}
        #: Async-output overlap accounting (``io/async_writer.py``):
        #: per-phase ``hidden_s`` (I/O that ran behind compute) vs
        #: ``exposed_s`` (driver-blocked), plus queue-depth high-water
        #: mark — how much I/O wall time the pipeline actually hid.
        self.io: Optional[dict] = None
        #: Fault/recovery events (``resilience/supervisor.FaultJournal``):
        #: every injected fault, health trip, and supervisor recovery
        #: action of the whole supervised run — the completing attempt
        #: merges the journal here, so one stats file tells the full
        #: story of how the run survived.
        self.faults: Optional[list] = None
        #: Hang-watchdog provenance (``resilience/watchdog.py``):
        #: armed/disabled, the per-phase deadlines in force, heartbeat
        #: count, and the expiry (phase/step) if the run hung — so a
        #: stats reader can tell "finished clean" from "finished after
        #: a watchdog-recovered wedge" without the journal.
        self.watchdog: Optional[dict] = None
        #: Halo-exchange budget (``parallel/icimodel.comm_report``):
        #: model-projected per-step ``hidden_us``/``exposed_us`` under
        #: the run's split-phase setting — the comm analog of the
        #: ``io`` overlap section (how much ICI time the split-phase
        #: exchange hides behind interior compute).
        self.comm: Optional[dict] = None
        #: Metrics snapshot (``obs/metrics.py``): the registered
        #: counters/gauges/histograms at run end — step-latency
        #: percentiles, queue depths, restart counts — so the stats
        #: file carries the same numbers a scraper would have seen.
        self.metrics: Optional[dict] = None
        #: Observability provenance (``obs/``): which sinks were armed
        #: (trace path + event/span counts, event-stream path, metrics
        #: path/interval) — a stats reader can find the companion files.
        self.obs: Optional[dict] = None
        #: Executable analytics (``obs/xstats.py``): per-compile cost /
        #: memory / collective-count records with compile wall time and
        #: the persistent-cache outcome, plus the model-vs-measured
        #: projection residual — the compiler's side of the run story.
        self.executables: Optional[dict] = None
        #: In-graph numerics telemetry (``obs/numerics.py``): probe
        #: count, the last per-field statistics, and each statistic's
        #: worst windowed drift — the baseline the precision policy
        #: (ROADMAP item 1) will gate against.
        self.numerics: Optional[dict] = None
        #: Per-member ensemble section (``ensemble/``, docs/ENSEMBLE.md):
        #: member params + seeds, the member-axis mesh split, and the
        #: latest per-member health probe — one stats file tells which
        #: member of a sweep did what. Also scales the
        #: ``cell_updates_per_s`` summary to the AGGREGATE across
        #: members (the number an ensemble run is judged by).
        self.ensemble: Optional[dict] = None
        self._t0 = time.perf_counter()

    @contextlib.contextmanager
    def phase(self, name: str, step: Optional[int] = None):
        tr = self.tracer
        if tr is not None and tr.enabled:
            with tr.span(name, phase=name, step=step):
                t = time.perf_counter()
                try:
                    yield
                finally:
                    self.phases[name] = self.phases.get(name, 0.0) + (
                        time.perf_counter() - t
                    )
            return
        t = time.perf_counter()
        try:
            yield
        finally:
            self.phases[name] = self.phases.get(name, 0.0) + (
                time.perf_counter() - t
            )

    def count(self, name: str, n: int = 1) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def record_io(self, overlap: Optional[dict]) -> None:
        """Attach the async-writer overlap stats
        (``AsyncStepWriter.overlap_stats()``) to the summary."""
        self.io = dict(overlap) if overlap else None

    def record_faults(self, events: Optional[list]) -> None:
        """Attach the run's fault journal (injected faults, health
        trips, recovery actions) to the summary."""
        self.faults = [dict(e) for e in events] if events else None

    def record_watchdog(self, info: Optional[dict]) -> None:
        """Attach the hang-watchdog provenance
        (``Watchdog.describe()``, or ``{"enabled": False}``)."""
        self.watchdog = dict(info) if info else None

    def record_comm(self, report: Optional[dict]) -> None:
        """Attach the halo-exchange budget
        (``parallel/icimodel.comm_report``) to the summary."""
        self.comm = dict(report) if report else None

    def record_metrics(self, snapshot: Optional[dict]) -> None:
        """Attach the end-of-run metrics snapshot
        (``MetricsRegistry.snapshot()``) to the summary."""
        self.metrics = dict(snapshot) if snapshot else None

    def record_obs(self, info: Optional[dict]) -> None:
        """Attach the observability-sink provenance (trace / events /
        metrics ``describe()`` dicts) to the summary."""
        self.obs = dict(info) if info else None

    def record_executables(self, info: Optional[dict]) -> None:
        """Attach the executable-analytics section (``xstats.summarize``
        header + per-compile records + projection residual)."""
        self.executables = dict(info) if info else None

    def record_numerics(self, info: Optional[dict]) -> None:
        """Attach the numerics-telemetry section
        (``NumericsRecorder.describe()``)."""
        self.numerics = dict(info) if info else None

    def record_ensemble(self, info: Optional[dict]) -> None:
        """Attach the per-member ensemble section
        (``EnsembleSettings.describe()`` + resolved seeds)."""
        self.ensemble = dict(info) if info else None

    def record_member_health(self, step: int, report) -> None:
        """Record the latest per-member health probe (an
        ``EnsembleHealthReport``) into the ensemble section — the
        last-probed ranges plus which members (if any) went
        non-finite, keyed by the boundary step."""
        if self.ensemble is None:
            self.ensemble = {}
        self.ensemble["health"] = {
            "step": step,
            **report.describe(),
            "member_reports": [m.describe() for m in report.members],
        }

    def summary(self) -> dict:
        total = time.perf_counter() - self._t0
        steps = self.counters.get("steps", 0)
        compute = self.phases.get("compute", total)
        # ACTIVE members only: idle pack slots (docs/SERVICE.md) ride
        # in the vmapped launch but do no work anyone asked for — the
        # aggregate throughput must not credit padding.
        members = (
            int(self.ensemble.get(
                "active_members", self.ensemble.get("members", 1)
            ))
            if self.ensemble else 1
        )
        return {
            "L": self.L,
            # Nested under one key so caller-supplied names can never
            # collide with (and silently clobber) the built-in fields.
            "config": dict(self.config),
            "steps": steps,
            "wall_s": round(total, 6),
            "phases_s": {k: round(v, 6) for k, v in self.phases.items()},
            "io": self.io,
            "comm": self.comm,
            "watchdog": self.watchdog,
            "faults": self.faults,
            "metrics": self.metrics,
            "obs": self.obs,
            "executables": self.executables,
            "numerics": self.numerics,
            "ensemble": self.ensemble,
            "counters": dict(self.counters),
            # Aggregate across ensemble members (members == 1 solo).
            "cell_updates_per_s": (
                round(self.L**3 * steps * members / compute, 3)
                if compute > 0 else None
            ),
        }

    def maybe_write(self) -> Optional[str]:
        """Write the summary where ``GS_TPU_STATS`` points (if set).

        In a multi-process run each rank records its own local timings;
        the path gets a ``.rank<N>`` suffix so ranks don't clobber each
        other's file.
        """
        path = env_raw("GS_TPU_STATS")
        if not path:
            return None
        import jax

        if jax.process_count() > 1:
            path = f"{path}.rank{jax.process_index()}"
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.summary(), f)
            f.write("\n")
        return path


@contextlib.contextmanager
def trace():
    """``jax.profiler`` trace of the run when ``GS_TPU_PROFILE`` is set."""
    out = env_raw("GS_TPU_PROFILE")
    if not out:
        yield
        return
    import jax

    jax.profiler.start_trace(out)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
