"""Minimal structured logging for the driver.

The reference gates rank-0 ``println`` on ``settings.verbose``
(``src/GrayScott.jl:88-91``); here only JAX process 0 logs ``info``, so
multi-host runs keep single-writer output. ``warn`` prints on every
rank regardless of ``verbose`` — a health trip on rank 3 must not be
invisible just because rank 3 is quiet.

``GS_LOG_FORMAT=json`` switches every line to one JSON object
(``{"ts", "t_rel_s", "level", "proc", "msg"}``) for log aggregators;
the default ``text`` keeps the historical ``[gray-scott +N.NNNs]``
prefix. The process-index lookup is resolved once and cached (it is
stable after ``jax.distributed`` init) instead of re-importing jax on
every log call.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

from ..config.env import env_str

#: Cached "is this process rank 0" answer. Before JAX initializes the
#: answer could change (a later ``jax.distributed.initialize`` assigns
#: ranks), so the pre-init True is NOT cached — only a successful
#: ``jax.process_index()`` result is.
_primary: Optional[bool] = None


def _is_primary() -> bool:
    global _primary
    if _primary is None:
        try:
            import jax

            _primary = jax.process_index() == 0
        except Exception:  # pragma: no cover — before/without jax init
            return True
    return _primary


def _proc_index() -> int:
    """Rank for the JSON records; 0 before/without jax (never forces a
    backend init — mirrors ``FaultJournal.from_env``)."""
    if "jax" in sys.modules:
        try:
            import jax

            return jax.process_index()
        except Exception:  # noqa: BLE001
            return 0
    return 0


LOG_FORMATS = ("text", "json")


class Logger:
    def __init__(self, verbose: bool = False, stream=None,
                 fmt: Optional[str] = None):
        self.verbose = verbose
        self.stream = stream or sys.stdout
        if fmt is None:
            fmt = env_str("GS_LOG_FORMAT", "text")
        fmt = (fmt or "text").strip().lower()
        if fmt not in LOG_FORMATS:
            raise ValueError(
                f"GS_LOG_FORMAT must be one of "
                f"{'|'.join(LOG_FORMATS)}, got {fmt!r}"
            )
        self.fmt = fmt
        self._t0 = time.perf_counter()

    def _emit(self, level: str, msg: str) -> None:
        dt = time.perf_counter() - self._t0
        if self.fmt == "json":
            print(
                json.dumps({
                    "ts": round(time.time(), 3),
                    "t_rel_s": round(dt, 3),
                    "level": level,
                    "proc": _proc_index(),
                    "msg": msg,
                }),
                file=self.stream, flush=True,
            )
        else:
            tag = "" if level == "info" else f" {level.upper()}:"
            print(f"[gray-scott +{dt:9.3f}s]{tag} {msg}",
                  file=self.stream, flush=True)

    def info(self, msg: str) -> None:
        if self.verbose and _is_primary():
            self._emit("info", msg)

    def warn(self, msg: str) -> None:
        """Always printed — warnings ignore ``verbose`` and the
        primary-rank gate (attribution rides in the JSON ``proc``
        field; in text mode duplicates across ranks are the cost of
        never losing one)."""
        self._emit("warn", msg)
