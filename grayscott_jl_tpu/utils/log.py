"""Minimal structured logging for the driver.

The reference gates rank-0 ``println`` on ``settings.verbose``
(``src/GrayScott.jl:88-91``); here only JAX process 0 logs, so multi-host
runs keep single-writer output.
"""

from __future__ import annotations

import sys
import time


def _is_primary() -> bool:
    try:
        import jax

        return jax.process_index() == 0
    except Exception:  # pragma: no cover — before/without jax init
        return True


class Logger:
    def __init__(self, verbose: bool = False, stream=None):
        self.verbose = verbose
        self.stream = stream or sys.stdout
        self._t0 = time.perf_counter()

    def info(self, msg: str) -> None:
        if self.verbose and _is_primary():
            dt = time.perf_counter() - self._t0
            print(f"[gray-scott +{dt:9.3f}s] {msg}", file=self.stream, flush=True)
