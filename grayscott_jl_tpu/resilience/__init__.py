"""Resilience subsystem: supervised runs that survive their failures.

Five cooperating parts (see docs/RESILIENCE.md for the operator view):

* :mod:`.faults` — a deterministic, replayable fault-injection plan
  (``GS_FAULTS``): transient I/O errors, NaN poisoning, preemption,
  Pallas kernel failure, driver hangs — each fired once at a chosen
  step — plus the preemption-aware graceful-shutdown pieces
  (``ShutdownListener``, ``GracefulShutdown``, the distinct
  ``EXIT_PREEMPTED``/``EXIT_HANG`` process exit codes);
* :mod:`.health` — a fused device-side ``isfinite``/range probe on the
  snapshot path with an ``abort`` / ``rollback`` / ``warn`` policy
  (``GS_HEALTH_POLICY``);
* :mod:`.watchdog` — per-phase deadlines over driver heartbeats
  (``GS_WATCHDOG*``): on expiry, all-thread stack dump into the
  journal, a classified transient ``hang`` teardown, and (for C-level
  wedges) a hard exit the next launch auto-resumes from;
* :mod:`.rendezvous` — multi-host restart consensus: cluster-wide
  attempt counter (max) and checkpoint quorum (min latest-durable
  step), over the JAX coordination-service KV or a shared directory;
* :mod:`.supervisor` — ``supervise(settings)`` wraps
  ``driver.run_once`` with failure classification, exponential backoff
  with deterministic jitter, (quorum) checkpoint auto-resume,
  Pallas->XLA degradation, and a durable JSONL fault journal merged
  into ``RunStats``.
"""

from .faults import (  # noqa: F401
    EXIT_HANG,
    EXIT_PREEMPTED,
    FAULT_KINDS,
    Fault,
    FaultPlan,
    GracefulShutdown,
    InjectedIOError,
    InjectedKernelError,
    PreemptionError,
    ShutdownListener,
    injected_hang_wait,
)
from .health import (  # noqa: F401
    HealthError,
    HealthGuard,
    HealthReport,
    resolve_policy,
)
from .supervisor import (  # noqa: F401
    FaultJournal,
    SupervisorContext,
    classify_failure,
    latest_durable_checkpoint,
    resume_marker,
    supervise,
    supervision_enabled,
)
from .watchdog import (  # noqa: F401
    HangError,
    Watchdog,
    resolve_watchdog,
)
