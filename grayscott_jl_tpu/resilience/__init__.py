"""Resilience subsystem: supervised runs that survive their failures.

Three cooperating parts (see docs/RESILIENCE.md for the operator view):

* :mod:`.faults` — a deterministic, replayable fault-injection plan
  (``GS_FAULTS``): transient I/O errors, NaN poisoning, preemption,
  Pallas kernel failure, each fired once at a chosen step;
* :mod:`.health` — a fused device-side ``isfinite``/range probe on the
  snapshot path with an ``abort`` / ``rollback`` / ``warn`` policy
  (``GS_HEALTH_POLICY``);
* :mod:`.supervisor` — ``supervise(settings)`` wraps
  ``driver.run_once`` with failure classification, exponential backoff
  with deterministic jitter, checkpoint auto-resume, Pallas->XLA
  degradation, and a JSONL fault journal merged into ``RunStats``.
"""

from .faults import (  # noqa: F401
    FAULT_KINDS,
    Fault,
    FaultPlan,
    InjectedIOError,
    InjectedKernelError,
    PreemptionError,
)
from .health import (  # noqa: F401
    HealthError,
    HealthGuard,
    HealthReport,
    resolve_policy,
)
from .supervisor import (  # noqa: F401
    FaultJournal,
    SupervisorContext,
    classify_failure,
    latest_durable_checkpoint,
    supervise,
    supervision_enabled,
)
