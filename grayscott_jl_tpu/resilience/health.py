"""Device-side field health guard.

A NaN blow-up (too-large ``dt``, bad parameter region, or a kernel
regression) silently corrupts every output step written after it; on a
long campaign that is hours of wasted accelerator time plus a poisoned
store. The guard is a cheap fused reduction — ``isfinite`` AND-reduce
plus min/max of both fields — evaluated on the *snapshot path* at
plot/checkpoint boundaries (``Simulation.snapshot_async(health=True)``
fuses it into the same jitted program as the snapshot's device copy, so
the scalars ride the boundary's existing D2H and no extra HBM pass is
spent between boundaries).

The probe family sharing that fused pass has three members: this
health probe (semantic validity — finite, in range), the numerics
recorder (``obs/numerics.py`` — statistics and drift), and the
integrity checksum (``resilience/integrity.device_field_checksum`` —
bit-level identity of the bytes bound for the stores, armed by
``GS_CKPT_VERIFY=full``). Health answers "is the trajectory sane",
integrity answers "are these the same bits the device computed";
a bitflip the checksum catches may be perfectly finite and in range.

Policy (``GS_HEALTH_POLICY`` / ``health_policy`` TOML key):

``abort`` (default)
    Raise :class:`HealthError` at the boundary — the poisoned step is
    never written, the run stops loudly.
``rollback``
    Raise :class:`HealthError` classified for the supervisor
    (``resilience/supervisor.py``): under ``GS_SUPERVISE`` the run
    resumes from the latest durable checkpoint instead of dying.
``warn``
    Log and record the event, keep running (the reference's implicit
    behavior, made visible).
``off``
    No probe is evaluated at all.
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional

__all__ = [
    "DRIFT_POLICIES",
    "POLICIES",
    "DriftError",
    "DriftGate",
    "EnsembleHealthReport",
    "HealthError",
    "HealthGuard",
    "HealthReport",
    "device_probe",
    "resolve_policy",
]

POLICIES = ("abort", "rollback", "warn", "off")

DRIFT_POLICIES = ("warn", "abort", "rollback", "off")


class DriftGate:
    """Policy gate over the windowed numerics-drift signal
    (``obs/numerics.py``) — the health gate for precision drift
    (ROADMAP item 1, docs/PRECISION.md): the ``bf16_f32acc`` posture
    changes the rounding of every accumulation, and this gate is where
    a run whose statistics walk away from the f32 reference window
    stops being a silent wrong answer.

    Policies (``GS_DRIFT_POLICY``), reusing the HealthGuard action
    vocabulary one-for-one:

    ``warn`` (default)
        Trips are logged, land as ``drift`` events on the unified
        stream (carrying the acting policy), and count in the RunStats
        ``numerics`` section.
    ``abort``
        Raise :class:`DriftError` at the probe — the run stops loudly
        before more drifted steps reach the stores (the supervisor
        does NOT restart an abort, exactly like a health abort).
    ``rollback``
        Raise :class:`DriftError` classified for the supervisor
        (``resilience/supervisor.py`` maps it through the same
        ``health`` taxonomy slot): under ``GS_SUPERVISE`` the run
        resumes from the latest durable checkpoint.
    ``off``
        No gating (the drift gauges still export).

    ``GS_DRIFT_LIMIT`` (default 0.5) is the relative-change trip
    threshold.
    """

    def __init__(self, policy: str = "warn", limit: float = 0.5):
        if policy not in DRIFT_POLICIES:
            raise ValueError(
                f"Unsupported drift policy: {policy!r}. "
                f"Supported: {', '.join(DRIFT_POLICIES)}"
            )
        if limit <= 0:
            raise ValueError(f"drift limit must be > 0, got {limit}")
        self.policy = policy
        self.limit = float(limit)

    @classmethod
    def from_env(cls, settings=None) -> "DriftGate":
        policy = (os.environ.get("GS_DRIFT_POLICY") or "warn").lower()
        raw = os.environ.get("GS_DRIFT_LIMIT", "").strip()
        try:
            limit = float(raw) if raw else 0.5
        except ValueError as e:
            raise ValueError(
                f"GS_DRIFT_LIMIT must be a number, got {raw!r}"
            ) from e
        return cls(policy, limit)

    @property
    def raising(self) -> bool:
        """Does a trip unwind the run (abort/rollback) rather than
        merely record?"""
        return self.policy in ("abort", "rollback")

    def check(self, step: int, drifts: dict) -> Optional[dict]:
        """Judge one probe's per-statistic drifts (``"field.stat" ->
        relative change``). Returns an event-able dict when any
        statistic exceeds the limit under an active policy, else
        None. The caller (``obs/numerics.NumericsRecorder``) records
        the event and then calls :meth:`enforce` so the trip is on the
        stream BEFORE an abort/rollback unwinds."""
        if self.policy == "off":
            return None
        tripped = {
            k: v for k, v in drifts.items() if abs(v) > self.limit
        }
        if not tripped:
            return None
        return {
            "policy": self.policy,
            "limit": self.limit,
            "tripped": tripped,
        }

    def enforce(self, step: int, event: dict) -> None:
        """Act on a tripped check: raise :class:`DriftError` under
        abort/rollback (the HealthGuard action reuse), no-op under
        warn."""
        if event is not None and self.raising:
            raise DriftError(step, event, self.policy)


class HealthReport:
    """Resolved (host-side) probe result for one boundary.

    Model-generic: carries one ``(min, max)`` range per model field,
    with the model's field names for attribution. The historical
    positional form ``HealthReport(finite, u_min, u_max, v_min,
    v_max)`` still constructs (names default to ``("u", "v")``), and
    the ``u_min``/``u_max``/``v_min``/``v_max`` accessors keep reading
    fields 0/1 — so two-field consumers and tests are unchanged.
    """

    def __init__(self, finite, *minmax, names=None, ranges=None):
        self.finite = bool(finite)
        if ranges is None:
            if len(minmax) % 2:
                raise ValueError(
                    "HealthReport needs (min, max) pairs per field"
                )
            ranges = tuple(
                (float(minmax[i]), float(minmax[i + 1]))
                for i in range(0, len(minmax), 2)
            )
        self.ranges = tuple(
            (float(lo), float(hi)) for lo, hi in ranges
        )
        if names is None:
            names = ("u", "v")[: len(self.ranges)]
            if len(names) < len(self.ranges):
                names = tuple(
                    f"f{i}" for i in range(len(self.ranges))
                )
        self.names = tuple(names)

    # Two-field accessors (Gray-Scott-era call sites and log lines).
    @property
    def u_min(self) -> float:
        return self.ranges[0][0]

    @property
    def u_max(self) -> float:
        return self.ranges[0][1]

    @property
    def v_min(self) -> float:
        return self.ranges[1][0]

    @property
    def v_max(self) -> float:
        return self.ranges[1][1]

    def range_summary(self) -> str:
        return ", ".join(
            f"{n} in [{lo}, {hi}]"
            for n, (lo, hi) in zip(self.names, self.ranges)
        )

    def describe(self) -> dict:
        return {
            "finite": self.finite,
            **{
                f"{n}_range": [lo, hi]
                for n, (lo, hi) in zip(self.names, self.ranges)
            },
        }


class EnsembleHealthReport(NamedTuple):
    """Per-member probe results for an ensemble boundary.

    The fused probe runs vmapped over the member axis
    (``EnsembleSimulation._probe_fn``), so each member's
    :class:`HealthReport` is individually resolved — the point of the
    exercise: ONE diverging member is attributed by index
    (:attr:`bad_members`) in the health report, the ``HealthError``
    message, and the FaultJournal event, instead of anonymously
    aborting a 64-member sweep.

    ``active`` masks IDLE pack slots (``serve/scheduler.py`` pads a
    partially-filled batch; docs/SERVICE.md): an idle slot's probe
    result never pollutes the aggregate verdict, the ranges, or the
    bad-member attribution — a padded member blowing up is a
    non-event, a real member blowing up still names its index. None
    (the solo-ensemble default) means every slot is real.
    """

    members: tuple  # of HealthReport
    active: Optional[tuple] = None  # of bool, None = all active

    def _active(self, i: int) -> bool:
        return self.active is None or bool(self.active[i])

    @property
    def active_members(self) -> list:
        return [m for i, m in enumerate(self.members)
                if self._active(i)]

    @property
    def finite(self) -> bool:
        return all(m.finite for m in self.active_members)

    @property
    def bad_members(self) -> list:
        return [i for i, m in enumerate(self.members)
                if self._active(i) and not m.finite]

    # Aggregate ranges so single-report consumers (log lines, the
    # HealthError message core) read an ensemble report transparently.
    @property
    def names(self) -> tuple:
        return self.members[0].names

    @property
    def ranges(self) -> tuple:
        live = self.active_members
        return tuple(
            (
                min(m.ranges[i][0] for m in live),
                max(m.ranges[i][1] for m in live),
            )
            for i in range(len(self.members[0].ranges))
        )

    @property
    def u_min(self) -> float:
        return min(m.u_min for m in self.active_members)

    @property
    def u_max(self) -> float:
        return max(m.u_max for m in self.active_members)

    @property
    def v_min(self) -> float:
        return min(m.v_min for m in self.active_members)

    @property
    def v_max(self) -> float:
        return max(m.v_max for m in self.active_members)

    def range_summary(self) -> str:
        return ", ".join(
            f"{n} in [{lo}, {hi}]"
            for n, (lo, hi) in zip(self.names, self.ranges)
        )

    def describe(self) -> dict:
        out = {
            "finite": self.finite,
            "members": len(self.members),
            "bad_members": self.bad_members,
            **{
                f"{n}_range": [lo, hi]
                for n, (lo, hi) in zip(self.names, self.ranges)
            },
        }
        if self.active is not None and not all(self.active):
            out["active_members"] = len(self.active_members)
        return out


class HealthError(RuntimeError):
    """A field failed the health check at a boundary."""

    def __init__(self, step: int, report, policy: str):
        detail = ""
        bad = getattr(report, "bad_members", None)
        if bad is not None:
            detail = f"; non-finite members={bad}"
        super().__init__(
            f"field health check failed at step {step} "
            f"(finite={report.finite}, {report.range_summary()}"
            f"{detail}); policy={policy}"
        )
        self.step = step
        self.report = report
        self.policy = policy


class DriftError(HealthError):
    """The numerics-drift gate tripped under an abort/rollback policy.

    Subclasses :class:`HealthError` so the supervisor's existing
    classification applies unchanged
    (``resilience/supervisor.classify_failure``): ``rollback`` maps to
    the recoverable ``health`` taxonomy slot (resume from the latest
    durable checkpoint), ``abort`` stays unclassified and the run dies
    loudly — the precision-drift gate literally reuses the HealthGuard
    recovery machinery (docs/PRECISION.md)."""

    def __init__(self, step: int, event: dict, policy: str):
        tripped = event.get("tripped", {})
        RuntimeError.__init__(
            self,
            f"numerics drift gate tripped at step {step}: "
            + ", ".join(f"{k}={v:+.3f}" for k, v in tripped.items())
            + f" (|drift| > {event.get('limit')}); policy={policy}"
        )
        self.step = step
        self.report = None
        self.event = dict(event)
        self.policy = policy


def device_probe(*fields):
    """The fused device-side reduction, model-generic: ``(finite,
    min_0, max_0, ..., min_n, max_n)`` as 0-d device arrays — one
    (min, max) pair per model field in declaration order. Traced
    inside the snapshot-copy jit (``Simulation.snapshot_async``) so
    XLA fuses it with the copy's HBM read — the fields are touched
    once for both."""
    import functools

    import jax.numpy as jnp

    finite = functools.reduce(
        lambda a, b: a & b, (jnp.isfinite(f).all() for f in fields)
    )
    out = (finite,)
    for f in fields:
        out += (f.min(), f.max())
    return out


def resolve_policy(settings=None) -> str:
    """``GS_HEALTH_POLICY`` env, else the ``health_policy`` TOML key,
    else ``abort``; unknown values raise at startup."""
    policy = os.environ.get("GS_HEALTH_POLICY")
    if policy is None and settings is not None:
        policy = getattr(settings, "health_policy", "")
    policy = (policy or "abort").lower()
    if policy not in POLICIES:
        raise ValueError(
            f"Unsupported health policy: {policy!r}. "
            f"Supported: {', '.join(POLICIES)}"
        )
    return policy


class HealthGuard:
    """Boundary-time policy enforcement over resolved probe reports."""

    def __init__(self, policy: str = "abort"):
        if policy not in POLICIES:
            raise ValueError(f"Unsupported health policy: {policy!r}")
        self.policy = policy

    @classmethod
    def from_env(cls, settings=None) -> "HealthGuard":
        return cls(resolve_policy(settings))

    @property
    def enabled(self) -> bool:
        return self.policy != "off"

    @staticmethod
    def record_metrics(report, metrics) -> None:
        """Mirror one boundary's probe into the metrics registry
        (``obs/metrics.py``): per-field min/max gauges, the aggregate
        finite flag, and — for ensembles — per-member health so a
        scraper can alert on one diverging member of a sweep. No-op
        cost when metrics are off (the registry hands out the shared
        null instrument)."""
        if metrics is None or report is None:
            return
        metrics.gauge("field_finite").set(int(report.finite))
        for name, (lo, hi) in zip(report.names, report.ranges):
            metrics.gauge("field_min", field=name).set(lo)
            metrics.gauge("field_max", field=name).set(hi)
        members = getattr(report, "members", None)
        if members is not None:
            bad = report.bad_members
            active = getattr(report, "active", None)
            metrics.gauge("ensemble_members_bad").set(len(bad))
            for i, m in enumerate(members):
                if active is not None and not active[i]:
                    continue  # idle pack slot: not a real member
                metrics.gauge(
                    "ensemble_member_finite", member=str(i)
                ).set(int(m.finite))

    def check(
        self, step: int, report, *, log=None, metrics=None
    ) -> Optional[dict]:
        """Enforce the policy on one boundary's report (a
        :class:`HealthReport` or, for ensembles, an
        :class:`EnsembleHealthReport` — whose ``describe()`` carries
        the non-finite member indices into the journal event).
        ``metrics`` (a :class:`~..obs.metrics.MetricsRegistry`)
        additionally mirrors every probe — healthy ones included —
        into the field-range gauges.

        Healthy (or disabled) returns None. Unhealthy: ``warn`` logs
        and returns a journal-able event dict; ``abort``/``rollback``
        raise :class:`HealthError` (the supervisor maps the policy to
        its recovery action).
        """
        if not self.enabled or report is None:
            return None
        self.record_metrics(report, metrics)
        if report.finite:
            return None
        if self.policy == "warn":
            event = {
                "event": "health",
                "kind": "health",
                "step": step,
                "policy": "warn",
                "action": "continued",
                **report.describe(),
            }
            if log is not None:
                log.warn(
                    f"field health check failed at step {step} "
                    f"(non-finite values); policy=warn, continuing"
                )
            return event
        raise HealthError(step, report, self.policy)
