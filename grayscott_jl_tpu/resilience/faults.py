"""Deterministic fault injection for supervised runs.

Long campaigns on shared accelerators die to a small set of recurring
failure shapes — transient I/O errors under the async writer, NaN
blow-ups from bad parameter regions, preemption of the chip grant, and
kernel-runtime regressions (arXiv:2309.10292 §5, arXiv:2404.02218) —
and a recovery path that is never exercised is a recovery path that
does not work. This module turns those shapes into a *replayable plan*:
``GS_FAULTS`` (or the ``faults`` TOML key) names exactly which fault
fires at which simulation step, e.g. ::

    GS_FAULTS="step=120:kind=io_error;step=300:kind=nan;step=500:kind=preempt"

The driver consumes the plan at its boundary loop (``driver.run_once``):
a fault fires at the first plot/checkpoint boundary at-or-after its
step, exactly once per plan instance. The supervisor
(``resilience/supervisor.py``) holds ONE plan across restart attempts,
so a fault that already fired does not re-fire on the resumed run —
which is what makes a chaos run deterministic end to end.

Fault kinds:

``io_error``
    Raises :class:`InjectedIOError` (an ``OSError``) inside the
    ``AsyncStepWriter`` write target for the due boundary — the fault
    surfaces on the driver thread as a *transient* ``AsyncIOError``,
    the same path a real disk/NFS hiccup takes.
``nan``
    Poisons one cell of the ``u`` field with NaN
    (``Simulation.poison_nan``) so the health guard
    (``resilience/health.py``) trips at the same boundary.
``preempt``
    Raises :class:`PreemptionError` at the boundary *before* its writes
    are submitted — the SIGTERM-mid-compute shape. Already-accepted
    async steps still drain durably on the abort path
    (``AsyncStepWriter.__exit__``), like a grace-window shutdown.
``kernel``
    Raises :class:`InjectedKernelError` (message carries ``Mosaic`` so
    it classifies like a real Pallas runtime failure) inside the
    compute phase. Only armed while the resolved kernel language is
    ``pallas`` — the supervisor's recovery is to degrade to XLA.
``hang``
    Stalls the driver thread at the boundary (:func:`injected_hang_wait`
    — small-chunk sleeps, bounded by ``GS_HANG_BOUND_S`` so an
    unwatched run stalls briefly instead of wedging forever). Under an
    armed watchdog (``resilience/watchdog.py``) the deadline expires
    mid-stall, the all-thread stack dump lands in the journal, and the
    stall unwinds as a :class:`~.watchdog.HangError` — the wedged-
    collective / dead-tunnel shape, chaos-testable without a real
    wedge.
``bitflip``
    Fail-silent corruption on the write path: XORs one bit of the
    boundary snapshot's device-side COPY (field/member-addressable —
    ``GS_FAULT_MEMBER`` picks the ensemble member, like ``nan``)
    *after* the in-graph integrity checksum read the pristine fields
    (``Simulation.snapshot_async(bitflip=...)``). The live trajectory
    is untouched; with ``GS_CKPT_VERIFY=full`` the host-side
    recomputation catches the mismatch before the poisoned step
    reaches any store and the boundary unwinds as a
    :class:`~.integrity.CorruptionError` (classified ``corruption``).
``ckpt_corrupt``
    Fail-silent durable corruption: flips one payload byte of the
    latest durable checkpoint entry in the PRIMARY store
    (``resilience/integrity.corrupt_store_byte`` — metadata and
    recorded CRCs untouched). Detected by verify-on-read at the next
    restore (replica failover when ``GS_CKPT_REPLICAS`` mirrors
    exist; a loud refusal when not) or by the ``GS_SCRUB`` boundary
    scrubber, which quarantines the entry.
``sdc``
    Fail-silent COMPUTE-path corruption: flips one mantissa bit of one
    LIVE cell in the shard owned by a named device
    (``Simulation.poison_sdc``; target via ``GS_FAULT_DEVICE``, member
    via ``GS_FAULT_MEMBER``) *before* the round runs — the corrupted
    value is an input to the step program, so the trajectory itself
    diverges. Distinct from ``bitflip``, which corrupts the write-path
    copy only and must stay invisible to SDC screening. Detected by
    ``GS_SDC_CHECK`` redundant-compute screening
    (``resilience/sdc.py``), attributed to the device, and raised as
    :class:`~.sdc.SDCError` (classified ``sdc``: restart from the last
    *verified* checkpoint; a repeat at the same device quarantines it).

This module also hosts the preemption-aware graceful-shutdown pieces
(they share the failure taxonomy): :class:`ShutdownListener` turns
SIGTERM/SIGINT into a boundary-checked request, and
:class:`GracefulShutdown` is the exit the driver raises after the
grace-window checkpoint + drain — mapped to the distinct
:data:`EXIT_PREEMPTED` process exit code so an external relauncher can
tell "preempted, resume me" from "failed".
"""

from __future__ import annotations

import dataclasses
import os
import signal
import threading
import time
from typing import List, Optional

__all__ = [
    "EXIT_HANG",
    "EXIT_PREEMPTED",
    "FAULT_KINDS",
    "Fault",
    "FaultPlan",
    "GracefulShutdown",
    "InjectedIOError",
    "InjectedKernelError",
    "PreemptionError",
    "ShutdownListener",
    "injected_hang_wait",
    "resolve_graceful_shutdown",
]

FAULT_KINDS = (
    "io_error", "nan", "preempt", "kernel", "hang", "bitflip",
    "ckpt_corrupt", "drift", "sdc",
)

#: Distinct process exit codes, chosen from the sysexits "temporary
#: failure" neighborhood so generic tooling reads them as retryable:
#: a graceful preemption exit (checkpoint written, resume me) ...
EXIT_PREEMPTED = 75
#: ... and the watchdog's hard hang exit (stacks + ``hang_exit`` marker
#: journaled; resume me from the last durable checkpoint).
EXIT_HANG = 76


class InjectedIOError(OSError):
    """Planned transient I/O failure (fires inside a write target)."""


class PreemptionError(RuntimeError):
    """The run lost its chip grant / received SIGTERM at a boundary."""


class GracefulShutdown(PreemptionError):
    """The run shut itself down cleanly after a shutdown request.

    Raised by the driver at the first boundary after SIGTERM/SIGINT,
    *after* the grace-window checkpoint is durable and the async writer
    drained. A ``PreemptionError`` subclass so it classifies as
    ``preemption`` — but the supervisor never restarts it in-process
    (the scheduler wants the process gone); it propagates to the CLI,
    which exits :data:`EXIT_PREEMPTED`. The journal's
    ``graceful_shutdown`` marker makes the next supervised launch
    auto-resume (``supervisor.resume_marker``).
    """

    def __init__(self, signum: int, step: int,
                 checkpoint_step: Optional[int] = None):
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        ck = (
            f"checkpoint durable at step {checkpoint_step}"
            if checkpoint_step is not None
            else "no checkpoint store configured"
        )
        super().__init__(
            f"graceful shutdown on {name} at step {step} ({ck})"
        )
        self.signum = signum
        self.step = step
        self.checkpoint_step = checkpoint_step


def resolve_graceful_shutdown(settings=None) -> bool:
    """``GS_GRACEFUL_SHUTDOWN`` env, else the ``graceful_shutdown``
    TOML key, default on."""
    raw = os.environ.get("GS_GRACEFUL_SHUTDOWN")
    if raw is not None:
        val = raw.strip().lower()
        if val in ("1", "true", "yes", "on"):
            return True
        if val in ("0", "false", "no", "off"):
            return False
        raise ValueError(
            f"GS_GRACEFUL_SHUTDOWN must be a boolean, got {raw!r}"
        )
    return bool(getattr(settings, "graceful_shutdown", True))


class ShutdownListener:
    """SIGTERM/SIGINT -> a boundary-checked shutdown request.

    The first signal only sets a flag — the driver finishes the
    in-flight compute chunk, writes a grace-window checkpoint at the
    boundary, drains the async writer, and raises
    :class:`GracefulShutdown`. A second signal (operator insisting, or
    the grace window ending) raises ``KeyboardInterrupt`` immediately —
    the pre-existing hard-kill behavior. Handlers are process-global
    state, so ``install``/``uninstall`` save and restore the previous
    handlers; installation is skipped off the main thread (Python
    forbids it) and when disabled, leaving behavior unchanged.

    ``watchdog``: when the hang watchdog has already expired, its
    ``interrupt_main`` arrives through the installed handler — the
    listener must re-raise it as ``KeyboardInterrupt`` instead of
    swallowing it into a graceful request the wedged driver will never
    check.

    ``on_request``: optional ``on_request(signum)`` callback fired once
    when the first signal arrives — the driver points it at the unified
    event stream (``obs/events.py``) so an operator tailing the run
    sees the preemption notice the moment it lands, not at the next
    boundary. Exceptions are swallowed: a monitoring hook inside a
    signal handler must never turn a graceful request into a crash.
    """

    def __init__(self, *, enabled: bool = True, watchdog=None,
                 on_request=None):
        self.enabled = enabled
        self.signum: Optional[int] = None
        self._watchdog = watchdog
        self._on_request = on_request
        self._prev: dict = {}

    @property
    def requested(self) -> bool:
        return self.signum is not None

    def _handle(self, signum, frame) -> None:
        if self._watchdog is not None and self._watchdog.expired:
            raise KeyboardInterrupt(
                "watchdog interrupt (run hung past its deadline)"
            )
        if self.signum is None:
            self.signum = signum
            if self._on_request is not None:
                try:
                    self._on_request(signum)
                except Exception:  # noqa: BLE001 — monitoring hook
                    pass
        else:
            raise KeyboardInterrupt(
                f"second signal {signum} during graceful shutdown"
            )

    def install(self) -> "ShutdownListener":
        if (not self.enabled
                or threading.current_thread()
                is not threading.main_thread()):
            return self
        for sig in (signal.SIGTERM, signal.SIGINT):
            self._prev[sig] = signal.signal(sig, self._handle)
        return self

    def uninstall(self) -> None:
        for sig, prev in self._prev.items():
            signal.signal(sig, prev)
        self._prev = {}

    def __enter__(self) -> "ShutdownListener":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


def injected_hang_wait(
    watchdog=None, shutdown=None, bound_s: Optional[float] = None
) -> None:
    """The ``hang`` fault body: stall the driver thread in small-chunk
    sleeps until the watchdog trips (raises
    :class:`~.watchdog.HangError`), a shutdown request arrives (the
    stall "resolves" — SIGTERM interrupts it so the graceful path can
    run), or the bound passes (an unwatched run stalls briefly and
    continues — faults change WHEN the run computes, never WHAT it
    writes). ``GS_HANG_BOUND_S`` defaults to 30 s.
    """
    if bound_s is None:
        from ..config.env import env_float

        bound_s = env_float("GS_HANG_BOUND_S", 30.0)
    t0 = time.monotonic()
    while time.monotonic() - t0 < bound_s:
        time.sleep(0.05)
        if watchdog is not None and watchdog.expired is not None:
            watchdog.check()  # raises HangError with the expired phase
        if shutdown is not None and shutdown.requested:
            return


class InjectedKernelError(RuntimeError):
    """Planned Pallas runtime failure; classifies like a real Mosaic
    error (the message carries the marker the classifier matches)."""

    def __init__(self, step: int):
        super().__init__(
            f"injected Mosaic kernel runtime failure at step {step}"
        )
        self.step = step


@dataclasses.dataclass
class Fault:
    """One planned fault: fires at the first boundary >= ``step``."""

    step: int
    kind: str
    fired: bool = False

    def describe(self) -> dict:
        return {"step": self.step, "kind": self.kind, "fired": self.fired}


class FaultPlan:
    """An ordered, consume-once set of planned faults.

    ``take`` is called from the driver thread for nan/preempt/kernel
    faults and from the async writer's worker thread for io_error
    faults; the fired flag is a plain attribute write (GIL-atomic, and
    each kind is only ever polled from one thread).
    """

    def __init__(self, faults: Optional[List[Fault]] = None):
        self.faults = sorted(faults or [], key=lambda f: (f.step, f.kind))

    def __bool__(self) -> bool:
        return bool(self.faults)

    def __len__(self) -> int:
        return len(self.faults)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Parse ``step=N:kind=K[;step=N:kind=K...]`` into a plan.

        Unknown kinds, missing fields, and malformed entries raise
        ``ValueError`` naming the offending entry — a mistyped chaos
        plan must fail at startup, not silently inject nothing.
        """
        faults = []
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            fields = {}
            for part in entry.split(":"):
                if "=" not in part:
                    raise ValueError(
                        f"GS_FAULTS entry {entry!r}: field {part!r} is not "
                        "key=value"
                    )
                k, v = part.split("=", 1)
                fields[k.strip()] = v.strip()
            unknown = set(fields) - {"step", "kind"}
            if unknown:
                raise ValueError(
                    f"GS_FAULTS entry {entry!r}: unknown field(s) "
                    f"{sorted(unknown)}"
                )
            if "step" not in fields or "kind" not in fields:
                raise ValueError(
                    f"GS_FAULTS entry {entry!r} needs both step= and kind="
                )
            try:
                step = int(fields["step"])
            except ValueError as e:
                raise ValueError(
                    f"GS_FAULTS entry {entry!r}: step must be an integer"
                ) from e
            if step < 0:
                raise ValueError(
                    f"GS_FAULTS entry {entry!r}: step must be >= 0"
                )
            kind = fields["kind"]
            if kind not in FAULT_KINDS:
                raise ValueError(
                    f"GS_FAULTS entry {entry!r}: unknown kind {kind!r} "
                    f"(supported: {', '.join(FAULT_KINDS)})"
                )
            faults.append(Fault(step=step, kind=kind))
        return cls(faults)

    @classmethod
    def from_env(cls, settings=None) -> "FaultPlan":
        """Plan from ``GS_FAULTS``, falling back to the ``faults`` TOML
        key (empty plan when neither is set)."""
        spec = os.environ.get("GS_FAULTS")
        if spec is None and settings is not None:
            spec = getattr(settings, "faults", "")
        return cls.parse(spec or "")

    def take(self, kind: str, step: int) -> Optional[Fault]:
        """The earliest unfired fault of ``kind`` due at-or-before
        ``step``, marked fired — or None. Consume-once: a restarted
        attempt sharing this plan never replays a fired fault."""
        for f in self.faults:
            if f.kind == kind and not f.fired and f.step <= step:
                f.fired = True
                return f
        return None

    def pending(self, kind: Optional[str] = None) -> List[Fault]:
        return [
            f for f in self.faults
            if not f.fired and (kind is None or f.kind == kind)
        ]

    def describe(self) -> List[dict]:
        return [f.describe() for f in self.faults]
