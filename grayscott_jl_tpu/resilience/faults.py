"""Deterministic fault injection for supervised runs.

Long campaigns on shared accelerators die to a small set of recurring
failure shapes — transient I/O errors under the async writer, NaN
blow-ups from bad parameter regions, preemption of the chip grant, and
kernel-runtime regressions (arXiv:2309.10292 §5, arXiv:2404.02218) —
and a recovery path that is never exercised is a recovery path that
does not work. This module turns those shapes into a *replayable plan*:
``GS_FAULTS`` (or the ``faults`` TOML key) names exactly which fault
fires at which simulation step, e.g. ::

    GS_FAULTS="step=120:kind=io_error;step=300:kind=nan;step=500:kind=preempt"

The driver consumes the plan at its boundary loop (``driver.run_once``):
a fault fires at the first plot/checkpoint boundary at-or-after its
step, exactly once per plan instance. The supervisor
(``resilience/supervisor.py``) holds ONE plan across restart attempts,
so a fault that already fired does not re-fire on the resumed run —
which is what makes a chaos run deterministic end to end.

Fault kinds:

``io_error``
    Raises :class:`InjectedIOError` (an ``OSError``) inside the
    ``AsyncStepWriter`` write target for the due boundary — the fault
    surfaces on the driver thread as a *transient* ``AsyncIOError``,
    the same path a real disk/NFS hiccup takes.
``nan``
    Poisons one cell of the ``u`` field with NaN
    (``Simulation.poison_nan``) so the health guard
    (``resilience/health.py``) trips at the same boundary.
``preempt``
    Raises :class:`PreemptionError` at the boundary *before* its writes
    are submitted — the SIGTERM-mid-compute shape. Already-accepted
    async steps still drain durably on the abort path
    (``AsyncStepWriter.__exit__``), like a grace-window shutdown.
``kernel``
    Raises :class:`InjectedKernelError` (message carries ``Mosaic`` so
    it classifies like a real Pallas runtime failure) inside the
    compute phase. Only armed while the resolved kernel language is
    ``pallas`` — the supervisor's recovery is to degrade to XLA.
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional

__all__ = [
    "FAULT_KINDS",
    "Fault",
    "FaultPlan",
    "InjectedIOError",
    "InjectedKernelError",
    "PreemptionError",
]

FAULT_KINDS = ("io_error", "nan", "preempt", "kernel")


class InjectedIOError(OSError):
    """Planned transient I/O failure (fires inside a write target)."""


class PreemptionError(RuntimeError):
    """The run lost its chip grant / received SIGTERM at a boundary."""


class InjectedKernelError(RuntimeError):
    """Planned Pallas runtime failure; classifies like a real Mosaic
    error (the message carries the marker the classifier matches)."""

    def __init__(self, step: int):
        super().__init__(
            f"injected Mosaic kernel runtime failure at step {step}"
        )
        self.step = step


@dataclasses.dataclass
class Fault:
    """One planned fault: fires at the first boundary >= ``step``."""

    step: int
    kind: str
    fired: bool = False

    def describe(self) -> dict:
        return {"step": self.step, "kind": self.kind, "fired": self.fired}


class FaultPlan:
    """An ordered, consume-once set of planned faults.

    ``take`` is called from the driver thread for nan/preempt/kernel
    faults and from the async writer's worker thread for io_error
    faults; the fired flag is a plain attribute write (GIL-atomic, and
    each kind is only ever polled from one thread).
    """

    def __init__(self, faults: Optional[List[Fault]] = None):
        self.faults = sorted(faults or [], key=lambda f: (f.step, f.kind))

    def __bool__(self) -> bool:
        return bool(self.faults)

    def __len__(self) -> int:
        return len(self.faults)

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """Parse ``step=N:kind=K[;step=N:kind=K...]`` into a plan.

        Unknown kinds, missing fields, and malformed entries raise
        ``ValueError`` naming the offending entry — a mistyped chaos
        plan must fail at startup, not silently inject nothing.
        """
        faults = []
        for entry in spec.split(";"):
            entry = entry.strip()
            if not entry:
                continue
            fields = {}
            for part in entry.split(":"):
                if "=" not in part:
                    raise ValueError(
                        f"GS_FAULTS entry {entry!r}: field {part!r} is not "
                        "key=value"
                    )
                k, v = part.split("=", 1)
                fields[k.strip()] = v.strip()
            unknown = set(fields) - {"step", "kind"}
            if unknown:
                raise ValueError(
                    f"GS_FAULTS entry {entry!r}: unknown field(s) "
                    f"{sorted(unknown)}"
                )
            if "step" not in fields or "kind" not in fields:
                raise ValueError(
                    f"GS_FAULTS entry {entry!r} needs both step= and kind="
                )
            try:
                step = int(fields["step"])
            except ValueError as e:
                raise ValueError(
                    f"GS_FAULTS entry {entry!r}: step must be an integer"
                ) from e
            if step < 0:
                raise ValueError(
                    f"GS_FAULTS entry {entry!r}: step must be >= 0"
                )
            kind = fields["kind"]
            if kind not in FAULT_KINDS:
                raise ValueError(
                    f"GS_FAULTS entry {entry!r}: unknown kind {kind!r} "
                    f"(supported: {', '.join(FAULT_KINDS)})"
                )
            faults.append(Fault(step=step, kind=kind))
        return cls(faults)

    @classmethod
    def from_env(cls, settings=None) -> "FaultPlan":
        """Plan from ``GS_FAULTS``, falling back to the ``faults`` TOML
        key (empty plan when neither is set)."""
        spec = os.environ.get("GS_FAULTS")
        if spec is None and settings is not None:
            spec = getattr(settings, "faults", "")
        return cls.parse(spec or "")

    def take(self, kind: str, step: int) -> Optional[Fault]:
        """The earliest unfired fault of ``kind`` due at-or-before
        ``step``, marked fired — or None. Consume-once: a restarted
        attempt sharing this plan never replays a fired fault."""
        for f in self.faults:
            if f.kind == kind and not f.fired and f.step <= step:
                f.fired = True
                return f
        return None

    def pending(self, kind: Optional[str] = None) -> List[Fault]:
        return [
            f for f in self.faults
            if not f.fired and (kind is None or f.kind == kind)
        ]

    def describe(self) -> List[dict]:
        return [f.describe() for f in self.faults]
