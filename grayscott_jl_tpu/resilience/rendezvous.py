"""Multi-host restart consensus for supervised runs.

Restarting one rank of a collective leaves its peers wedged in halo
ppermutes, which is why supervision used to refuse
``jax.process_count() > 1`` outright. The missing piece is small: on a
classified failure every process must (1) restart *together* and
(2) resume from the *same* checkpoint step. This module provides that
agreement:

* each process publishes ``(attempt, latest-durable-checkpoint-step)``
  for the current rendezvous round and gathers every peer's value —
  publish-then-gather is itself the barrier;
* the **attempt counter** adopted is the cluster ``max`` — backoff
  schedules and the ``GS_MAX_RESTARTS`` budget stay cluster-wide even
  if one rank classified an extra local failure;
* the **restart step** adopted is the cluster ``min`` of the
  latest-durable-checkpoint steps (the checkpoint quorum): a step is
  only resumable if *every* host can restore it from the store it can
  see. Any host with no durable checkpoint drags the quorum to
  "restart from scratch" — a missing shard can never be papered over.

Two transports, selected by :func:`from_env`:

* :class:`KVRendezvous` — the JAX coordination-service key-value store,
  available whenever ``jax.distributed.initialize()`` ran (TPU pods,
  and the CPU multi-process tests' explicit ``GS_TPU_COORDINATOR``
  launch). Keys are unique per (launch, round, process), so the
  no-overwrite KV contract is never violated.
* :class:`FileRendezvous` — a shared-directory fallback
  (``GS_RENDEZVOUS_DIR``, default ``<output>.rendezvous/``) for
  multi-process setups without a live coordination client; files are
  atomically published (tmp + rename) and namespaced by a launch id
  derived from the coordinator address so a relaunch never reads a
  previous launch's rounds.

Symmetry assumption: fault classification is deterministic and faults
fire at boundaries, so all ranks reach ``agree`` for the same failure;
a rank that never arrives (a true wedge) trips the gather timeout
(``GS_RENDEZVOUS_TIMEOUT_S``) and the hang watchdog's ``collective``
deadline, turning a silent wedge into a classified, journaled failure.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import List, Optional, Tuple

from ..config.env import env_raw, env_str

__all__ = [
    "FileRendezvous",
    "KVRendezvous",
    "RendezvousTimeout",
    "atomic_publish",
    "from_env",
    "resolve_timeout_s",
]


def atomic_publish(path: str, payload: str) -> None:
    """Atomically publish ``payload`` at ``path`` (tmp + fsync +
    rename): readers see the old bytes or the new bytes, never a torn
    write. This is the one file-KV write primitive — the restart
    rendezvous publishes its votes through it, and the serve fleet's
    shared scheduler state (``serve/cluster.py``) builds its whole KV
    namespace on it plus ``os.rename`` for exclusive claims."""
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class RendezvousTimeout(RuntimeError):
    """A peer never published its restart vote within the timeout."""


def resolve_timeout_s() -> float:
    raw = os.environ.get("GS_RENDEZVOUS_TIMEOUT_S", "120")
    try:
        v = float(raw)
    except ValueError as e:
        raise ValueError(
            f"GS_RENDEZVOUS_TIMEOUT_S must be a number, got {raw!r}"
        ) from e
    if v <= 0:
        raise ValueError(
            f"GS_RENDEZVOUS_TIMEOUT_S must be > 0, got {v}"
        )
    return v


def _decide(votes: List[dict]) -> Tuple[int, Optional[int]]:
    """(cluster attempt, quorum restart step) from every process's
    published ``{"attempt": int, "ckpt": int}`` vote (-1 = no durable
    checkpoint on that host)."""
    attempt = max(int(v["attempt"]) for v in votes)
    steps = [int(v["ckpt"]) for v in votes]
    lowest = min(steps)
    return attempt, (None if lowest < 0 else lowest)


class _Rendezvous:
    """Shared publish/gather skeleton; subclasses provide transport."""

    def __init__(self, nprocs: int, proc: int, *, timeout_s: float):
        self.nprocs = int(nprocs)
        self.proc = int(proc)
        self.timeout_s = float(timeout_s)
        #: Local round counter; symmetric classification keeps every
        #: process's counter in lockstep (see module docstring).
        self.round = 0

    def agree(
        self, attempt: int, ckpt_step: Optional[int]
    ) -> Tuple[int, Optional[int]]:
        """Publish this process's vote, gather all peers', return
        ``(cluster_attempt, quorum_restart_step)`` — identical on every
        process by construction."""
        self.round += 1
        payload = json.dumps(
            {"attempt": int(attempt),
             "ckpt": -1 if ckpt_step is None else int(ckpt_step)}
        )
        self._publish(self.round, payload)
        votes = [json.loads(v) for v in self._gather(self.round)]
        return _decide(votes)

    def agree_mesh(
        self, local_devices: int, proposed_dims: Optional[Tuple] = None
    ) -> dict:
        """Mesh-agreement round (docs/RESHARD.md): every host of the
        (possibly replaced) slice publishes its local device count and
        its forced mesh proposal (``GS_TPU_MESH_DIMS``, or None for
        "derive"), and all hosts adopt the SAME target topology before
        restore — the elastic-resume precondition: a replacement slice
        of a different shape must agree on its decomposition, or the
        per-shard selection reads would reconstruct different grids.

        Returns ``{"devices": total, "dims": adopted-or-None,
        "procs": n}`` — identical on every host by construction.
        Disagreeing proposals, or a proposal that does not factor the
        gathered device total, raise
        :class:`~..reshard.plan.ReshardError` loudly: a cluster that
        cannot agree on its own shape must not restore into it.
        """
        from ..reshard.plan import ReshardError

        self.round += 1
        payload = json.dumps({
            "devices": int(local_devices),
            "dims": (None if proposed_dims is None
                     else [int(d) for d in proposed_dims]),
        })
        self._publish(self.round, payload)
        votes = [json.loads(v) for v in self._gather(self.round)]
        total = sum(int(v["devices"]) for v in votes)
        proposals = {
            None if v["dims"] is None else tuple(v["dims"])
            for v in votes
        }
        if len(proposals) > 1:
            raise ReshardError(
                f"mesh-agreement round {self.round}: hosts disagree on "
                f"the target mesh ({sorted(p or () for p in proposals)})"
                " — set the same GS_TPU_MESH_DIMS on every host, or "
                "none"
            )
        adopted = proposals.pop()
        if adopted is not None:
            n = 1
            for d in adopted:
                n *= int(d)
            if n != total:
                raise ReshardError(
                    f"mesh-agreement round {self.round}: proposed mesh "
                    f"{adopted} does not factor the slice's {total} "
                    "devices"
                )
        return {
            "devices": total,
            "dims": None if adopted is None else list(adopted),
            "procs": self.nprocs,
        }

    def _publish(self, round_no: int, payload: str) -> None:
        raise NotImplementedError

    def _gather(self, round_no: int) -> List[str]:
        raise NotImplementedError

    def describe(self) -> dict:
        return {
            "transport": type(self).__name__,
            "nprocs": self.nprocs,
            "proc": self.proc,
            "round": self.round,
        }


class KVRendezvous(_Rendezvous):
    """Consensus over the JAX coordination-service key-value store."""

    def __init__(self, client, nprocs: int, proc: int, *, timeout_s: float):
        super().__init__(nprocs, proc, timeout_s=timeout_s)
        self._client = client

    def _key(self, round_no: int, proc: int) -> str:
        return f"gs/restart_rdv/r{round_no}/p{proc}"

    def _publish(self, round_no: int, payload: str) -> None:
        self._client.key_value_set(self._key(round_no, self.proc), payload)

    def _gather(self, round_no: int) -> List[str]:
        timeout_ms = int(self.timeout_s * 1000)
        out = []
        for p in range(self.nprocs):
            try:
                out.append(
                    self._client.blocking_key_value_get(
                        self._key(round_no, p), timeout_ms
                    )
                )
            except Exception as e:  # jaxlib raises its own error type
                raise RendezvousTimeout(
                    f"restart rendezvous round {round_no}: process {p} "
                    f"never published within {self.timeout_s:.0f}s ({e})"
                ) from e
        return out


class FileRendezvous(_Rendezvous):
    """Consensus over a shared directory (atomic per-process files)."""

    def __init__(
        self, directory: str, nprocs: int, proc: int, *,
        timeout_s: float, launch_id: str = "0",
    ):
        super().__init__(nprocs, proc, timeout_s=timeout_s)
        self.directory = directory
        self.launch_id = launch_id
        os.makedirs(directory, exist_ok=True)

    def _path(self, round_no: int, proc: int) -> str:
        return os.path.join(
            self.directory, f"l{self.launch_id}.r{round_no}.p{proc}"
        )

    def _publish(self, round_no: int, payload: str) -> None:
        atomic_publish(self._path(round_no, self.proc), payload)

    def _gather(self, round_no: int) -> List[str]:
        deadline = time.monotonic() + self.timeout_s
        out: List[Optional[str]] = [None] * self.nprocs
        while True:
            for p in range(self.nprocs):
                if out[p] is None:
                    try:
                        with open(self._path(round_no, p),
                                  encoding="utf-8") as f:
                            out[p] = f.read()
                    except FileNotFoundError:
                        pass
            if all(v is not None for v in out):
                return out  # type: ignore[return-value]
            if time.monotonic() > deadline:
                missing = [p for p, v in enumerate(out) if v is None]
                raise RendezvousTimeout(
                    f"restart rendezvous round {round_no}: processes "
                    f"{missing} never published within "
                    f"{self.timeout_s:.0f}s (dir {self.directory})"
                )
            time.sleep(0.05)


def from_env(settings) -> Optional[_Rendezvous]:
    """The rendezvous for this run, or None for single-process runs.

    Transport: ``GS_RENDEZVOUS_DIR`` forces the filesystem transport
    (tests, shared-NFS setups); otherwise the coordination-service KV
    client when one is live; otherwise a filesystem rendezvous next to
    the output store.
    """
    import jax

    nprocs = jax.process_count()
    if nprocs <= 1:
        return None
    proc = jax.process_index()
    timeout_s = resolve_timeout_s()

    forced_dir = env_raw("GS_RENDEZVOUS_DIR")
    if not forced_dir:
        client = None
        try:
            from jax._src import distributed

            client = distributed.global_state.client
        except Exception:  # pragma: no cover — private-API drift
            client = None
        if client is not None:
            return KVRendezvous(client, nprocs, proc, timeout_s=timeout_s)

    directory = forced_dir or (settings.output + ".rendezvous")
    # Namespace rounds by launch so a relaunch (fresh supervisor, round
    # counter back at 0) never matches a previous launch's files. The
    # coordinator address is the natural shared-but-per-launch token.
    coord = env_str("GS_TPU_COORDINATOR", "")
    launch_id = f"{zlib.crc32(coord.encode()):08x}" if coord else "0"
    return FileRendezvous(
        directory, nprocs, proc, timeout_s=timeout_s, launch_id=launch_id
    )
