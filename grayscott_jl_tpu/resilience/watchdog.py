"""Hang watchdog: per-phase deadlines over the driver's heartbeat.

A wedged run is the one failure the supervisor cannot classify — it
never raises. BENCH_r05's wedged TPU tunnel burned 19+ minutes with
zero diagnosis, and a pod rank stuck in a collective wedges every peer
silently. Production stencil stacks treat stall detection as a runtime
responsibility, not an operator one (arXiv:2309.10292 §5 supervises
Frontier runs the same way; arXiv:2404.02218 argues the runtime layer
must absorb it). The watchdog closes that hole:

* the driver (``driver.run_once``) heartbeats at its host-side
  boundaries — ``compile`` (first jitted round + autotune), ``step_round``
  (one fused boundary-to-boundary device round, halo collectives
  included), ``io`` (boundary snapshot/submit incl. backpressure),
  ``drain`` (async-writer close), ``checkpoint`` (graceful-shutdown
  checkpoint), ``collective`` (multi-host rendezvous waits) — and each
  heartbeat arms that phase's deadline;
* a monitor thread checks the armed deadline; on expiry it dumps every
  thread's stack into the :class:`~.supervisor.FaultJournal` (durable —
  ``record`` fsyncs), classifies the event as a transient ``hang``, and
  tears the run down: first an ``interrupt_main`` so a Python-level
  stall unwinds as :class:`HangError` (which the supervisor restarts
  from the quorum checkpoint), then — if the run is still wedged after
  ``GS_WATCHDOG_GRACE_S`` (a C-level wedge no interrupt can reach) — a
  hard ``os._exit`` with the distinct hang exit code, leaving a
  ``hang_exit`` journal marker the next supervised launch auto-resumes
  from (``supervisor.resume_marker``).

This module must stay importable without JAX: ``bench.py``'s parent
process (which never imports jax, by design) arms a watchdog over its
late TPU probe loop.

Knobs (env wins over the ``watchdog`` / ``watchdog_deadline_s`` TOML
keys): ``GS_WATCHDOG`` = ``on`` | ``off`` | ``auto`` (auto = armed iff
supervision is), ``GS_WATCHDOG_DEADLINE_S`` (one deadline for every
phase), ``GS_WATCHDOG_<PHASE>_S`` (per-phase override, e.g.
``GS_WATCHDOG_STEP_ROUND_S``), ``GS_WATCHDOG_GRACE_S`` (seconds between
the soft interrupt and the hard exit; 0 disables the hard exit).
"""

from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Dict, Optional

from ..config.env import env_raw
from .faults import EXIT_HANG

__all__ = [
    "DEFAULT_DEADLINES",
    "HangError",
    "Watchdog",
    "resolve_watchdog",
]

#: Per-phase deadline defaults (seconds). Generous in absolute terms —
#: the point is distinguishing "slow" from "wedged forever", not
#: policing performance. ``compile`` covers the first fused round
#: (jit + autotune measurements); ``step_round`` covers one
#: boundary-to-boundary device round including its halo collectives
#: (they execute inside the jitted program, so they cannot heartbeat
#: separately); ``collective`` covers host-side multi-host waits
#: (restart rendezvous); ``probe_loop`` is bench.py's late TPU probe
#: loop (kept in lockstep with GS_BENCH_PROBE_BUDGET's default).
DEFAULT_DEADLINES: Dict[str, float] = {
    "compile": 1800.0,
    "step_round": 600.0,
    "io": 300.0,
    "drain": 600.0,
    "checkpoint": 600.0,
    "collective": 300.0,
    "probe_loop": 360.0,
    # A live reshape (reshard/restore.reshape_live) pays a target-mesh
    # compile plus the device-path move — budget it like a compile
    # (GS_WATCHDOG_RESHAPE_S overrides).
    "reshape": 1800.0,
}


class HangError(RuntimeError):
    """The watchdog expired: the run hung past a phase deadline.

    Classified as transient (``hang``) by the supervisor — the recovery
    is a restart from the (quorum) checkpoint, exactly like a
    preemption."""

    def __init__(self, phase: str, step: Optional[int], deadline_s: float):
        at = f" at step {step}" if step is not None else ""
        super().__init__(
            f"watchdog: run hung in phase {phase!r}{at} "
            f"(no heartbeat for {deadline_s:.1f}s)"
        )
        self.phase = phase
        self.step = step
        self.deadline_s = deadline_s


def _env_float(name: str) -> Optional[float]:
    raw = os.environ.get(name)
    if raw is None or raw.strip() == "":
        return None
    try:
        v = float(raw)
    except ValueError as e:
        raise ValueError(f"{name} must be a number, got {raw!r}") from e
    if v <= 0:
        raise ValueError(f"{name} must be > 0, got {v}")
    return v


def resolve_watchdog(settings=None) -> Optional[Dict[str, float]]:
    """Resolved per-phase deadlines, or ``None`` when the watchdog is
    off.

    ``GS_WATCHDOG`` env (``on``/``off``/``auto``) wins over the
    ``watchdog`` TOML key; ``auto`` (the default) arms the watchdog
    exactly when supervision is armed — an unsupervised run has no
    restart loop to hand a ``hang`` to, so by default it is left alone.
    Deadlines: built-in per-phase defaults, overridden globally by
    ``GS_WATCHDOG_DEADLINE_S`` (or the ``watchdog_deadline_s`` TOML
    key), then per-phase by ``GS_WATCHDOG_<PHASE>_S``.
    """
    raw = os.environ.get("GS_WATCHDOG")
    if raw is None:
        raw = getattr(settings, "watchdog", "") or "auto"
    mode = raw.strip().lower()
    mode = {"1": "on", "true": "on", "yes": "on",
            "0": "off", "false": "off", "no": "off", "": "auto"}.get(
                mode, mode)
    if mode not in ("on", "off", "auto"):
        raise ValueError(
            f"watchdog / GS_WATCHDOG must be on/off/auto, got {raw!r}"
        )
    if mode == "off":
        return None
    if mode == "auto":
        from .supervisor import supervision_enabled

        if not supervision_enabled(settings):
            return None

    deadlines = dict(DEFAULT_DEADLINES)
    base = _env_float("GS_WATCHDOG_DEADLINE_S")
    if base is None and settings is not None:
        toml_base = float(getattr(settings, "watchdog_deadline_s", 0.0))
        if toml_base > 0:
            base = toml_base
    if base is not None:
        deadlines = {k: base for k in deadlines}
    for phase in deadlines:
        v = _env_float(f"GS_WATCHDOG_{phase.upper()}_S")
        if v is not None:
            deadlines[phase] = v
    return deadlines


def _dump_stacks(skip_ident: Optional[int] = None, limit: int = 12) -> list:
    """Every live thread's stack tail, JSON-able — the diagnosis a
    wedged run otherwise takes a debugger attach to get."""
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in sys._current_frames().items():
        if ident == skip_ident:
            continue
        stack = [s.strip() for s in traceback.format_stack(frame)[-limit:]]
        out.append({"thread": names.get(ident, f"tid-{ident}"),
                    "stack": stack})
    return out


class Watchdog:
    """Deadline monitor over driver heartbeats.

    One phase is armed at a time (:meth:`heartbeat`); the monitor
    thread fires at most once — after expiry the event is frozen so the
    journal tells one coherent story. All methods are thread-safe;
    ``heartbeat`` is a lock + two attribute writes, cheap enough for
    every boundary.
    """

    def __init__(
        self,
        deadlines: Optional[Dict[str, float]] = None,
        *,
        journal=None,
        grace_s: Optional[float] = None,
        on_expire=None,
        tracer=None,
    ):
        self.deadlines = dict(deadlines or DEFAULT_DEADLINES)
        if not self.deadlines:
            raise ValueError("watchdog needs at least one phase deadline")
        for phase, d in self.deadlines.items():
            if d <= 0:
                raise ValueError(
                    f"watchdog deadline for {phase!r} must be > 0, got {d}"
                )
        self.journal = journal
        #: Span tracer fed one edge per heartbeat (``obs/trace.py``):
        #: the heartbeat already marks every phase transition, so the
        #: top-level phase timeline of the Chrome trace costs nothing
        #: the watchdog wasn't paying. None = resolve the process-wide
        #: tracer lazily at the first heartbeat (obs is stdlib-only, so
        #: the no-jax-in-bench-parent rule holds).
        self._tracer = tracer
        if grace_s is None:
            raw = env_raw("GS_WATCHDOG_GRACE_S")
            if raw is None or raw.strip() == "":
                grace_s = 60.0
            else:
                try:
                    grace_s = float(raw)
                except ValueError as e:
                    raise ValueError(
                        f"GS_WATCHDOG_GRACE_S must be a number, got {raw!r}"
                    ) from e
                if grace_s < 0:
                    raise ValueError(
                        f"GS_WATCHDOG_GRACE_S must be >= 0, got {grace_s}"
                    )
        #: Seconds between the soft interrupt and the hard ``os._exit``;
        #: 0 disables the hard exit (soft teardown only).
        self.grace_s = float(grace_s)
        #: Called from the monitor thread on expiry; default interrupts
        #: the main thread so a Python-level stall unwinds as an
        #: exception the driver converts to :class:`HangError`.
        self._on_expire = on_expire if on_expire is not None else (
            self._interrupt_main)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._armed = None  # (phase, step, deadline_s, armed_at)
        self._expired: Optional[dict] = None
        self._heartbeats = 0
        self._thread: Optional[threading.Thread] = None
        # Check often enough that the tightest deadline is detected
        # promptly, but never busier than 50 Hz.
        self._tick = min(0.5, max(0.02, min(self.deadlines.values()) / 5.0))

    @staticmethod
    def _interrupt_main() -> None:
        import _thread

        _thread.interrupt_main()

    # ------------------------------------------------------------- control

    def start(self) -> "Watchdog":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._run, name="gs-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        """Disarm and join the monitor; after ``stop`` no interrupt or
        hard exit can fire (the run unwound on its own). Idempotent."""
        with self._lock:
            self._stop.set()
            self._armed = None
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # ---------------------------------------------------------- heartbeats

    def heartbeat(self, phase: str, step: Optional[int] = None) -> None:
        """Arm ``phase``'s deadline from now (any previously armed phase
        is replaced). Unknown phases get the tightest configured
        deadline — better a premature trip than an unwatched phase.
        One heartbeat = one span edge in the trace (``obs/trace.py``)."""
        tr = self._tracer
        if tr is None:
            from ..obs.trace import get_tracer

            tr = self._tracer = get_tracer()
        tr.edge(phase, step)
        deadline = self.deadlines.get(phase)
        if deadline is None:
            deadline = min(self.deadlines.values())
        with self._lock:
            if self._stop.is_set() or self._expired is not None:
                return
            self._heartbeats += 1
            self._armed = (phase, step, deadline, time.monotonic())

    def touch(self, phase: str, step: Optional[int] = None) -> None:
        """Re-arm only if ``phase`` is the currently armed phase — how a
        worker thread (e.g. the async writer during drain) reports
        progress without clobbering the driver's own armed phase."""
        with self._lock:
            if (self._armed is None or self._stop.is_set()
                    or self._expired is not None):
                return
            if self._armed[0] == phase:
                self._heartbeats += 1
                self._armed = (phase, step, self._armed[2], time.monotonic())

    def disarm(self) -> None:
        with self._lock:
            self._armed = None

    # ------------------------------------------------------------- expiry

    @property
    def expired(self) -> Optional[dict]:
        """The frozen expiry event, or None while healthy."""
        return self._expired

    def check(self) -> None:
        """Raise :class:`HangError` if the watchdog has expired."""
        e = self._expired
        if e is not None:
            raise HangError(e["phase"], e.get("step"), e["deadline_s"])

    def describe(self) -> dict:
        """JSON-able provenance for ``RunStats``."""
        e = self._expired
        return {
            "enabled": True,
            "deadlines_s": dict(self.deadlines),
            "grace_s": self.grace_s,
            "heartbeats": self._heartbeats,
            "expired": (
                {"phase": e["phase"], "step": e.get("step"),
                 "deadline_s": e["deadline_s"]}
                if e is not None else None
            ),
        }

    # ------------------------------------------------------------- monitor

    def _run(self) -> None:
        while not self._stop.wait(self._tick):
            with self._lock:
                if self._armed is None or self._expired is not None:
                    continue
                phase, step, deadline, t0 = self._armed
                if time.monotonic() - t0 < deadline:
                    continue
                event = {
                    "event": "hang",
                    "kind": "hang",
                    "phase": phase,
                    "step": step,
                    "deadline_s": deadline,
                    "threads": _dump_stacks(skip_ident=threading.get_ident()),
                }
                self._expired = event
                self._armed = None
            # Journal + interrupt outside the lock: record() takes its
            # own lock and fsyncs; interrupt_main must never deadlock
            # against a heartbeat.
            if self._tracer is not None:
                # Expiry implies an armed phase, which implies at least
                # one heartbeat resolved the tracer.
                self._tracer.instant(
                    "watchdog_expired", step=step, phase=phase,
                    deadline_s=deadline,
                )
            if self.journal is not None:
                try:
                    self.journal.record(**event)
                except Exception:  # noqa: BLE001 — diagnosis must not kill teardown
                    pass
            try:
                self._on_expire()
            except Exception:  # noqa: BLE001
                pass
            if self.grace_s > 0:
                # Soft teardown got its chance; a C-level wedge (stuck
                # collective, dead PJRT client) never unwinds from an
                # interrupt. The distinct exit code + durable journal
                # marker turn the wedge into a relaunch-resumable event.
                if self._stop.wait(self.grace_s):
                    return
                if self.journal is not None:
                    try:
                        self.journal.record(
                            event="hang_exit", kind="hang", phase=phase,
                            step=step, exit_code=EXIT_HANG,
                        )
                    except Exception:  # noqa: BLE001
                        pass
                os._exit(EXIT_HANG)
            return
