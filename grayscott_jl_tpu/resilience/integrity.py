"""Defense-in-depth data integrity: checksums, replicas, scrubbing.

The resilience stack up to here recovers every *fail-stop* fault —
preemption, hangs, I/O errors, killed workers — but a flipped bit in a
field buffer or a scribbled checkpoint byte is *fail-silent*: the run
either crashes an unsupervised restore or, worse, resumes wrong and
every downstream contract "passes" on poisoned data. Long production
campaigns make silent data corruption a when-not-if event (the
Frontier end-to-end workflow paper, arXiv:2309.10292, motivates
exactly this durability regime); a corrupt store must be a detected,
attributed, and *survived* event — never a wrong answer. Three layers
(docs/RESILIENCE.md "Data integrity"):

**Checksums** — every BP-lite payload block gets a CRC32 recorded in a
per-writer *integrity sidecar file* inside the store directory
(``integrity.<w>.json`` — metadata only; the ``md.json`` format and
the payload bytes are untouched, so every existing byte-identity
contract on stores is preserved). The reader recomputes the CRC on
every block read (``GS_CKPT_VERIFY=read``, the default) and raises
:class:`CorruptionError` naming the file, offset, and both CRCs
instead of serving poisoned bytes. ``GS_CKPT_VERIFY=full``
additionally arms (a) a write-side read-back verify after every
checkpoint save and (b) a cheap in-graph **device-side field
checksum** (:func:`device_field_checksum`) fused into the snapshot-
copy jit next to the health and numerics probes: the wrapped uint
sum of the raw field bits is computed on device over the pristine
fields, and re-derived on the host from the very bytes about to hit
the stores — a mismatch means the data changed somewhere on the
device-copy → D2H → serialization path, and the boundary raises
*before* the poisoned step reaches any store.

**Replicas** — ``GS_CKPT_REPLICAS=N`` mirrors every checkpoint write
to ``<path>.r1`` .. ``<path>.r<N-1>`` (ensemble member stores
included). Restore, elastic reshard, and serve-requeue all try the
candidates in *health order* (most durable steps first, primary
winning ties) and fail over on a corrupt or unreadable candidate,
emitting a ``replica_failover`` event per skip; with a sole corrupted
replica the restore refuses loudly instead of resuming wrong.

**Scrubbing** — ``GS_SCRUB=1`` arms a boundary-time scrubber
(:class:`Scrubber`) that audits the durable steps of every checkpoint
replica against the recorded CRCs and *quarantines* corrupt step
entries (``quarantine.json`` — the reader hides them, so "latest
durable checkpoint" silently rolls past a rotten entry), emitting
``scrub`` / ``corruption`` events.

The supervisor classifies a detected corruption as
restartable-with-failover, but repeated corruption of the *same step*
is non-transient (gave_up, not an infinite restart loop) —
``resilience/supervisor.py``. The fault matrix grows ``bitflip``
(device-side, field/member-addressable — exercises the checksum
detection end to end) and ``ckpt_corrupt`` (flips a byte in a durable
checkpoint store — exercises verify-on-read, scrub, and failover);
``resilience/faults.py``.

Stdlib + numpy to import; JAX only inside the device-probe helpers.
"""

from __future__ import annotations

import glob
import json
import os
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config.env import env_flag, env_int, env_str

__all__ = [
    "VERIFY_MODES",
    "CorruptionError",
    "Scrubber",
    "corrupt_store_byte",
    "device_field_checksum",
    "host_field_checksum",
    "latest_durable_step_replicated",
    "quarantine_path",
    "read_quarantine",
    "recoverable_restore_error",
    "replica_paths",
    "replicate_store",
    "resolve_config",
    "resolve_replicas",
    "resolve_scrub",
    "resolve_verify",
    "restore_candidates",
    "restore_with_failover",
    "scrub_store",
    "verify_last_step",
    "verify_store",
]

VERIFY_MODES = ("off", "read", "full")

_QUARANTINE = "quarantine.json"


class CorruptionError(RuntimeError):
    """Recorded and recomputed checksums disagree: the bytes changed
    between write and read (or between device and host). Carries
    enough attribution for the "named step + file + CRC mismatch"
    contract; the supervisor classifies it as ``corruption``."""

    def __init__(self, detail: str, *, path: Optional[str] = None,
                 file: Optional[str] = None, offset: Optional[int] = None,
                 step: Optional[int] = None, var: Optional[str] = None,
                 member: Optional[int] = None):
        where = []
        if var is not None:
            where.append(f"var {var!r}")
        if step is not None:
            where.append(f"step {step}")
        if member is not None:
            where.append(f"member {member}")
        if file is not None:
            where.append(f"file {file!r}"
                         + (f" offset {offset}" if offset is not None
                            else ""))
        if path is not None:
            where.append(f"store {path}")
        suffix = f" ({', '.join(where)})" if where else ""
        super().__init__(detail + suffix)
        self.detail = detail
        self.path = path
        self.file = file
        self.offset = offset
        self.step = step
        self.var = var
        self.member = member


# --------------------------------------------------------------- knobs


def resolve_replicas(settings=None) -> int:
    """``GS_CKPT_REPLICAS`` — total checkpoint store copies (primary
    included), default 1 (no mirrors)."""
    n = env_int("GS_CKPT_REPLICAS", 1)
    if n < 1:
        raise ValueError(
            f"GS_CKPT_REPLICAS must be >= 1, got {n}"
        )
    return n


def resolve_verify(settings=None) -> str:
    """``GS_CKPT_VERIFY`` — ``off`` | ``read`` (default: recompute the
    CRC of every BP-lite block read) | ``full`` (read + write-side
    read-back verify + the in-graph device-side field checksum on the
    snapshot path)."""
    mode = (env_str("GS_CKPT_VERIFY", "read") or "read").strip().lower()
    if mode not in VERIFY_MODES:
        raise ValueError(
            f"GS_CKPT_VERIFY must be one of {'|'.join(VERIFY_MODES)}, "
            f"got {mode!r}"
        )
    return mode


def resolve_scrub(settings=None) -> Tuple[bool, int]:
    """``GS_SCRUB`` (default off) arms the boundary-time checkpoint
    scrubber; ``GS_SCRUB_EVERY`` audits every N-th checkpoint boundary
    (default 1 = every one)."""
    every = env_int("GS_SCRUB_EVERY", 1)
    if every < 1:
        raise ValueError(f"GS_SCRUB_EVERY must be >= 1, got {every}")
    return env_flag("GS_SCRUB", False), every


def resolve_config(settings=None) -> dict:
    """The resolved integrity configuration the driver echoes into
    ``RunStats.config["integrity"]``."""
    scrub, every = resolve_scrub(settings)
    return {
        "replicas": resolve_replicas(settings),
        "verify": resolve_verify(settings),
        "scrub": scrub,
        "scrub_every": every,
    }


# ------------------------------------------------------------ checksums


def file_crc(data: bytes) -> int:
    """CRC32 of one payload block's bytes (zlib, unsigned)."""
    return zlib.crc32(data) & 0xFFFFFFFF


def host_field_checksum(arr) -> int:
    """Host-side mirror of :func:`device_field_checksum` over one
    array's raw bytes: the wrapped (mod 2^32) sum of the array viewed
    as little-endian unsigned words. Word width follows the dtype
    (2-byte dtypes sum 16-bit words, everything else 32-bit words) so
    the value matches the device reduction bit for bit."""
    a = np.ascontiguousarray(arr)
    if a.size == 0:
        return 0
    word = "<u2" if a.dtype.itemsize == 2 else "<u4"
    words = a.view(np.dtype(word))
    return int(words.astype(np.uint64).sum() % (1 << 32))


def device_field_checksum(*fields):
    """The fused in-graph per-field checksum probe: one wrapped uint32
    sum of each field's raw bits, traced inside the snapshot-copy jit
    next to the health probe (``Simulation.snapshot_async``) so the
    fields are read from HBM once for copy + health + checksum
    together. Integer addition is associative and commutative mod
    2^32, so the value is exact and layout-independent — no tolerance,
    no reduction-order caveats."""
    import jax.numpy as jnp
    from jax import lax

    out = ()
    for f in fields:
        width = jnp.dtype(f.dtype).itemsize
        bits = lax.bitcast_convert_type(
            f, jnp.uint16 if width == 2 else jnp.uint32
        )
        out += (jnp.sum(bits.astype(jnp.uint32), dtype=jnp.uint32),)
    return out


def apply_bitflip(arr, index: Sequence[int], bit: int = 0):
    """XOR one bit of one element's bit pattern — the ``bitflip``
    fault body, applied to the snapshot's device-side copy
    (field/member-addressable via ``index``) so the live trajectory is
    untouched while the bytes bound for the stores are silently wrong.
    Any single-bit flip changes the wrapped word sum by a nonzero
    delta, so :func:`device_field_checksum` detection is guaranteed,
    not probabilistic.

    ``bit`` selects which bit of the storage word flips (default 0,
    the lowest — PR 14's at-rest fault). The compute-path ``sdc``
    fault flips a HIGH mantissa bit instead: a lowest-bit flip in a
    flat region can be diffusively absorbed below one ulp within a
    single round, and the screening contract is about *persistent*
    wrong answers, not sub-ulp transients."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    def flip(x):
        width = jnp.dtype(x.dtype).itemsize
        word = jnp.uint16 if width == 2 else jnp.uint32
        bits = lax.bitcast_convert_type(x, word)
        idx = tuple(index) + (0,) * (bits.ndim - len(index))
        flipped = bits.at[idx].set(bits[idx] ^ word(1 << bit))
        return lax.bitcast_convert_type(flipped, x.dtype)

    return jax.jit(flip)(arr)


# ------------------------------------------------------------- replicas


def replica_paths(path: str, n: Optional[int] = None) -> List[str]:
    """The write-side replica set for a checkpoint store: the primary
    plus ``<path>.r1`` .. ``<path>.r<n-1>`` mirror directories."""
    if n is None:
        n = resolve_replicas()
    return [path] + [f"{path}.r{k}" for k in range(1, n)]


def _existing_replicas(path: str) -> List[str]:
    """Replica mirrors present on disk (discovered, not configured —
    a relaunch with ``GS_CKPT_REPLICAS=1`` still fails over to
    mirrors a previous launch wrote)."""
    out = []
    for p in glob.glob(glob.escape(path) + ".r*"):
        tail = p[len(path) + 2:]
        if p[len(path):].startswith(".r") and tail.isdigit():
            out.append((int(tail), p))
    return [p for _, p in sorted(out)]


def restore_candidates(path: str) -> List[str]:
    """Restore-side candidate stores in *health order*: primary plus
    every on-disk mirror, ordered by latest durable step descending
    (a stale or empty replica is tried last), the primary winning
    ties. The first candidate is what a replication-unaware restore
    would have used."""
    from ..io.checkpoint import latest_durable_step

    cands = [path] + _existing_replicas(path)
    if len(cands) == 1:
        return cands

    def health(p: str) -> int:
        s = latest_durable_step(p)
        return -1 if s is None else s

    return sorted(cands, key=health, reverse=True)  # stable: primary first


def latest_durable_step_replicated(
        path: str, max_step: Optional[int] = None) -> Optional[int]:
    """The best "latest durable checkpoint step" any replica of
    ``path`` can serve — the replicated form of
    ``io.checkpoint.latest_durable_step`` the supervisor's resume
    quorum consults (a half-written primary must not drag the quorum
    down while a mirror holds the step). ``max_step`` caps the answer
    at the last *verified* boundary (SDC recovery,
    ``resilience/sdc.py``)."""
    from ..io.checkpoint import latest_durable_step

    steps = [latest_durable_step(p, max_step=max_step)
             for p in [path] + _existing_replicas(path)]
    live = [s for s in steps if s is not None]
    return max(live) if live else None


def recoverable_restore_error(exc: BaseException) -> bool:
    """Is this restore failure worth trying another replica for?
    Corruption, unreadable stores, and missing/absent step entries
    fail over; config-identity errors (wrong model/precision/L) would
    fail identically on every replica and re-raise immediately."""
    if isinstance(exc, CorruptionError):
        return True
    if isinstance(exc, (FileNotFoundError, OSError)):
        return True
    if isinstance(exc, RuntimeError):
        return "Unreadable BP-lite metadata" in str(exc)
    if isinstance(exc, ValueError):
        msg = str(exc)
        return ("contains no steps" in msg
                or "no entry for simulation step" in msg)
    return False


def restore_with_failover(path: str, attempt, *, journal=None,
                          log=None):
    """Run ``attempt(candidate_path)`` against the replica candidates
    of ``path`` in health order, failing over on recoverable errors
    (:func:`recoverable_restore_error`) with a ``replica_failover``
    event per skipped candidate. Exhausting every candidate re-raises
    the LAST error — with ``GS_CKPT_REPLICAS=1`` and a corrupted sole
    store that is the loud CRC-mismatch refusal, never a silent wrong
    resume. This is the one failover implementation restore, elastic
    reshard, and the serve requeue path all route through."""
    candidates = restore_candidates(path)
    last: Optional[BaseException] = None
    for i, cand in enumerate(candidates):
        if last is not None:
            _announce_failover(path, cand, last, journal=journal,
                               log=log)
        try:
            return attempt(cand)
        except BaseException as exc:  # noqa: BLE001 — filtered below
            if not recoverable_restore_error(exc) or (
                    i == len(candidates) - 1):
                raise
            last = exc
    raise last  # pragma: no cover — loop always returns or raises


def _announce_failover(path: str, next_path: str, exc: BaseException,
                       *, journal=None, log=None) -> None:
    detail = f"{type(exc).__name__}: {exc}"
    if journal is not None:
        journal.record(event="replica_failover", path=path,
                       next=next_path, detail=detail)
    else:
        from ..obs import events as obs_events

        obs_events.get_events().emit(
            "replica_failover", path=path, next=next_path, detail=detail
        )
    from ..utils.log import Logger

    (log or Logger()).warn(
        f"checkpoint replica failover: {detail}; trying {next_path}"
    )


# ----------------------------------------------------------- quarantine


def quarantine_path(store: str) -> str:
    return os.path.join(store, _QUARANTINE)


def read_quarantine(store: str) -> frozenset:
    """Quarantined step-entry indices of a store (raw ``md.json``
    positions). A torn or malformed marker degrades to "nothing
    quarantined" — quarantine is an availability optimization, the
    per-read CRC verify still refuses corrupt payloads."""
    try:
        with open(quarantine_path(store), encoding="utf-8") as f:
            doc = json.load(f)
        return frozenset(int(i) for i in doc["quarantined"])
    except (FileNotFoundError, NotADirectoryError, ValueError,
            TypeError, KeyError):
        return frozenset()


def add_quarantine(store: str, indices) -> None:
    """Atomically extend the store's quarantine marker."""
    merged = sorted(read_quarantine(store) | {int(i) for i in indices})
    tmp = quarantine_path(store) + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"quarantined": merged}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, quarantine_path(store))


def remove_quarantine(store: str) -> None:
    try:
        os.remove(quarantine_path(store))
    except (FileNotFoundError, NotADirectoryError):
        pass


# ------------------------------------------------------------- scrubber


def scrub_store(path: str, *, journal=None, quarantine: bool = True
                ) -> Optional[dict]:
    """Audit every durable, not-yet-quarantined step entry of a
    BP-lite store against the recorded block CRCs; quarantine the
    corrupt ones. Returns an audit summary (``None`` for a store with
    no committed metadata yet). Runs off the raw metadata — the
    on-disk truth — so it never disturbs a live writer (metadata is
    replaced atomically) and never consumes reader state."""
    from ..io import bplite

    md_path = os.path.join(path, "md.json")
    if not os.path.isfile(md_path):
        return None
    try:
        with open(md_path, encoding="utf-8") as f:
            md0 = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    nwriters = int(md0.get("nwriters", 1))
    already = read_quarantine(path)
    corrupt: Dict[int, str] = {}
    audited = 0
    checked = 0
    for w in range(nwriters):
        name = "md.json" if w == 0 else f"md.{w}.json"
        try:
            with open(os.path.join(path, name), encoding="utf-8") as f:
                md = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if not md.get("variables"):
            md = dict(md, variables=md0.get("variables", {}))
        crcs = bplite.read_integrity_crcs(path, w)
        n = bplite.durable_step_count(md, path)
        for i, step_blocks in enumerate(md.get("steps", [])[:n]):
            if i in already or i in corrupt:
                continue
            if w == 0:
                audited += 1
            bad = _scrub_step(path, md, step_blocks, crcs)
            checked += bad[1]
            if bad[0] is not None:
                corrupt[i] = bad[0]
    report = {
        "path": path,
        "steps_audited": audited,
        "blocks_checked": checked,
        "corrupt": sorted(corrupt),
    }
    for i, detail in sorted(corrupt.items()):
        if journal is not None:
            journal.record(event="corruption", path=path, step_index=i,
                           detail=detail)
    if corrupt and quarantine:
        add_quarantine(path, corrupt)
    if journal is not None:
        journal.record(event="scrub", path=path,
                       steps_audited=audited,
                       corrupt=len(corrupt))
    return report


def _scrub_step(path: str, md: dict, step_blocks: dict, crcs: dict
                ) -> Tuple[Optional[str], int]:
    """CRC-audit one step entry; returns ``(first mismatch detail or
    None, blocks checked)``. Blocks without a recorded CRC (pre-
    integrity stores, the real-ADIOS2 engine) are skipped, not
    failed."""
    from ..io.bplite import _block_nbytes

    checked = 0
    for var, blocks in step_blocks.items():
        if var.startswith("_"):
            continue
        for b in blocks:
            want = crcs.get((b.get("file"), int(b.get("offset", 0))))
            if want is None:
                continue
            nbytes = _block_nbytes(md.get("variables", {}), var, b)
            if nbytes is None:
                continue
            try:
                with open(os.path.join(path, b["file"]), "rb") as f:
                    f.seek(int(b["offset"]))
                    data = f.read(nbytes)
            except OSError as e:
                return (f"unreadable payload for {var!r}: {e}", checked)
            checked += 1
            got = file_crc(data)
            if got != int(want):
                return (
                    f"CRC mismatch for {var!r} in {b['file']} at "
                    f"offset {b['offset']}: recorded "
                    f"{int(want):#010x}, read {got:#010x}",
                    checked,
                )
    return (None, checked)


class Scrubber:
    """Boundary-time audit of the run's checkpoint stores.

    The driver calls :meth:`maybe_scrub` at every checkpoint boundary;
    every ``GS_SCRUB_EVERY``-th call audits each checkpoint store the
    run writes (every replica; every ensemble member) and quarantines
    corrupt durable entries, so a rotten checkpoint is found while the
    run is still alive — not at the 3 a.m. restore that needed it."""

    def __init__(self, settings, *, journal=None, every: int = 1):
        self.settings = settings
        self.journal = journal
        self.every = max(1, int(every))
        self._boundaries = 0
        self.reports: List[dict] = []

    def _paths(self) -> List[str]:
        out: List[str] = []
        ens = getattr(self.settings, "ensemble", None)
        root = self.settings.checkpoint_output
        if ens is not None:
            from ..ensemble.io import member_path

            roots = [member_path(root, i, ens.n)
                     for i in range(ens.n) if ens.members[i].active]
        else:
            roots = [root]
        for r in roots:
            out.extend([r] + _existing_replicas(r))
        return out

    def maybe_scrub(self, step: int) -> Optional[List[dict]]:
        self._boundaries += 1
        if (self._boundaries - 1) % self.every:
            return None
        reports = []
        for p in self._paths():
            rep = scrub_store(p, journal=self.journal)
            if rep is not None:
                rep["step"] = step
                reports.append(rep)
        self.reports.extend(reports)
        return reports

    def describe(self) -> dict:
        return {
            "every": self.every,
            "audits": len(self.reports),
            "corrupt_found": sum(
                len(r["corrupt"]) for r in self.reports
            ),
        }


# ------------------------------------------------------- write-side etc


def verify_last_step(path: str) -> None:
    """Write-side read-back verify (``GS_CKPT_VERIFY=full``): re-read
    every variable of the store's last durable step through the
    CRC-verified read path, raising :class:`CorruptionError` if the
    bytes that landed do not match what was checksummed at ``put``
    time. Catches the write-path silent corruptions (bad DMA, lying
    disk cache) while the data is one boundary old, not one campaign
    old."""
    from ..io.bplite import BpReader

    r = BpReader(path, verify="read")
    try:
        n = r.num_steps()
        if n == 0:
            return
        for name in r.available_variables():
            try:
                r.get(name, step=n - 1)
            except KeyError:
                continue
    finally:
        r.close()


def verify_store(path: str) -> dict:
    """Full CRC audit of a finished store (the result-cache read gate,
    ``serve/cache.py``): every durable step entry's recorded block CRCs
    are recomputed against the payload bytes on disk, raising
    :class:`CorruptionError` naming the first corrupt entry. Unlike
    :func:`scrub_store` this never quarantines — the caller's contract
    is "serve these bytes or refuse", not "repair the store" — and a
    store with no committed metadata fails loudly rather than passing
    vacuously (a cache must not vouch for a store it cannot read)."""
    report = scrub_store(path, quarantine=False)
    if report is None:
        raise CorruptionError(
            f"store {path} has no readable metadata — nothing to "
            "verify, nothing to serve"
        )
    if report["corrupt"]:
        raise CorruptionError(
            f"store {path}: CRC mismatch in step entr"
            f"{'ies' if len(report['corrupt']) > 1 else 'y'} "
            f"{report['corrupt']} "
            f"({report['steps_audited']} audited)"
        )
    return report


def replicate_store(path: str, n: Optional[int] = None) -> List[str]:
    """Mirror a finished store to its ``.r1`` .. ``.r<n-1>`` replica
    paths (``GS_CKPT_REPLICAS`` when ``n`` is None) — the publish-time
    durability half of the result cache: a cached artifact whose
    primary later rots on disk fails over to a mirror instead of
    degrading to a relaunch. Copies land atomically (tmp dir + rename)
    so a concurrent reader never sees a half-copied mirror; existing
    mirrors are left alone (first publish wins — the store is
    content-addressed, every writer holds identical bytes). Returns
    the mirror paths written."""
    import shutil

    if n is None:
        n = resolve_replicas()
    written = []
    for mirror in replica_paths(path, n)[1:]:
        if os.path.exists(mirror):
            continue
        tmp = f"{mirror}.copy.{os.getpid()}"
        try:
            shutil.copytree(path, tmp)
            os.rename(tmp, mirror)
        except FileExistsError:
            shutil.rmtree(tmp, ignore_errors=True)
        except OSError:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        else:
            written.append(mirror)
    return written


def primary_checkpoint_path(settings) -> str:
    """The PRIMARY checkpoint store a ``ckpt_corrupt`` fault targets:
    the solo store, or — for ensembles — the faulted member's
    (``GS_FAULT_MEMBER``, like the ``nan``/``bitflip`` kinds)."""
    ens = getattr(settings, "ensemble", None)
    root = settings.checkpoint_output
    if ens is None:
        return root
    from ..ensemble.io import member_path

    member = env_int("GS_FAULT_MEMBER", 0) % ens.n
    return member_path(root, member, ens.n)


def corrupt_store_byte(path: str) -> Optional[dict]:
    """The ``ckpt_corrupt`` fault body: XOR one payload byte of the
    latest durable step's first field block in store ``path`` —
    metadata and recorded CRCs untouched, so the corruption is exactly
    the silent kind the verify/scrub/failover machinery exists to
    catch. Returns what was flipped (or None when the store has no
    durable field payload yet)."""
    from ..io import bplite

    md_path = os.path.join(path, "md.json")
    if not os.path.isfile(md_path):
        return None
    try:
        with open(md_path, encoding="utf-8") as f:
            md = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    n = bplite.durable_step_count(md, path)
    for i in range(n - 1, -1, -1):
        for var, blocks in md.get("steps", [])[i].items():
            if var.startswith("_") or var == "step":
                continue
            for b in blocks:
                nbytes = bplite._block_nbytes(
                    md.get("variables", {}), var, b
                )
                if not nbytes:
                    continue
                offset = int(b.get("offset", 0)) + nbytes // 2
                fpath = os.path.join(path, b["file"])
                with open(fpath, "r+b") as f:
                    f.seek(offset)
                    byte = f.read(1)
                    if not byte:
                        continue
                    f.seek(offset)
                    f.write(bytes([byte[0] ^ 0x01]))
                    f.flush()
                    os.fsync(f.fileno())
                return {
                    "path": path,
                    "file": b["file"],
                    "offset": offset,
                    "var": var,
                    "step_index": i,
                }
    return None
