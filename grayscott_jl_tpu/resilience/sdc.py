"""Compute-path silent-data-corruption (SDC) screening, attribution
and degraded-device quarantine.

PR 14's integrity layer guards data *at rest*: every byte that leaves
the device is CRC'd, checksummed, replicated and verified on read. None
of that helps when a device silently computes a wrong answer — the bad
trajectory is then CRC'd, replicated, content-address-cached and served
as truth. At scale this is the dominant unguarded failure class
(cf. the Frontier end-to-end experience, arxiv 2309.10292), and the
framework's bitwise-determinism contract makes the classic defense —
redundant compute — uniquely cheap here: recompute the round, compare
one exact checksum.

Modes (``GS_SDC_CHECK``, cadence ``GS_SDC_EVERY``):

* ``off``    — no screening (default). Zero overhead, zero change.
* ``spot``   — every Nth boundary, re-run the step rounds since the
  previous boundary from a retained device-side anchor copy and compare
  the exact wrapped-uint field checksums
  (:func:`~.integrity.device_field_checksum` — reduction-order-free, so
  replay-vs-live is an **equality**, not a tolerance). The comparison is
  fused in-graph; only scalars cross D2H.
* ``shadow`` — like spot, but the replay is placed on a rotated
  device/shard permutation of the same mesh, so a deterministic
  per-core fault cannot re-corrupt its own replay and self-confirm.

A mismatch is attributed to a device by pulling the diverging shards to
the host and bisecting over **disjoint device subsets**
(:func:`bisect_failing`), then picking the blast center (the failing
device with the most differing words — the injected fault model lands
at a shard center, so the short screening window keeps the divergence
inside one block). The ensemble engine's per-member checksum vectors
additionally name the diverging member(s) for free.

Detection raises :class:`SDCError` — supervisor classification
``sdc``: restartable from the last **verified** checkpoint (a step the
screener has proven, not just a durable one), and *repeated attribution
to the same device* is treated as non-transient **for that device**:
it is quarantined (:func:`quarantine_device` — fleet KV doc when
serving, ``GS_DEVICE_BLOCKLIST`` solo) so device selection excludes it
on the restart and the driver reshapes a live run away from it between
rounds (PR 18's ``reshape_live``).

Knobs (documented in docs/RESILIENCE.md):

* ``GS_SDC_CHECK``       — off | spot | shadow.
* ``GS_SDC_EVERY``       — screen every Nth write boundary (default 1).
* ``GS_DEVICE_BLOCKLIST``— comma-separated quarantined device names
  (``cpu:3,tpu:0``); union'd with fleet KV ``quarantine/*`` docs.
* ``GS_FAULT_DEVICE``    — device name the injected ``sdc`` chaos
  fault poisons (default: highest-id device in the mesh).

Single-process scope: screening compares addressable shards and is
armed by the driver only when ``jax.process_count() == 1`` (the same
gate as PR 14's snapshot checksums); multi-host screening would need a
cross-host checksum gather and is out of scope here.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..config.env import env_int, env_raw, env_str

__all__ = [
    "SDCError",
    "Screener",
    "bisect_failing",
    "device_name",
    "feasible_dims",
    "quarantine_device",
    "resolve_blocklist",
    "resolve_fault_device",
    "resolve_sdc",
    "usable_devices",
]

_MODES = ("off", "spot", "shadow")

#: Fleet-KV key prefix for quarantine docs (``serve/cluster.FleetKV``).
QUARANTINE_PREFIX = "quarantine/"


class SDCError(RuntimeError):
    """A redundant-compute screen disagreed with the live trajectory —
    some device computed (or stored an intermediate) wrong, silently.

    Carries the attribution the supervisor's ``sdc`` classification
    acts on: ``device`` (blast-center attribution, None when it could
    not be localized), ``member`` (ensemble member index, when the
    per-member checksum vectors localized one), ``step`` (the boundary
    that failed screening) and ``verified_step`` (the last boundary
    screening *proved* — resume must not trust anything newer, even if
    durable)."""

    def __init__(
        self, detail: str, *, step: Optional[int] = None,
        verified_step: Optional[int] = None,
        device: Optional[str] = None, member: Optional[int] = None,
        mode: str = "spot",
    ) -> None:
        parts = [detail]
        if step is not None:
            parts.append(f"step={step}")
        if device is not None:
            parts.append(f"device={device}")
        if member is not None:
            parts.append(f"member={member}")
        parts.append(f"verified_step={verified_step}")
        super().__init__("; ".join(parts))
        self.detail = detail
        self.step = step
        self.verified_step = verified_step
        self.device = device
        self.member = member
        self.mode = mode


# ------------------------------------------------------------ resolvers


def resolve_sdc(settings=None) -> dict:
    """Resolve the screening posture: ``{"mode", "every"}`` from
    ``GS_SDC_CHECK``/``GS_SDC_EVERY`` (env wins) over the optional
    ``sdc_check``/``sdc_every`` settings keys. Invalid values fail
    loudly — a typo'd screening mode must not silently mean "off"."""
    mode = env_str("GS_SDC_CHECK", "").strip().lower()
    if not mode:
        mode = str(getattr(settings, "sdc_check", "") or "").strip().lower()
    mode = mode or "off"
    if mode not in _MODES:
        raise ValueError(
            f"GS_SDC_CHECK={mode!r} is not one of {'/'.join(_MODES)}"
        )
    if env_raw("GS_SDC_EVERY") is not None:
        every = env_int("GS_SDC_EVERY")
    else:
        every = int(getattr(settings, "sdc_every", 0) or 0) or 1
    if every < 1:
        raise ValueError(f"GS_SDC_EVERY={every} must be >= 1")
    return {"mode": mode, "every": every}


def resolve_fault_device(settings=None) -> Optional[str]:
    """Device name the injected ``sdc`` chaos fault targets
    (``GS_FAULT_DEVICE``, e.g. ``cpu:5``), or None for the default
    (highest-id device owning a shard)."""
    name = env_str("GS_FAULT_DEVICE", "").strip()
    return name or None


def device_name(dev) -> str:
    """Canonical device name used everywhere attribution/quarantine
    speaks about hardware: ``<platform>:<id>`` (matches
    ``device_memory_stats``)."""
    return f"{dev.platform}:{dev.id}"


def resolve_blocklist() -> frozenset:
    """The quarantined-device set: ``GS_DEVICE_BLOCKLIST`` (comma-
    separated names) union'd with the fleet KV ``quarantine/*`` docs
    when a serve fleet namespace is armed (``GS_SERVE_FLEET_DIR``) —
    one worker's attribution quarantines the device fleet-wide. Fast
    empty-frozenset path when neither source is set."""
    names = {
        tok.strip()
        for tok in env_str("GS_DEVICE_BLOCKLIST", "").split(",")
        if tok.strip()
    }
    fleet = env_str("GS_SERVE_FLEET_DIR", "")
    if fleet:
        try:
            from ..serve.cluster import FleetKV

            kv = FleetKV(fleet)
            for key in kv.keys("quarantine"):
                doc = kv.get(QUARANTINE_PREFIX + key)
                if isinstance(doc, dict) and doc.get("device"):
                    names.add(str(doc["device"]))
        except OSError:
            pass  # unreadable namespace: env blocklist still applies
    return frozenset(names)


def quarantine_device(
    name: str, *, journal=None, step: Optional[int] = None,
    reason: str = "",
) -> None:
    """Quarantine ``name``: extend ``GS_DEVICE_BLOCKLIST`` in this
    process's environment (in-process supervisor restarts and child
    launches both inherit it), publish a fleet KV quarantine doc when
    serving (any worker's screener protects the whole fleet), and
    journal a ``device_quarantined`` event."""
    current = [
        tok.strip()
        for tok in env_str("GS_DEVICE_BLOCKLIST", "").split(",")
        if tok.strip()
    ]
    if name not in current:
        current.append(name)
        os.environ["GS_DEVICE_BLOCKLIST"] = ",".join(current)
    fleet = env_str("GS_SERVE_FLEET_DIR", "")
    if fleet:
        try:
            from ..serve.cluster import FleetKV

            kv = FleetKV(fleet)
            key = QUARANTINE_PREFIX + name.replace(":", "_")
            if kv.get(key) is None:
                # First verdict wins: a re-quarantine must not clobber
                # the original attribution's provenance.
                kv.put(key, {
                    "device": name,
                    "reason": reason,
                    "step": step,
                    "t": round(time.time(), 3),
                })
        except OSError:
            pass  # env blocklist above is the durable-enough fallback
    if journal is not None:
        journal.record(
            event="device_quarantined", kind="sdc", device=name,
            step=step, reason=reason,
        )


def usable_devices(platform: Optional[str] = None) -> list:
    """The device inventory minus the quarantine set — what mesh
    construction, reshape targeting and the supervisor's exhaustion
    check may actually use."""
    import jax

    blocked = resolve_blocklist()
    devices = jax.devices(platform) if platform else jax.devices()
    if not blocked:
        return list(devices)
    return [d for d in devices if device_name(d) not in blocked]


def feasible_dims(
    max_blocks: int, L: int,
) -> Optional[Tuple[int, int, int]]:
    """The largest ``n <= max_blocks`` whose balanced factorization
    decomposes an ``L``-cube with every block owning true-domain cells,
    as mesh dims — the reshape-away target when quarantine shrinks the
    inventory to an awkward count (7 devices cannot split L=32; 4
    can). None when even one block is infeasible (never for L >= 1)."""
    from ..parallel.domain import CartDomain

    for n in range(max_blocks, 0, -1):
        try:
            return CartDomain.create(n, L).dims
        except ValueError:
            continue
    return None


# ---------------------------------------------------------- attribution


def bisect_failing(
    items: Sequence, healthy: Callable[[Tuple], bool],
) -> List:
    """Group-test localization over **disjoint subsets**: return every
    item of ``items`` implicated by the predicate, probing
    ``healthy(subset)`` on recursively halved disjoint subsets — a
    single faulty device costs O(log n) probes instead of n. ``healthy``
    must be monotone (a subset containing no faulty item reports
    True)."""
    items = tuple(items)
    if not items:
        return []
    if healthy(items):
        return []
    if len(items) == 1:
        return [items[0]]
    mid = len(items) // 2
    return (
        bisect_failing(items[:mid], healthy)
        + bisect_failing(items[mid:], healthy)
    )


def _bits(a: np.ndarray) -> np.ndarray:
    """The array's raw bit pattern as unsigned words — bitwise
    comparison that treats NaN payloads exactly (``!=`` would mark
    equal NaNs as diverged and identical bits as converged is all we
    need)."""
    a = np.ascontiguousarray(a)
    return a.view(np.dtype(f"uint{a.dtype.itemsize * 8}"))


# -------------------------------------------------------------- screener


class Screener:
    """The boundary-time redundant-compute screen.

    Protocol (driven by ``driver.py`` at each write boundary, **before**
    any poison faults and before the boundary's stores are written so a
    detection unwinds without persisting a corrupt byte):

    1. ``check(step)`` — on every ``every``-th boundary, replay the
       rounds since the anchor via ``Simulation.replay_fields`` (a
       non-donating twin of the live runner; ``shadow`` mode places it
       on a rotated device permutation) and compare the in-graph
       per-field checksums. Equal: journal ``sdc_check`` and advance
       ``verified_step``. Unequal: attribute and raise
       :class:`SDCError`.
    2. ``rearm(step)`` — retain a fresh device-side copy of the live
       fields as the next anchor. Called after the boundary's chaos
       poisons so an injected ``nan``/``drift`` never masquerades as
       compute-path SDC.

    Bitwise transparency: the screener only ever *reads* the live
    buffers (the anchor is the same +0-copy idiom as
    ``snapshot_async``), so a screened run's trajectory and stores are
    byte-identical to ``GS_SDC_CHECK=off`` — asserted across the model
    x kernel x precision matrix in tier-1.
    """

    def __init__(
        self, sim, *, mode: str = "spot", every: int = 1,
        journal=None, log: Optional[Callable[[str], None]] = None,
    ) -> None:
        if mode not in _MODES or mode == "off":
            raise ValueError(f"Screener mode {mode!r}")
        self.mode = mode
        self.every = max(1, int(every))
        self.journal = journal
        self.log = log
        self.checks = 0
        self.mismatches = 0
        self.verified_step: Optional[int] = None
        #: Set when shadow mode degraded to same-placement replay
        #: because the sim only has one device to run on.
        self.shadow_degraded = False
        self._bind(sim)

    def _bind(self, sim) -> None:
        self.sim = sim
        self._anchor: Optional[Tuple[int, tuple]] = None
        self._boundaries = 0
        self._ck_fn = None
        self._shadow: Optional[list] = None
        if self.mode == "shadow":
            devs = self._devices()
            if len(devs) > 1:
                self._shadow = devs[1:] + devs[:1]
                self.shadow_degraded = False
            else:
                self.shadow_degraded = True

    def rebind(self, sim) -> None:
        """Adopt a new Simulation (the driver swapped it via
        ``reshape_live``) — anchors, jitted probes and the shadow
        permutation are all placement-specific and rebuilt lazily."""
        self._bind(sim)

    def _devices(self) -> list:
        mesh = getattr(self.sim, "mesh", None)
        if mesh is not None:
            return list(mesh.devices.flat)
        return [self.sim.device]

    def _checksums(self, fields) -> tuple:
        import jax

        fn = self._ck_fn
        if fn is None:
            probe = self.sim._checksum_probe_fn()

            def run(*fs):
                return probe(*fs)

            fn = self._ck_fn = jax.jit(run)
        return tuple(np.asarray(c) for c in fn(*fields))

    def rearm(self, step: int) -> None:
        """Retain the live fields (fresh non-donated device copies) as
        the anchor the next check replays from."""
        self._anchor = (int(step), self.sim.retain_fields())

    def describe(self) -> dict:
        return {
            "mode": self.mode,
            "every": self.every,
            "checks": self.checks,
            "mismatches": self.mismatches,
            "verified_step": self.verified_step,
            "shadow_degraded": self.shadow_degraded,
        }

    def check(self, step: int) -> bool:
        """Screen this boundary. Returns True when a replay comparison
        actually ran (cadence due and an anchor existed), False when
        skipped. Raises :class:`SDCError` on mismatch."""
        step = int(step)
        self._boundaries += 1
        if self._anchor is None:
            return False
        if self._boundaries % self.every:
            return False
        a_step, a_fields = self._anchor
        nsteps = step - a_step
        if nsteps <= 0:
            return False
        replay = self.sim.replay_fields(
            a_fields, a_step, nsteps, devices=self._shadow,
        )
        live_ck = self._checksums(self.sim.fields)
        rep_ck = self._checksums(replay)
        self.checks += 1
        if all(
            np.array_equal(a, b) for a, b in zip(live_ck, rep_ck)
        ):
            self.verified_step = step
            if self.journal is not None:
                self.journal.record(
                    event="sdc_check", step=step, mode=self.mode,
                    replayed_steps=nsteps, status="ok",
                )
            return True
        self.mismatches += 1
        device, member, diverged = self._attribute(replay, live_ck, rep_ck)
        detail = (
            f"SDC screen ({self.mode}) mismatch: replay of "
            f"{nsteps} step(s) from verified anchor at step {a_step} "
            f"disagrees with the live trajectory "
            f"({diverged} diverging word(s) localized)"
        )
        if self.journal is not None:
            self.journal.record(
                event="sdc_mismatch", kind="sdc", step=step,
                mode=self.mode, device=device, member=member,
                replayed_steps=nsteps,
                verified_step=self.verified_step,
            )
        if self.log is not None:
            self.log(
                f"SDC mismatch at step {step} attributed to "
                f"device={device} member={member}"
            )
        raise SDCError(
            detail, step=step, verified_step=self.verified_step,
            device=device, member=member, mode=self.mode,
        )

    # -- attribution ----------------------------------------------------

    def _attribute(
        self, replay, live_ck, rep_ck,
    ) -> Tuple[Optional[str], Optional[int], int]:
        """Localize the mismatch: ``(device, member, n_diff_words)``.

        Member (ensemble): the per-member checksum vectors disagree at
        the diverging members' rows — no extra device work.

        Device: pull the diverging shards to the host lazily, bisect
        over disjoint device subsets (:func:`bisect_failing` — a
        deterministic per-device fault implicates its subset in every
        probe), then take the blast center among the implicated
        devices: the one owning the most diverging words."""
        member: Optional[int] = None
        rows = set()
        for a, b in zip(live_ck, rep_ck):
            if a.shape and a.shape == b.shape:
                rows.update(int(i) for i in np.nonzero(a != b)[0])
        if rows:
            member = min(rows)

        live = self.sim.fields
        rep_host = [np.asarray(r) for r in replay]
        shards: Dict[str, list] = {}
        for fi, f in enumerate(live):
            for sh in f.addressable_shards:
                shards.setdefault(device_name(sh.device), []).append(
                    (fi, sh)
                )
        pulled: Dict[int, np.ndarray] = {}

        def diff_words(fi: int, sh) -> int:
            key = id(sh)
            if key not in pulled:
                idx = (
                    sh.index if isinstance(sh.index, tuple)
                    else (sh.index,)
                )
                a = _bits(np.asarray(sh.data))
                b = _bits(rep_host[fi][idx])
                pulled[key] = (a != b)
            return int(pulled[key].sum())

        def healthy(subset) -> bool:
            return all(
                diff_words(fi, sh) == 0
                for dev in subset
                for fi, sh in shards[dev]
            )

        failing = bisect_failing(tuple(sorted(shards)), healthy)
        if not failing:
            return None, member, 0
        counts = {
            dev: sum(diff_words(fi, sh) for fi, sh in shards[dev])
            for dev in failing
        }
        total = sum(counts.values())
        device = sorted(
            failing, key=lambda d: (-counts[d], d)
        )[0]
        return device, member, total
