"""Run supervision: classify failures, back off, auto-resume.

The open-loop driver dies on the first async-writer error, NaN blow-up,
preemption, or Mosaic regression — with whatever the checkpoint store
happened to hold. ``supervise(settings)`` closes the loop around a
refactored ``driver.run_once``; it is the preemption-safe-loop shape
shared with long-training stacks (arXiv:2309.10292 §5 runs the same
checkpoint/restart discipline on Frontier; arXiv:2404.02218 argues the
runtime layer, not user code, must absorb these):

* **classify** the failure — ``transient-io`` (an ``AsyncIOError``
  whose original is an OS-level error, or a bare ``OSError``),
  ``preemption`` (:class:`~.faults.PreemptionError`), ``health``
  (:class:`~.health.HealthError` under the ``rollback`` policy), or
  ``kernel`` (a Mosaic/Pallas runtime failure). Anything else — a
  config error, a programming bug — re-raises immediately: retrying an
  unclassified failure just burns accelerator time.
* **retry** with exponential backoff (base ``GS_RESTART_BACKOFF_S``,
  default 0.5 s, cap 30 s) plus deterministic jitter (crc32 of the
  attempt/kind, not a live RNG — replayable), up to ``GS_MAX_RESTARTS``.
* **auto-resume**: before each retry the latest *durable* checkpoint is
  located (``bplite.BpReader`` exposes only complete steps, so a crash
  mid-checkpoint never resumes from a torn entry) and the settings are
  rewritten to ``restart=true`` pointing at ``checkpoint_output``. No
  checkpoint yet means a from-scratch restart.
* **degrade** ``kernel_language`` Pallas->XLA on a kernel-runtime
  failure, recording the degradation in the ``kernel_selection``
  provenance of the final ``RunStats`` — the run finishes slower
  rather than not at all, and the stats say why.
* **journal** every failure and recovery action as JSONL
  (:class:`FaultJournal`); the completing attempt merges the full
  journal into ``RunStats`` as its ``faults`` section.

Supervision is per-process: multi-host runs (``jax.process_count() >
1``) need an external restarter that relaunches all ranks together, so
``driver.main`` refuses to supervise them (see docs/RESILIENCE.md).
"""

from __future__ import annotations

import dataclasses
import os
import time
import zlib
from typing import List, Optional

from .faults import FaultPlan, InjectedKernelError, PreemptionError
from .health import HealthError

__all__ = [
    "FaultJournal",
    "SupervisorContext",
    "classify_failure",
    "latest_durable_checkpoint",
    "restart_backoff",
    "resolve_max_restarts",
    "supervise",
    "supervision_enabled",
]

_TRUTHY = {"1", "true", "yes", "on"}
_FALSY = {"0", "false", "no", "off"}


def supervision_enabled(settings=None) -> bool:
    """``GS_SUPERVISE`` env, else the ``supervise`` TOML key."""
    raw = os.environ.get("GS_SUPERVISE")
    if raw is not None:
        val = raw.strip().lower()
        if val in _TRUTHY:
            return True
        if val in _FALSY:
            return False
        raise ValueError(
            f"GS_SUPERVISE must be a boolean (0/1/true/false), got {raw!r}"
        )
    return bool(getattr(settings, "supervise", False))


def resolve_max_restarts(settings=None) -> int:
    """``GS_MAX_RESTARTS`` env, else the ``max_restarts`` TOML key."""
    raw = os.environ.get("GS_MAX_RESTARTS")
    if raw is not None:
        try:
            n = int(raw)
        except ValueError as e:
            raise ValueError(
                f"GS_MAX_RESTARTS must be an integer, got {raw!r}"
            ) from e
    else:
        n = int(getattr(settings, "max_restarts", 3))
    if n < 0:
        raise ValueError(f"max restarts must be >= 0, got {n}")
    return n


def restart_backoff(attempt: int, kind: str) -> float:
    """Exponential backoff with deterministic jitter.

    ``base * 2**attempt`` capped at 30 s, plus up to 25% jitter derived
    from crc32(attempt:kind) — spread-out restarts without an RNG, so a
    replayed chaos run sleeps the same schedule every time.
    """
    base = float(os.environ.get("GS_RESTART_BACKOFF_S", "0.5"))
    if base < 0:
        raise ValueError(
            f"GS_RESTART_BACKOFF_S must be >= 0, got {base}"
        )
    delay = min(base * (2 ** attempt), 30.0)
    frac = (zlib.crc32(f"{attempt}:{kind}".encode()) % 1000) / 1000.0
    return delay * (1.0 + 0.25 * frac)


class FaultJournal:
    """Append-only fault/recovery event log, mirrored to JSONL.

    Events are plain dicts; ``record`` is called from the driver thread
    (nan/preempt/health/recovery events) and from the async writer's
    worker thread (fired io_error injections), so the file append is
    lock-guarded. The journal object outlives run attempts — the
    completing attempt merges ``events`` into ``RunStats``.
    """

    def __init__(self, path: Optional[str] = None):
        import threading

        self.path = path
        self.events: List[dict] = []
        self._lock = threading.Lock()

    @classmethod
    def from_env(cls, settings=None) -> "FaultJournal":
        """Journal at ``GS_FAULT_JOURNAL``; default ``<output>.faults.jsonl``
        under supervision, in-memory only otherwise."""
        path = os.environ.get("GS_FAULT_JOURNAL")
        if not path and settings is not None and supervision_enabled(settings):
            path = settings.output + ".faults.jsonl"
        return cls(path or None)

    def record(self, **event) -> dict:
        import json

        event.setdefault("t", round(time.time(), 3))
        with self._lock:
            self.events.append(event)
            if self.path:
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(event) + "\n")
        return event


@dataclasses.dataclass
class SupervisorContext:
    """Per-attempt state the supervisor threads through ``run_once``."""

    plan: FaultPlan
    journal: FaultJournal
    attempt: int = 0
    #: kernel_selection provenance patch after a Pallas->XLA degrade.
    degraded: Optional[dict] = None


#: Message fragments that identify a kernel-runtime failure raised by
#: the TPU compiler/runtime stack (vs our injected marker, which
#: carries "Mosaic" too).
_KERNEL_MARKERS = ("mosaic", "pallas")


def classify_failure(exc: BaseException) -> Optional[str]:
    """Map a run failure onto the recovery taxonomy, or None (fatal).

    The classification deliberately whitelists: only failure shapes
    with a known recovery action are retried. ``AsyncIOError`` is
    unwrapped to its original exception (``io/async_writer.py`` tags
    transience there, where the failing write happened).
    """
    from ..io.async_writer import AsyncIOError

    if isinstance(exc, PreemptionError):
        return "preemption"
    if isinstance(exc, HealthError):
        # abort policy means abort: only rollback is recoverable.
        return "health" if exc.policy == "rollback" else None
    if isinstance(exc, InjectedKernelError):
        return "kernel"
    if isinstance(exc, AsyncIOError):
        return "transient-io" if exc.transient else None
    if isinstance(exc, OSError):
        return "transient-io"
    # Real Mosaic/Pallas runtime failures surface as XLA runtime errors
    # whose type lives in jaxlib; match on the message rather than
    # importing a version-dependent exception type.
    name = type(exc).__name__
    if name in ("XlaRuntimeError", "InternalError"):
        msg = str(exc).lower()
        if any(m in msg for m in _KERNEL_MARKERS):
            return "kernel"
    return None


def latest_durable_checkpoint(settings) -> Optional[int]:
    """Simulation step of the latest *complete* checkpoint entry, or
    None. Checkpoints are always BP-lite stores
    (``io/checkpoint.py`` pins ``prefer_adios2=False``), and the
    reader's durability validation (``io/bplite.py``) already hides a
    torn final entry — so whatever this returns is safe to resume from.
    """
    if not settings.checkpoint:
        return None
    from ..io.bplite import BpReader

    try:
        r = BpReader(settings.checkpoint_output)
    except FileNotFoundError:
        return None
    try:
        n = r.num_steps()
        if n == 0:
            return None
        return int(r.get("step", step=n - 1))
    finally:
        r.close()


def _resolved_language(settings) -> str:
    from ..config.settings import KERNEL_LANGUAGES

    return KERNEL_LANGUAGES.get(
        settings.kernel_language.lower(), settings.kernel_language.lower()
    )


def supervise(settings, *, n_devices: Optional[int] = None, seed: int = 0):
    """Run ``driver.run_once`` under the restart loop; returns the
    completed attempt's :class:`~..simulation.Simulation`.

    ``settings`` is mutated across attempts (restart target, degraded
    kernel language) — the supervisor owns the run's lifecycle, and the
    final settings describe how the run actually finished.
    """
    from ..driver import run_once
    from ..utils.log import Logger

    log = Logger(verbose=True)
    plan = FaultPlan.from_env(settings)
    journal = FaultJournal.from_env(settings)
    limit = resolve_max_restarts(settings)
    attempt = 0
    degraded: Optional[dict] = None

    while True:
        ctx = SupervisorContext(
            plan=plan, journal=journal, attempt=attempt, degraded=degraded
        )
        try:
            return run_once(
                settings, n_devices=n_devices, seed=seed, context=ctx
            )
        except BaseException as exc:  # noqa: BLE001 — classify, then re-raise
            kind = classify_failure(exc)
            if kind is None or attempt >= limit:
                journal.record(
                    event="gave_up",
                    kind=kind or "fatal",
                    attempt=attempt,
                    error=f"{type(exc).__name__}: {exc}",
                )
                raise

            actions = []
            if kind == "kernel":
                lang = _resolved_language(settings)
                if lang in ("pallas", "auto"):
                    degraded = {
                        "degraded_from": lang,
                        "degraded_reason": f"{type(exc).__name__}: {exc}",
                        "degraded_at_attempt": attempt,
                    }
                    settings.kernel_language = "XLA"
                    actions.append("degraded_pallas_to_xla")
                else:
                    # Already on XLA: a kernel failure there has no
                    # softer language to fall back to.
                    journal.record(
                        event="gave_up", kind=kind, attempt=attempt,
                        error=f"{type(exc).__name__}: {exc}",
                        reason="kernel failure with no degradation left",
                    )
                    raise

            resume = latest_durable_checkpoint(settings)
            if resume is not None:
                settings.restart = True
                settings.restart_input = settings.checkpoint_output
                settings.restart_step = resume
                actions.append(f"resumed_from_checkpoint_step_{resume}")
            else:
                # No durable checkpoint: restart the trajectory from
                # scratch (unless the operator's own restart settings
                # already point somewhere — leave those alone).
                if not settings.restart:
                    actions.append("restarted_from_scratch")
                else:
                    actions.append("restarted_from_configured_checkpoint")

            delay = restart_backoff(attempt, kind)
            journal.record(
                event="recovery",
                kind=kind,
                attempt=attempt,
                error=f"{type(exc).__name__}: {exc}",
                action=";".join(actions),
                backoff_s=round(delay, 3),
            )
            log.info(
                f"supervisor: {kind} failure "
                f"({type(exc).__name__}: {exc}); attempt "
                f"{attempt + 1}/{limit} recovers with "
                f"[{', '.join(actions)}] after {delay:.2f}s"
            )
            time.sleep(delay)
            attempt += 1
